"""Sidecar metrics listener: a tiny stdlib HTTP server exposing
`/metrics` (Prometheus text exposition), `/healthz` (JSON liveness),
`/debug/recorder` (the flight recorder's ring as JSON, newest last,
plus the recent exemplar roots), `/debug/docs` (the per-doc
capacity surface: hot-doc cost vectors + headroom; `?k=n` bounds the
table), and `/debug/slo_slots` (the raw mergeable SLO window slots
plus replica identity -- what the fleet aggregation plane
(telemetry/fleet.py) sums across replicas before recomputing
percentiles, so a fleet merge is bit-identical to a single-replica
recompute) so a fleet of sidecars is scrapeable and post-mortem-able
without touching the stream protocol.  Runs as a daemon thread next to
the stream loop; the same payloads are also answerable in-band via the
`metrics` / `healthz` / `dump` request types (sidecar/server.py) for
transports that already hold a stream open.
"""

import json
import threading

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

CONTENT_TYPE = 'text/plain; version=0.0.4; charset=utf-8'


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):
        from . import healthz, render_prometheus
        path, _, query = self.path.partition('?')
        if path == '/metrics':
            body = render_prometheus().encode()
            ctype = CONTENT_TYPE
        elif path == '/healthz':
            body = (json.dumps(healthz()) + '\n').encode()
            ctype = 'application/json'
        elif path == '/debug/recorder':
            from . import attribution, recorder
            body = (json.dumps(
                {'events': recorder.events_json(),
                 'exemplars': attribution.recent_exemplars()},
                default=str) + '\n').encode()
            ctype = 'application/json'
        elif path == '/debug/slo_slots':
            from . import attribution, replica_id, uptime_s
            body = (json.dumps(
                {'replica_id': replica_id(),
                 'uptime_s': round(uptime_s(), 3),
                 'slots': attribution.slo_slots()},
                default=str) + '\n').encode()
            ctype = 'application/json'
        elif path == '/debug/docs':
            from . import capacity
            try:
                k = int(parse_qs(query).get('k', ['0'])[0]) or None
            except ValueError:
                k = None
            body = (json.dumps(capacity.debug_docs(k=k), default=str)
                    + '\n').encode()
            ctype = 'application/json'
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header('Content-Type', ctype)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):
        pass    # scrapes every few seconds must not spam stderr


def start_metrics_server(port, host='127.0.0.1'):
    """Starts the listener on (host, port) in a daemon thread; port 0
    binds an ephemeral port.  Returns the server (server.server_port
    holds the bound port; server.shutdown() stops it)."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever,
                              name='amtpu-metrics', daemon=True)
    thread.start()
    return server
