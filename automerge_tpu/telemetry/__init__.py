"""automerge_tpu.telemetry -- the observability layer (PR 1).

Replaces the flat `trace.py` occupancy counter with three composable
pieces, threaded through every layer of the stack (frontend -> sidecar
-> pool -> kernels -> sync):

  * a metric REGISTRY (`registry`): counters, gauges, log-bucketed
    histograms; thread-safe; near-zero-cost when idle.  The standard
    families below fire per batch / per sidecar request, never per op.
  * structured SPANS (`span`, `span_with_context`): request/batch-scoped
    timing carrying a trace id and attributes, propagated across the
    sidecar process boundary, exportable as JSONL
    (`AMTPU_TRACE_FILE=...`).  Spans are opt-in: `enable()` / `disable()`
    at runtime, or `AMTPU_TRACE=1` at startup (the legacy gate).
  * PROMETHEUS exposition (`render_prometheus`): the registry plus
    families derived from the span occupancy table and the always-on
    flat metric map, served by the sidecar's `metrics` request type and
    the optional HTTP listener (`httpd.start_metrics_server`).

The always-on flat map (`metric` / `metrics_snapshot`) is kept verbatim
from trace.py: the handful of numbers every bench line must report
unconditionally -- oracle-fallback and degradation counters, measured
device seconds.  Incremented once per BATCH, never per op.

`automerge_tpu.trace` remains as a compatibility shim over this module,
so pre-PR-1 call sites and the `trace.ENABLED = True` toggle keep
working.

Metric catalog: docs/OBSERVABILITY.md.
"""

import threading
import time
from ..utils.common import env_bool, env_float, env_int

from .metrics import (DEFAULT_BUCKETS, MetricRegistry,  # noqa: F401
                      format_value)
from .spans import (NULL_SPAN, current_span,  # noqa: F401
                    current_trace_context, disable, enable, enabled,
                    new_id, new_root_context, new_trace_id, phase_add,
                    phase_count, phase_report, phase_reset,
                    phase_snapshot, set_trace_file, span,
                    span_with_context, trace_file)

_START_TIME = time.time()


def uptime_s():
    """Seconds since this process imported telemetry -- the per-replica
    uptime healthz and /debug/slo_slots report (fleet skew tables key
    on it to spot the freshly-restarted replica)."""
    return time.time() - _START_TIME


_replica_id_cached = None


def replica_id():
    """A stable identity for THIS replica, latched at first use:
    ``AMTPU_REPLICA_ID`` when set (a fleet operator names replicas),
    else ``<hostname>:<pid>`` -- unique per process, stable for its
    lifetime, and debuggable at a glance.  Carried by healthz and
    ``/debug/slo_slots`` so the fleet plane (telemetry/fleet.py) can
    attribute merged windows and headroom skew per replica."""
    global _replica_id_cached
    if _replica_id_cached is None:
        import socket as _socket
        from ..utils.common import env_str
        import os as _os
        _replica_id_cached = env_str('AMTPU_REPLICA_ID', '') \
            or '%s:%d' % (_socket.gethostname(), _os.getpid())
    return _replica_id_cached

registry = MetricRegistry()

# -- standard families (the catalog's core; docs/OBSERVABILITY.md) ----------

BATCHES = registry.counter(
    'amtpu_batches_total', 'Batches applied, by pool entry point',
    ('pool',))
BATCH_LATENCY = registry.histogram(
    'amtpu_batch_latency_seconds',
    'Wall-clock latency of one apply-batch pass, by pool entry point',
    ('pool',))
OPS = registry.counter(
    'amtpu_ops_total', 'Operations counted on committed batches only '
    '(engine path: exact causally-applied ops; dict-level native path: '
    'submitted ops incl. duplicates/queued -- the bytes path cannot '
    'count without a decode it avoids)')
DOCS = registry.counter(
    'amtpu_docs_total', 'Documents touched by committed batches')
SIDECAR_REQS = registry.counter(
    'amtpu_sidecar_requests_total', 'Sidecar protocol requests served',
    ('cmd', 'outcome'))
SIDECAR_LATENCY = registry.histogram(
    'amtpu_sidecar_request_seconds', 'Sidecar request service time',
    ('cmd',))
SYNC_MSGS = registry.counter(
    'amtpu_sync_messages_total', 'Connection sync messages processed',
    ('direction',))
SIDECAR_INTERNAL = registry.counter(
    'amtpu_sidecar_internal_errors_total',
    'Unexpected exceptions the sidecar dispatch answered as the '
    'InternalError envelope (the serve loop survived them)')

# fallback reasons pre-seeded into the exposition AND every bench_block
# so dashboards/gates see explicit zeros before the first degradation
# (the same names trace.metric('fallback.<reason>') call sites emit).
# 'oracle' counts register rows that actually reached the host oracle
# after the escalation ladder; 'escalated.wN' counts rows resolved on
# device by the W=N tier (make fallback-check asserts oracle == 0 with
# the tier counters present).
KNOWN_FALLBACK_REASONS = ('layout_batches', 'overflow_batches',
                          'overflow_rows', 'member_overflow_rows',
                          'oracle', 'escalated.w16', 'escalated.w32',
                          'escalated.w64')

# collect-path counters (`trace.metric('collect.<name>')` call sites),
# pre-seeded into every bench_block so gates can assert explicit zeros:
# packed_member_batches  -- member-mode batches served by the packed
#                           epilogue (ONE i32/row + sparse conflicts)
# full_matrix_readback   -- batches that read back the full
#                           winner/conflicts/alive/overflow matrices
#                           (AMTPU_PACKED_EPILOGUE=0, Tp >= 2^24, or the
#                           kernel-overflow fused fallback)
# conflict_sparse/dense  -- which side of the AMTPU_CONF_DENSE_THRESH
#                           switch each conflicts fetch took
# ready_reorder          -- pipelined phase-b picks served out of
#                           submission order because their device
#                           outputs resolved first
# wait_in_order          -- rounds where nothing was ready and collect
#                           blocked on the oldest submission
KNOWN_COLLECT_KEYS = ('packed_member_batches', 'full_matrix_readback',
                      'conflict_sparse', 'conflict_dense',
                      'ready_reorder', 'wait_in_order',
                      'device_merge_chunks', 'overlap_s')

# pool-resident batch state (ISSUE 6; glossary: docs/OBSERVABILITY.md),
# pre-seeded so the perf-smoke resident gate reads zeros -- not
# missing keys -- when the cache is disabled or cold
KNOWN_RESIDENT_BATCH_KEYS = ('batch_hits', 'batch_noop',
                             'batch_full_uploads',
                             'batch_full_upload_rows',
                             'batch_delta_rows', 'batch_hit_rows',
                             'batch_gen_invalidation',
                             'batch_grow_uploads',
                             'batch_cache_dropped',
                             'latch_flip_ignored',
                             'dispatches')

# cross-batch wave pipelining (ISSUE 6 tentpole c), pre-seeded so bench
# artifacts distinguish "never engaged" (explicit zeros) from "not
# recorded": batches that took the wave path / total doc-disjoint waves
KNOWN_PIPELINE_KEYS = ('batches', 'waves', 'serial_replay')

# mesh execution mode (ISSUE 7; `trace.metric('mesh.<name>')` call
# sites in native/mesh_pool.py + the sp fence in native/resident.py;
# glossary: docs/OBSERVABILITY.md), pre-seeded into every bench_block
# so a MULTICHIP line always carries the full mesh story:
# batches / shards        mesh-driven batches and the dp chips that
#                           carried payload across them
# chip_docs               docs placed on chips (sum; / shards = mean
#                           per-chip occupancy)
# occupancy_skew          per-batch max-min docs across chips (FNV
#                           routing imbalance)
# encode_shard_skew_s     per-batch max-min of the chips' threaded
#                           phase-a (host decode/begin+dispatch) walls
# collective_wait_s       time a collector blocked on a chip whose
#                           device outputs had not resolved (nothing
#                           else was ready)
# device_shortfall        mesh pools built with fewer devices than
#                           dp x sp (round-robin placement degradation)
# sp_fenced / sp_engaged  resident dispatches the sp-axis crossover
#                           fence kept single-chip vs routed sharded
# latch_flip_ignored      AMTPU_MESH* env flips after the first batch
#                           (warned once, ignored -- the topology and
#                           jit caches latched)
KNOWN_MESH_KEYS = ('batches', 'shards', 'chip_docs', 'occupancy_skew',
                   'encode_shard_skew_s', 'collective_wait_s',
                   'device_shortfall', 'sp_fenced', 'sp_engaged',
                   'latch_flip_ignored')

# resilience counters (`telemetry.metric('resilience.<name>')` call
# sites; glossary: docs/RESILIENCE.md), pre-seeded into every
# bench_block and the healthz payload so gates and dashboards see
# explicit zeros before the first fault:
# retry.attempts/success/    bounded-backoff retries of transient
#   exhausted                  failures and their outcomes
# bisect.rounds              doc-set splits while isolating poison docs
# quarantined                docs answered as per-doc error envelopes
# degraded                   docs healed on the full-host path
#                              (AMTPU_DEGRADE=1; DISTINCT from
#                              fallback.oracle -- perf gates stay
#                              meaningful)
# rollback /                 failed batches rolled back to the pre-begin
#   rollback_unavailable       pool state, or found past the point of
#                              rollback (emit already ran)
# fault_injected             armed `automerge_tpu.faults` sites that
#                              fired (also per-site subkeys)
KNOWN_RESILIENCE_KEYS = ('retry.attempts', 'retry.success',
                         'retry.exhausted', 'bisect.rounds',
                         'quarantined', 'degraded', 'rollback',
                         'rollback_unavailable', 'fault_injected')

# scheduler counters (`telemetry.metric('scheduler.<name>')` call sites
# in automerge_tpu/scheduler/; glossary: docs/OBSERVABILITY.md,
# architecture: docs/SERVING.md), pre-seeded into every bench_block so
# gates and dashboards see explicit zeros before the first gateway
# request:
# flushes            dispatcher flush cycles that executed work
# coalesced_ops      mutating requests coalesced into batch flushes
# batched_docs       docs carried by gateway batch flushes
# exec_ops           ordered ops the dispatcher ran serially (local
#                      changes, loads, queued reads, serial replays)
# bypass_reads       read-only requests served inline off the reader
#                      thread (no queue, no flush wait)
# parked             claim passes that left an op queued because its
#                      doc already had an op in the flush
# shed               mutating requests refused with the Overloaded
#                      envelope (admission control)
# serial_fallback    flushes replayed serially after a whole-batch
#                      protocol error (per-request results restored)
# quarantined        per-doc resilience envelopes routed back to the
#                      originating request by a flush
KNOWN_SCHEDULER_KEYS = ('flushes', 'coalesced_ops', 'batched_docs',
                        'exec_ops', 'bypass_reads', 'parked', 'shed',
                        'serial_fallback', 'quarantined')

# batched sync fan-out counters (`telemetry.metric('sync.fanout.<name>')`
# call sites in sync/fanout.py + scheduler/gateway.py; glossary:
# docs/OBSERVABILITY.md, architecture: docs/SERVING.md), pre-seeded into
# every bench_block's `fanout` sub-object so the fanout-check gate and
# the BENCH_FANOUT artifact read explicit zeros, never missing keys:
# flushes / docs        fan-out passes that had work, and the dirty
#                         docs they evaluated
# frames                event frames written to subscriber connections
# encode_reuse          coalesced sends served from an ALREADY-encoded
#                         frame (N subscribers -> N-1 reuses); the
#                         encode-once proof fanout-check gates
# coalesced_peers       subscribers served the shared coalesced frame
# straggler_peers       subscribers with divergent clocks served a
#                         per-peer filtered delta
# uptodate_peers        subscribers whose clock already covered the
#                         flush (incl. the originator echo)
# bytes_encoded /       wire bytes encoded vs written; on_wire /
#   bytes_on_wire         encoded = the fan-out amplification factor
# subscribes /          subscription lifecycle events (drops = peers
#   unsubscribes / drops   torn down with their connection)
# backfills             subscribe-time missing-changes backfills
# presence_frames       ephemeral (cursor) frames, incl. piggybacked
# quarantine_frames     resilience envelopes fanned to subscribers of a
#                         quarantined doc
# vector_passes /       classification passes served by the vectorized
#   scalar_passes         matrix vs the per-peer scalar loop
#                         (AMTPU_FANOUT_VECTOR=0)
# errors                fan-out passes that raised (flush survived)
# patch_subscribes      mode:"patch" subscriptions accepted (thin
#                         clients; docs/SERVING.md read path)
# patch_frames          incremental patch frames staged (the flush's
#                         captured patch, encoded once per doc)
# patch_full_frames     full-state patch frames staged (stragglers,
#                         resyncs, flushes with no captured patch)
# patch_full_builds /   get_patch materializations for full-state
#   patch_full_reuse      frames vs auth-clock memo hits
KNOWN_FANOUT_KEYS = ('flushes', 'docs', 'frames', 'encode_reuse',
                     'coalesced_peers', 'straggler_peers',
                     'uptodate_peers', 'bytes_encoded',
                     'bytes_on_wire', 'writes_coalesced', 'subscribes',
                     'unsubscribes', 'drops', 'backfills',
                     'presence_frames', 'quarantine_frames',
                     'vector_passes', 'scalar_passes', 'errors',
                     'straggler_reuse', 'backfill_reuse',
                     'regressed_peers', 'prefix_subscribes',
                     'prefix_attaches', 'subscribe_shed',
                     'patch_subscribes', 'patch_frames',
                     'patch_full_frames', 'patch_full_builds',
                     'patch_full_reuse')

# bounded-egress counters (`telemetry.metric('egress.<name>')` call
# sites in scheduler/egress.py + scheduler/gateway.py; glossary:
# docs/OBSERVABILITY.md, degradation tiers: docs/RESILIENCE.md),
# pre-seeded into every bench_block's `egress` sub-object and surfaced
# by the healthz `egress` section:
# staged_frames/staged_bytes  frames/bytes staged on per-conn egress
#                               queues (responses AND events)
# writes / write_errors       frames fully written / transports that
#                               died on a write error
# sheds / shed_frames /       tier-1 overflow events, the event frames
#   shed_bytes                  they dropped, and the bytes freed
# resyncs                     tier-2 drop-to-resubscribe envelopes
#                               (subscription rows freed)
# wedge_evictions             tier-3 consumers disconnected after
#                               AMTPU_EGRESS_WEDGE_S of zero progress
KNOWN_EGRESS_KEYS = ('staged_frames', 'staged_bytes', 'writes',
                     'write_errors', 'sheds', 'shed_frames',
                     'shed_bytes', 'resyncs', 'wedge_evictions',
                     'overflow_evictions')

# columnar storage tier counters (`telemetry.metric('storage.<name>')`
# call sites in automerge_tpu/storage/ + native/__init__.py +
# scheduler/gateway.py; glossary: docs/OBSERVABILITY.md, architecture:
# docs/STORAGE.md), pre-seeded into every bench_block's `storage` sub
# -object so the storage-check gate reads explicit zeros:
# columnar.encodes/decodes   codec passes
# columnar.changes           changes columnar-encoded
# columnar.residual_changes  changes carried verbatim (non-canonical
#                              bytes / exotic shapes; byte round-trip
#                              holds either way)
# columnar.bytes_in/_out     raw change bytes in vs blob bytes out (the
#                              compression ratio the gate bounds)
# save_v2                    v2 columnar containers emitted by save()
# snapshot_backfills         straggler queries served by merging the
#                              columnar snapshot with the C++ tail
# gc.compactions             settled-prefix folds into the snapshot
# gc.changes_folded          changes those folds moved out of the arena
# gc.bytes_freed             raw-change bytes released by truncation
# gc.skipped_json            compactions no-op'd by the
#                              AMTPU_STORAGE_FORMAT=json oracle arm
# gc.failed                  compactions that raised (flush survived)
# evictions / reloads        cold-doc LRU evictions and reload-on-touch
#                              restores
# evict_failed               docs that refused to checkpoint (kept
#                              resident)
# cold_bytes_written         checkpoint bytes written to the cold store
# gc.clocks_folded           per-change all_deps clock pairs freed by
#                              folding into the densified clock table
# restore.docs/.bytes        docs + blob bytes restored from the cold
#                              store by restore_from_store
# restore.batches            decode+apply batches the restore ran
# restore.corrupt            blobs quarantined on checksum failure
#                              (doc skipped, restore continues)
# restore.failed             docs whose decode/apply raised (skipped
#                              via the resilience path)
# sync_saves                 docs write-through checkpointed pre-ack
#                              (AMTPU_STORAGE_SYNC; acked => durable)
# sync_failed                write-through saves that raised (doc
#                              skipped; the ack still goes out)
KNOWN_STORAGE_KEYS = ('columnar.encodes', 'columnar.decodes',
                      'columnar.changes', 'columnar.residual_changes',
                      'columnar.bytes_in', 'columnar.bytes_out',
                      'save_v2', 'snapshot_backfills',
                      'gc.compactions', 'gc.changes_folded',
                      'gc.bytes_freed', 'gc.skipped_json', 'gc.failed',
                      'gc.ops_folded', 'gc.rechunks',
                      'evictions', 'reloads', 'reload_failed',
                      'evict_failed', 'cold_bytes_written',
                      'evicted_bytes', 'pressure_evictions',
                      'native_encodes', 'python_encodes',
                      'native_decodes', 'python_decodes',
                      'native_loads', 'durable_writes',
                      'manifest_writes', 'manifest_recovered',
                      'manifest_corrupt', 'checksum_failed',
                      'gc.clocks_folded',
                      'restore.docs', 'restore.bytes',
                      'restore.batches', 'restore.corrupt',
                      'restore.failed',
                      'sync_saves', 'sync_failed')

# flight-recorder counters (`telemetry.metric('recorder.<name>')` call
# sites in telemetry/recorder.py; event catalog: docs/OBSERVABILITY.md),
# pre-seeded into every bench_block so gates read explicit zeros:
# dumps         JSONL ring dumps written (quarantine, state-suspect,
#                 respawn, SIGTERM, the `dump` request)
# dump_failed   dumps that could not be written (full disk, bad dir);
#                 the triggering failure is never re-raised
KNOWN_RECORDER_KEYS = ('dumps', 'dump_failed')

# per-doc capacity accounting counters (`telemetry.metric(
# 'capacity.<name>')` call sites in telemetry/capacity.py; capacity
# section: docs/OBSERVABILITY.md), pre-seeded into every bench_block:
# refreshes       native per-doc stats passes (throttled by
#                   AMTPU_CAPACITY_REFRESH_S; healthz scrapes and
#                   per-flush pressure checks share one)
# pressure_high   refreshes that measured memory pressure at or past
#                   AMTPU_MEM_PRESSURE_EVICT (the proactive-eviction
#                   signal)
KNOWN_CAPACITY_KEYS = ('refreshes', 'pressure_high')

# SLO / attribution counters (`telemetry.metric('slo.<name>')` call
# sites in telemetry/attribution.py; request-stage glossary:
# docs/OBSERVABILITY.md), pre-seeded into every bench_block:
# requests    gateway requests the critical-path attribution finished
# breaches    attributed requests whose through-emit wall exceeded
#               AMTPU_SLO_P99_MS
# exemplars   tail-sampled exemplar span trees emitted (slow or
#               failed/quarantined requests)
KNOWN_SLO_KEYS = ('requests', 'breaches', 'exemplars')

# distributed-tracing counters (`telemetry.metric('trace.<name>')` call
# sites in telemetry/spans.py + sidecar/client.py; distributed-tracing
# section: docs/OBSERVABILITY.md), pre-seeded into every bench_block:
# roots        outbound sidecar requests stamped with a freshly minted
#                root wire context (the caller had no ambient span)
# propagated   outbound requests that carried the caller's ambient span
#                context across the wire instead
# rotations    size-capped trace-file rotations (keep-1; the single
#                -winner path of the ISSUE 16 race fix)
KNOWN_TRACE_KEYS = ('roots', 'propagated', 'rotations')

# fleet aggregation counters (`telemetry.metric('fleet.<name>')` call
# sites in telemetry/fleet.py; fleet section: docs/OBSERVABILITY.md),
# pre-seeded into every bench_block:
# scrapes        replica healthz/slo-slot scrapes that answered
# scrape_errors  replicas that failed to answer a scrape (the merged
#                  surface marks them down instead of silently
#                  shrinking the fleet)
KNOWN_FLEET_KEYS = ('scrapes', 'scrape_errors')

# fleet-router counters (`telemetry.metric('router.<name>')` call sites
# in router/gateway.py; routing section: docs/OBSERVABILITY.md),
# pre-seeded into every bench_block:
# requests         frames forwarded to an owner replica
# local            pure commands (ping/metrics/healthz/dump) answered
#                    from the router process itself
# split_ops        requests that spanned owners and fanned into
#                    per-owner sub-requests (apply_batch / doc-set or
#                    prefix subscribe)
# parked           frames queued in a per-doc FIFO behind a live
#                    migration (released in arrival order at commit)
# redirects        WrongReplica answers re-forwarded to the owner the
#                    envelope named (bounded by AMTPU_ROUTE_REDIRECTS)
# upstream_errors  forwards answered with a retryable Overloaded
#                    envelope because the owner replica was unreachable
#                    or its connection died mid-request
# resyncs          migration-handoff resync events staged to
#                    subscribed connections (their auto-resubscribe
#                    re-homes the stream on the new owner)
# health.probes        heartbeat pings the fleet health monitor sent
# health.misses        probe deadlines missed or transport deaths
#                        reported (each feeds the per-member machine)
# health.suspects      up -> suspect transitions (first miss)
# health.deaths        suspect/up -> dead transitions (miss ladder,
#                        transport storm, or supervisor kill report)
# health.recoveries    suspect -> up transitions (a probe answered
#                        again; that member's parked frames replay)
# health.parked        mutating frames parked for a suspect/dead
#                        member's docs (released or failed by the
#                        failover executor)
# health.park_overflow frames refused the park because the
#                        AMTPU_FLEET_PARK_MB byte budget was full
#                        (answered with the retryable envelope)
# health.park_expired  parked frames flushed with the retryable
#                        envelope after AMTPU_FLEET_PARK_S (a wedged
#                        failover must not hold clients hostage)
KNOWN_ROUTER_KEYS = ('requests', 'local', 'split_ops', 'parked',
                     'redirects', 'upstream_errors', 'resyncs',
                     'health.probes', 'health.misses',
                     'health.suspects', 'health.deaths',
                     'health.recoveries', 'health.parked',
                     'health.park_overflow', 'health.park_expired')

# fleet-failover counters (`telemetry.metric('failover.<name>')` call
# sites in router/failover.py, router/supervisor.py, router/gateway.py;
# docs/RESILIENCE.md fleet degradation tiers), pre-seeded into every
# bench_block:
# failovers       dead members the executor finished re-placing
# docs_recovered  docs restored onto survivors from the dead member's
#                   durable store (exactly-once under (actor,seq) dedup)
# docs_lost       docs with nothing durable to restore (their parked
#                   frames answered the terminal ReplicaFailed envelope)
# replayed        parked frames released (or failed) by a failover
# rejoins         supervised respawns that joined the ring as a new
#                   generation member
# respawns        supervisor respawn attempts (capped backoff)
# quarantined     lineages barred from respawn after
#                   AMTPU_FLEET_FLAP_MAX deaths
# retried_reads   read-only frames whose upstream died mid-flight and
#                   were parked for one transparent post-failover retry
KNOWN_FAILOVER_KEYS = ('failovers', 'docs_recovered', 'docs_lost',
                       'replayed', 'rejoins', 'respawns',
                       'quarantined', 'retried_reads')

# live-migration counters (`telemetry.metric('migrate.<name>')` call
# sites in scheduler/gateway.py + router/rebalance.py; migration
# section: docs/OBSERVABILITY.md), pre-seeded into every bench_block:
# out_docs / out_bytes   docs / handoff bytes a source replica saved
#                          into the durable handoff store (migrate_out)
# in_docs / in_bytes     docs / handoff bytes a target replica restored
#                          (migrate_in; retries re-count)
# wrong_replica          ops a replica refused with the typed
#                          WrongReplica envelope (doc migrated away)
# migrations             docs whose move fully committed (ring override
#                          installed)
# failed                 migrations abandoned past the executor deadline
#                          (drain or migrate_in never completed)
# errors                 unexpected migrate_out/migrate_in/scan faults
#                          answered as InternalError
# rebalance_passes       rebalancer scrape->score->plan passes
KNOWN_MIGRATE_KEYS = ('out_docs', 'out_bytes', 'in_docs', 'in_bytes',
                      'wrong_replica', 'migrations', 'failed',
                      'errors', 'rebalance_passes')

# read-path counters (`telemetry.metric('readview.<name>')` call sites
# in readview/snapshot.py, readview/replica.py, sidecar/server.py,
# scheduler/gateway.py; read-path section: docs/SERVING.md, glossary:
# docs/OBSERVABILITY.md), pre-seeded into every bench_block:
# snapshots_served        `snapshot` requests answered (container bytes
#                           + frontier clock)
# snapshot_hits /         frontier-clock cache hits vs container builds
#   snapshot_builds         (an unchanged doc serves cached bytes)
# read_only_refused       mutations a read-only replica answered with
#                           the typed ReadOnly envelope
# replica_bootstrap_docs  docs a read replica restored arena-direct
#                           from its ColdStore before subscribing
# replica_events          fan-out frames the replica consumer drained
# replica_changes         change bytes applied into the replica pool
#                           (live frames, backfill, and resyncs)
# replica_apply_errors    frames whose apply raised (the consumer
#                           survives and forces a catch-up)
# replica_probes          upstream frontier probes the staleness SLO
#                           loop completed
# replica_slo_breaches    docs stale past AMTPU_READ_STALENESS_SLO_S
#                           (each forces a catch-up)
# replica_resyncs         forced get_missing_changes catch-up walks
KNOWN_READVIEW_KEYS = ('snapshots_served', 'snapshot_hits',
                       'snapshot_builds', 'read_only_refused',
                       'replica_bootstrap_docs', 'replica_events',
                       'replica_changes', 'replica_apply_errors',
                       'replica_probes', 'replica_slo_breaches',
                       'replica_resyncs')

# docs per gateway flush are effectively powers of two: exact log2 bounds
BATCH_OCCUPANCY_BUCKETS = tuple(float(2 ** i) for i in range(13))

BATCH_OCCUPANCY = registry.histogram(
    'amtpu_batch_occupancy',
    'Documents coalesced into one gateway batch flush (docs/SERVING.md; '
    'median > 4 is the serve-check gate on concurrent traffic)',
    buckets=BATCH_OCCUPANCY_BUCKETS)

# queue wait in MILLISECONDS: 0.001ms .. ~67s, log2
QUEUE_WAIT_BUCKETS = tuple(1e-3 * 2 ** i for i in range(27))

QUEUE_WAIT = registry.histogram(
    'amtpu_queue_wait_ms',
    'Milliseconds a mutating request waited in the gateway queue '
    'between arrival and the start of its flush',
    buckets=QUEUE_WAIT_BUCKETS)

# change->fanout latency shares the queue-wait bucket layout (ms, log2)
FANOUT_LATENCY = registry.histogram(
    'amtpu_fanout_latency_ms',
    'Milliseconds from a mutating request\'s gateway admission to a '
    'subscriber fan-out frame write for its doc (docs/SERVING.md '
    'fan-out section; bounded by the flush window + flush execution)',
    buckets=QUEUE_WAIT_BUCKETS)

# escalation tier widths are powers of two: exact log2 bucket bounds
ESCALATION_TIER_BUCKETS = tuple(float(2 ** i) for i in range(4, 15))

# tier histogram: one observation per escalated register GROUP at the
# tier width that resolved it -- the distribution of live-writer
# antichain widths the ladder actually served
ESCALATION_TIER = registry.histogram(
    'amtpu_escalation_tier_width',
    'Escalation-ladder tier width (W) observed per escalated register '
    'group', buckets=ESCALATION_TIER_BUCKETS)


# ---------------------------------------------------------------------------
# always-on flat metrics (trace.metric compat; one dict update per batch)
# ---------------------------------------------------------------------------

_flat_lock = threading.Lock()
_flat = {}


def metric(name, n=1):
    """Unconditionally accumulates `n` into the always-on counter."""
    with _flat_lock:
        _flat[name] = _flat.get(name, 0.0) + n


# healthz's `degraded` flag must mean "degrading RECENTLY", not "ever
# degraded since process start" -- a long-lived server that quarantined
# one poison doc at t0 must not look drain-worthy forever.  Resilience
# events stamp this; healthz compares against the window.
_last_degraded_ts = 0.0


def note_degraded():
    """One quarantine/degrade event happened now (called by
    automerge_tpu.resilience alongside its counters)."""
    global _last_degraded_ts
    _last_degraded_ts = time.time()


def _degraded_window_s():
    return env_float('AMTPU_DEGRADED_WINDOW_S', 300.0)


def metrics_reset():
    with _flat_lock:
        _flat.clear()


# healthz payload extensions: long-lived subsystems (the serve gateway's
# scheduler) register a section provider so BOTH healthz surfaces -- the
# in-band `healthz` command and the HTTP /healthz listener -- report
# their state without either transport knowing the subsystem exists.
_healthz_sections = {}


def register_healthz_section(name, provider):
    """Adds `provider()` (returning a JSON-safe dict) under `name` in
    every healthz payload; re-registering a name replaces it, None
    removes it."""
    if provider is None:
        _healthz_sections.pop(name, None)
    else:
        _healthz_sections[name] = provider


def metrics_snapshot():
    """{name: value} of the always-on counters since metrics_reset()."""
    with _flat_lock:
        return dict(_flat)


# ---------------------------------------------------------------------------
# batch + device helpers (the per-layer call sites)
# ---------------------------------------------------------------------------

def observe_batch(pool, seconds, docs=0, ops=0):
    """One apply-batch pass completed: latency histogram + counters.
    `pool` names the entry point ('engine' | 'native' | 'sharded'), so
    whole-batch and per-shard latencies stay separate series."""
    BATCHES.labels(pool).inc()
    BATCH_LATENCY.labels(pool).observe(seconds)
    if docs:
        DOCS.inc(docs)
    if ops:
        OPS.inc(ops)
    # flight-recorder commit event (begin/rollback stamp in native/):
    # one ring append per completed batch, any entry point
    recorder.record('batch.commit', n=docs, detail=pool)


def devtime_on():
    """AMTPU_DEVTIME=1: synchronous per-dispatch device timing (checked
    per call, not latched -- bench.py flips it for one dedicated pass)."""
    return env_bool('AMTPU_DEVTIME', False)


def observe_device_dispatch(seconds, n=1):
    """One synchronous (block_until_ready) kernel dispatch measured:
    lands in the flat map under the names bench.py already reads."""
    metric('device.dispatch_sync_s', seconds)
    metric('device.dispatches', n)


# ---------------------------------------------------------------------------
# exposition
# ---------------------------------------------------------------------------

def _render_derived(out):
    """Families derived at scrape time from the span occupancy table and
    the flat map -- keeps the hot paths at one dict update while the
    scrape surface stays fully structured."""
    from .metrics import _labels_text

    phases = phase_snapshot()
    out.append('# HELP amtpu_phase_seconds_total Per-phase host occupancy '
               'seconds (sums across shard threads; exceeds wall time '
               'when shards overlap); only populated while tracing is '
               'enabled')
    out.append('# TYPE amtpu_phase_seconds_total counter')
    for name in sorted(phases):
        out.append('amtpu_phase_seconds_total%s %s' % (
            _labels_text(('phase',), (name,)),
            format_value(float(phases[name]['s']))))
    out.append('# HELP amtpu_phase_calls_total Per-phase call counts '
               '(see amtpu_phase_seconds_total)')
    out.append('# TYPE amtpu_phase_calls_total counter')
    for name in sorted(phases):
        out.append('amtpu_phase_calls_total%s %s' % (
            _labels_text(('phase',), (name,)),
            format_value(phases[name]['n'])))

    flat = metrics_snapshot()
    fallbacks = {r: 0.0 for r in KNOWN_FALLBACK_REASONS}
    rest = {}
    for k, v in flat.items():
        if k.startswith('fallback.'):
            fallbacks[k.split('.', 1)[1]] = v
        elif k not in ('device.dispatch_sync_s', 'device.dispatches'):
            rest[k] = v
    out.append('# HELP amtpu_fallback_total Oracle-fallback / degradation '
               'events by reason (always on; nonzero means a batch left '
               'the fast path)')
    out.append('# TYPE amtpu_fallback_total counter')
    for reason in sorted(fallbacks):
        out.append('amtpu_fallback_total%s %s' % (
            _labels_text(('reason',), (reason,)),
            format_value(fallbacks[reason])))
    out.append('# HELP amtpu_device_seconds_total Measured synchronous '
               'device time (block_until_ready; populated under '
               'AMTPU_DEVTIME=1)')
    out.append('# TYPE amtpu_device_seconds_total counter')
    out.append('amtpu_device_seconds_total %s'
               % format_value(float(flat.get('device.dispatch_sync_s',
                                             0.0))))
    out.append('# HELP amtpu_device_dispatches_total Synchronously '
               'measured kernel dispatches (AMTPU_DEVTIME=1)')
    out.append('# TYPE amtpu_device_dispatches_total counter')
    out.append('amtpu_device_dispatches_total %s'
               % format_value(float(flat.get('device.dispatches', 0.0))))
    out.append('# HELP amtpu_runtime_counter Remaining always-on flat '
               'counters, exported verbatim by name')
    out.append('# TYPE amtpu_runtime_counter gauge')
    for k in sorted(rest):
        out.append('amtpu_runtime_counter%s %s' % (
            _labels_text(('name',), (k,)), format_value(float(rest[k]))))

    out.append('# HELP amtpu_telemetry_enabled Whether span tracing is '
               'currently enabled (1) or idle (0)')
    out.append('# TYPE amtpu_telemetry_enabled gauge')
    out.append('amtpu_telemetry_enabled %d' % (1 if enabled() else 0))
    out.append('# HELP amtpu_up Process liveness (constant 1 while the '
               'exporter answers)')
    out.append('# TYPE amtpu_up gauge')
    out.append('amtpu_up 1')


def render_prometheus():
    """Full Prometheus text exposition (format 0.0.4) for this process."""
    out = []
    for fam in registry.families():
        fam.render(out)
    _render_derived(out)
    return '\n'.join(out) + '\n'


def healthz():
    """Liveness payload for /healthz and the in-band `healthz` command.
    Batch counts report per pool label (summing them would double-count
    a sharded batch against its per-shard sub-batches).  The resilience
    block surfaces degraded/quarantine state (docs/RESILIENCE.md):
    `degraded` is WINDOWED -- true only when a quarantine/degrade event
    happened within the last AMTPU_DEGRADED_WINDOW_S seconds (default
    300) -- so one poison doc at t0 doesn't mark a long-lived server
    drain-worthy forever; the cumulative counters stay in `resilience`.
    `restarts` is the supervising client's respawn count (exported into
    this process via AMTPU_SIDECAR_RESTARTS on each respawn)."""
    flat = metrics_snapshot()
    res = {k: 0.0 for k in KNOWN_RESILIENCE_KEYS}
    res.update({k.split('.', 1)[1]: v for k, v in flat.items()
                if k.startswith('resilience.')})
    restarts = env_int('AMTPU_SIDECAR_RESTARTS', 0)
    degraded_age = time.time() - _last_degraded_ts if _last_degraded_ts \
        else None
    extra = {}
    for name, provider in list(_healthz_sections.items()):
        try:
            extra[name] = provider()
        except Exception as e:
            # a broken section provider degrades ITS section, never the
            # liveness answer itself
            extra[name] = {'error': '%s: %s' % (type(e).__name__, e)}
    return dict(extra, **{
        'ok': True, 'uptime_s': round(uptime_s(), 3),
            'replica_id': replica_id(),
            'telemetry_enabled': enabled(),
            'batches': BATCHES.snapshot() or {},
            'restarts': restarts,
            'degraded': (degraded_age is not None
                         and degraded_age < _degraded_window_s()),
            'last_degraded_age_s': (None if degraded_age is None
                                    else round(degraded_age, 3)),
            'resilience': res,
            # the SLO surface (docs/OBSERVABILITY.md): rolling
            # per-class p50/p99 + multi-window burn rates, and the
            # flight recorder's ring state -- process-wide, so both
            # healthz transports carry them without registration
            'slo': attribution.slo_section(),
            'recorder': recorder.RECORDER.healthz_section()})


def bench_block():
    """The per-BENCH-line embed: fallback rates, device seconds, batch
    latency summaries, and (when tracing) the phase occupancy table."""
    flat = metrics_snapshot()
    fallbacks = {r: 0.0 for r in KNOWN_FALLBACK_REASONS}
    fallbacks.update({k.split('.', 1)[1]: round(v, 6)
                      for k, v in flat.items()
                      if k.startswith('fallback.')})
    collect = {r: 0.0 for r in KNOWN_COLLECT_KEYS}
    collect.update({k.split('.', 1)[1]: round(v, 6)
                    for k, v in flat.items()
                    if k.startswith('collect.')})
    resilience = {r: 0.0 for r in KNOWN_RESILIENCE_KEYS}
    resilience.update({k.split('.', 1)[1]: round(v, 6)
                       for k, v in flat.items()
                       if k.startswith('resilience.')})
    scheduler = {r: 0.0 for r in KNOWN_SCHEDULER_KEYS}
    scheduler.update({k.split('.', 1)[1]: round(v, 6)
                      for k, v in flat.items()
                      if k.startswith('scheduler.')})
    resident = {r: 0.0 for r in KNOWN_RESIDENT_BATCH_KEYS}
    resident.update({k.split('.', 1)[1]: round(v, 6)
                     for k, v in flat.items()
                     if k.startswith('resident.')})
    pipeline = {r: 0.0 for r in KNOWN_PIPELINE_KEYS}
    pipeline.update({k.split('.', 1)[1]: round(v, 6)
                     for k, v in flat.items()
                     if k.startswith('pipeline.')})
    mesh = {r: 0.0 for r in KNOWN_MESH_KEYS}
    mesh.update({k.split('.', 1)[1]: round(v, 6)
                 for k, v in flat.items()
                 if k.startswith('mesh.')})
    fanout = {r: 0.0 for r in KNOWN_FANOUT_KEYS}
    fanout.update({k.split('sync.fanout.', 1)[1]: round(v, 6)
                   for k, v in flat.items()
                   if k.startswith('sync.fanout.')})
    fanout['latency_ms'] = FANOUT_LATENCY.summary() or {}
    egress = {r: 0.0 for r in KNOWN_EGRESS_KEYS}
    egress.update({k.split('.', 1)[1]: round(v, 6)
                   for k, v in flat.items()
                   if k.startswith('egress.')})
    storage = {r: 0.0 for r in KNOWN_STORAGE_KEYS}
    storage.update({k.split('.', 1)[1]: round(v, 6)
                    for k, v in flat.items()
                    if k.startswith('storage.')})
    rec = {r: 0.0 for r in KNOWN_RECORDER_KEYS}
    rec.update({k.split('.', 1)[1]: round(v, 6)
                for k, v in flat.items()
                if k.startswith('recorder.')})
    slo = {r: 0.0 for r in KNOWN_SLO_KEYS}
    slo.update({k.split('.', 1)[1]: round(v, 6)
                for k, v in flat.items()
                if k.startswith('slo.')})
    cap = {r: 0.0 for r in KNOWN_CAPACITY_KEYS}
    cap.update({k.split('.', 1)[1]: round(v, 6)
                for k, v in flat.items()
                if k.startswith('capacity.')})
    trc = {r: 0.0 for r in KNOWN_TRACE_KEYS}
    trc.update({k.split('.', 1)[1]: round(v, 6)
                for k, v in flat.items()
                if k.startswith('trace.')})
    fleet = {r: 0.0 for r in KNOWN_FLEET_KEYS}
    fleet.update({k.split('.', 1)[1]: round(v, 6)
                  for k, v in flat.items()
                  if k.startswith('fleet.')})
    router = {r: 0.0 for r in KNOWN_ROUTER_KEYS}
    router.update({k.split('.', 1)[1]: round(v, 6)
                   for k, v in flat.items()
                   if k.startswith('router.')})
    migrate = {r: 0.0 for r in KNOWN_MIGRATE_KEYS}
    migrate.update({k.split('.', 1)[1]: round(v, 6)
                    for k, v in flat.items()
                    if k.startswith('migrate.')})
    failover = {r: 0.0 for r in KNOWN_FAILOVER_KEYS}
    failover.update({k.split('.', 1)[1]: round(v, 6)
                     for k, v in flat.items()
                     if k.startswith('failover.')})
    readview = {r: 0.0 for r in KNOWN_READVIEW_KEYS}
    readview.update({k.split('.', 1)[1]: round(v, 6)
                     for k, v in flat.items()
                     if k.startswith('readview.')})
    block = {
        'fallbacks': fallbacks,
        'collect': collect,
        'resilience': resilience,
        'scheduler': scheduler,
        'resident': resident,
        'pipeline': pipeline,
        'mesh': mesh,
        'fanout': fanout,
        'egress': egress,
        'storage': storage,
        'recorder': rec,
        'slo': slo,
        'capacity': cap,
        'trace': trc,
        'fleet': fleet,
        'router': router,
        'migrate': migrate,
        'failover': failover,
        'readview': readview,
        'device_s': round(flat.get('device.dispatch_sync_s', 0.0), 4),
        'device_dispatches': int(flat.get('device.dispatches', 0)),
        'batch_latency': BATCH_LATENCY.snapshot() or {},
        'ops_total': OPS.value,
        'docs_total': DOCS.value,
    }
    if enabled():
        block['phases'] = {k: {'s': round(v['s'], 4), 'n': v['n']}
                           for k, v in phase_snapshot().items()}
    return block


def collect_share(block):
    """(share, collect_s, basis_s) of `device.collect` against the
    summed native batch time, read from one bench_block-shaped dict.
    The ONE definition both bench.py's `collect_share` artifact field
    and the perf-smoke gate divide by -- if the latency-block shape or
    the native-vs-sharded fallback rule changes, it changes for both."""
    lat = block.get('batch_latency') or {}
    basis = ((lat.get('native') or {}).get('sum', 0.0)
             or (lat.get('sharded') or {}).get('sum', 0.0)
             or (lat.get('mesh') or {}).get('sum', 0.0))
    coll = ((block.get('phases') or {}).get('device.collect')
            or {}).get('s', 0.0)
    return (coll / basis if basis else 0.0), coll, basis


def reset_all():
    """Test/bench isolation: zero the registry, the flat map, and the
    phase occupancy table (enable state and exporter are untouched)."""
    registry.reset()
    metrics_reset()
    phase_reset()


# imported LAST: these modules resolve names from this module (registry,
# buckets, metric) lazily, so they must load after those exist
from . import attribution, capacity, recorder  # noqa: E402,F401

