"""Per-doc resource accounting + capacity observability (ISSUE 15,
docs/OBSERVABILITY.md capacity section).

The stack's counters are pool-wide: ``amtpu_history_bytes`` is one
number, eviction is blind LRU, and nothing can answer "which 10 docs
account for half the arena / the fan-out amplification / the egress
backlog".  This module is the always-on cost model that closes the gap
-- the same "price it before you shard it" discipline the PR-12
attribution layer applied to latency, applied to memory and bandwidth.
ROADMAP #1's router reads the same surface as its migration inventory
(``doc_id -> cost vector``).

Three pieces:

  * **cost vectors** -- every doc's
    ``{arena_bytes, ops, disk_bytes, subscribers, fanned_bytes,
    egress_bytes}``.  The native tier (arena bytes, op records, folded
    ops, resident-clock rows) comes from ONE C call for the whole pool
    (``amtpu_doc_stats``: per-DocState counters maintained at the
    exact sites that mutate them; totals reconcile bit-exactly with
    ``amtpu_history_bytes`` / ``amtpu_op_count``).  The Python tiers
    feed in at their natural choke points: ColdStore per-doc on-disk
    bytes, fan-out staging (`note_fanout`: encoded vs fanned bytes +
    live subscriber counts), egress staging (`note_egress`: per-doc
    share of queued bytes at stage time).
  * **hot-doc table** -- the streaming tiers (fanned/egress bytes) are
    tracked in :class:`SpaceSaver` top-K sketches, so 1M docs cost
    O(K) memory; the snapshot tiers (arena/disk) rank from the flat
    stats arrays at refresh time.  Served at the healthz ``capacity``
    section, the HTTP ``/debug/docs`` endpoint, and the
    ``amtpu_doc_cost_bytes{tier}`` gauges; rendered live by
    `tools/amtpu_top.py`.
  * **headroom estimator** -- process RSS + device buffer bytes +
    arena + WAL + egress backlog vs ``AMTPU_MEM_BUDGET_MB``, with a
    burn-rate-style pressure signal (`amtpu_mem_pressure`, exhaustion
    ETA) that drives `storage.evict` PROACTIVELY (evict before OOM,
    not just past a doc-count cap; docs/STORAGE.md eviction-pressure
    section).

Thread model: `note_fanout` / `note_egress` are hot-path appends
guarded by one tracker lock (called per doc per flush, never per op);
`refresh` is throttled to ``AMTPU_CAPACITY_REFRESH_S`` so healthz
scrapes and per-flush pressure checks share one native stats pass.
The telemetry overhead gate (`tools/telemetry_check.py`) no-ops the
module-level `note_*` seams in its raw arm, so the always-on cost is
priced against the same 6% bar as the recorder.
"""

import heapq
import os
import sys
import threading
import time

from ..utils.common import env_float, env_int

from . import metric, metrics_snapshot, registry

#: cost-vector field names, in surface order (docs/OBSERVABILITY.md)
COST_FIELDS = ('arena_bytes', 'ops', 'disk_bytes', 'subscribers',
               'fanned_bytes', 'egress_bytes', 'clock_bytes')

DOC_COST = registry.gauge(
    'amtpu_doc_cost_bytes',
    'Pool-wide per-tier doc cost totals (ISSUE 15; docs/OBSERVABILITY.md '
    'capacity section): arena = retained raw change bytes, disk = '
    'ColdStore on-disk bytes, fanned = cumulative fan-out wire bytes '
    'attributed per doc, egress = cumulative per-doc bytes staged on '
    'bounded egress queues, clock = causal-clock state (sparse '
    'all_deps pairs + densified fold table + resident clock rows; '
    'ISSUE 17 -- clock folding shrinks this tier)', ('tier',))
MEM_USED = registry.gauge(
    'amtpu_mem_used_bytes',
    'Headroom estimator components (ISSUE 15): rss (process resident '
    'set), arena (C++ retained history), device (live jax buffer '
    'bytes), wal (sidecar checkpoint WAL), egress (queued egress '
    'backlog), cold_disk (ColdStore on-disk bytes; informational, not '
    'counted against the memory budget)', ('component',))
MEM_BUDGET = registry.gauge(
    'amtpu_mem_budget_bytes',
    'Configured memory budget (AMTPU_MEM_BUDGET_MB; 0 = unbudgeted)')
MEM_PRESSURE = registry.gauge(
    'amtpu_mem_pressure',
    'used/budget fraction of the headroom estimator (0 when no budget '
    'is configured); past AMTPU_MEM_PRESSURE_EVICT the gateway evicts '
    'cold docs proactively')


def mem_budget_bytes():
    """``AMTPU_MEM_BUDGET_MB`` in bytes (0 = unbudgeted)."""
    return max(0, env_int('AMTPU_MEM_BUDGET_MB', 0)) * (1 << 20)


def pressure_evict_frac():
    """Pressure fraction past which the gateway evicts proactively
    (``AMTPU_MEM_PRESSURE_EVICT``; <= 0 disables pressure eviction)."""
    return env_float('AMTPU_MEM_PRESSURE_EVICT', 0.85)


def pressure_evict_cooldown_s():
    """Min seconds between pressure-eviction passes
    (``AMTPU_PRESSURE_EVICT_COOLDOWN_S``).  RSS-based pressure may
    never clear even after evictions free C++ heap (glibc rarely
    returns arena pages to the OS), so without a cooldown a stuck
    signal would evict the LRU tail on EVERY flush and thrash
    evict/reload forever; the cooldown bounds that to one bounded pass
    per window while the signal stays high."""
    return env_float('AMTPU_PRESSURE_EVICT_COOLDOWN_S', 30.0)


def capacity_topk():
    """Hot-doc table depth (``AMTPU_CAPACITY_TOPK``)."""
    return max(1, env_int('AMTPU_CAPACITY_TOPK', 10))


def _refresh_min_s():
    return env_float('AMTPU_CAPACITY_REFRESH_S', 1.0)


def _sketch_cap():
    return max(8, env_int('AMTPU_CAPACITY_SKETCH', 128))


class SpaceSaver(object):
    """Weighted space-saving top-K sketch (Metwally et al.): tracks the
    heaviest keys of an unbounded stream in O(K) memory.  Estimates
    OVERCOUNT only -- ``est - err <= true <= est`` -- and any key whose
    true weight exceeds total/K is guaranteed present, which is exactly
    the hot-doc contract (a doc hot enough to matter cannot hide).

    `offer` is O(log K) amortized via a lazy min-heap (stale entries are
    skipped at eviction and the heap compacts past 8K entries)."""

    __slots__ = ('k', 'counts', 'errs', '_heap', 'total')

    def __init__(self, k):
        self.k = max(1, int(k))
        self.counts = {}         # key -> estimated weight
        self.errs = {}           # key -> overestimation bound
        self._heap = []          # lazy (est, key) min-heap
        self.total = 0           # stream weight seen (exact)

    def offer(self, key, inc=1):
        if inc <= 0:
            return
        self.total += inc
        counts = self.counts
        if key in counts:
            counts[key] += inc
            heapq.heappush(self._heap, (counts[key], key))
        elif len(counts) < self.k:
            counts[key] = inc
            self.errs[key] = 0
            heapq.heappush(self._heap, (inc, key))
        else:
            # evict the current minimum (skipping stale heap entries)
            while True:
                est, mk = self._heap[0]
                if counts.get(mk) == est:
                    break
                heapq.heappop(self._heap)
            heapq.heappop(self._heap)
            del counts[mk]
            del self.errs[mk]
            counts[key] = est + inc
            self.errs[key] = est
            heapq.heappush(self._heap, (counts[key], key))
        if len(self._heap) > 8 * self.k:
            self._heap = [(v, k2) for k2, v in counts.items()]
            heapq.heapify(self._heap)

    def top(self, n=None):
        """[(key, est, err)] heaviest-first (at most `n`)."""
        items = sorted(self.counts.items(), key=lambda kv: -kv[1])
        if n is not None:
            items = items[:n]
        return [(k, v, self.errs.get(k, 0)) for k, v in items]


class HeadroomEstimator(object):
    """Memory headroom + burn-rate signal against AMTPU_MEM_BUDGET_MB.

    `sample(components)` folds one measurement: `used` is process RSS
    when readable (RSS is the number the OOM killer reads; every other
    component is a slice of it), else the component sum.  The burn rate
    is an EMA of d(used)/dt, so `exhaustion_s` -- seconds until the
    budget is breached at the current burn -- stays stable across
    scrape jitter.  Constructor overrides (`budget_bytes`, `used_fn`)
    exist for the unit lanes and `tools/capacity_check.py`; production
    reads the env."""

    def __init__(self, budget_bytes=None, used_fn=None, clock=None):
        self._budget = budget_bytes
        self._used_fn = used_fn
        self._clock = clock or time.monotonic
        self._last = None         # (t, used)
        self._rate = None         # EMA bytes/s (positive = growing)

    @property
    def budget(self):
        return mem_budget_bytes() if self._budget is None \
            else self._budget

    def sample(self, components):
        """Folds one measurement; returns the headroom dict the
        capacity section embeds."""
        if self._used_fn is not None:
            used = int(self._used_fn())
        else:
            used = int(components.get('rss') or 0)
            if used <= 0:
                used = int(sum(v for k, v in components.items()
                               if k != 'cold_disk'))
        t = self._clock()
        if self._last is not None and t > self._last[0]:
            inst = (used - self._last[1]) / (t - self._last[0])
            self._rate = inst if self._rate is None \
                else 0.7 * self._rate + 0.3 * inst
        self._last = (t, used)
        budget = self.budget
        pressure = (used / budget) if budget > 0 else 0.0
        out = {'used_bytes': used, 'budget_bytes': budget,
               'pressure': round(pressure, 4),
               'pressure_evict': pressure_evict_frac(),
               'burn_bytes_s': round(self._rate, 1)
               if self._rate is not None else None,
               'exhaustion_s': None}
        if budget > 0 and self._rate is not None and self._rate > 0 \
                and used < budget:
            out['exhaustion_s'] = round((budget - used) / self._rate, 1)
        return out

    def evict_due(self, pressure):
        """True when the pressure signal says the gateway should evict
        cold docs BEFORE the doc-count cap forces it."""
        frac = pressure_evict_frac()
        return frac > 0 and self.budget > 0 and pressure >= frac


def _read_rss_bytes():
    """Resident set size from /proc/self/statm (0 where unreadable)."""
    try:
        with open('/proc/self/statm', 'rb') as f:
            pages = int(f.read().split()[1])
        return pages * (os.sysconf('SC_PAGESIZE') or 4096)
    except (OSError, ValueError, IndexError):
        return 0


def _device_buffer_bytes():
    """Live jax device-buffer bytes.  Never IMPORTS jax (a scrape must
    not trigger backend init); 0 when jax is idle or the walk fails."""
    jax = sys.modules.get('jax')
    if jax is None:
        return 0
    try:
        return int(sum(getattr(a, 'nbytes', 0)
                       for a in jax.live_arrays()))
    except Exception:
        return 0


class CapacityTracker(object):
    """Process-wide per-doc cost registry one serving process owns.

    The gateway attaches its pool / storage tier / egress stats at
    start (`attach`); the fan-out and egress choke points feed the
    streaming sketches through the module-level `note_fanout` /
    `note_egress` seams; everything else (healthz section,
    /debug/docs, gauges, the pressure signal) reads through
    `refresh`, which is throttled so scrapes and per-flush pressure
    checks share one native stats pass."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pool = None          # guarded-by: self._lock
        self._pool_lock = None     # guarded-by: self._lock
        self._storage = None       # guarded-by: self._lock
        self._egress_fn = None     # guarded-by: self._lock
        self._fanned = SpaceSaver(_sketch_cap())   # guarded-by: self._lock
        self._egressed = SpaceSaver(_sketch_cap())  # guarded-by: self._lock
        self._subs = {}            # guarded-by: self._lock
        self._encoded = {}         # guarded-by: self._lock
        self.estimator = HeadroomEstimator()
        self._last_refresh = 0.0   # guarded-by: self._lock
        self._snap = None          # guarded-by: self._lock
        self._native = None        # guarded-by: self._lock
        self._last_pressure_pass = None   # guarded-by: self._lock

    # -- wiring ---------------------------------------------------------

    def attach(self, pool=None, pool_lock=None, storage_tier=None,
               egress_fn=None):
        """Wires the serving process's tiers in.  `pool_lock` is the
        gateway's pool serialization (an RLock): refresh acquires it
        around the native stats pass, so a healthz scrape can never
        race the dispatcher's C++ mutations (the dispatcher's own
        per-flush pressure check re-enters it harmlessly)."""
        with self._lock:
            if pool is not None:
                self._pool = pool
            if pool_lock is not None:
                self._pool_lock = pool_lock
            if storage_tier is not None:
                self._storage = storage_tier
            if egress_fn is not None:
                self._egress_fn = egress_fn

    def detach(self):
        with self._lock:
            self._pool = self._pool_lock = self._storage = None
            self._egress_fn = None

    def reset(self):
        """Test isolation: fresh sketches + snapshot (wiring kept)."""
        with self._lock:
            self._fanned = SpaceSaver(_sketch_cap())
            self._egressed = SpaceSaver(_sketch_cap())
            self._subs = {}
            self._encoded = {}
            self._snap = None
            self._native = None
            self._last_refresh = 0.0
            self.estimator = HeadroomEstimator()

    # -- streaming feeds (hot path: per doc per flush) ------------------

    def note_fanout(self, doc_id, encoded_bytes, fanned_bytes,
                    subscribers):
        with self._lock:
            if fanned_bytes > 0:
                self._fanned.offer(doc_id, fanned_bytes)
            if encoded_bytes > 0:
                # cumulative encoded-once bytes: fanned / encoded is
                # the doc's fan-out amplification on the hot-doc table
                self._encoded[doc_id] = \
                    self._encoded.get(doc_id, 0) + encoded_bytes
            self._subs[doc_id] = int(subscribers)
            if len(self._subs) > 4 * _sketch_cap() \
                    or len(self._encoded) > 4 * _sketch_cap():
                # bound the gauge maps like the sketches: keep ONLY the
                # docs the sketch still tracks (the hot set), so a
                # rebuild shrinks to <= K entries and the trigger can
                # never hold permanently -- subscriber/encoded gauges
                # for cold-tail docs are deliberately dropped (every
                # surface only renders the hot set anyway)
                keep = set(self._fanned.counts)
                self._subs = {d: n for d, n in self._subs.items()
                              if d in keep}
                self._encoded = {d: n for d, n in self._encoded.items()
                                 if d in keep}

    def note_egress(self, doc_id, n_bytes):
        with self._lock:
            if n_bytes > 0:
                self._egressed.offer(doc_id, n_bytes)

    # -- the refreshed snapshot -----------------------------------------

    def refresh(self, force=False):
        """Recomputes the native + storage tiers (throttled) and
        returns the capacity snapshot dict; streaming-tier reads are
        always live.  Never raises: a broken pool degrades its tier to
        an 'error' entry, not the scrape."""
        now = time.monotonic()
        with self._lock:
            if not force and self._snap is not None \
                    and now - self._last_refresh < _refresh_min_s():
                return self._snap
            pool, pool_lock, storage, egress_fn = \
                self._pool, self._pool_lock, self._storage, \
                self._egress_fn
        snap = {'ts': round(time.time(), 3)}
        arena_total = ops_total = clock_total = 0
        arena_top, clock_top = [], []
        native = None
        clock_by_doc = {}
        if pool is not None:
            try:
                if pool_lock is not None:
                    with pool_lock:
                        ids, stats = pool.doc_stats()
                else:
                    ids, stats = pool.doc_stats()
                native = (ids, stats)
                if len(ids):
                    arena_total = int(stats[:, 0].sum())
                    ops_total = int(stats[:, 1].sum())
                    k = capacity_topk()
                    order = stats[:, 0].argsort()[::-1][:k]
                    arena_top = [(ids[i], int(stats[i, 0]),
                                  int(stats[i, 1]))
                                 for i in order if stats[i, 0] > 0]
                    # clock tier (ISSUE 17): sparse all_deps pairs
                    # (8 B each) + densified per-doc fold table +
                    # pool-resident clock rows converted to bytes --
                    # the per-doc surface clock folding shrinks
                    if stats.shape[1] >= 8:
                        row_b = 0
                        try:
                            row_b = int(pool.resclk_row_bytes())
                        except Exception:
                            pass
                        clk = (stats[:, 6] * 8 + stats[:, 7] +
                               stats[:, 5] * row_b)
                        clock_total = int(clk.sum())
                        corder = clk.argsort()[::-1][:k]
                        clock_top = [(ids[i], int(clk[i]),
                                      int(stats[i, 6]))
                                     for i in corder if clk[i] > 0]
                        clock_by_doc = {d: int(v)
                                        for d, v in zip(ids, clk)}
                snap['docs_resident'] = len(ids)
            except Exception as e:
                snap['native_error'] = '%s: %s' % (type(e).__name__, e)
        disk_total, disk_top, cold_docs = 0, [], 0
        if storage is not None:
            try:
                store = storage.store
                disk_total = store.bytes
                cold_docs = len(store)
                k = capacity_topk()
                disk_top = heapq.nlargest(
                    k, ((store.disk_bytes(d), d)
                        for d in store.doc_ids()))
                disk_top = [(d, n) for n, d in disk_top if n > 0]
            except Exception as e:
                snap['storage_error'] = '%s: %s' % (type(e).__name__, e)
        egress_q = 0
        if egress_fn is not None:
            try:
                egress_q = int((egress_fn() or {}).get('queued_bytes', 0))
            except Exception:
                pass
        flat = metrics_snapshot()
        wal = int(flat.get('sidecar.client.wal_bytes', 0))
        components = {'rss': _read_rss_bytes(), 'arena': arena_total,
                      'device': _device_buffer_bytes(), 'wal': wal,
                      'egress': egress_q, 'cold_disk': disk_total}
        with self._lock:
            fanned_top = self._fanned.top(capacity_topk())
            egress_top = self._egressed.top(capacity_topk())
            fanned_total = self._fanned.total
            egress_total = self._egressed.total
            subs = dict(self._subs)
            encoded = dict(self._encoded)
            headroom = self.estimator.sample(components)
        snap['totals'] = {'arena_bytes': arena_total, 'ops': ops_total,
                          'disk_bytes': disk_total,
                          'cold_docs': cold_docs,
                          'fanned_bytes': fanned_total,
                          'egress_bytes': egress_total,
                          'clock_bytes': clock_total}
        snap['top'] = {
            'arena': [{'doc': d, 'arena_bytes': b, 'ops': o,
                       'subscribers': subs.get(d, 0)}
                      for d, b, o in arena_top],
            'clock': [{'doc': d, 'clock_bytes': b, 'clk_pairs': p}
                      for d, b, p in clock_top],
            'disk': [{'doc': d, 'disk_bytes': b} for d, b in disk_top],
            'fanned': [{'doc': d, 'fanned_bytes': v, 'err': e,
                        'encoded_bytes': encoded.get(d, 0),
                        'amplification':
                            round(v / encoded[d], 1)
                            if encoded.get(d) else None,
                        'subscribers': subs.get(d, 0)}
                       for d, v, e in fanned_top],
            'egress': [{'doc': d, 'egress_bytes': v, 'err': e}
                       for d, v, e in egress_top],
        }
        snap['components'] = components
        snap['headroom'] = headroom
        if self.estimator.evict_due(headroom['pressure']):
            metric('capacity.pressure_high')
        # gauges: the scrape surface mirrors the snapshot
        DOC_COST.labels('arena').set(arena_total)
        DOC_COST.labels('disk').set(disk_total)
        DOC_COST.labels('fanned').set(fanned_total)
        DOC_COST.labels('egress').set(egress_total)
        DOC_COST.labels('clock').set(clock_total)
        for comp, v in components.items():
            MEM_USED.labels(comp).set(v)
        MEM_BUDGET.set(headroom['budget_bytes'])
        MEM_PRESSURE.set(headroom['pressure'])
        metric('capacity.refreshes')
        with self._lock:
            self._snap = snap
            self._last_refresh = now
            self._native = native
            self._clock_by_doc = clock_by_doc
        return snap

    def pressure(self):
        """Current pressure fraction (refreshing if stale) -- the
        per-flush signal the gateway's proactive eviction keys on."""
        return self.refresh().get('headroom', {}).get('pressure', 0.0)

    def evict_due(self):
        # unbudgeted / disabled deployments (the default) must not pay
        # the native stats pass on the flush critical path at all --
        # the refresh inside pressure() only runs once this gate holds
        if pressure_evict_frac() <= 0 or self.estimator.budget <= 0:
            return False
        # cooldown: a stuck-high signal (RSS rarely drops even after
        # evictions free C++ heap) must not evict the LRU tail on
        # every flush -- one bounded pass per window
        with self._lock:
            last = self._last_pressure_pass
        if last is not None and \
                time.monotonic() - last < pressure_evict_cooldown_s():
            return False
        return self.estimator.evict_due(self.pressure())

    def note_pressure_pass(self):
        """The gateway ran one pressure-eviction pass: start the
        cooldown window (whatever it evicted)."""
        with self._lock:
            self._last_pressure_pass = time.monotonic()

    def cost_vectors(self, doc_ids=None, refresh=True):
        """{doc_key: cost vector} -- ROADMAP #1's migration inventory.
        With `doc_ids` None, covers every resident doc (one native
        stats pass) plus every cold doc the store holds.
        ``refresh=False`` reuses the caller's just-forced snapshot
        (debug_docs) instead of paying a second native pass."""
        if refresh:
            self.refresh(force=True)
        with self._lock:
            native = getattr(self, '_native', None)
            clock_by_doc = getattr(self, '_clock_by_doc', {})
            storage = self._storage
            fanned = dict(self._fanned.counts)
            egressed = dict(self._egressed.counts)
            subs = dict(self._subs)
        out = {}
        if native is not None:
            ids, stats = native
            for i, d in enumerate(ids):
                out[d] = {'arena_bytes': int(stats[i, 0]),
                          'ops': int(stats[i, 1]),
                          'disk_bytes': 0,
                          'subscribers': subs.get(d, 0),
                          'fanned_bytes': int(fanned.get(d, 0)),
                          'egress_bytes': int(egressed.get(d, 0)),
                          'clock_bytes': clock_by_doc.get(d, 0)}
        if storage is not None:
            try:
                for d in storage.store.doc_ids():
                    v = out.setdefault(
                        d, {'arena_bytes': 0, 'ops': 0, 'disk_bytes': 0,
                            'subscribers': subs.get(d, 0),
                            'fanned_bytes': int(fanned.get(d, 0)),
                            'egress_bytes': int(egressed.get(d, 0)),
                            'clock_bytes': 0})
                    v['disk_bytes'] = storage.store.disk_bytes(d)
            except Exception:
                pass
        if doc_ids is not None:
            out = {d: out[d] for d in doc_ids if d in out}
        return out

    # -- surfaces -------------------------------------------------------

    def capacity_section(self):
        """The healthz ``capacity`` section (registered by the
        gateway)."""
        snap = dict(self.refresh())
        snap.pop('components', None)   # /debug/docs carries the detail
        return snap

    def debug_docs(self, k=None):
        """The ``/debug/docs`` body: full snapshot + cost-vector rows
        for the hot docs of every tier.  THROTTLED like healthz
        (`AMTPU_CAPACITY_REFRESH_S`): a polling client must not force
        a full native stats pass under the pool lock per request."""
        snap = self.refresh()
        hot = []
        for rows in snap.get('top', {}).values():
            hot.extend(r['doc'] for r in rows)
        vecs = self.cost_vectors(refresh=False)
        seen, docs = set(), []
        for d in hot:
            if d in seen or d not in vecs:
                continue
            seen.add(d)
            docs.append(dict(vecs[d], doc=d))
        if k is not None:
            docs = docs[:int(k)]
        return dict(snap, hot_docs=docs, cost_fields=list(COST_FIELDS))


TRACKER = CapacityTracker()


def note_fanout(doc_id, encoded_bytes, fanned_bytes, subscribers):
    """Module-level hot-path seam (patchable by the overhead gate):
    one dirty doc's fan-out staging this flush."""
    TRACKER.note_fanout(doc_id, encoded_bytes, fanned_bytes, subscribers)


def note_egress(doc_id, n_bytes):
    """Module-level hot-path seam: one doc's frame bytes staged on a
    bounded egress queue."""
    TRACKER.note_egress(doc_id, n_bytes)


def attach(**kw):
    TRACKER.attach(**kw)


def detach():
    TRACKER.detach()


def capacity_section():
    return TRACKER.capacity_section()


def debug_docs(k=None):
    return TRACKER.debug_docs(k=k)
