"""Always-on flight recorder: a fixed-size ring of compact structured
events, stamped from the hot paths at one-append cost, dumped as JSONL
when something goes wrong (docs/OBSERVABILITY.md event catalog;
docs/RESILIENCE.md quarantine story).

The stack's counters say HOW OFTEN things happen; when a doc
quarantines or a request lands at p99.9 they cannot say WHAT HAPPENED
in the seconds before.  The recorder closes that gap without span
machinery: every interesting transition (batch begin/commit/rollback,
retry/bisect/quarantine, wave dispatch/collect, eviction/reload,
fan-out flush, shed transitions, injected faults, sidecar respawns)
appends one tuple into a pre-sized ring.  No lock: slot index comes
from an atomic ``itertools.count`` and each slot store is a single
opaque reference write, so concurrent writers can interleave but never
tear a record or block each other -- the CPython-level guarantee the
hot paths need (a torn *ring* would mean a lost event, which the
overwrite semantics already permit).

Dump triggers (each rate-limited per reason, ``force`` overrides):
quarantine and state-suspect batches (`automerge_tpu.resilience`),
sidecar respawn (`sidecar/client.py`), SIGTERM (`sidecar/server.py`),
the ``dump`` sidecar request, and the HTTP ``/debug/recorder`` endpoint
(`telemetry/httpd.py`, which serves the ring in place rather than
writing a file).  Dumps are JSONL files under ``AMTPU_RECORDER_DIR``
(default: a per-process tempdir) named
``amtpu-recorder-<pid>-<reason>-<seq>.jsonl``.

Sizing: ``AMTPU_RECORDER_EVENTS`` slots (default 4096; read once at
import -- the ring is pre-allocated).  At gateway rates the ring spans
the last O(seconds) of activity, exactly the window a post-mortem
needs.
"""

import itertools
import json
import os
import sys
import tempfile
import threading
import time

from ..utils.common import env_float, env_int, env_str

#: the event-name universe (docs/OBSERVABILITY.md has the catalog);
#: informational -- record() does not validate against it (an append
#: must stay one tuple), but tests and the docs lockstep use it
EVENTS = (
    'batch.begin', 'batch.commit', 'batch.rollback',
    'wave.dispatch', 'wave.collect',
    'resilience.retry', 'resilience.bisect', 'resilience.quarantine',
    'resilience.state_suspect',
    'fault.injected',
    'storage.evict', 'storage.reload',
    'fanout.flush',
    'egress.shed', 'egress.resync', 'egress.evict',
    'shed.on', 'shed.off',
    'sidecar.respawn',
    'request.slow',
)


class Recorder(object):
    """One pre-sized event ring.  ``record`` is the hot-path append;
    everything else is cold (dump/snapshot copy the slots)."""

    def __init__(self, size):
        self.size = max(16, int(size))
        # fixed-size slot vector: index = seq % size.  Writers race
        # benignly (an overwritten slot simply loses the older event,
        # which is the ring's contract); no slot ever holds a torn
        # record because the store is one reference assignment.
        self._slots = [None] * self.size
        self._seq = itertools.count()
        self._last_dump = {}      # reason -> monotonic ts (dump-side)
        self._dump_lock = threading.Lock()
        self._dump_n = itertools.count()
        self._dumps_written = 0   # successful dumps (healthz)

    # -- hot path -------------------------------------------------------

    def record(self, event, doc=None, n=0, detail=None, trace=None):
        """Appends one event: (seq, wall-clock ts, name, doc, n,
        detail, trace).  One counter bump + one tuple + one slot store.
        `trace` is the originating request's 32-hex trace id when the
        caller has one (ISSUE 16) -- it makes ring events correlatable
        with the cross-process trace tree at zero extra cost."""
        i = next(self._seq)
        self._slots[i % self.size] = (i, time.time(), event, doc, n,
                                      detail, trace)

    # -- cold surface ---------------------------------------------------

    def snapshot(self):
        """Events currently in the ring, oldest first.  Records racing
        with writers may skew a little at the wrap point; every entry
        returned is internally consistent."""
        slots = list(self._slots)
        out = [s for s in slots if s is not None]
        out.sort(key=lambda s: s[0])
        return out

    def events_json(self):
        """The snapshot as JSON-safe dicts (the /debug/recorder body
        and the per-line dump shape)."""
        return self.tail(float('-inf'))

    def tail(self, since_ts, limit=None):
        """Events at or after wall-clock `since_ts`, newest last -- the
        exemplar attachment window (telemetry/attribution.py).  `limit`
        bounds to the newest N BEFORE any dicts are built, so a hot
        sampler never pays for the whole ring."""
        slots = self.snapshot()
        if limit is not None:
            slots = slots[-int(limit):]
        return [{'seq': s[0], 'ts': round(s[1], 6), 'event': s[2],
                 'doc': s[3], 'n': s[4], 'detail': s[5],
                 'trace': s[6] if len(s) > 6 else None}
                for s in slots if s[1] >= since_ts]

    def dump(self, reason, force=False):
        """Writes the ring as JSONL under ``AMTPU_RECORDER_DIR`` and
        returns ``{'path', 'events', 'reason'}`` -- or None when the
        per-reason rate limit (``AMTPU_RECORDER_MIN_DUMP_S``) says this
        trigger fired too recently (a quarantine storm must not turn
        into a disk-write storm).  Never raises: a full disk degrades
        the DUMP, not the failing operation that triggered it."""
        now = time.monotonic()
        with self._dump_lock:
            last = self._last_dump.get(reason)
            min_s = env_float('AMTPU_RECORDER_MIN_DUMP_S', 5.0)
            if not force and last is not None and now - last < min_s:
                return None
            self._last_dump[reason] = now
            seq = next(self._dump_n)
        events = self.events_json()
        path = None
        try:
            # _dump_dir() may itself raise (uncreatable AMTPU_RECORDER
            # _DIR, read-only FS): it must degrade like a failed write,
            # never propagate into the quarantine/suspect path that
            # triggered the dump
            path = os.path.join(
                _dump_dir(), 'amtpu-recorder-%d-%s-%d.jsonl'
                % (os.getpid(), reason.replace(os.sep, '_'), seq))
            with open(path, 'w') as f:
                f.write(json.dumps({'recorder_dump': reason,
                                    'ts': round(time.time(), 6),
                                    'pid': os.getpid(),
                                    'events': len(events)}) + '\n')
                for e in events:
                    f.write(json.dumps(e, default=str) + '\n')
        except OSError as e:
            metric('recorder.dump_failed')
            print('amtpu recorder: %s dump to %r failed (%s)'
                  % (reason, path, e), file=sys.stderr)
            return None
        metric('recorder.dumps')
        self._dumps_written += 1
        return {'path': path, 'events': len(events), 'reason': reason}

    def healthz_section(self):
        slots = list(self._slots)
        n = sum(1 for s in slots if s is not None)
        newest = max((s[0] for s in slots if s is not None),
                     default=-1)
        return {'size': self.size, 'events': n,
                'last_seq': newest,
                'dumps': self._dumps_written}


def metric(name, v=1):
    """Thin forwarder to the package counter (late-bound: this module
    loads while telemetry/__init__ is still executing, and the static
    telemetry-key checker keys on `metric(...)` call sites)."""
    from . import metric as _m
    _m(name, v)


_dump_dir_cached = None


def _dump_dir():
    """``AMTPU_RECORDER_DIR`` or a per-process tempdir (created lazily:
    a process that never dumps never touches the filesystem)."""
    global _dump_dir_cached
    configured = env_str('AMTPU_RECORDER_DIR', '')
    if configured:
        os.makedirs(configured, exist_ok=True)
        return configured
    if _dump_dir_cached is None:
        _dump_dir_cached = tempfile.mkdtemp(prefix='amtpu-recorder-')
    return _dump_dir_cached


RECORDER = Recorder(env_int('AMTPU_RECORDER_EVENTS', 4096))


def record(event, doc=None, n=0, detail=None, trace=None):
    """Module-level hot-path append (patchable by the overhead gate)."""
    RECORDER.record(event, doc=doc, n=n, detail=detail, trace=trace)


def dump(reason, force=False):
    return RECORDER.dump(reason, force=force)


def snapshot():
    return RECORDER.snapshot()


def events_json():
    return RECORDER.events_json()


def tail(since_ts, limit=None):
    return RECORDER.tail(since_ts, limit=limit)
