"""Fleet aggregation plane: scrape N replicas' observability
endpoints and merge them into ONE coherent view (ISSUE 16;
docs/OBSERVABILITY.md fleet section).

The per-replica SLO surface (telemetry/attribution.py) keeps raw
10-second window slots -- per-class latency bucket counts + totals +
breach counts -- precisely so a fleet can aggregate them CORRECTLY:
slots from different replicas sum element-wise, and the merged
percentiles/burn recompute from the summed counts via the same pure
function (`attribution.section_from_slots`) each replica's own healthz
uses.  Averaging per-replica p99s would be statistically meaningless;
summing slots makes the fleet merge bit-identical to what a single
replica would report had it served all the traffic.

Scraping uses only stdlib HTTP (`/healthz` + `/debug/slo_slots` per
replica, telemetry/httpd.py); a dead replica degrades to an error row,
never the whole fleet view.  `tools/amtpu_fleet.py` is the CLI;
`tools/amtpu_top.py --fleet` renders the same sections live.
"""

import json
import urllib.request

from .attribution import section_from_slots


def metric(name, v=1):
    """Late-bound forwarder to the package counter (mirrors
    telemetry/recorder.py; the static telemetry-key checker keys on
    `metric(...)` call sites)."""
    from . import metric as _m
    _m(name, v)


def _get_json(url, timeout):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def scrape(base_url, timeout=2.0):
    """One replica's observability snapshot: ``/healthz`` plus the raw
    mergeable SLO slots from ``/debug/slo_slots``.  Returns
    ``{'url', 'replica_id', 'uptime_s', 'healthz', 'slots'}`` -- or a
    degraded ``{'url', 'error'}`` row when the replica is unreachable
    (counted in ``fleet.scrape_errors``; the caller keeps aggregating
    the survivors)."""
    url = base_url.rstrip('/')
    try:
        health = _get_json(url + '/healthz', timeout)
        slots = _get_json(url + '/debug/slo_slots', timeout)
        metric('fleet.scrapes')
        return {'url': url,
                'replica_id': slots.get('replica_id')
                or health.get('replica_id') or url,
                'uptime_s': slots.get('uptime_s',
                                      health.get('uptime_s')),
                'healthz': health,
                'slots': slots.get('slots') or {}}
    except Exception as e:
        metric('fleet.scrape_errors')
        return {'url': url,
                'error': '%s: %s' % (type(e).__name__, e)}


def merge_slots(slots_by_replica):
    """Element-wise sum of per-class SLO window slots across replicas:
    ``[{cls: {slot: [bucket_counts, total, breaches]}}, ...]`` -> one
    merged map of the same shape.  Slot keys arrive as JSON strings
    from the wire and ints from in-process snapshots; both normalize
    to int so the cutoff arithmetic in `section_from_slots` holds."""
    merged = {}
    for slots_by_class in slots_by_replica:
        for cls, slots in (slots_by_class or {}).items():
            dst = merged.setdefault(cls, {})
            for slot, entry in slots.items():
                counts, total, breaches = entry[0], entry[1], entry[2]
                key = int(slot)
                cur = dst.get(key)
                if cur is None:
                    dst[key] = [list(counts), int(total),
                                int(breaches)]
                    continue
                if len(counts) > len(cur[0]):
                    cur[0].extend([0] * (len(counts) - len(cur[0])))
                for i, c in enumerate(counts):
                    cur[0][i] += c
                cur[1] += int(total)
                cur[2] += int(breaches)
    return merged


def fleet_slo_section(scrapes, now_slot=None):
    """The merged fleet SLO section: sum the live replicas' slots, then
    recompute percentiles/burn through the SAME pure function each
    replica's healthz uses -- merged-equals-recompute by construction."""
    merged = merge_slots([s.get('slots') for s in scrapes
                          if 'error' not in s])
    return section_from_slots(merged, now_slot=now_slot)


def fleet_headroom(scrapes):
    """Capacity/headroom across the fleet: per-replica rows (the skew
    table -- one hot replica hides inside a healthy fleet average) plus
    the aggregate used/budget and the max-min pressure skew."""
    rows = []
    used_sum = budget_sum = 0
    pressures = []
    for s in scrapes:
        if 'error' in s:
            continue
        cap = (s.get('healthz') or {}).get('capacity') or {}
        hr = cap.get('headroom') or {}
        totals = cap.get('totals') or {}
        row = {'replica_id': s.get('replica_id'),
               'uptime_s': s.get('uptime_s'),
               'used_bytes': hr.get('used_bytes'),
               'budget_bytes': hr.get('budget_bytes'),
               'pressure': hr.get('pressure'),
               'exhaustion_s': hr.get('exhaustion_s'),
               'arena_bytes': totals.get('arena_bytes'),
               'egress_bytes': totals.get('egress_bytes')}
        rows.append(row)
        used_sum += int(hr.get('used_bytes') or 0)
        budget_sum += int(hr.get('budget_bytes') or 0)
        if isinstance(hr.get('pressure'), (int, float)):
            pressures.append(float(hr['pressure']))
    out = {'replicas': rows,
           'used_bytes': used_sum,
           'budget_bytes': budget_sum,
           'pressure': round(used_sum / budget_sum, 4)
           if budget_sum > 0 else 0.0}
    out['pressure_skew'] = round(max(pressures) - min(pressures), 4) \
        if pressures else 0.0
    return out


def fleet_routing(scrapes):
    """Doc-placement view across the fleet (ISSUE 18): one row per
    member that serves a ``routing`` healthz section (replicas report
    owned/disowned docs and migration counters; a router reports ring
    membership and live migrations), plus a ring-version consistency
    verdict -- during a rebalance the versions legitimately diverge,
    and ``consistent`` flips back once every member has seen the
    latest placement."""
    rows, versions = [], []
    for s in scrapes:
        if 'error' in s:
            continue
        rt = (s.get('healthz') or {}).get('routing')
        if not isinstance(rt, dict):
            continue
        row = {'replica_id': rt.get('replica_id') or s.get('replica_id'),
               'role': rt.get('role', 'replica'),
               'ring_version': rt.get('ring_version')}
        for k in ('owned_docs', 'disowned_docs', 'migrations_in',
                  'migrations_out', 'members', 'overrides',
                  'migrating_docs'):
            if k in rt:
                row[k] = rt[k]
        rows.append(row)
        if isinstance(rt.get('ring_version'), int):
            versions.append(rt['ring_version'])
    return {'members': rows,
            'ring_version_min': min(versions) if versions else None,
            'ring_version_max': max(versions) if versions else None,
            'consistent': len(set(versions)) <= 1}


def fleet_health(scrapes):
    """Member liveness across the fleet (ISSUE 19): the router's
    healthz ``fleet_health`` section (per-member up/suspect/dead/
    quarantined state from the heartbeat monitor + current park
    budget) merged across whichever scraped processes serve one --
    normally just the router; rows from several routers union."""
    members = {}
    park = {'parked_docs': 0, 'parked_bytes': 0}
    seen = False
    for s in scrapes:
        if 'error' in s:
            continue
        fh = (s.get('healthz') or {}).get('fleet_health')
        if not isinstance(fh, dict):
            continue
        seen = True
        members.update(fh.get('members') or {})
        park['parked_docs'] += int(fh.get('parked_docs') or 0)
        park['parked_bytes'] += int(fh.get('parked_bytes') or 0)
    if not seen:
        return None
    states = [m.get('state') for m in members.values()]
    out = {'members': members,
           'up': states.count('up'),
           'suspect': states.count('suspect'),
           'dead': states.count('dead'),
           'quarantined': states.count('quarantined')}
    out.update(park)
    return out


def fleet_section(scrapes, now_slot=None):
    """The whole fleet view from a list of `scrape()` results: replica
    roll-call (live/error rows), the merged SLO section, the headroom
    table, the routing/placement table, and (when a router is in the
    scrape set) the member-liveness table.  Pure given its inputs --
    tests and the obs-check gate recompute it from captured scrapes."""
    errors = [{'url': s['url'], 'error': s['error']}
              for s in scrapes if 'error' in s]
    live = [s for s in scrapes if 'error' not in s]
    out = {'replicas': [{'replica_id': s.get('replica_id'),
                         'url': s['url'],
                         'uptime_s': s.get('uptime_s')}
                        for s in live],
           'errors': errors,
           'slo': fleet_slo_section(scrapes, now_slot=now_slot),
           'headroom': fleet_headroom(scrapes),
           'routing': fleet_routing(scrapes)}
    health = fleet_health(scrapes)
    if health is not None:
        out['health'] = health
    return out


def scrape_fleet(urls, timeout=2.0):
    """Scrape every url and assemble the fleet section; the one-call
    surface `amtpu_fleet --once` and `amtpu_top --fleet` use."""
    scrapes = [scrape(u, timeout=timeout) for u in urls]
    return scrapes, fleet_section(scrapes)
