"""Structured spans: request/batch-scoped timing with attribute bags,
Dapper-style id propagation, and an optional JSONL exporter.

A span carries (trace_id, span_id, parent_id, name, attrs).  The trace
id is minted at the outermost span (one frontend change, one sidecar
request, one bench batch) and inherited by every nested span, so a
JSONL export groups all phase timings of one request under one id --
including across the sidecar process boundary, where the client injects
`{"trace": {"traceId":..., "spanId":...}}` into the request envelope and
the server resumes the trace (`span_with_context`).  Trace ids are
128-bit (32 hex chars, W3C-traceparent-shaped) so a fleet of replicas
never collides ids; span ids stay 64-bit (16 hex).  Each process writes
its OWN trace file -- `tools/amtpu_trace.py` assembles the cross-process
tree by trace id with per-process clock-skew normalization
(docs/OBSERVABILITY.md distributed-tracing section).

Cost model: when disabled, `span()` returns a shared no-op object after
ONE attribute check -- no allocation, no clock read (the overhead gate
`make telemetry-check` pins this).  When enabled, each span exit
accumulates into the phase-occupancy table (the numbers `report()`
prints -- occupancy seconds can exceed wall time when shard threads
overlap) and appends one JSONL record if an export file is configured
(`AMTPU_TRACE_FILE` or `set_trace_file`).

Propagation is contextvars-based: nesting follows the call stack within
a thread/async context.  Worker threads (ShardedNativePool) start fresh
contexts, so their spans begin new traces -- their timings still land in
the shared occupancy table, which is the cross-thread aggregate.
"""

import contextvars
import json
import os
import sys
import threading
import time
from ..utils.common import env_bool, env_int, env_str

_current = contextvars.ContextVar('amtpu_current_span', default=None)

_lock = threading.Lock()
_seconds = {}
_counts = {}

_export_lock = threading.Lock()
_export_path = None
_export_file = None


class _State(object):
    """Mutable enable flag behind one attribute load (kept off the
    module dict so the hot-path check is a slot read)."""
    __slots__ = ('on',)


_state = _State()
_state.on = env_bool('AMTPU_TRACE', False)


def enabled():
    return _state.on


def enable():
    _state.on = True


def disable():
    _state.on = False


def new_id():
    """16-hex-char id (64 random bits) -- Dapper-sized, cheap to mint."""
    return os.urandom(8).hex()


def new_trace_id():
    """32-hex-char trace id (128 random bits, the W3C traceparent
    width): fleet-wide uniqueness so multi-replica assembly never
    merges unrelated requests."""
    return os.urandom(16).hex()


def new_root_context():
    """A fresh root wire context `{'traceId', 'spanId'}` -- what
    SidecarClient stamps on an outbound request when the caller has no
    ambient span (the request IS the root; the server's spans become
    its children)."""
    return {'traceId': new_trace_id(), 'spanId': new_id()}


class _NullSpan(object):
    """Shared no-op for the disabled path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attr(self, key, value):
        pass


NULL_SPAN = _NullSpan()


class Span(object):
    __slots__ = ('name', 'trace_id', 'span_id', 'parent_id', 'attrs',
                 'start', '_t0', '_token')

    def __init__(self, name, trace_id, parent_id, attrs):
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_id()
        self.parent_id = parent_id
        self.attrs = attrs

    def set_attr(self, key, value):
        self.attrs[key] = value

    def __enter__(self):
        self._token = _current.set(self)
        self.start = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        _current.reset(self._token)
        if exc_type is not None:
            self.attrs['error'] = exc_type.__name__
        with _lock:
            _seconds[self.name] = _seconds.get(self.name, 0.0) + dur
            _counts[self.name] = _counts.get(self.name, 0) + 1
        if _export_path is not None:
            _export(self, dur)
        return False


def span(name, **attrs):
    """Context manager timing a block as `name`; attrs are attached to
    the JSONL record.  No-op (shared null object) when disabled."""
    if not _state.on:
        return NULL_SPAN
    parent = _current.get()
    if parent is not None:
        return Span(name, parent.trace_id, parent.span_id, attrs)
    return Span(name, new_trace_id(), None, attrs)


def span_with_context(name, trace_id, parent_span_id, **attrs):
    """A span resuming a REMOTE trace (the sidecar server adopting the
    client's ids).  Falls back to `span()` semantics when no context is
    given."""
    if not _state.on:
        return NULL_SPAN
    if not trace_id:
        return span(name, **attrs)
    return Span(name, str(trace_id), parent_span_id, attrs)


def current_span():
    return _current.get()


def current_trace_context():
    """{'traceId', 'spanId'} of the active span, or None -- the envelope
    a client injects into outbound sidecar requests."""
    cur = _current.get()
    if cur is None:
        return None
    return {'traceId': cur.trace_id, 'spanId': cur.span_id}


# ---------------------------------------------------------------------------
# JSONL export
# ---------------------------------------------------------------------------

def set_trace_file(path):
    """Points the JSONL exporter at `path` (append mode; None turns the
    exporter off).  One JSON object per completed span."""
    global _export_path, _export_file
    with _export_lock:
        if _export_file is not None:
            _export_file.close()
            _export_file = None
        _export_path = path or None


def trace_file():
    return _export_path


def _max_export_bytes():
    """Size cap on the JSONL export (``AMTPU_TRACE_FILE_MAX_MB``,
    default 256; <=0 disables the cap).  Long-lived traced servers must
    not grow the span file without bound."""
    return env_int('AMTPU_TRACE_FILE_MAX_MB', 256) * 1024 * 1024


def _maybe_rotate_locked(cap):
    """Keep-1 rotation (caller holds _export_lock): the live file moves
    to ``<path>.1`` (replacing any previous rotation) and a fresh file
    opens, so the export footprint is bounded at ~2x the cap while the
    most recent cap's worth of spans always survives.

    Single-winner by construction: the size is re-read from the LIVE
    handle here, under the lock, immediately before the replace.  A
    thread that observed the over-cap condition but reached this point
    after another thread already rotated finds the fresh (small) file
    and returns without rotating -- two threads crossing the cap
    concurrently can no longer both rotate and drop the just-written
    ``<path>.1`` (the ISSUE 16 rotation-race fix; regression test in
    tests/test_tracing.py)."""
    global _export_file
    if _export_file is None or _export_file.tell() <= cap:
        return
    _export_file.close()
    _export_file = None
    os.replace(_export_path, _export_path + '.1')
    from . import metric
    metric('trace.rotations')


def _export(sp, dur):
    rec = {'name': sp.name, 'trace': sp.trace_id, 'span': sp.span_id,
           'parent': sp.parent_id, 'start': round(sp.start, 6),
           'dur_s': round(dur, 9)}
    if sp.attrs:
        rec['attrs'] = sp.attrs
    _write_line(json.dumps(rec, default=str) + '\n')


def export_record(rec):
    """Appends one arbitrary JSON-safe record to the trace file when
    one is configured -- the tail-sampled exemplar path
    (telemetry/attribution.py), which must export even while span
    tracing is disabled (exemplars ARE the sample).  No-op without a
    configured file."""
    if _export_path is None:
        return
    _write_line(json.dumps(rec, default=str) + '\n')


def _write_line(line):
    global _export_file, _export_path
    with _export_lock:
        if _export_path is None:      # raced with set_trace_file(None)
            return
        try:
            if _export_file is None:
                _export_file = open(_export_path, 'a')
            _export_file.write(line)
            _export_file.flush()
            cap = _max_export_bytes()
            if cap > 0:
                _maybe_rotate_locked(cap)
        except OSError as e:
            # a broken export path (bad dir, full disk) must degrade
            # TRACING, never the instrumented operation: disable the
            # exporter and say so once
            print('amtpu telemetry: span export to %r failed (%s); '
                  'exporter disabled' % (_export_path, e),
                  file=sys.stderr)
            _export_path = None
            _export_file = None


_trace_file_env = env_str('AMTPU_TRACE_FILE', '')
if _trace_file_env:
    set_trace_file(_trace_file_env)


# ---------------------------------------------------------------------------
# phase occupancy (the `trace` module's original surface)
# ---------------------------------------------------------------------------

def phase_add(phase, seconds, n=1):
    """Accumulates pre-measured seconds into a phase (gated like spans;
    the C++ runtime's internal timers land here)."""
    if not _state.on:
        return
    with _lock:
        _seconds[phase] = _seconds.get(phase, 0.0) + seconds
        _counts[phase] = _counts.get(phase, 0) + n


def phase_count(counter, n=1):
    if not _state.on:
        return
    with _lock:
        _counts[counter] = _counts.get(counter, 0) + n


def phase_reset():
    with _lock:
        _seconds.clear()
        _counts.clear()


def phase_snapshot():
    """{phase: {'s': seconds, 'n': calls}} accumulated since reset."""
    with _lock:
        keys = set(_seconds) | set(_counts)
        return {k: {'s': _seconds.get(k, 0.0), 'n': _counts.get(k, 0)}
                for k in sorted(keys)}


def phase_report():
    snap = phase_snapshot()
    if not snap:
        return 'trace: (empty)'
    width = max(len(k) for k in snap)
    lines = ['trace (occupancy seconds; threads overlap):']
    for k, v in sorted(snap.items(), key=lambda kv: -kv[1]['s']):
        lines.append('  %-*s %8.3fs  x%d' % (width, k, v['s'], v['n']))
    return '\n'.join(lines)
