"""Metric primitives: counters, gauges, log-bucketed histograms, and a
thread-safe registry rendering Prometheus text exposition (format 0.0.4).

Design constraints (docs/OBSERVABILITY.md):
  * near-zero cost when idle -- a metric that is never touched costs one
    dict entry; an update is one lock acquire + O(1) arithmetic.  Every
    call site in the batch pipeline fires per BATCH (or per sidecar
    request), never per op.
  * thread-safe -- `ShardedNativePool` drives shards from concurrent
    threads, so every child shares the registry's lock (contention is
    negligible at batch granularity; tests/test_telemetry.py hammers it).
  * percentiles derivable offline -- histograms use fixed log2 bucket
    bounds, so p50/p95/p99 come from the bucket counts alone and two
    scrapes can be subtracted before quantiling.

Stdlib-only: this module is imported before jax/numpy are safe to load
(the sidecar pins the platform first).
"""

import threading

# log2-spaced latency bounds: 1us .. ~67s, 27 finite buckets (+Inf is
# implicit).  Wide enough for a single-op host batch and a multi-minute
# cold-compile batch alike.
DEFAULT_BUCKETS = tuple(1e-6 * 2 ** i for i in range(27))

_ESCAPES = {'\\': '\\\\', '"': '\\"', '\n': '\\n'}


def _escape(s, quote=False):
    out = []
    for ch in str(s):
        if ch in _ESCAPES and (quote or ch != '"'):
            out.append(_ESCAPES[ch])
        else:
            out.append(ch)
    return ''.join(out)


def format_value(v):
    """Prometheus sample value: integers render bare, floats via repr
    (full precision; scientific notation is valid exposition)."""
    if isinstance(v, float):
        if v == float('inf'):
            return '+Inf'
        if v != v:
            return 'NaN'
        if v.is_integer() and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


def bucket_index(bounds, v):
    """Index of the bucket holding `v` against fixed sorted `bounds`
    (len(bounds) = the +Inf bucket).  Bisection: the binary search
    beats log() calls and stays exact at the boundaries.  The ONE
    bucket search shared by HistogramChild and the SLO windows
    (telemetry/attribution.py)."""
    lo, hi = 0, len(bounds)
    while lo < hi:
        mid = (lo + hi) // 2
        if v <= bounds[mid]:
            hi = mid
        else:
            lo = mid + 1
    return lo


def quantile_from_counts(bounds, counts, total, q):
    """Linear-interpolated quantile from bucket counts (the same
    estimate Prometheus' histogram_quantile computes server-side; +Inf
    observations clamp to the top finite bound).  Returns 0.0 on an
    empty histogram.  Shared by HistogramChild and the SLO windows so
    healthz p99s cannot drift from the exposition's."""
    if total == 0:
        return 0.0
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target and c > 0:
            lo = bounds[i - 1] if i > 0 else 0.0
            if i >= len(bounds):          # +Inf bucket: clamp
                return bounds[-1]
            hi = bounds[i]
            return lo + (hi - lo) * (target - (cum - c)) / c
    return bounds[-1]


def _labels_text(labelnames, labelvalues):
    if not labelnames:
        return ''
    return '{%s}' % ','.join(
        '%s="%s"' % (n, _escape(v, quote=True))
        for n, v in zip(labelnames, labelvalues))


class _Child(object):
    """One time series (a concrete label-value binding of a family)."""

    __slots__ = ('_lock',)

    def __init__(self, lock):
        self._lock = lock


class CounterChild(_Child):
    __slots__ = ('value',)

    def __init__(self, lock):
        _Child.__init__(self, lock)
        self.value = 0.0

    def inc(self, n=1):
        if n < 0:
            raise ValueError('counters only go up (got %r)' % (n,))
        with self._lock:
            self.value += n


class GaugeChild(_Child):
    __slots__ = ('value',)

    def __init__(self, lock):
        _Child.__init__(self, lock)
        self.value = 0.0

    def set(self, v):
        with self._lock:
            self.value = float(v)

    def inc(self, n=1):
        with self._lock:
            self.value += n

    def dec(self, n=1):
        self.inc(-n)


class HistogramChild(_Child):
    __slots__ = ('bounds', 'counts', 'sum', 'count')

    def __init__(self, lock, bounds):
        _Child.__init__(self, lock)
        self.bounds = bounds
        # counts[i] observations in (bounds[i-1], bounds[i]]; the last
        # slot is the +Inf bucket
        self.counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def _bucket_index(self, v):
        return bucket_index(self.bounds, v)

    def observe(self, v):
        i = self._bucket_index(v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def read(self):
        """Atomic (counts copy, sum, count) -- scrapes and summaries must
        not tear against a concurrent observe(), or the exposition's
        +Inf bucket can disagree with _count."""
        with self._lock:
            return list(self.counts), self.sum, self.count

    def quantile(self, q):
        """Linear-interpolated quantile from the bucket counts (the same
        estimate Prometheus' histogram_quantile computes server-side).
        Returns 0.0 on an empty histogram."""
        counts, _sum, total = self.read()
        return self._quantile_from(counts, total, q)

    def _quantile_from(self, counts, total, q):
        return quantile_from_counts(self.bounds, counts, total, q)

    def summary(self):
        """{count, sum, p50, p95, p99} -- the bench-line embed shape;
        all fields derive from ONE atomic read."""
        counts, sum_, count = self.read()
        return {'count': count, 'sum': round(sum_, 6),
                'p50': round(self._quantile_from(counts, count, 0.50), 6),
                'p95': round(self._quantile_from(counts, count, 0.95), 6),
                'p99': round(self._quantile_from(counts, count, 0.99), 6)}


_CHILD_TYPES = {'counter': CounterChild, 'gauge': GaugeChild,
                'histogram': HistogramChild}


class MetricFamily(object):
    """A named metric with a fixed label schema; children are the
    concrete series.  An unlabeled family proxies child methods
    directly (family.inc(...) == family.labels().inc(...))."""

    def __init__(self, name, help_, type_, labelnames, lock, buckets=None):
        self.name = name
        self.help = help_
        self.type = type_
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets) if buckets else DEFAULT_BUCKETS
        self._lock = lock
        self._children = {}       # guarded-by: self._lock
        if not self.labelnames:
            self.labels()   # materialize the single series eagerly

    def labels(self, *values, **kw):
        if kw:
            if values:
                raise ValueError('pass label values positionally OR by '
                                 'name, not both')
            if set(kw) != set(self.labelnames):
                raise ValueError('%s expects labels %r, got %r'
                                 % (self.name, self.labelnames,
                                    tuple(sorted(kw))))
            values = tuple(str(kw[n]) for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError('%s expects labels %r, got %r'
                             % (self.name, self.labelnames, values))
        with self._lock:
            child = self._children.get(values)
            if child is None:
                cls = _CHILD_TYPES[self.type]
                child = (cls(self._lock, self.buckets)
                         if self.type == 'histogram' else cls(self._lock))
                self._children[values] = child
        return child

    # unlabeled convenience surface
    def inc(self, n=1):
        self.labels().inc(n)

    def set(self, v):
        self.labels().set(v)

    def dec(self, n=1):
        self.labels().dec(n)

    def observe(self, v):
        self.labels().observe(v)

    def quantile(self, q):
        return self.labels().quantile(q)

    def summary(self):
        return self.labels().summary()

    @property
    def value(self):
        return self.labels().value

    # -- exposition -----------------------------------------------------

    def render(self, out):
        out.append('# HELP %s %s' % (self.name, _escape(self.help)))
        out.append('# TYPE %s %s' % (self.name, self.type))
        with self._lock:
            items = sorted(self._children.items())
        for values, child in items:
            lt = _labels_text(self.labelnames, values)
            if self.type == 'histogram':
                counts, sum_, count = child.read()
                cum = 0
                for i, bound in enumerate(child.bounds):
                    cum += counts[i]
                    blt = _labels_text(
                        self.labelnames + ('le',),
                        values + (format_value(float(bound)),))
                    out.append('%s_bucket%s %d' % (self.name, blt, cum))
                cum += counts[-1]
                blt = _labels_text(self.labelnames + ('le',),
                                   values + ('+Inf',))
                out.append('%s_bucket%s %d' % (self.name, blt, cum))
                out.append('%s_sum%s %s' % (self.name, lt,
                                            format_value(sum_)))
                out.append('%s_count%s %d' % (self.name, lt, count))
            else:
                out.append('%s%s %s' % (self.name, lt,
                                        format_value(child.value)))

    def snapshot(self):
        """Plain-dict view for bench embedding: scalar for an unlabeled
        family, {label-values: scalar} otherwise; histograms summarize."""
        with self._lock:
            items = sorted(self._children.items())

        def one(child):
            return child.summary() if self.type == 'histogram' \
                else child.value
        if not self.labelnames:
            return one(items[0][1]) if items else None
        return {','.join(v): one(c) for v, c in items}

    def reset(self):
        with self._lock:
            for child in self._children.values():
                if self.type == 'histogram':
                    child.counts = [0] * (len(child.bounds) + 1)
                    child.sum = 0.0
                    child.count = 0
                else:
                    child.value = 0.0


class MetricRegistry(object):
    """Ordered collection of families sharing one lock; `render()` is
    the full Prometheus exposition body."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families = {}       # guarded-by: self._lock

    def _get_or_make(self, name, help_, type_, labelnames, buckets=None):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.type != type_ or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        'metric %s re-registered with a different '
                        'type/label schema' % name)
                return fam
            fam = MetricFamily(name, help_, type_, labelnames,
                               threading.Lock(), buckets)
            self._families[name] = fam
            return fam

    def counter(self, name, help_, labelnames=()):
        return self._get_or_make(name, help_, 'counter', labelnames)

    def gauge(self, name, help_, labelnames=()):
        return self._get_or_make(name, help_, 'gauge', labelnames)

    def histogram(self, name, help_, labelnames=(), buckets=None):
        return self._get_or_make(name, help_, 'histogram', labelnames,
                                 buckets)

    def families(self):
        with self._lock:
            return list(self._families.values())

    def render(self):
        out = []
        for fam in self.families():
            fam.render(out)
        return '\n'.join(out) + '\n'

    def snapshot(self):
        return {fam.name: fam.snapshot() for fam in self.families()}

    def reset(self):
        for fam in self.families():
            fam.reset()
