"""Per-request critical-path attribution + the SLO surface
(docs/OBSERVABILITY.md request-stage glossary).

Always on, no span machinery: the gateway threads one
:class:`Clock` -- a monotonic timestamp vector -- through every
request's life (admission -> queue -> flush claim -> pool dispatch ->
device collect -> emit -> fan-out write).  Each stage is the DELTA
between consecutive marks, so the stages partition the request wall
exactly: `sum(stages through emit) == total` by construction, which is
what `make obs-check` gates.  Per-stage milliseconds land in the
``amtpu_request_stage_ms{stage=...}`` histogram family (stage
``total`` is the through-emit wall; ``fanout`` is the post-response
subscriber-write tail, attributed on top of the total).

Tail-sampled exemplars: a request whose total exceeds ``AMTPU_SLOW_MS``
(and every failed/quarantined one) retroactively emits a full span
tree -- one root ``request.exemplar`` record plus one child per stage,
with the flight recorder's surrounding events attached -- through the
span JSONL exporter (``AMTPU_TRACE_FILE``; written even while span
tracing is disabled, exemplars ARE the tail sample) and into a bounded
in-memory deque (``recent_exemplars()``, served by /debug/recorder's
sibling surface and tests).

SLO surface: every attributed request also lands in per-class rolling
windows (10 s slots), from which the healthz ``slo`` section derives
rolling p50/p99 per request class (``mutate`` / ``read`` / ``control``)
and multi-window error-budget burn rates against ``AMTPU_SLO_P99_MS``
(budget: 1% of requests may exceed the target; burn 1.0 = spending
exactly budget, >1 = on track to exhaust it).

Flush-phase seams: the native driver stamps always-on per-batch
dispatch/collect seconds into a thread-local accumulator
(:func:`note_flush_phase`); the gateway brackets its pool call with
:func:`flush_phases_begin` / :func:`flush_phases_end` to split the
shared apply wall into the ``dispatch`` and ``collect`` stages.
Outside a bracket the seam is one thread-local read returning None --
the cost `make telemetry-check` keeps inside the idle-overhead budget.
"""

import collections
import threading
import time

from ..utils.common import env_float

#: the stage universe, in pipeline order (docs/OBSERVABILITY.md)
REQUEST_STAGES = ('admit', 'queue', 'claim', 'dispatch', 'collect',
                  'emit', 'fanout')

#: request classes the SLO windows track
CLASSES = ('mutate', 'read', 'control')

_MUTATE_CMDS = ('apply_changes', 'apply_batch', 'apply_local_change',
                'load')
_CONTROL_CMDS = ('subscribe', 'unsubscribe', 'presence')


def class_of(cmd):
    if cmd in _MUTATE_CMDS:
        return 'mutate'
    if cmd in _CONTROL_CMDS:
        return 'control'
    return 'read'


def slow_ms():
    """Exemplar threshold: requests slower than this (ms) emit a full
    retroactive span tree (``AMTPU_SLOW_MS``)."""
    return env_float('AMTPU_SLOW_MS', 250.0)


def slo_p99_ms():
    """The p99 latency target the burn rates measure against
    (``AMTPU_SLO_P99_MS``)."""
    return env_float('AMTPU_SLO_P99_MS', 100.0)


def _family():
    """The stage histogram family, resolved lazily: this module is
    imported while telemetry/__init__ is still executing."""
    global _STAGE_MS
    if _STAGE_MS is None:
        from . import QUEUE_WAIT_BUCKETS, registry
        _STAGE_MS = registry.histogram(
            'amtpu_request_stage_ms',
            'Milliseconds one gateway request spent in each pipeline '
            'stage (admit/queue/claim/dispatch/collect/emit; "total" '
            'is the through-emit wall the stages partition exactly; '
            '"fanout" is the post-response subscriber-write tail)',
            ('stage',), buckets=QUEUE_WAIT_BUCKETS)
    return _STAGE_MS


_STAGE_MS = None


class Clock(object):
    """One request's timestamp vector.  `mark(stage)` closes the stage
    begun at the previous mark; `mark_split` closes one wall segment as
    two stages (the shared flush apply, split dispatch/collect);
    `add(stage, s)` attributes extra seconds outside the partition
    (the fan-out tail)."""

    __slots__ = ('t0', 'prev', 'stages', 'cls', 'trace')

    def __init__(self, cls, t0=None, trace=None):
        """`t0` backdates the clock to frame receipt (the gateway reader
        stamps it before decoding), so `admit` really covers decode ->
        routing -> admission, not just Clock construction.  `trace` is
        the request's wire context (`{'traceId','spanId'}` or None):
        the exemplar tree adopts it so cross-process assembly sees one
        trace, not a freshly minted island (ISSUE 16)."""
        t = time.perf_counter() if t0 is None else t0
        self.t0 = t
        self.prev = t
        self.stages = []
        self.cls = cls
        self.trace = trace

    def mark(self, stage):
        t = time.perf_counter()
        self.stages.append((stage, t - self.prev))
        self.prev = t

    def mark_split(self, stage1, stage2, stage2_s):
        """Closes the segment since the previous mark as `stage1` +
        `stage2`, giving `stage2` at most `stage2_s` of it -- `stage1`
        absorbs the remainder, so the partition stays exact even when
        the measured sub-phase is smaller than the wall segment."""
        t = time.perf_counter()
        seg = t - self.prev
        s2 = min(max(stage2_s, 0.0), seg)
        self.stages.append((stage1, seg - s2))
        self.stages.append((stage2, s2))
        self.prev = t

    def add(self, stage, seconds):
        self.stages.append((stage, max(0.0, seconds)))


def finish(clock, ok=True, cmd=None, rid=None, doc=None):
    """Final accounting for one request: stage histograms, SLO windows,
    and (slow or failed) the exemplar span tree.  `total` is the sum of
    the partition stages (everything except the fan-out tail)."""
    from . import metric
    fam = _family()
    total_s = 0.0
    for stage, dur in clock.stages:
        fam.labels(stage).observe(dur * 1000.0)
        if stage != 'fanout':
            total_s += dur
    total_ms = total_s * 1000.0
    fam.labels('total').observe(total_ms)
    metric('slo.requests')
    breach = total_ms > slo_p99_ms()
    if breach:
        metric('slo.breaches')
    _SLO.observe(clock.cls, total_ms, breach)
    if not ok or total_ms > slow_ms():
        _emit_exemplar(clock, ok, total_ms, cmd, rid, doc)


# ---------------------------------------------------------------------------
# flush-phase seams (native driver -> gateway)
# ---------------------------------------------------------------------------

_flush_local = threading.local()


def flush_phases_begin():
    """Gateway-side: start accumulating the pool call's per-batch
    dispatch/collect seconds on this thread."""
    _flush_local.phases = {}


def note_flush_phase(stage, seconds):
    """Native-driver seam: accumulate always-on per-batch phase seconds
    into the active bracket (one thread-local read + dict add; a no-op
    costing one attribute miss outside a bracket)."""
    d = getattr(_flush_local, 'phases', None)
    if d is not None:
        d[stage] = d.get(stage, 0.0) + seconds


def flush_phases_end():
    """Gateway-side: close the bracket, returning {stage: seconds}."""
    d = getattr(_flush_local, 'phases', None)
    _flush_local.phases = None
    return d or {}


# ---------------------------------------------------------------------------
# exemplars (the tail sample)
# ---------------------------------------------------------------------------

_EXEMPLAR_KEEP = 32

#: events attached per exemplar (the recorder ring can be huge; the
#: post-mortem only needs the immediate neighbourhood)
_EXEMPLAR_EVENTS_MAX = 256

_exemplars = collections.deque(maxlen=_EXEMPLAR_KEEP)
_exemplar_last = 0.0


def _emit_exemplar(clock, ok, total_ms, cmd, rid, doc):
    global _exemplar_last
    from . import metric
    from .recorder import RECORDER, record
    from .spans import export_record, new_id, new_trace_id
    # rate limit (AMTPU_EXEMPLAR_MIN_S, default 50ms): exemplars are a
    # TAIL SAMPLE, not a log -- under a quarantine storm or an error
    # -spamming client, every failing request would otherwise pay a
    # full ring snapshot + JSONL write on the dispatcher's critical
    # path, collapsing flush throughput exactly when the server is
    # already unhealthy.  Benign write-write race: two threads racing
    # the stamp emit two exemplars, which the sample survives.
    now_mono = time.monotonic()
    if now_mono - _exemplar_last < env_float('AMTPU_EXEMPLAR_MIN_S',
                                             0.05):
        return
    _exemplar_last = now_mono
    metric('slo.exemplars')
    # adopt the request's wire trace context (ISSUE 16): the exemplar
    # tree and the recorder event join the cross-process trace the
    # client started, so `amtpu_trace` assembles one tree per request
    # instead of per-process islands; parent = the client's span id
    tctx = clock.trace if isinstance(clock.trace, dict) else {}
    trace_id = tctx.get('traceId') or new_trace_id()
    parent_id = tctx.get('spanId')
    root_id = new_id()
    record('request.slow', doc=doc, n=int(total_ms),
           detail=cmd if ok else '%s!' % (cmd,), trace=trace_id)
    now = time.time()
    start = now - (time.perf_counter() - clock.t0)
    root = {'name': 'request.exemplar', 'trace': trace_id,
            'span': root_id, 'parent': parent_id,
            'start': round(start, 6), 'dur_s': round(total_ms / 1e3, 6),
            'attrs': {'cmd': cmd, 'rid': rid, 'doc': doc,
                      'class': clock.cls, 'ok': bool(ok),
                      'total_ms': round(total_ms, 3)},
            # the recorder's surrounding events: what the ring still
            # holds from just before this request began (newest
            # _EXEMPLAR_EVENTS_MAX -- the neighbourhood, not the ring)
            'events': RECORDER.tail(start - 1.0,
                                    limit=_EXEMPLAR_EVENTS_MAX)}
    children = []
    t = start
    for stage, dur in clock.stages:
        children.append({'name': 'request.stage.%s' % stage,
                         'trace': trace_id, 'span': new_id(),
                         'parent': root_id, 'start': round(t, 6),
                         'dur_s': round(dur, 9)})
        if stage != 'fanout':
            t += dur
    _exemplars.append(root)
    export_record(root)
    for ch in children:
        export_record(ch)


def recent_exemplars():
    """The last few exemplar roots (bounded deque), newest last."""
    return list(_exemplars)


# ---------------------------------------------------------------------------
# SLO windows (rolling slots -> healthz `slo` section)
# ---------------------------------------------------------------------------

#: slot granularity and horizon: 10 s slots x 360 = one hour of history
_SLOT_S = 10
_SLOTS = 360

#: the windows healthz reports (seconds); burn rates use the last two
#: (the SRE multi-window pattern: a fast window catches a cliff, a slow
#: one catches a leak)
WINDOWS_S = (60, 300, 3600)


class _SloWindows(object):
    """Per-class rolling latency/breach slots.  One lock; observe() is
    one bucket increment, section() walks at most _SLOTS entries per
    class (cold: healthz only)."""

    def __init__(self):
        self._lock = threading.Lock()
        # class -> {slot_index: [bucket_counts, total, breaches]}
        self._slots = {c: collections.OrderedDict() for c in CLASSES}
        self._bounds = None       # resolved lazily (QUEUE_WAIT_BUCKETS)

    def _bucket(self, ms):
        # the bucket search and quantile estimator are metrics.py's --
        # healthz slo p99s must agree with histogram_quantile over the
        # exposition for the same data
        from .metrics import bucket_index
        if self._bounds is None:
            from . import QUEUE_WAIT_BUCKETS
            self._bounds = QUEUE_WAIT_BUCKETS
        return bucket_index(self._bounds, ms)

    def observe(self, cls, ms, breach):
        slot = int(time.time()) // _SLOT_S
        b = self._bucket(ms)
        with self._lock:
            slots = self._slots.get(cls)
            if slots is None:
                return
            ent = slots.get(slot)
            if ent is None:
                ent = slots[slot] = [[0] * (len(self._bounds) + 1),
                                     0, 0]
                while len(slots) > _SLOTS:
                    slots.popitem(last=False)
            ent[0][b] += 1
            ent[1] += 1
            if breach:
                ent[2] += 1

    def slots_snapshot(self):
        """JSON-safe deep copy of the raw mergeable slot state:
        ``{class: {slot_index: [bucket_counts, total, breaches]}}``.
        This -- not the derived percentiles -- is the unit the fleet
        plane aggregates: slots from N replicas SUM element-wise, and
        :func:`section_from_slots` over the sum is bit-identical to one
        replica having observed all the traffic (percentile averaging
        is a lie; docs/OBSERVABILITY.md fleet section).  Served raw by
        ``/debug/slo_slots`` (telemetry/httpd.py)."""
        with self._lock:
            return {cls: {slot: [list(ent[0]), ent[1], ent[2]]
                          for slot, ent in slots.items()}
                    for cls, slots in self._slots.items()}

    def section(self):
        """The healthz ``slo`` payload: per class per window
        {count, p50_ms, p99_ms, breach_frac}, plus burn rates for the
        two slowest windows against the 1% budget."""
        out = section_from_slots(self.slots_snapshot())
        out['exemplars_kept'] = len(_exemplars)
        return out


def section_from_slots(slots_by_class, now_slot=None, bounds=None):
    """Derives the ``slo`` section from a slot snapshot
    (:meth:`_SloWindows.slots_snapshot` shape; slot keys may be ints or
    the strings JSON made of them).  PURE and deterministic: the single
    -replica healthz section and the fleet-merged section both come
    from here, so an N-replica merge is bit-consistent with a
    per-replica recompute by construction -- integer bucket counts sum
    in any order, and the quantile estimator is metrics.py's."""
    from .metrics import quantile_from_counts
    if bounds is None:
        from . import QUEUE_WAIT_BUCKETS
        bounds = QUEUE_WAIT_BUCKETS
    if now_slot is None:
        now_slot = int(time.time()) // _SLOT_S

    def merged(cls, window_s):
        cutoff = now_slot - max(1, window_s // _SLOT_S)
        counts = None
        total = breaches = 0
        for slot in sorted(slots_by_class.get(cls, {})):
            bc, t, br = slots_by_class[cls][slot]
            if int(slot) <= cutoff:
                continue
            if counts is None:
                counts = list(bc)
            else:
                counts = [a + b for a, b in zip(counts, bc)]
            total += t
            breaches += br
        return counts, total, breaches

    def quant(counts, total, q):
        if counts is None:
            return 0.0
        return quantile_from_counts(bounds, counts, total, q)

    classes = {}
    for cls in CLASSES:
        per = {}
        for w in WINDOWS_S:
            counts, total, breaches = merged(cls, w)
            per['%ds' % w] = {
                'count': total,
                'p50_ms': round(quant(counts, total, 0.50), 3),
                'p99_ms': round(quant(counts, total, 0.99), 3),
                'breach_frac': round(breaches / total, 6)
                if total else 0.0,
            }
        classes[cls] = per
    burn = {}
    for w in WINDOWS_S[-2:]:
        tot = br = 0
        for cls in CLASSES:
            _c, t, b = merged(cls, w)
            tot += t
            br += b
        # budget: 1% of requests may exceed the p99 target; burn
        # 1.0 = spending exactly budget over this window
        burn['%ds' % w] = round((br / tot) / 0.01, 3) if tot else 0.0
    return {'target_p99_ms': slo_p99_ms(),
            'slow_ms': slow_ms(),
            'classes': classes,
            'burn': burn}


_SLO = _SloWindows()


def slo_section():
    return _SLO.section()


def slo_slots():
    """The raw mergeable slot snapshot of this process (the fleet
    plane's scrape unit)."""
    return _SLO.slots_snapshot()
