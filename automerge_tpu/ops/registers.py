"""Batched LWW register resolution.

The reference resolves each assignment sequentially: partition the register's
ops into overwritten (causally superseded) vs concurrent, append the new op,
sort by actor descending; the first op is the winner, the rest are conflicts
(`/root/reference/backend/op_set.js:188-231`).

This kernel computes the same result for EVERY op of a whole multi-document
batch in one dispatch.  Key idea: after sorting ops by (register-group,
application-time), op `p` is alive at time `t` iff no later op `q` with
time_q <= t at the same register causally supersedes it
(supersedes = NOT concurrent, reference op_set.js:7-16).  Supersession is
evaluated over a fixed window of W predecessors -- register survivor sets are
concurrent antichains, which stay tiny in real workloads; a full window
(possible overflow) is flagged so the host can fall back to the oracle for
that register, keeping byte parity always.

All ops across all docs are flattened into one array; groups are globally
unique ids for (doc, obj, key), so no per-doc padding is needed.
"""

from functools import partial

import jax
import jax.numpy as jnp

# Window of predecessors considered per op.  Conflict sets larger than this
# overflow to the host oracle (rare: needs >W concurrent writers on one key).
WINDOW = 8


@partial(jax.jit, static_argnames=('window',))
def resolve_registers_members(time, actor, seq, mem_idx, is_del,
                              clock_table, clock_idx, window=WINDOW):
    """Member-explicit register resolution -- EXACT for up to `window`
    concurrent actor streams per key.

    The sliding-window variant (`resolve_registers`) sees the W rows
    immediately preceding each op, so a key written many times (hot map
    keys, 8 actors x many rounds) fills the window with DEAD sequential
    versions and overflows to the host constantly.  Here the host builds
    `mem_idx[t, w]`: the row index of the w-th candidate predecessor --
    the LATEST row of each actor stream active on the key before t (an op
    with an older same-actor successor is always superseded, so only
    per-actor-latest rows can survive; the true bound is the concurrent
    antichain width, not the write count).  -1 marks empty slots.

    Supersession among members orders by TIME (later member supersedes a
    non-concurrent earlier one); winner/conflict order is actor rank
    descending with ties newest-first, matching the batch tie rule
    (backend/op_set.py apply_assign).

    Returns the same dict as `resolve_registers`, in original row order;
    `overflow` is all-False (the host flags >window-stream groups itself
    and routes them to the oracle fallback before dispatch).
    """
    T = time.shape[0]
    W = window
    clock = clock_table[clock_idx]
    A = clock.shape[1]

    valid_m = mem_idx >= 0                                    # [T, W]
    midx = jnp.clip(mem_idx, 0, T - 1)
    all_idx = jnp.concatenate(
        [jnp.arange(T, dtype=jnp.int32)[:, None], midx], axis=1)  # [T, W+1]
    all_valid = jnp.concatenate(
        [jnp.ones((T, 1), bool), valid_m], axis=1)
    m_actor = actor[all_idx]
    m_seq = seq[all_idx]
    m_time = time[all_idx]
    m_del = is_del[all_idx]
    m_clock = clock[all_idx]                                  # [T, W+1, A]

    onehot = jax.nn.one_hot(m_actor, A, dtype=jnp.int32)
    P = jnp.einsum('tua,tva->tuv', m_clock, onehot)           # [T,W+1,W+1]
    u_clock_at_v = P
    v_clock_at_u = jnp.swapaxes(P, 1, 2)
    u_seq = m_seq[:, :, None]
    v_seq = m_seq[:, None, :]
    concurrent = (u_clock_at_v < v_seq) & (v_clock_at_u < u_seq)
    later = m_time[:, :, None] > m_time[:, None, :]
    supersedes = later & ~concurrent \
        & all_valid[:, :, None] & all_valid[:, None, :]

    superseded = jnp.any(supersedes, axis=1)                  # [T, W+1]
    alive = all_valid & ~superseded & ~m_del

    superseded_wo_self = jnp.any(supersedes[:, 1:, :], axis=1)
    alive_before = all_valid & ~superseded_wo_self & ~m_del
    visible_before = jnp.any(alive_before[:, 1:], axis=1)

    alive_after = jnp.sum(alive, axis=1).astype(jnp.int32)

    # winner/conflicts order: actor desc, ties newest-first.  Composite
    # int64 keys are unavailable on default-precision TPU, so compose two
    # stable argsorts: time desc first, then actor desc.
    t_order = jnp.argsort(-m_time, axis=1, stable=True)
    actor_t = jnp.take_along_axis(m_actor, t_order, axis=1)
    alive_t = jnp.take_along_axis(alive, t_order, axis=1)
    src_t = jnp.take_along_axis(all_idx, t_order, axis=1)
    actor_keyed = jnp.where(alive_t, actor_t, -1)
    a_order = jnp.argsort(-actor_keyed, axis=1, stable=True)
    sorted_alive = jnp.take_along_axis(alive_t, a_order, axis=1)
    sorted_src = jnp.where(sorted_alive,
                           jnp.take_along_axis(src_t, a_order, axis=1), -1)

    winner = sorted_src[:, 0]
    conflicts = sorted_src[:, 1:]

    out = {
        'alive_after': alive_after,
        'winner': winner,
        'conflicts': conflicts,
        'visible_before': visible_before,
        'overflow': jnp.zeros((T,), jnp.bool_),
    }
    if window > 14:
        raise ValueError(
            'packed alive_after field is 4 bits; window=%d overflows it '
            '(max alive_after is window+1)' % window)
    out['packed'] = (jnp.where(out['winner'] >= 0, out['winner'],
                               0xffffff).astype(jnp.int32)
                     | (out['alive_after'] << 24))
    return out


@partial(jax.jit, static_argnames=('window',))
def resolve_registers(group, time, actor, seq, clock=None, is_del=None,
                      alive_in=None, window=WINDOW, sort_idx=None,
                      clock_table=None, clock_idx=None):
    """Resolves every register op of a batch.

    Args:
      group: [T] int32 -- register group id ((doc, obj, key) interned);
             -1 for padding rows.
      time:  [T] int32 -- application position (unique, total order; state
             ops carry times below every batch op).
      actor: [T] int32 -- actor rank of the op's change.
      seq:   [T] int32 -- seq of the op's change.
      clock: [T, A] int32 -- allDeps row of the op's change.
      is_del:[T] bool -- 'del' ops overwrite but never join the register.
      alive_in: [T] bool -- for pre-existing state ops: True; for batch ops:
             True (they are considered at their own time).
      sort_idx: optional [T] int32 -- precomputed np.lexsort((time, group))
             permutation; hoisted to the host by batch callers because
             XLA:CPU compiles large in-graph sorts in tens of seconds.
      clock_table/clock_idx: optional [C, A] + [T] -- deduplicated clock
             rows (ops of one change share a row): host->device traffic
             shrinks ~16x and the full [T, A] matrix materializes only
             on device.  Exactly one of `clock` or the
             (clock_table, clock_idx) pair must be given.

    Returns dict of [T]-shaped outputs (original op order):
      alive_after: int32 -- register size right after this op.
      winner:      int32 -- op index (into this batch array) of the register
                   winner after this op, or -1 if the register is empty.
      conflicts:   int32 [T, window] -- losing op indices, actor-descending,
                   -1 padded.
      visible_before: bool -- register non-empty just before this op.
      overflow:    bool -- window saturated; host must re-resolve this group.
    """
    T = group.shape[0]
    W = window
    if (clock is None) == (clock_table is None) or \
            (clock_table is None) != (clock_idx is None):
        raise ValueError('pass exactly one of clock or '
                         '(clock_table, clock_idx)')
    if clock_table is not None:
        clock = clock_table[clock_idx]
    A = clock.shape[1]

    # sort by (group, time); padding (group == -1) sorts first and is inert
    if sort_idx is None:
        sort_idx = jnp.lexsort((time, group))
    g_s = group[sort_idx]
    t_s = time[sort_idx]
    a_s = actor[sort_idx]
    q_s = seq[sort_idx]
    c_s = clock[sort_idx]
    d_s = is_del[sort_idx]

    # Window member w of op i lives at sorted position i - w (w in 1..W):
    # a SLIDING window, so member arrays are shifted copies, not gathers
    # (TPU: slices fuse; random gathers do not).
    def shifted(arr, w, fill):
        if w >= arr.shape[0]:
            return jnp.full(arr.shape, fill, arr.dtype)
        pad = jnp.full((w,) + arr.shape[1:], fill, arr.dtype)
        return jnp.concatenate([pad, arr[:-w]], axis=0)

    def members(arr, fill):
        """[T, W+1, ...]: slot 0 = self, slot w = w-th predecessor."""
        return jnp.stack([arr] + [shifted(arr, w, fill)
                                  for w in range(1, W + 1)], axis=1)

    m_actor = members(a_s, 0)
    m_seq = members(q_s, 0)
    m_del = members(d_s, False)
    m_group = members(g_s, -2)
    m_valid = (m_group == g_s[:, None]) & (g_s >= 0)[:, None]   # [T, W+1]
    m_clock = members(c_s, 0)                                   # [T, W+1, A]

    # pairwise: does member u supersede member v?  (u applied later, and they
    # are NOT concurrent).  Member order by slot: slot 0 is the latest op,
    # larger slots are earlier.  u later than v  <=>  slot_u < slot_v.
    #
    # clock_u[actor_v] via one-hot batched matmul (MXU work) instead of a
    # [T, W+1, W+1] random gather:  P[t, u, v] = m_clock[t, u, actor_v].
    onehot = jax.nn.one_hot(m_actor, A, dtype=jnp.int32)        # [T, W+1, A]
    P = jnp.einsum('tua,tva->tuv', m_clock, onehot)             # [T,W+1,W+1]
    u_clock_at_v = P
    v_clock_at_u = jnp.swapaxes(P, 1, 2)
    u_seq = m_seq[:, :, None]
    v_seq = m_seq[:, None, :]
    concurrent = (u_clock_at_v < v_seq) & (v_clock_at_u < u_seq)  # [T,W+1,W+1]
    later = (jnp.arange(W + 1)[:, None] < jnp.arange(W + 1)[None, :])  # u<v slot
    supersedes = later[None, :, :] & ~concurrent \
        & m_valid[:, :, None] & m_valid[:, None, :]

    # alive after op i: member v is alive iff valid and no member u (at or
    # before time_i, i.e. any slot) supersedes it, and v is not a del
    superseded = jnp.any(supersedes, axis=1)                        # [T, W+1]
    alive = m_valid & ~superseded & ~m_del                          # [T, W+1]

    # visible before op i: drop self (slot 0), member alive considering only
    # supersessions by predecessors (exclude slot-0 superseder)
    superseded_wo_self = jnp.any(supersedes[:, 1:, :], axis=1)      # [T, W+1]
    alive_before = m_valid & ~superseded_wo_self & ~m_del
    visible_before = jnp.any(alive_before[:, 1:], axis=1)

    alive_after = jnp.sum(alive, axis=1).astype(jnp.int32)

    # winner: alive member with max actor rank; conflicts: remaining alive
    # members, actor-descending (the reference's sortBy(actor).reverse())
    actor_keyed = jnp.where(alive, m_actor, -1)
    order = jnp.argsort(-actor_keyed, axis=1, stable=True)          # [T, W+1]
    sorted_alive = jnp.take_along_axis(alive, order, axis=1)
    member_src = members(sort_idx, -1)                              # [T, W+1]
    sorted_src = jnp.take_along_axis(member_src, order, axis=1)
    sorted_src = jnp.where(sorted_alive, sorted_src, -1)

    winner = sorted_src[:, 0]
    conflicts = sorted_src[:, 1:]

    # overflow: the whole window is same-group valid AND the earliest window
    # slot is still alive -- older ops beyond the window could matter
    window_full = jnp.all(m_valid[:, 1:], axis=1)
    overflow = window_full & (g_s >= 0)

    # scatter back to original op order
    out = {
        'alive_after': jnp.zeros((T,), jnp.int32).at[sort_idx].set(alive_after),
        'winner': jnp.full((T,), -1, jnp.int32).at[sort_idx].set(winner),
        'conflicts': jnp.full((T, W), -1, jnp.int32).at[sort_idx].set(conflicts),
        'visible_before': jnp.zeros((T,), jnp.bool_).at[sort_idx].set(visible_before),
        'overflow': jnp.zeros((T,), jnp.bool_).at[sort_idx].set(overflow),
    }
    # transfer-packed summary: winner (24 bits, 0xffffff = none) | alive
    # (4 bits) | overflow (1 bit).  One [T] i32 D2H instead of four arrays;
    # conflicts rows are fetched lazily only where alive > 1.  Callers must
    # use the unpacked outputs when T >= 2**24.
    if window > 14:
        raise ValueError(
            'packed alive_after field is 4 bits; window=%d overflows it '
            '(max alive_after is window+1)' % window)
    out['packed'] = (jnp.where(out['winner'] >= 0, out['winner'],
                               0xffffff).astype(jnp.int32)
                     | (out['alive_after'] << 24)
                     | (out['overflow'].astype(jnp.int32) << 28))
    return out


@jax.jit
def gather_rows(mat, rows):
    """Row gather for the lazy conflicts fetch."""
    return mat[rows]


def _resolve(group, time, actor, seq, clock_table, clock_idx, is_del,
             alive_in, sort_idx, mem_idx, window):
    """Mode dispatch: member-explicit when the host built mem_idx (groups
    wider than the sliding window), else the sliding-window kernel."""
    if mem_idx is not None:
        return resolve_registers_members(time, actor, seq, mem_idx, is_del,
                                         clock_table, clock_idx,
                                         window=window)
    return resolve_registers(group, time, actor, seq, is_del=is_del,
                             alive_in=alive_in, window=window,
                             sort_idx=sort_idx, clock_table=clock_table,
                             clock_idx=clock_idx)


@partial(jax.jit, static_argnames=('window',))
def resolve_and_rank(group, time, actor, seq, clock_table, clock_idx,
                     is_del, alive_in, sort_idx,
                     eobj, epar, ectr, eact, evalid, lin_sort, n_iters,
                     window=WINDOW, mem_idx=None):
    """Register resolution + RGA linearization in ONE dispatch: the two
    computations are independent, so fusing them halves the dispatch /
    sync round trips of a batch (the device link has ~70ms latency per
    blocking transfer in this deployment)."""
    from .list_rank import linearize
    reg = _resolve(group, time, actor, seq, clock_table, clock_idx, is_del,
                   alive_in, sort_idx, mem_idx, window)
    rank = linearize(eobj, epar, ectr, eact, evalid, n_iters,
                     sort_idx=lin_sort)
    return reg, rank


def dominance_op_inputs(reg, rank, oe, dom_src, ov):
    """Per-op dominance inputs derived from the register outputs and a
    fresh rank vector: orank gathers the touched element's rank, od is
    the op's visibility delta (alive_after - visible_before of its
    register row).  Shared by the unsharded and sp-sharded resident
    kernels so the derivation cannot drift between them."""
    C = rank.shape[0]
    orank = jnp.where(ov, rank[jnp.clip(oe, 0, C - 1)], -1)
    T = reg['alive_after'].shape[0]
    row = jnp.clip(dom_src, 0, T - 1)
    od = jnp.where(dom_src >= 0,
                   (reg['alive_after'][row] > 0).astype(jnp.int32)
                   - reg['visible_before'][row].astype(jnp.int32),
                   0)
    return orank, od


def resolve_rank_dominate_resident(group, time, actor, seq, clock_table,
                                   clock_idx, is_del, alive_in, sort_idx,
                                   epar, ectr, eact, ev, n_elems,
                                   oe, dom_src, ov,
                                   n_iters=1, window=WINDOW, chunk=64):
    """The fused resolver over a DEVICE-RESIDENT single-object arena
    (SURVEY hard part 5: incremental state across batches).

    Unlike `resolve_rank_dominate`, the arena columns (epar/ectr/eact)
    and the element-visibility vector (ev, f32) are long-lived device
    arrays owned by the pool's resident cache -- the host uploads only
    per-batch deltas (appended rows, register rows, per-op arrays).
    Derivations the host used to precompute per batch happen in-graph:

      * the sibling sort (lin_sort) runs as linearize's in-graph lexsort,
      * v0 IS the resident ev,
      * er_src is the identity (single object at arena base 0),
      * orank gathers from the freshly computed rank.

    Args mirror resolve_rank_dominate where shared; epar/ectr/eact/ev are
    [C] (C = the block's padded arena size), n_elems the live count,
    oe/dom_src/ov are [1, Tp] per-op arrays.  Returns the same
    (reg, rank, combo) contract, so the packed-transfer consumer in the
    native driver is unchanged.
    """
    from .list_rank import dominance_grouped, linearize
    reg = _resolve(group, time, actor, seq, clock_table, clock_idx, is_del,
                   alive_in, sort_idx, None, window)
    C = epar.shape[0]
    valid = jnp.arange(C, dtype=jnp.int32) < n_elems
    obj0 = jnp.zeros((C,), jnp.int32)
    rank = linearize(obj0, epar, ectr, eact, valid, n_iters)
    er = jnp.where(valid, rank, -1)[None, :]
    orank, od = dominance_op_inputs(reg, rank, oe, dom_src, ov)
    idx = dominance_grouped(ev[None, :], er, oe, orank, od, ov, chunk=chunk)
    combo = jnp.concatenate([reg['packed'], idx.reshape(-1)])
    return reg, rank, combo


@partial(jax.jit, static_argnames=('window', 'chunk'))
def resolve_rank_dominate(group, time, actor, seq, clock_table, clock_idx,
                          is_del, alive_in, sort_idx,
                          eobj, epar, ectr, eact, evalid, lin_sort, n_iters,
                          v0, er_src, oe, orank_src, dom_src, ov,
                          window=WINDOW, chunk=64, mem_idx=None):
    """The full resolver in ONE device dispatch: register resolution, RGA
    linearization, AND per-op list dominance indexes.

    The reference interleaves these stages per op (apply -> skip-list
    indexOf, `/root/reference/backend/op_set.js:233-295` + skip_list.js);
    here the dominance stage's rank-dependent inputs are gathered ON
    DEVICE from the linearize output, and its visibility deltas are
    derived from the register kernel's own alive/visible outputs -- so a
    whole multi-doc batch costs a single dispatch and a single packed
    device->host transfer (winner/alive/overflow + dominance indexes),
    with no rank readback at all on the common path.

    Dominance-layout args (built by the C++ runtime at begin):
      v0:        [W, Lp] f32 -- element visibility at batch start.
      er_src:    [W, Lp] i32 -- arena-global element index, -1 padding.
      oe:        [W, Tp] i32 -- local element index per timeline op.
      orank_src: [W, Tp] i32 -- arena-global index of the touched element.
      dom_src:   [W, Tp] i32 -- register row of the timeline op, -1 pad.
      ov:        [W, Tp] bool.

    Returns (reg dict, rank [L], combo [T + W*Tp] i32) where combo is the
    packed register summary concatenated with the dominance indexes --
    fetch it with ONE transfer; rank stays device-resident unless the
    overflow fallback needs it.
    """
    from .list_rank import dominance_grouped, linearize
    reg = _resolve(group, time, actor, seq, clock_table, clock_idx, is_del,
                   alive_in, sort_idx, mem_idx, window)
    rank = linearize(eobj, epar, ectr, eact, evalid, n_iters,
                     sort_idx=lin_sort)
    L = rank.shape[0]
    er = jnp.where(er_src >= 0, rank[jnp.clip(er_src, 0, L - 1)], -1)
    orank = jnp.where(orank_src >= 0, rank[jnp.clip(orank_src, 0, L - 1)],
                      -1)
    T = reg['alive_after'].shape[0]
    row = jnp.clip(dom_src, 0, T - 1)
    od = jnp.where(dom_src >= 0,
                   (reg['alive_after'][row] > 0).astype(jnp.int32)
                   - reg['visible_before'][row].astype(jnp.int32),
                   0)
    idx = dominance_grouped(v0, er, oe, orank, od, ov, chunk=chunk)
    combo = jnp.concatenate([reg['packed'], idx.reshape(-1)])
    return reg, rank, combo
