"""Batched LWW register resolution.

The reference resolves each assignment sequentially: partition the register's
ops into overwritten (causally superseded) vs concurrent, append the new op,
sort by actor descending; the first op is the winner, the rest are conflicts
(`/root/reference/backend/op_set.js:188-231`).

This kernel computes the same result for EVERY op of a whole multi-document
batch in one dispatch.  Key idea: after sorting ops by (register-group,
application-time), op `p` is alive at time `t` iff no later op `q` with
time_q <= t at the same register causally supersedes it
(supersedes = NOT concurrent, reference op_set.js:7-16).  Supersession is
evaluated over a fixed window of W predecessors -- register survivor sets are
concurrent antichains, which stay tiny in real workloads; a full window
(possible overflow) is flagged, and the host ESCALATES the flagged groups
through wider member-window size classes (W in {16, 32, 64, ...}) in one
re-dispatch per tier (`escalate_overflow`) -- still on device, still exact.
The scalar oracle remains the parity REFEREE (differential tests), not the
executor: only groups wider than every tier (AMTPU_MAX_TIER, default 1024
candidate rows) ever reach the host oracle, and the fuzz/bench workloads
never produce one.

All ops across all docs are flattened into one array; groups are globally
unique ids for (doc, obj, key), so no per-doc padding is needed.
"""

from collections import namedtuple
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.common import env_bool, env_int

# Window of predecessors considered per op in the base dispatch.  Conflict
# sets larger than this overflow and escalate through the tier ladder.
WINDOW = 8

# The packed transfer word carries alive_after in 6 bits (24..29),
# saturated here; every packed-path consumer only tests alive > 0 / > 1.
PACKED_ALIVE_MAX = 63

#: Bit layout of the packed register word, defined ONCE for every
#: encoder/decoder (pack_register_word, _merge_packed_rows,
#: NativeDocPool._unpack_packed; native/core.cpp mirrors it and
#: docs/ARCHITECTURE.md pins it).  Plain ints: usable from numpy and
#: traced jit code alike.
PACKED_WINNER_MASK = 0xffffff    # low 24 bits; == mask means "no winner"
PACKED_WINNER_NONE = 0xffffff
PACKED_ALIVE_SHIFT = 24
PACKED_ALIVE_MASK = 0x3f
PACKED_OVF_SHIFT = 30


def pack_register_word(winner, alive_after, overflow=None):
    """Encodes the packed [T] i32 transfer word: winner (24 bits,
    PACKED_WINNER_NONE = none) | alive_after (6 bits, saturated at
    PACKED_ALIVE_MAX) | overflow in bit PACKED_OVF_SHIFT.  Works on jnp
    and np arrays; the decode twin is NativeDocPool._unpack_packed."""
    xp = jnp if isinstance(winner, jnp.ndarray) else np
    word = (xp.where(winner >= 0, winner,
                     PACKED_WINNER_NONE).astype(xp.int32)
            | (xp.minimum(alive_after, PACKED_ALIVE_MAX).astype(xp.int32)
               << PACKED_ALIVE_SHIFT))
    if overflow is not None:
        word = word | (overflow.astype(xp.int32) << PACKED_OVF_SHIFT)
    return word


def _pairwise_clock(m_actor, clock_table=None, m_cidx=None, m_clock=None):
    """P[t, u, v] = clock of member u at the actor of member v -- the
    pairwise supersession input.  Two formulations, bit-equal:

      * one-hot einsum (batched matmul): MXU-shaped work, the right form
        on accelerators;
      * flat gather from the compact clock table (or take_along_axis on
        an already-materialized [T, W+1, A] m_clock): measured 3.5x
        faster than the int32 einsum on XLA:CPU at the config-4 shape,
        and the table form never materializes m_clock at all.

    Entries for invalid members are garbage under the gather forms (the
    clipped indexes read arbitrary real rows); every consumer masks by
    member validity, so the two forms stay bit-equal where it matters.
    """
    import jax as _jax
    on_cpu = _jax.default_backend() == 'cpu'
    if on_cpu and clock_table is not None:
        A = clock_table.shape[1]
        idx = m_cidx[:, :, None] * A + m_actor[:, None, :]
        return clock_table.reshape(-1)[idx]
    if m_clock is None:
        m_clock = clock_table[m_cidx]
    if on_cpu:
        Wp1 = m_actor.shape[1]
        idx = jnp.broadcast_to(m_actor[:, None, :],
                               (m_actor.shape[0], Wp1, Wp1))
        return jnp.take_along_axis(m_clock, idx, axis=2)
    A = m_clock.shape[2]
    onehot = jax.nn.one_hot(m_actor, A, dtype=jnp.int32)
    return jnp.einsum('tua,tva->tuv', m_clock, onehot)


def _order_by_paircount(m_actor, m_time, alive, m_src, W):
    """Winner/conflicts from member arrays without a sort: position by
    pairwise count over (actor desc, time desc) -- times are unique, so
    the order is total -- then scatter through a position one-hot.
    Returns (winner [T], conflicts [T, W]) with -1 padding."""
    a_u = m_actor[:, :, None]
    a_v = m_actor[:, None, :]
    t_u = m_time[:, :, None]
    t_v = m_time[:, None, :]
    precede = alive[:, None, :] & \
        ((a_v > a_u) | ((a_v == a_u) & (t_v > t_u)))          # v before u
    pos = jnp.sum(precede.astype(jnp.int32), axis=2)          # [T, W+1]
    src = jnp.where(alive, m_src, -1)
    winner = jnp.sum(jnp.where((pos == 0) & alive, src + 1, 0), axis=1) - 1
    kpos = jax.lax.broadcasted_iota(jnp.int32,
                                    (m_actor.shape[0], W + 1, W), 2)
    poh = (pos[:, :, None] == kpos + 1) & alive[:, :, None]
    conflicts = jnp.sum(jnp.where(poh, (src + 1)[:, :, None], 0), axis=1) - 1
    return winner, conflicts


@partial(jax.jit, static_argnames=('window', 'want_visible_before'))
def resolve_registers_members(time, actor, seq, mem_idx, is_del,
                              clock_table, clock_idx, window=WINDOW,
                              want_visible_before=True):
    """Member-explicit register resolution -- EXACT for up to `window`
    concurrent actor streams per key.

    The sliding-window variant (`resolve_registers`) sees the W rows
    immediately preceding each op, so a key written many times (hot map
    keys, 8 actors x many rounds) fills the window with DEAD sequential
    versions and overflows to the host constantly.  Here the host builds
    `mem_idx[t, w]`: the row index of the w-th candidate predecessor --
    the LATEST row of each actor stream active on the key before t (an op
    with an older same-actor successor is always superseded, so only
    per-actor-latest rows can survive; the true bound is the concurrent
    antichain width, not the write count).  -1 marks empty slots.

    Supersession among members orders by TIME (later member supersedes a
    non-concurrent earlier one); winner/conflict order is actor rank
    descending with ties newest-first, matching the batch tie rule
    (backend/op_set.py apply_assign).

    Returns the same dict as `resolve_registers`, in original row order;
    `overflow` is all-False (the host flags >window-stream groups itself
    and routes them through the escalation ladder -- a wider tier of this
    same kernel -- before dispatch; see `escalate_overflow`).

    `want_visible_before=False` drops the visible_before output AND its
    compute (a second [T, W+1, W+1] reduction chain) -- the native
    packed epilogue never reads it (C++ tracks its own running
    visibility); only the engine path and the fused dominance derivation
    need it.
    """
    T = time.shape[0]
    W = window

    valid_m = mem_idx >= 0                                    # [T, W]
    midx = jnp.clip(mem_idx, 0, T - 1)
    all_idx = jnp.concatenate(
        [jnp.arange(T, dtype=jnp.int32)[:, None], midx], axis=1)  # [T, W+1]
    all_valid = jnp.concatenate(
        [jnp.ones((T, 1), bool), valid_m], axis=1)
    m_actor = actor[all_idx]
    m_seq = seq[all_idx]
    m_time = time[all_idx]
    m_del = is_del[all_idx]
    # member clocks gather INDICES first, then pairwise values straight
    # from the compact deduplicated table (_pairwise_clock: flat gather
    # on CPU -- [T, W+1, A] never materializes -- one-hot einsum on
    # accelerators): [T, W+1] small gather + the pairwise lookup beat
    # materializing [T, A] and gathering the blown-up matrix
    m_cidx = clock_idx[all_idx]                               # [T, W+1]
    P = _pairwise_clock(m_actor, clock_table, m_cidx)         # [T,W+1,W+1]
    u_clock_at_v = P
    v_clock_at_u = jnp.swapaxes(P, 1, 2)
    u_seq = m_seq[:, :, None]
    v_seq = m_seq[:, None, :]
    concurrent = (u_clock_at_v < v_seq) & (v_clock_at_u < u_seq)
    later = m_time[:, :, None] > m_time[:, None, :]
    supersedes = later & ~concurrent \
        & all_valid[:, :, None] & all_valid[:, None, :]

    superseded = jnp.any(supersedes, axis=1)                  # [T, W+1]
    alive = all_valid & ~superseded & ~m_del

    visible_before = None
    if want_visible_before:
        superseded_wo_self = jnp.any(supersedes[:, 1:, :], axis=1)
        alive_before = all_valid & ~superseded_wo_self & ~m_del
        visible_before = jnp.any(alive_before[:, 1:], axis=1)

    alive_after = jnp.sum(alive, axis=1).astype(jnp.int32)

    # winner/conflicts order: actor desc, ties newest-first.  Ordering
    # WITHOUT argsort: times are unique, so each alive member's output
    # position is a PAIRWISE COUNT --
    #   pos(u) = #{v alive : actor_v > actor_u
    #              or (actor_v == actor_u and time_v > time_u)}
    # -- and winner/conflicts scatter through a position one-hot.  The
    # same formulation as the Pallas stencil kernel, bit-equal to the
    # two-stable-argsort epilogue it replaced; a stable argsort over
    # [T, W+1] was the single costliest op of this kernel on XLA:CPU.
    winner, conflicts = _order_by_paircount(m_actor, m_time, alive,
                                            all_idx, W)

    out = {
        'alive_after': alive_after,
        'winner': winner,
        'conflicts': conflicts,
        'overflow': jnp.zeros((T,), jnp.bool_),
    }
    if want_visible_before:
        out['visible_before'] = visible_before
    out['packed'] = pack_register_word(out['winner'], out['alive_after'])
    return out


@partial(jax.jit, static_argnames=('window',))
def resolve_registers(group, time, actor, seq, clock=None, is_del=None,
                      alive_in=None, window=WINDOW, sort_idx=None,
                      clock_table=None, clock_idx=None):
    """Resolves every register op of a batch.

    Args:
      group: [T] int32 -- register group id ((doc, obj, key) interned);
             -1 for padding rows.
      time:  [T] int32 -- application position (unique, total order; state
             ops carry times below every batch op).
      actor: [T] int32 -- actor rank of the op's change.
      seq:   [T] int32 -- seq of the op's change.
      clock: [T, A] int32 -- allDeps row of the op's change.
      is_del:[T] bool -- 'del' ops overwrite but never join the register.
      alive_in: [T] bool -- for pre-existing state ops: True; for batch ops:
             True (they are considered at their own time).
      sort_idx: optional [T] int32 -- precomputed np.lexsort((time, group))
             permutation; hoisted to the host by batch callers because
             XLA:CPU compiles large in-graph sorts in tens of seconds.
      clock_table/clock_idx: optional [C, A] + [T] -- deduplicated clock
             rows (ops of one change share a row): host->device traffic
             shrinks ~16x and the full [T, A] matrix materializes only
             on device.  Exactly one of `clock` or the
             (clock_table, clock_idx) pair must be given.

    Returns dict of [T]-shaped outputs (original op order):
      alive_after: int32 -- register size right after this op.
      winner:      int32 -- op index (into this batch array) of the register
                   winner after this op, or -1 if the register is empty.
      conflicts:   int32 [T, window] -- losing op indices, actor-descending,
                   -1 padded.
      visible_before: bool -- register non-empty just before this op.
      overflow:    bool -- window saturated; the host escalates this group
                   through a wider kernel tier (`escalate_overflow`).
    """
    T = group.shape[0]
    W = window
    if (clock is None) == (clock_table is None) or \
            (clock_table is None) != (clock_idx is None):
        raise ValueError('pass exactly one of clock or '
                         '(clock_table, clock_idx)')

    # sort by (group, time); padding (group == -1) sorts first and is inert
    if sort_idx is None:
        sort_idx = jnp.lexsort((time, group))
    g_s = group[sort_idx]
    t_s = time[sort_idx]
    a_s = actor[sort_idx]
    q_s = seq[sort_idx]
    d_s = is_del[sort_idx]

    # Window member w of op i lives at sorted position i - w (w in 1..W):
    # a SLIDING window, so member arrays are shifted copies, not gathers
    # (TPU: slices fuse; random gathers do not).
    def shifted(arr, w, fill):
        if w >= arr.shape[0]:
            return jnp.full(arr.shape, fill, arr.dtype)
        pad = jnp.full((w,) + arr.shape[1:], fill, arr.dtype)
        return jnp.concatenate([pad, arr[:-w]], axis=0)

    def members(arr, fill):
        """[T, W+1, ...]: slot 0 = self, slot w = w-th predecessor."""
        return jnp.stack([arr] + [shifted(arr, w, fill)
                                  for w in range(1, W + 1)], axis=1)

    m_actor = members(a_s, 0)
    m_seq = members(q_s, 0)
    m_del = members(d_s, False)
    m_group = members(g_s, -2)
    m_valid = (m_group == g_s[:, None]) & (g_s >= 0)[:, None]   # [T, W+1]

    # pairwise: does member u supersede member v?  (u applied later, and they
    # are NOT concurrent).  Member order by slot: slot 0 is the latest op,
    # larger slots are earlier.  u later than v  <=>  slot_u < slot_v.
    #
    # P[t, u, v] = clock of member u at the actor of member v; formulation
    # picked per backend in _pairwise_clock (flat table gather on CPU,
    # one-hot batched matmul on accelerators).  Invalid-member entries are
    # masked by m_valid below.
    if clock_table is not None:
        m_cidx = members(clock_idx[sort_idx], 0)                # [T, W+1]
        P = _pairwise_clock(m_actor, clock_table, m_cidx)
    else:
        P = _pairwise_clock(m_actor, m_clock=members(clock[sort_idx], 0))
    u_clock_at_v = P
    v_clock_at_u = jnp.swapaxes(P, 1, 2)
    u_seq = m_seq[:, :, None]
    v_seq = m_seq[:, None, :]
    concurrent = (u_clock_at_v < v_seq) & (v_clock_at_u < u_seq)  # [T,W+1,W+1]
    later = (jnp.arange(W + 1)[:, None] < jnp.arange(W + 1)[None, :])  # u<v slot
    supersedes = later[None, :, :] & ~concurrent \
        & m_valid[:, :, None] & m_valid[:, None, :]

    # alive after op i: member v is alive iff valid and no member u (at or
    # before time_i, i.e. any slot) supersedes it, and v is not a del
    superseded = jnp.any(supersedes, axis=1)                        # [T, W+1]
    alive = m_valid & ~superseded & ~m_del                          # [T, W+1]

    # visible before op i: drop self (slot 0), member alive considering only
    # supersessions by predecessors (exclude slot-0 superseder)
    superseded_wo_self = jnp.any(supersedes[:, 1:, :], axis=1)      # [T, W+1]
    alive_before = m_valid & ~superseded_wo_self & ~m_del
    visible_before = jnp.any(alive_before[:, 1:], axis=1)

    alive_after = jnp.sum(alive, axis=1).astype(jnp.int32)

    # winner: alive member with max actor rank; conflicts: remaining alive
    # members, actor-descending (the reference's sortBy(actor).reverse()),
    # ties newest-first (slot ascending = time descending).  Ordered by
    # pairwise count instead of a stable argsort over [T, W+1] -- the
    # Pallas kernel's formulation, bit-equal and far cheaper on XLA:CPU.
    m_t = members(t_s, 0)
    member_src = members(sort_idx, -1)                              # [T, W+1]
    winner, conflicts = _order_by_paircount(m_actor, m_t, alive,
                                            member_src, W)

    # overflow: the whole window is same-group valid AND the earliest window
    # slot is still alive -- older ops beyond the window could matter
    window_full = jnp.all(m_valid[:, 1:], axis=1)
    overflow = window_full & (g_s >= 0)

    # scatter back to original op order
    out = {
        'alive_after': jnp.zeros((T,), jnp.int32).at[sort_idx].set(alive_after),
        'winner': jnp.full((T,), -1, jnp.int32).at[sort_idx].set(winner),
        'conflicts': jnp.full((T, W), -1, jnp.int32).at[sort_idx].set(conflicts),
        'visible_before': jnp.zeros((T,), jnp.bool_).at[sort_idx].set(visible_before),
        'overflow': jnp.zeros((T,), jnp.bool_).at[sort_idx].set(overflow),
    }
    # transfer-packed summary: winner (24 bits, 0xffffff = none) | alive
    # (6 bits, SATURATED at PACKED_ALIVE_MAX -- consumers only test >0 and
    # >1; the exact count stays in the unpacked alive_after) | overflow
    # (bit 30).  One [T] i32 D2H instead of four arrays; conflicts rows
    # are fetched lazily only where alive > 1.  Callers must use the
    # unpacked outputs when T >= 2**24.
    out['packed'] = pack_register_word(out['winner'], out['alive_after'],
                                       out['overflow'])
    return out


@jax.jit
def gather_rows(mat, rows):
    """Row gather for the lazy conflicts fetch."""
    return mat[rows]


def _merge_packed_rows(base, rows_p, tier_packed, sub_p):
    """Scatters one escalation-tier chunk's packed words into the base
    packed array ON DEVICE (ISSUE 6 tentpole b): tier-local winner
    indexes translate to global batch rows through `sub_p` (the chunk's
    row map), alive bits carry over, and the overflow bit stays clear --
    the scattered rows are, by construction, resolved.  Padding slots of
    `rows_p` carry an out-of-bounds index and drop.  After the chain of
    chunk merges, ONE device->host transfer returns the packed word
    already resolved for every tier-escalated row; the host's only
    remaining merge work is the residual (oracle) flag vector."""
    win = tier_packed & PACKED_WINNER_MASK
    n = sub_p.shape[0]
    win_g = jnp.where(win == PACKED_WINNER_NONE, PACKED_WINNER_NONE,
                      sub_p[jnp.clip(win, 0, n - 1)])
    word = (((tier_packed >> PACKED_ALIVE_SHIFT) & PACKED_ALIVE_MASK)
            << PACKED_ALIVE_SHIFT) | win_g
    return base.at[rows_p].set(word, mode='drop')


_merge_packed_jit = None
_merge_packed_donated = None


def device_merge_on():
    """AMTPU_DEVICE_MERGE=0 keeps the escalation-tier merge on the host
    (the PR-3 scatter); default on (checked per batch, not latched --
    the A/B parity lane flips it)."""
    return env_bool('AMTPU_DEVICE_MERGE', True)


def merge_packed_rows(base, rows_p, tier_packed, sub_p):
    """Backend-dispatched `_merge_packed_rows`: the base word is DONATED
    on accelerators (each chunk merge reuses the previous buffer instead
    of allocating -- the donate_argnums pattern proven on the tier
    staging path); on CPU donation buys nothing and jit aliases anyway."""
    global _merge_packed_jit, _merge_packed_donated
    if jax.default_backend() == 'cpu':
        if _merge_packed_jit is None:
            _merge_packed_jit = jax.jit(_merge_packed_rows)
        return _merge_packed_jit(base, rows_p, tier_packed, sub_p)
    if _merge_packed_donated is None:
        _merge_packed_donated = jax.jit(_merge_packed_rows,
                                        donate_argnums=(0,))
    return _merge_packed_donated(base, rows_p, tier_packed, sub_p)


def _resolve(group, time, actor, seq, clock_table, clock_idx, is_del,
             alive_in, sort_idx, mem_idx, window,
             want_visible_before=True):
    """Mode dispatch: member-explicit when the host built mem_idx (groups
    wider than the sliding window), else the sliding-window kernel.
    `want_visible_before` only prunes the member kernel (the sliding
    kernel computes it either way)."""
    if mem_idx is not None:
        return resolve_registers_members(
            time, actor, seq, mem_idx, is_del, clock_table, clock_idx,
            window=window, want_visible_before=want_visible_before)
    return resolve_registers(group, time, actor, seq, is_del=is_del,
                             alive_in=alive_in, window=window,
                             sort_idx=sort_idx, clock_table=clock_table,
                             clock_idx=clock_idx)


@partial(jax.jit, static_argnames=('window',))
def resolve_and_rank(group, time, actor, seq, clock_table, clock_idx,
                     is_del, alive_in, sort_idx,
                     eobj, epar, ectr, eact, evalid, lin_sort, n_iters,
                     window=WINDOW, mem_idx=None):
    """Register resolution + RGA linearization in ONE dispatch: the two
    computations are independent, so fusing them halves the dispatch /
    sync round trips of a batch (the device link has ~70ms latency per
    blocking transfer in this deployment).  Member-mode visible_before
    is pruned: this entry's consumers (the native mode='old' paths) take
    running visibility from the C++ mirrors, never from the kernel."""
    from .list_rank import linearize
    reg = _resolve(group, time, actor, seq, clock_table, clock_idx, is_del,
                   alive_in, sort_idx, mem_idx, window,
                   want_visible_before=False)
    rank = linearize(eobj, epar, ectr, eact, evalid, n_iters,
                     sort_idx=lin_sort)
    return reg, rank


def dominance_op_inputs(reg, rank, oe, dom_src, ov):
    """Per-op dominance inputs derived from the register outputs and a
    fresh rank vector: orank gathers the touched element's rank, od is
    the op's visibility delta (alive_after - visible_before of its
    register row).  Shared by the unsharded and sp-sharded resident
    kernels so the derivation cannot drift between them."""
    C = rank.shape[0]
    orank = jnp.where(ov, rank[jnp.clip(oe, 0, C - 1)], -1)
    T = reg['alive_after'].shape[0]
    row = jnp.clip(dom_src, 0, T - 1)
    od = jnp.where(dom_src >= 0,
                   (reg['alive_after'][row] > 0).astype(jnp.int32)
                   - reg['visible_before'][row].astype(jnp.int32),
                   0)
    return orank, od


def resolve_rank_dominate_resident(group, time, actor, seq, clock_table,
                                   clock_idx, is_del, alive_in, sort_idx,
                                   epar, ectr, eact, ev, n_elems,
                                   oe, dom_src, ov,
                                   n_iters=1, window=WINDOW, chunk=64):
    """The fused resolver over a DEVICE-RESIDENT single-object arena
    (SURVEY hard part 5: incremental state across batches).

    Unlike `resolve_rank_dominate`, the arena columns (epar/ectr/eact)
    and the element-visibility vector (ev, f32) are long-lived device
    arrays owned by the pool's resident cache -- the host uploads only
    per-batch deltas (appended rows, register rows, per-op arrays).
    Derivations the host used to precompute per batch happen in-graph:

      * the sibling sort (lin_sort) runs as linearize's in-graph lexsort,
      * v0 IS the resident ev,
      * er_src is the identity (single object at arena base 0),
      * orank gathers from the freshly computed rank.

    Args mirror resolve_rank_dominate where shared; epar/ectr/eact/ev are
    [C] (C = the block's padded arena size), n_elems the live count,
    oe/dom_src/ov are [1, Tp] per-op arrays.  Returns the same
    (reg, rank, combo) contract, so the packed-transfer consumer in the
    native driver is unchanged.
    """
    from .list_rank import dominance_grouped, linearize
    reg = _resolve(group, time, actor, seq, clock_table, clock_idx, is_del,
                   alive_in, sort_idx, None, window)
    C = epar.shape[0]
    valid = jnp.arange(C, dtype=jnp.int32) < n_elems
    obj0 = jnp.zeros((C,), jnp.int32)
    rank = linearize(obj0, epar, ectr, eact, valid, n_iters)
    er = jnp.where(valid, rank, -1)[None, :]
    orank, od = dominance_op_inputs(reg, rank, oe, dom_src, ov)
    idx = dominance_grouped(ev[None, :], er, oe, orank, od, ov, chunk=chunk)
    combo = jnp.concatenate([reg['packed'], idx.reshape(-1)])
    return reg, rank, combo


@partial(jax.jit, static_argnames=('window', 'chunk'))
def resolve_rank_dominate(group, time, actor, seq, clock_table, clock_idx,
                          is_del, alive_in, sort_idx,
                          eobj, epar, ectr, eact, evalid, lin_sort, n_iters,
                          v0, er_src, oe, orank_src, dom_src, ov,
                          window=WINDOW, chunk=64, mem_idx=None):
    """The full resolver in ONE device dispatch: register resolution, RGA
    linearization, AND per-op list dominance indexes.

    The reference interleaves these stages per op (apply -> skip-list
    indexOf, `/root/reference/backend/op_set.js:233-295` + skip_list.js);
    here the dominance stage's rank-dependent inputs are gathered ON
    DEVICE from the linearize output, and its visibility deltas are
    derived from the register kernel's own alive/visible outputs -- so a
    whole multi-doc batch costs a single dispatch and a single packed
    device->host transfer (winner/alive/overflow + dominance indexes),
    with no rank readback at all on the common path.

    Dominance-layout args (built by the C++ runtime at begin):
      v0:        [W, Lp] f32 -- element visibility at batch start.
      er_src:    [W, Lp] i32 -- arena-global element index, -1 padding.
      oe:        [W, Tp] i32 -- local element index per timeline op.
      orank_src: [W, Tp] i32 -- arena-global index of the touched element.
      dom_src:   [W, Tp] i32 -- register row of the timeline op, -1 pad.
      ov:        [W, Tp] bool.

    Returns (reg dict, rank [L], combo [T + W*Tp] i32) where combo is the
    packed register summary concatenated with the dominance indexes --
    fetch it with ONE transfer; rank stays device-resident unless the
    overflow fallback needs it.
    """
    from .list_rank import dominance_grouped, linearize
    reg = _resolve(group, time, actor, seq, clock_table, clock_idx, is_del,
                   alive_in, sort_idx, mem_idx, window)
    rank = linearize(eobj, epar, ectr, eact, evalid, n_iters,
                     sort_idx=lin_sort)
    L = rank.shape[0]
    er = jnp.where(er_src >= 0, rank[jnp.clip(er_src, 0, L - 1)], -1)
    orank = jnp.where(orank_src >= 0, rank[jnp.clip(orank_src, 0, L - 1)],
                      -1)
    T = reg['alive_after'].shape[0]
    row = jnp.clip(dom_src, 0, T - 1)
    od = jnp.where(dom_src >= 0,
                   (reg['alive_after'][row] > 0).astype(jnp.int32)
                   - reg['visible_before'][row].astype(jnp.int32),
                   0)
    idx = dominance_grouped(v0, er, oe, orank, od, ov, chunk=chunk)
    combo = jnp.concatenate([reg['packed'], idx.reshape(-1)])
    return reg, rank, combo


# ---------------------------------------------------------------------------
# tiered escalation ladder (host driver)
#
# The base dispatch runs at WINDOW; groups it flags as overflowed are
# re-encoded into a flat padded member-window layout and re-dispatched
# through power-of-two size classes W in {16, 32, 64, ...} -- ONE device
# pass per tier present in the batch, never one host replay per group.
# Member candidates are the per-actor-LATEST rows of each stream (only
# those can survive: an op with a newer same-actor successor is always
# superseded), extended with every row of an actor's latest seq so that
# same-change duplicate assigns -- the one shape the fixed member build
# in native/core.cpp routes to overflow -- bucket into a (slightly
# wider) tier instead of the oracle.  A group only reaches the host
# oracle when its candidate width exceeds every tier (AMTPU_MAX_TIER).
# ---------------------------------------------------------------------------

#: smallest escalation tier; the ladder is floor, 2*floor, 4*floor, ...
ESCALATION_FLOOR = 16

#: widest tier before a group falls back to the host oracle
#: (AMTPU_MAX_TIER overrides)
DEFAULT_MAX_TIER = 1024

#: cap on ONE tier dispatch's dominant device intermediate -- the
#: [Tn, W+1, W+1] pairwise supersession tensor (i32).  Groups whose own
#: padded cost exceeds this are memory-unboundable at any chunking and
#: take the host oracle (counted fallback.oracle); multi-group tiers are
#: CHUNKED into as many dispatches as the budget requires.  256 MB
#: matches the dominance kernel's slab cap.  AMTPU_ESCALATE_BUDGET_MB
#: overrides.
DEFAULT_ESCALATION_BUDGET = 256 << 20


#: row cap per tier-chunk dispatch (AMTPU_ESC_CHUNK overrides).  Shape
#: bucketing pads each chunk to the next power of two, so one huge chunk
#: wastes up to ~2x its rows in padding compute (config 4: 80k flagged
#: rows padded to 131k); capping chunks at a power-of-two row count
#: bounds the waste to the LAST chunk, keeps the jit cache on one shape
#: per tier, and turns the tier into several async dispatches that
#: overlap the driver's other host work.  A lone group wider than the
#: cap still dispatches alone (groups are indivisible).
DEFAULT_ESC_CHUNK = 32768


def _esc_chunk_rows():
    n = env_int('AMTPU_ESC_CHUNK', DEFAULT_ESC_CHUNK)
    return n if n > 0 else DEFAULT_ESC_CHUNK


def _escalation_budget():
    # unset -> the built-in default; an EXPLICIT 0 is a zero-byte
    # budget, forcing every overflowed group to the host oracle (the
    # A/B hook the parity lanes use) -- distinct sentinels keep that
    mb = env_int('AMTPU_ESCALATE_BUDGET_MB', -1)
    return (mb << 20) if mb >= 0 else DEFAULT_ESCALATION_BUDGET


def escalation_enabled():
    """AMTPU_ESCALATE=0 disables the ladder (every overflowed group then
    takes the host oracle, the pre-escalation behaviour) -- an A/B and
    parity-test hook, checked per batch."""
    return env_bool('AMTPU_ESCALATE', True)


def _tier_of(n, floor=ESCALATION_FLOOR):
    w = floor
    while w < n:
        w *= 2
    return w


def _dispatch_cost(n_rows, W):
    """Bytes of the dominant [Tn, W+1, W+1] i32 intermediate of one
    member-kernel dispatch, at the PADDED row count."""
    return _tier_of(n_rows, ESCALATION_FLOOR) * (W + 1) * (W + 1) * 4


def escalate_overflow(group, time, actor, seq, is_del, clock_table,
                      clock_idx, overflow, floor=ESCALATION_FLOOR,
                      max_tier=None):
    """Resolves every row of every overflow-flagged register group through
    wider member-window kernel tiers (synchronous composition of
    `escalate_overflow_dispatch` + `escalate_overflow_collect`; pipelined
    callers split the two so tier kernels overlap other host work).

    Args (host numpy, original row order; padding rows carry group == -1):
      group/time/actor/seq/is_del: the register columns fed to the base
          dispatch.
      clock_table, clock_idx: deduplicated clock rows (callers with a
          dense [T, A] clock pass it as the table with clock_idx=arange).
      overflow: [T] bool -- the base kernel's overflow flags (sliding
          mode) or the host-computed member flags.  The WHOLE group of any
          flagged row is re-resolved (flags may cover only the saturated
          suffix).

    Returns (resolved, oracle_rows, tier_rows):
      resolved:   {row: (winner_row, [conflict_rows...], alive_after,
                  visible_before)} -- indices are GLOBAL rows, covering
                  every row of every escalated group.
      oracle_rows: np.int32 [n] -- rows of groups wider than every tier
                  OR too large for the device-scratch budget; the caller
                  must resolve these with the host oracle.
      tier_rows:  {W: row count} -- rows resolved per tier (the caller's
                  telemetry source).
    """
    pending, oracle_rows, tier_rows = escalate_overflow_dispatch(
        group, time, actor, seq, is_del, clock_table, clock_idx,
        overflow, floor=floor, max_tier=max_tier)
    return escalate_overflow_collect(pending), oracle_rows, tier_rows


def _member_windows(rows, actor, seq):
    """Member-candidate windows for ONE escalated group, vectorized.

    `rows` are the group's global row ids in (group, time) order.  Row
    j's candidacy ends at the first later row of the same actor with a
    DIFFERENT seq (a same-actor successor supersedes it; same-change
    duplicate assigns share a seq and accumulate) -- and the superseding
    row itself still SEES j, because member lists are built before the
    stream update.  So j is a member of row i's window iff
    j < i <= kill(j), which turns the whole build into interval
    expansion instead of per-row Python list copies (the old streams
    loop was O(rows * width) of interpreter work per group).

    Returns a CSR group record (rows, lens [k], vals, width): row i's
    candidates are the next lens[i] entries of vals (group-LOCAL
    indexes), the same layout the C++ escalation layout (amtpu_esc_*)
    emits.
    """
    k = len(rows)
    a = np.asarray(actor[rows])
    s = np.asarray(seq[rows])
    # kill[j]: reverse scan over each actor's time-ordered rows (the
    # stable argsort groups actors while preserving time order within)
    order = np.argsort(a, kind='stable')
    kill = np.full(k, k, np.int64)
    for x in range(k - 2, -1, -1):
        j, nxt = order[x], order[x + 1]
        if a[j] == a[nxt]:
            kill[j] = nxt if s[j] != s[nxt] else kill[nxt]
    # per-row window width without materializing the pair list:
    # lens(i) = #{j : j < i <= kill(j)} via a difference array
    delta = np.zeros(k + 2, np.int64)
    delta[1:k + 1] += 1
    np.subtract.at(delta, kill + 1, 1)
    lens_i = np.cumsum(delta)[:k]
    width = int(lens_i.max(initial=0))
    if width == 0:
        return (rows, lens_i, np.zeros(0, np.int64), 0)
    # expand each j into its target rows [j+1, min(kill(j), k-1)] as
    # (i, j) pairs (kill == k marks never-killed candidates); sorted by
    # i, the j's are exactly the CSR value runs
    jlens = np.minimum(kill, k - 1) - np.arange(k)
    total = int(jlens.sum())
    j_rep = np.repeat(np.arange(k, dtype=np.int64), jlens)
    cum = np.concatenate(([0], np.cumsum(jlens)[:-1]))
    i_tgt = j_rep + 1 + (np.arange(total) - np.repeat(cum, jlens))
    ordp = np.argsort(i_tgt, kind='stable')
    return (rows, lens_i, j_rep[ordp], width)


def _tier_alloc(Tn, W):
    return {
        'mem': np.empty((Tn, W), np.int32),
        'time': np.empty((Tn,), np.int32),
        'actor': np.empty((Tn,), np.int32),
        'seq': np.empty((Tn,), np.int32),
        'isdel': np.empty((Tn,), bool),
        'cidx': np.empty((Tn,), np.int32),
    }


def _tier_buffers(Tn, W):
    # Every dispatch gets FRESH staging arrays.  An earlier revision
    # reused thread-local buffers on the CPU backend, assuming the
    # dispatch-time host->device copy is synchronous -- it is not: jax's
    # CPU backend ZERO-COPIES 64-byte-aligned numpy inputs and dispatch
    # is async, so refilling a reused buffer for chunk B while chunk A's
    # kernel is still consuming the same memory silently corrupts A's
    # inputs (alignment-dependent, nondeterministic).  On accelerators
    # the fresh arrays additionally feed donate_argnums.
    return _tier_alloc(Tn, W)


_members_donated = None


def _dispatch_members_tier(time, actor, seq, mem, is_del, clock_table,
                           clock_idx, window, want_visible_before=True):
    """One tier-chunk dispatch.  On accelerators the per-row inputs are
    DONATED: XLA reuses their freshly transferred device buffers for
    outputs instead of allocating per dispatch (the host staging arrays
    are numpy and stay owned by _tier_buffers).  clock_table is shared
    across chunks and never donated."""
    global _members_donated
    import jax
    if jax.default_backend() == 'cpu':
        return resolve_registers_members(
            time, actor, seq, mem, is_del, clock_table, clock_idx,
            window=window, want_visible_before=want_visible_before)
    if _members_donated is None:
        _members_donated = jax.jit(
            resolve_registers_members,
            static_argnames=('window', 'want_visible_before'),
            donate_argnums=(0, 1, 2, 3, 4, 6))
    return _members_donated(time, actor, seq, mem, is_del, clock_table,
                            clock_idx, window=window,
                            want_visible_before=want_visible_before)


def escalate_overflow_dispatch(group, time, actor, seq, is_del,
                               clock_table, clock_idx, overflow,
                               floor=ESCALATION_FLOOR, max_tier=None,
                               want_visible_before=True):
    """The dispatch half of the ladder: host member-window build + one
    ASYNC kernel dispatch per tier chunk.  Only the O(Tn) outputs start
    device->host copies (packed epilogue); the [Tn, W] conflicts matrix
    stays device-resident for the collect half's sparse row gather.
    Returns (pending, oracle_rows, tier_rows) where `pending` is fed to
    `escalate_overflow_collect_arrays` -- callers with a phased pipeline
    dispatch here (phase a) and collect after their other host work
    (phase b), so tier kernels overlap it.

    `want_visible_before=False` (the native drivers) drops that output
    and its kernel compute; collected chunks then carry all-False vb."""
    group = np.asarray(group)
    time = np.asarray(time)
    actor = np.asarray(actor)
    seq = np.asarray(seq)
    is_del = np.asarray(is_del)
    clock_idx = np.asarray(clock_idx, np.int32)

    flagged = np.asarray(overflow, bool) & (group >= 0)
    ovf_gids = np.unique(group[flagged])
    if ovf_gids.size == 0:
        return [], np.zeros((0,), np.int32), {}

    # all rows of the flagged groups, in (group, time) order
    sel = np.isin(group, ovf_gids)
    sel_rows = np.nonzero(sel)[0]
    order = np.lexsort((time[sel_rows], group[sel_rows]))
    sel_rows = sel_rows[order]
    bounds = np.nonzero(np.diff(group[sel_rows]))[0] + 1
    groups = [_member_windows(rows, actor, seq)
              for rows in np.split(sel_rows, bounds)]
    return escalate_dispatch_groups(
        groups, time, actor, seq, is_del, clock_table, clock_idx,
        floor=floor, max_tier=max_tier,
        want_visible_before=want_visible_before)


def escalate_dispatch_groups(groups, time, actor, seq, is_del,
                             clock_table, clock_idx,
                             floor=ESCALATION_FLOOR, max_tier=None,
                             want_visible_before=True):
    """Dispatch half over PREBUILT CSR group records
    (rows, lens, vals, width) -- either `_member_windows` output or the
    C++ escalation layout (amtpu_esc_*), which the native driver reads
    instead of re-deriving windows host-side.  Same return contract as
    `escalate_overflow_dispatch`."""
    from .. import faults, telemetry

    if faults.ARMED:
        # tier dispatch is pure device work over a still-live batch
        # handle: a fault here propagates to the phase-a/b handlers,
        # which roll the pool back -- retry/bisect stay byte-safe
        faults.fire('escalation.tier')
    if max_tier is None:
        max_tier = env_int('AMTPU_MAX_TIER', DEFAULT_MAX_TIER)
    time = np.asarray(time)
    actor = np.asarray(actor)
    seq = np.asarray(seq)
    is_del = np.asarray(is_del)
    clock_idx = np.asarray(clock_idx, np.int32)

    budget = _escalation_budget()
    pending = []
    tier_rows = {}
    tiers = {}        # W -> [group record]
    oracle_rows = []
    for grp in groups:
        rows, width = grp[0], grp[3]
        W = _tier_of(max(width, 1), floor)
        if W > max_tier or _dispatch_cost(len(rows), W) > budget:
            # wider than every tier, or memory-unboundable at any
            # chunking: the one remaining host-oracle route
            oracle_rows.extend(int(r) for r in rows)
            continue
        tiers.setdefault(W, []).append(grp)
        telemetry.ESCALATION_TIER.observe(W)

    chunk_cap = _esc_chunk_rows()
    for W, entries in sorted(tiers.items()):
        # chunk the tier so each dispatch's [Tn, W+1, W+1] intermediate
        # stays under the scratch budget (a lone group always fits: the
        # bucketing above sent oversized ones to the oracle) AND under
        # the row cap (padding-waste bound, see DEFAULT_ESC_CHUNK)
        chunks, cur, cur_rows = [], [], 0
        for entry in entries:
            n_rows = len(entry[0])
            if cur and (_dispatch_cost(cur_rows + n_rows, W) > budget
                        or cur_rows + n_rows > chunk_cap):
                chunks.append(cur)
                cur, cur_rows = [], 0
            cur.append(entry)
            cur_rows += n_rows
        chunks.append(cur)
        for chunk in chunks:
            sub_rows = np.concatenate([g[0] for g in chunk])
            n = len(sub_rows)
            Tn = _tier_of(n, ESCALATION_FLOOR)  # shape-bucketed padding
            bufs = _tier_buffers(Tn, W)
            mem = bufs['mem']
            mem[:] = -1
            # CSR -> padded window matrix, vectorized per CHUNK: row and
            # member indexes are group-local; adding each group's chunk
            # offset makes them chunk-local
            offs = np.concatenate(
                ([0], np.cumsum([len(g[0]) for g in chunk])))
            lens_cat = np.concatenate([g[1] for g in chunk])
            total = int(lens_cat.sum())
            if total:
                vals_cat = np.concatenate(
                    [g[2] + off for g, off in zip(chunk, offs)])
                ii = np.repeat(np.arange(n), lens_cat)
                starts = np.concatenate(([0], np.cumsum(lens_cat)[:-1]))
                slot = np.arange(total) - np.repeat(starts, lens_cat)
                mem[ii, slot] = vals_cat

            def pad(name, col, fill):
                out = bufs[name]
                out[:n] = col[sub_rows]
                out[n:] = fill
                return out

            with telemetry.span('device.escalate', tier=W, rows=n):
                out = _dispatch_members_tier(
                    pad('time', time, 0), pad('actor', actor, 0),
                    pad('seq', seq, 0), mem, pad('isdel', is_del, False),
                    clock_table, pad('cidx', clock_idx, 0), W,
                    want_visible_before=want_visible_before)
                for key in ('packed', 'winner', 'alive_after',
                            'visible_before'):
                    if key in out and hasattr(out[key],
                                              'copy_to_host_async'):
                        out[key].copy_to_host_async()
            pending.append((W, sub_rows, out))
            tier_rows[W] = tier_rows.get(W, 0) + n
            telemetry.metric('fallback.escalated.w%d' % W, n)

    return pending, np.asarray(oracle_rows, np.int32), tier_rows


#: one collected tier chunk: `rows` are global batch rows; `winner` /
#: `conflicts` carry GLOBAL row ids (-1 padded); `conf_rows` indexes
#: into `rows` (only rows that kept >1 member have a conflicts row)
EscalatedChunk = namedtuple(
    'EscalatedChunk',
    ['rows', 'winner', 'conf_rows', 'conflicts', 'alive',
     'visible_before'])


def escalate_overflow_collect_arrays(pending, need_winner=True):
    """The collect half, vectorized: awaits each tier chunk's O(Tn)
    outputs and translates tier-local indices to global batch rows.
    Conflicts are row-gathered ON DEVICE only where a register kept >1
    member (the tiers' packed epilogue: the [Tn, W] matrix never
    transfers whole).  Returns a list of EscalatedChunk.

    `need_winner=False` skips the winner transfer + translation (chunk
    .winner is None): the device-merge path (`merge_packed_rows`)
    already scattered the tier winners into the packed word on device,
    so the collect half only owes conflicts + aliveness."""
    chunks = []
    for W, sub_rows, out in pending:
        n = len(sub_rows)
        sub = np.ascontiguousarray(sub_rows, np.int64)
        win = np.asarray(out['winner'])[:n] if need_winner else None
        alive = np.ascontiguousarray(np.asarray(out['alive_after'])[:n],
                                     np.int32)
        if 'visible_before' in out:
            vb = np.ascontiguousarray(
                np.asarray(out['visible_before'])[:n], bool)
        else:
            vb = np.zeros((n,), bool)
        conf_rows = np.nonzero(alive > 1)[0].astype(np.int32)
        conf_g = np.zeros((0, W), np.int32)
        if conf_rows.size:
            padlen = 1
            while padlen < conf_rows.size:
                padlen *= 2
            rows_p = np.zeros((padlen,), np.int32)
            rows_p[:conf_rows.size] = conf_rows
            conf = np.asarray(gather_rows(out['conflicts'],
                                          rows_p))[:conf_rows.size]
            conf_g = np.where(conf >= 0, sub[np.clip(conf, 0, n - 1)],
                              -1).astype(np.int32)
        win_g = None
        if win is not None:
            win_g = np.where(win >= 0, sub[np.clip(win, 0, n - 1)],
                             -1).astype(np.int32)
        chunks.append(EscalatedChunk(sub.astype(np.int32), win_g,
                                     conf_rows, conf_g, alive, vb))
    return chunks


def escalate_overflow_collect(pending):
    """Dict-contract collect: the global-row `resolved` map
    (`escalate_overflow`'s documented contract), built from the
    vectorized chunks.  Batch drivers consume the array chunks directly
    (`escalate_overflow_collect_arrays`); this form remains for
    per-row consumers and the kernel unit tests."""
    resolved = {}
    for ch in escalate_overflow_collect_arrays(pending):
        conf_of = {}
        for i, local in enumerate(ch.conf_rows):
            conf_of[int(local)] = [int(c) for c in ch.conflicts[i]
                                   if c >= 0]
        for i, r in enumerate(ch.rows):
            resolved[int(r)] = (int(ch.winner[i]), conf_of.get(i, []),
                                int(ch.alive[i]),
                                bool(ch.visible_before[i]))
    return resolved


def merge_escalated_arrays(winner, conflicts, alive, overflow, chunks,
                           visible_before=None):
    """Vectorized merge of EscalatedChunks into the (host, writable)
    register output arrays: scatters winner/conflicts/alive, widens the
    conflicts matrix when a tier kept more survivors than its column
    count, and clears the overflow flag of every resolved row -- flags
    left standing afterwards are exactly the rows the caller must route
    to the host oracle.  Returns the four (possibly replaced) arrays."""
    if not chunks:
        return winner, conflicts, alive, overflow
    width = conflicts.shape[1] if conflicts.ndim == 2 else 0
    need = width
    for ch in chunks:
        if ch.conf_rows.size:
            need = max(need, int((ch.conflicts >= 0).sum(axis=1)
                                 .max(initial=0)))
    if need > width:
        wide = np.full((conflicts.shape[0], need), -1, conflicts.dtype)
        if width:
            wide[:, :width] = conflicts
        conflicts = wide
    for ch in chunks:
        winner[ch.rows] = ch.winner
        conflicts[ch.rows, :] = -1
        if ch.conf_rows.size:
            m = min(ch.conflicts.shape[1], conflicts.shape[1])
            conflicts[ch.rows[ch.conf_rows], :m] = ch.conflicts[:, :m]
        alive[ch.rows] = ch.alive
        overflow[ch.rows] = 0
        if visible_before is not None:
            visible_before[ch.rows] = ch.visible_before
    return winner, conflicts, alive, overflow


def merge_escalated(winner, conflicts, alive, overflow, resolved):
    """Scatters `escalate_overflow` results into the (host) register
    output arrays, widening the conflicts matrix when a tier kept more
    survivors than its column count, and CLEARING the overflow flag of
    every resolved row -- flags left standing afterwards are exactly the
    rows the caller must route to the host oracle.  Returns the four
    (possibly replaced) arrays."""
    if not resolved:
        return winner, conflicts, alive, overflow
    width = conflicts.shape[1] if conflicts.ndim == 2 else 0
    need = max(len(c) for (_, c, _, _) in resolved.values())
    if need > width:
        wide = np.full((conflicts.shape[0], need), -1, conflicts.dtype)
        wide[:, :width] = conflicts
        conflicts = wide
    for row, (w, confs, al, _vb) in resolved.items():
        winner[row] = w
        conflicts[row, :] = -1
        if confs:
            conflicts[row, :len(confs)] = confs
        alive[row] = al
        overflow[row] = 0
    return winner, conflicts, alive, overflow
