"""Batched causal scheduling kernels.

The reference drains its causal-ready queue with a sequential fixpoint loop
(`/root/reference/backend/op_set.js:279-295`): scan the queue in order, apply
every change whose vector-clock deps are satisfied, repeat until no progress.

Here the same fixpoint runs as a jitted multi-pass `lax.scan` inside a
`lax.while_loop`, over *columnar* change records, and `vmap`s over a document
batch: one device dispatch schedules the queues of thousands of docs.  The
clock algebra (elementwise max / compare) is exactly the VPU-friendly shape
the survey calls for (SURVEY.md section 2, "Batched scheduling kernel").

Conventions:
  - actors are dense int ranks whose order equals the lexicographic order of
    the actor-ID strings (so LWW tie-breaks compare equal to the reference)
  - a change record is (actor, seq, deps[A]); deps rows use 0 for "no dep"
  - invalid/padding rows have actor == -1
"""

import jax
import jax.numpy as jnp
from functools import partial

NOT_APPLIED = jnp.int32(2147483647)


@partial(jax.jit, static_argnames=())
def schedule_queue(clock, actor, seq, deps, valid):
    """Schedules one doc's queued changes.

    Args:
      clock: [A] int32 -- applied seq per actor.
      actor: [C] int32 -- authoring actor rank per change (-1 = padding).
      seq:   [C] int32.
      deps:  [C, A] int32 -- dependency clock per change.
      valid: [C] bool.

    Returns (order, new_clock):
      order:  [C] int32 -- application position (0-based, queue order within
              a pass, passes concatenated); NOT_APPLIED for changes whose
              deps were never satisfied; -2 for duplicates (seq already
              covered by the clock at their turn).
      new_clock: [A] int32.
    """
    C = actor.shape[0]
    A = clock.shape[0]

    def one_pass(state):
        clock, order, counter, _progress = state

        def step(carry, i):
            clock, order, counter = carry
            a = actor[i]
            s = seq[i]
            dep_row = deps[i].at[jnp.maximum(a, 0)].set(s - 1)
            ready = valid[i] & (a >= 0) & jnp.all(dep_row <= clock) \
                & (order[i] == NOT_APPLIED)
            duplicate = ready & (s <= clock[jnp.maximum(a, 0)])
            fresh = ready & ~duplicate
            clock = jax.lax.cond(
                fresh,
                lambda c: c.at[a].set(jnp.maximum(c[a], s)),
                lambda c: c,
                clock)
            order = order.at[i].set(
                jnp.where(fresh, counter, jnp.where(duplicate, -2, order[i])))
            counter = counter + fresh.astype(jnp.int32)
            return (clock, order, counter), ready

        (clock, order, counter), readies = jax.lax.scan(
            step, (clock, order, counter), jnp.arange(C))
        return clock, order, counter, jnp.any(readies)

    def cond(state):
        return state[3]

    init = (clock, jnp.full((C,), NOT_APPLIED, jnp.int32), jnp.int32(0),
            jnp.bool_(True))
    clock, order, counter, _ = jax.lax.while_loop(cond, one_pass, init)
    return order, clock


schedule_queue_batch = jax.jit(jax.vmap(schedule_queue, in_axes=(0, 0, 0, 0, 0)))
"""vmapped scheduler: clock [D, A], actor/seq [D, C], deps [D, C, A],
valid [D, C] -> (order [D, C], new_clock [D, A])."""


@jax.jit
def transitive_deps_batch(base_deps, state_all_deps, actor_offsets, actor_counts):
    """Transitively closes dependency clocks for a batch of changes.

    The reference folds each change's deps through the per-actor state log
    (`op_set.js:29-37`): allDeps = elementwise-max over the allDeps rows of
    every (actor, seq) the change depends on, with the declared dep seqs
    pinned.  For the well-formed inputs the protocol produces (dep frontiers
    and full clocks are self-consistent -- a declared dep is never below what
    another dep transitively implies) pin-and-merge equals elementwise max.

    Per-actor state rows are dense in seq, so row(actor, seq) =
    actor_offsets[actor] + seq - 1.

    Args:
      base_deps: [C, A] int32 -- each change's declared deps (authoring actor
                 pinned to seq-1 already folded in by the caller).
      state_all_deps: [S, A] int32 -- allDeps rows of applied changes, grouped
                 by actor, seq-ascending.
      actor_offsets: [A] int32 -- start row per actor.
      actor_counts:  [A] int32 -- applied changes per actor.

    Returns closed [C, A].
    """
    C, A = base_deps.shape

    def close_one(deps_row):
        def fold(acc, a):
            s = deps_row[a]
            in_state = (s > 0) & (s <= actor_counts[a])
            row_idx = actor_offsets[a] + jnp.maximum(s - 1, 0)
            trans = jnp.where(
                in_state,
                state_all_deps[jnp.clip(row_idx, 0, state_all_deps.shape[0] - 1)],
                jnp.zeros((A,), jnp.int32))
            return jnp.maximum(acc, trans), None
        acc, _ = jax.lax.scan(fold, jnp.zeros((A,), jnp.int32), jnp.arange(A))
        return jnp.maximum(acc, jnp.maximum(deps_row, 0))

    return jax.vmap(close_one)(base_deps)


@jax.jit
def is_concurrent_pairs(clock_a, actor_a, seq_a, clock_b, actor_b, seq_b):
    """Vectorized pairwise concurrency test (reference: op_set.js:7-16):
    two ops are concurrent iff neither one's change clock covers the other.

    All args are [N] (actor ranks) or [N, A] (clocks); returns [N] bool."""
    n = actor_a.shape[0]
    idx = jnp.arange(n)
    a_knows_b = clock_a[idx, actor_b] >= seq_b
    b_knows_a = clock_b[idx, actor_a] >= seq_a
    return ~a_knows_b & ~b_knows_a


def clock_union(clock_a, clock_b):
    """Vector-clock union = elementwise max.  Over a replica mesh axis this
    is `jax.lax.pmax` (see automerge_tpu/parallel/replica.py)."""
    return jnp.maximum(clock_a, clock_b)


def close_batch_all_deps(batch_deps, batch_actor, batch_seq,
                         state_all_deps, actor_offsets, actor_counts,
                         batch_offsets, n_iters):
    """Transitive closure of allDeps for a batch of *applied* changes that may
    depend on each other, via iterative doubling over the dependency DAG
    (log-depth, replacing the reference's sequential per-change fold).

    Applied batch changes are seq-dense per actor: change (a, s) with
    s > actor_counts[a] lives at batch row
    batch_offsets[a] + (s - actor_counts[a] - 1).

    Args:
      batch_deps:  [C, A] declared deps with authoring actor pinned to seq-1.
      batch_actor: [C] int32 (-1 padding).
      batch_seq:   [C] int32.
      state_all_deps: [S, A], actor_offsets/actor_counts: [A] (see
          transitive_deps_batch).
      batch_offsets: [A] int32 -- first batch row per actor (rows grouped by
          actor, seq-ascending), -1 if none.
      n_iters: static int -- ceil(log2(max chain depth)) + 1.

    Returns allDeps [C, A] for every batch change.
    """
    import jax
    import jax.numpy as jnp
    C, A = batch_deps.shape
    S = state_all_deps.shape[0]

    base = jnp.maximum(batch_deps, 0)

    def lookup(table, a, s):
        """allDeps row for dep (a, s): state row, batch row, or zeros."""
        in_state = (s > 0) & (s <= actor_counts[a])
        srow = actor_offsets[a] + jnp.maximum(s - 1, 0)
        state_row = jnp.where(
            in_state,
            state_all_deps[jnp.clip(srow, 0, max(S - 1, 0))],
            jnp.zeros((A,), jnp.int32)) if S > 0 else jnp.zeros((A,), jnp.int32)
        brow = batch_offsets[a] + (s - actor_counts[a] - 1)
        in_batch = (s > actor_counts[a]) & (batch_offsets[a] >= 0) & \
            (brow >= 0) & (brow < C)
        batch_row = jnp.where(
            in_batch, table[jnp.clip(brow, 0, C - 1)], jnp.zeros((A,), jnp.int32))
        return jnp.maximum(state_row, batch_row)

    def one_round(table):
        def close_row(deps_row, table_row):
            def fold(acc, a):
                s = deps_row[a]
                row = jnp.where(s > 0, lookup(table, a, s),
                                jnp.zeros((A,), jnp.int32))
                return jnp.maximum(acc, row), None
            acc, _ = jax.lax.scan(fold, table_row, jnp.arange(A))
            return acc
        return jax.vmap(close_row)(base, table)

    table = base
    for _ in range(n_iters):
        table = one_round(table)
    return table


close_batch_all_deps_jit = jax.jit(close_batch_all_deps,
                                   static_argnames=('n_iters',))
