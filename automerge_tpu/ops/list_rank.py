"""Parallel RGA list linearization.

The reference walks an insertion tree sequentially: children of each parent
sorted descending by (elem-counter, actor), DFS preorder gives the list
order; a skip list maps visible elements to indexes
(`/root/reference/backend/op_set.js:383-437`, `backend/skip_list.js`).

Here the whole forest is linearized in O(log L) parallel steps:

  1. sort elements by (object, parent, -counter, -actor) -> sibling groups
     with first-child / next-sibling links,
  2. resolve each node's DFS "escape" pointer (next sibling, else parent's
     escape) by pointer doubling,
  3. dfs_next = first child else escape; list-rank the dfs_next chain by
     pointer doubling -> total-order rank per element.

RGA guarantees existing elements never reorder when new ones insert, so
ranks computed on the final forest are valid at every intermediate time
step.  Per-op list indexes then become *dominance counts* -- "visible
elements of the same object with smaller rank at time t" -- evaluated as
chunked mask matmuls (MXU work), not sequential skip-list probes.

All (doc, object) segments are flattened into one arena; `obj` ids are dense
ints in [0, L), globally unique across docs, so a single dispatch linearizes
every list of every doc.
"""

from functools import partial

import jax
import jax.numpy as jnp


def ceil_log2(n):
    bits = 0
    while (1 << bits) < max(n, 1):
        bits += 1
    return bits


@jax.jit
def linearize(obj, parent, ctr, actor, valid, n_iters, sort_idx=None):
    """Computes the total RGA order of every element of every list object.

    Args:
      obj:    [L] int32 -- list-object id per element (dense, < L).
      parent: [L] int32 -- arena index of the insertion parent, -1 for head.
      ctr:    [L] int32 -- elemId counter.
      actor:  [L] int32 -- elemId actor rank (string-order preserving).
      valid:  [L] bool.
      n_iters: int >= ceil(log2(L)) + 1 (pointer-doubling rounds).  Runs as
              a dynamic-trip-count device loop: the [L] shapes still key one
              compile per size bucket, but the HLO stays small (the rounds
              are not unrolled), which keeps XLA compile time flat.
      sort_idx: optional [L] int32 -- precomputed host-side sibling sort
              permutation (np.lexsort((-actor, -ctr, parent, obj-with-
              invalid-last))).  XLA:CPU compiles large in-graph sorts in
              tens of seconds, so batch callers hoist the sort to numpy;
              omitted (None) the sort runs in-graph (small per-doc shapes
              under vmap, e.g. the sharded mesh pipeline).

    Returns:
      rank: [L] int32 -- position in the object's total element order
            (counting all elements, visible or not); -1 for invalid rows.
    """
    L = obj.shape[0]
    BIG = jnp.int32(2 ** 30)
    rows = jnp.arange(L)

    # --- 1. sibling sort: (obj, parent, -ctr, -actor); invalid rows last ---
    if sort_idx is None:
        skey_obj = jnp.where(valid, obj, BIG)
        sort_idx = jnp.lexsort((-actor, -ctr, parent, skey_obj))
    s_valid = valid[sort_idx]
    s_obj = jnp.where(s_valid, obj[sort_idx], -2)
    s_parent = jnp.where(s_valid, parent[sort_idx], -3)

    prev_same = (rows > 0) & (jnp.roll(s_obj, 1) == s_obj) \
        & (jnp.roll(s_parent, 1) == s_parent)
    next_same = (rows < L - 1) & (jnp.roll(s_obj, -1) == s_obj) \
        & (jnp.roll(s_parent, -1) == s_parent)

    # next sibling (in descending sibling order): arena index, -1 if last
    nxt_arena = jnp.where(next_same, sort_idx[jnp.clip(rows + 1, 0, L - 1)], -1)
    sib_next = jnp.full((L,), -1, jnp.int32).at[sort_idx].set(nxt_arena)

    # first child per parent element: first sorted row of each
    # (obj, parent >= 0) group
    is_first_nonhead = ~prev_same & (s_parent >= 0) & s_valid
    scatter_tgt = jnp.where(is_first_nonhead, s_parent, L)   # L rows drop
    first_child = jnp.full((L,), -1, jnp.int32).at[scatter_tgt].set(
        jnp.where(is_first_nonhead, sort_idx, -1), mode='drop')

    # --- 2. escape pointers: next sibling, else parent's escape ------------
    # sentinel: -1 = unresolved, -2 = resolved "no escape" (end of object)
    esc0 = jnp.where(sib_next >= 0, sib_next,
                     jnp.where(parent == -1, -2, -1))

    def esc_round(_i, state):
        esc, link = state
        link_safe = jnp.clip(link, 0, L - 1)
        consult = esc[link_safe]
        unresolved = (esc == -1) & (link >= 0)
        esc = jnp.where(unresolved & (consult != -1), consult, esc)
        # shortcut the consult chain (doubling: link <- link's link)
        link = jnp.where(unresolved, link[link_safe], link)
        return esc, link

    esc, _ = jax.lax.fori_loop(0, n_iters + 1, esc_round, (esc0, parent))
    escape = jnp.where(esc == -2, -1, esc)

    # --- 3. dfs_next + list ranking ---------------------------------------
    dfs_next = jnp.where(first_child >= 0, first_child, escape)
    dfs_next = jnp.where(valid, dfs_next, -1)

    def rank_round(_i, state):
        dist, nxt = state
        take = nxt >= 0
        nxt_safe = jnp.clip(nxt, 0, L - 1)
        dist = dist + jnp.where(take, dist[nxt_safe], 0)
        nxt = jnp.where(take, nxt[nxt_safe], nxt)
        return dist, nxt

    dist, _ = jax.lax.fori_loop(
        0, n_iters,
        rank_round,
        (jnp.where(dfs_next >= 0, 1, 0).astype(jnp.int32), dfs_next))

    # per-object element count -> rank = size - 1 - hops_to_end
    obj_sizes = jax.ops.segment_sum(
        valid.astype(jnp.int32), jnp.where(valid, obj, L),
        num_segments=L + 1)
    size_of_elem = obj_sizes[jnp.clip(obj, 0, L)]
    rank = jnp.where(valid, size_of_elem - 1 - dist, -1)
    return rank


@partial(jax.jit, static_argnames=('chunk',))
def dominance_grouped(vis0, elem_rank, op_elem, op_rank, op_delta, op_valid,
                      chunk=64):
    """Per-object dominance indexes: like `dominance_indexes`, but the batch
    axis IS the list-object axis, so the same-object mask term vanishes and
    per-chunk work is O(L_obj * K) instead of O(L_total * K).

    Args:
      vis0:      [O, L] float32 -- visibility (0/1) per element at batch
                 start; padding rows are 0.
      elem_rank: [O, L] int32 -- total-order rank per element (-1 padding;
                 never counted because vis stays 0 there).
      op_elem:   [O, T] int32 -- local element index each op toggles
                 (-1 = padding).
      op_rank:   [O, T] int32 -- rank of the touched element.
      op_delta:  [O, T] int32 -- visibility change in {-1, 0, +1}.
      op_valid:  [O, T] bool.
      chunk: static int; T must be a multiple of it.

    Returns: index [O, T] int32.
    """
    O, L = vis0.shape
    T = op_elem.shape[1]
    K = chunk
    if T % K != 0:
        raise ValueError('T=%d must be a multiple of chunk=%d' % (T, K))
    n_chunks = T // K
    tri = (jnp.arange(K)[:, None] < jnp.arange(K)[None, :])

    def per_obj(vis, rank, oe, orank, od, ov):
        def body(vis, c):
            sl = c * K
            e = jax.lax.dynamic_slice(oe, (sl,), (K,))
            r = jax.lax.dynamic_slice(orank, (sl,), (K,))
            d = jax.lax.dynamic_slice(od, (sl,), (K,))
            v = jax.lax.dynamic_slice(ov, (sl,), (K,))
            d = jnp.where(v, d, 0)   # padding rows must not leak into corr
            # base: visible elements ranked below, at chunk start
            mask = (rank[:, None] < r[None, :])                     # [L, K]
            base = vis @ mask.astype(jnp.float32)                   # [K]
            # within-chunk: earlier op j toggling a lower-ranked element
            cross = tri & (r[:, None] < r[None, :])
            corr = jnp.sum(cross * d[:, None].astype(jnp.float32), axis=0)
            idx = (base + corr).astype(jnp.int32)
            upd = jax.ops.segment_sum(
                jnp.where(v, d, 0).astype(jnp.float32),
                jnp.clip(jnp.where(v & (e >= 0), e, L), 0, L),
                num_segments=L + 1)[:L]
            return vis + upd, idx
        _, idxs = jax.lax.scan(body, vis, jnp.arange(n_chunks))
        return idxs.reshape(-1)

    return jax.vmap(per_obj)(vis0, elem_rank, op_elem, op_rank,
                             op_delta, op_valid)


@partial(jax.jit, static_argnames=('chunk', 'axis_name'))
def dominance_indexes(elem_obj, elem_rank, vis0, op_elem, op_obj, op_rank,
                      op_delta, op_valid, chunk=128, axis_name=None,
                      l_offset=0):
    """Per-op list indexes as time-windowed dominance counts.

    index(op t on element e) = #{e' : obj(e') == obj(e), rank(e') < rank(e),
                                 visible just before t}

    Visibility evolves one element per op (op_delta in {-1, 0, +1}).  Ops are
    processed in application order in chunks: each chunk queries the running
    visibility vector with one [L] x [L, K] mask product (MXU work), then
    applies within-chunk pairwise corrections (K x K) and updates the vector.

    Sequence-parallel mode (`axis_name` set, inside shard_map): the element
    arrays hold only this device's block of the arena; base counts become
    partial sums completed with `lax.psum` over `axis_name`, and visibility
    updates apply only to ops whose global element index (rebased by
    `l_offset`) falls inside the local block.

    Args:
      elem_obj: [L] int32, elem_rank: [L] int32, vis0: [L] float32 (0/1).
      op_elem: [T] int32 -- arena element index each op touches (-1 = none);
               global indexes in sequence-parallel mode.
      op_obj:  [T] int32, op_rank: [T] int32 -- of the touched element.
      op_delta:[T] int32 -- visibility change this op causes.
      op_valid:[T] bool.
      chunk: static int.
      axis_name: static -- mesh axis to psum partial counts over, or None.
      l_offset: int -- global index of this device's first element.

    Returns: index [T] int32 -- visible-before-e count for each op.
    """
    L = elem_obj.shape[0]
    T = op_elem.shape[0]
    K = chunk
    n_chunks = (T + K - 1) // K
    Tp = n_chunks * K

    def pad(x, fill):
        return jnp.concatenate(
            [x, jnp.full((Tp - T,) + x.shape[1:], fill, x.dtype)])

    op_elem_p = pad(op_elem, -1)
    op_obj_p = pad(op_obj, -2)
    op_rank_p = pad(op_rank, -1)
    op_delta_p = pad(op_delta, 0)
    op_valid_p = pad(op_valid, False)

    def body(vis, c):
        sl = c * K
        e = jax.lax.dynamic_slice(op_elem_p, (sl,), (K,))
        o = jax.lax.dynamic_slice(op_obj_p, (sl,), (K,))
        r = jax.lax.dynamic_slice(op_rank_p, (sl,), (K,))
        d = jax.lax.dynamic_slice(op_delta_p, (sl,), (K,))
        v = jax.lax.dynamic_slice(op_valid_p, (sl,), (K,))

        # base counts against visibility at chunk start: [L, K] mask
        mask = (elem_obj[:, None] == o[None, :]) \
            & (elem_rank[:, None] < r[None, :])
        base = vis @ mask.astype(jnp.float32)                      # [K]
        if axis_name is not None:
            base = jax.lax.psum(base, axis_name)

        # within-chunk corrections: op j before op k, same object, and the
        # element op j touches ranks below op k's element
        cross = (jnp.arange(K)[:, None] < jnp.arange(K)[None, :]) \
            & (o[:, None] == o[None, :]) & (r[:, None] < r[None, :])
        corr = jnp.sum(cross * d[:, None].astype(jnp.float32), axis=0)  # [K]

        idx = (base + corr).astype(jnp.int32)

        # visibility update: net delta per element of the local block
        le = e - l_offset
        in_block = (le >= 0) & (le < L) & v
        upd = jax.ops.segment_sum(
            jnp.where(in_block, d, 0).astype(jnp.float32),
            jnp.clip(jnp.where(in_block, le, L), 0, L),
            num_segments=L + 1)[:L]
        vis = vis + upd
        return vis, idx

    _, idxs = jax.lax.scan(body, vis0, jnp.arange(n_chunks))
    return idxs.reshape(-1)[:T]
