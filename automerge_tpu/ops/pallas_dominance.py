"""Pallas TPU kernel for per-object dominance indexes.

Same contract as `list_rank.dominance_grouped` (reference semantics:
SkipList index queries, /root/reference/backend/skip_list.js:261-279,
batched as time-windowed dominance counts): for each list object, walk its
op timeline in chunks of K, counting visible lower-ranked elements per op
against a running visibility vector.

The Pallas formulation keeps the per-object visibility vector resident in
VMEM scratch across the whole timeline (the XLA version re-materializes it
through the scan carry), and drives the three inner products per chunk --
base counts, within-chunk corrections, visibility update -- as explicit
VMEM-blocked compute:

  grid = (W,)   one program per list object; per program:
    vis   [1, L]  f32  scratch, initialized from v0
    per chunk c:
      maskT [K, L] = (rank_chunk[:, None] > elem_rank[None, :])
      base  = maskT @ vis^T                      (MXU, [K, 1])
      corr  = lower-tri within-chunk correction  (VPU, [K, K])
      vis  += sum_k delta_k * onehot(elem_k)     (VPU, [K, L])

Eligibility: L and K multiples of 128/lane tiling are padded by the
caller's shape buckets; the dispatcher `dominance_grouped_auto` falls back
to the XLA kernel off-TPU or for tiny shapes.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import list_rank


# objects processed per grid program (the sublane tiling minimum)
_ROWS = 8


def _kernel(v0_ref, er_ref, oe_ref, orank_ref, od_ref, ov_ref, idx_ref,
            vis_ref, *, n_chunks, K, L):
    R = _ROWS
    vis_ref[:] = v0_ref[:]
    er = er_ref[:]                      # [R, L] int32
    tri = (jax.lax.broadcasted_iota(jnp.int32, (K, K), 0) <
           jax.lax.broadcasted_iota(jnp.int32, (K, K), 1))

    def chunk(c, _):
        sl = c * K
        e = oe_ref[:, pl.ds(sl, K)]                    # [R, K]
        r = orank_ref[:, pl.ds(sl, K)]
        v = ov_ref[:, pl.ds(sl, K)]
        # padding rows carry d=0 into corr regardless of caller zero-fill
        d = od_ref[:, pl.ds(sl, K)].astype(jnp.float32) * \
            v.astype(jnp.float32)

        # base: visible elements with rank below, at chunk start
        # (multiply-reduce on the VPU; Mosaic rejects batched dot_general)
        maskT = (r[:, :, None] > er[:, None, :]).astype(jnp.float32)
        base = jnp.sum(maskT * vis_ref[:][:, None, :], axis=2)   # [R, K]

        # within-chunk: earlier op j toggling a lower-ranked element
        # (masks kept f32: Mosaic only broadcasts a new minor dim for
        # 32-bit types, so bool [R, K, None] inserts will not lower)
        cross = (tri[None] & (r[:, :, None] < r[:, None, :])) \
            .astype(jnp.float32)                              # [R, K, K]
        corr = jnp.sum(cross * d[:, :, None], axis=1)         # [R, K]

        idx_ref[:, pl.ds(sl, K)] = (base + corr).astype(jnp.int32)

        # visibility update: one-hot scatter as a masked broadcast-sum
        le = jax.lax.broadcasted_iota(jnp.int32, (R, K, L), 2)
        vmask = (v.astype(jnp.float32) *
                 (e >= 0).astype(jnp.float32) * d)            # [R, K]
        hot = (le == e[:, :, None]).astype(jnp.float32)
        vis_ref[:] = vis_ref[:] + jnp.sum(hot * vmask[:, :, None], axis=1)
        return 0

    jax.lax.fori_loop(0, n_chunks, chunk, 0)


@functools.partial(jax.jit, static_argnames=('chunk', 'interpret'))
def dominance_grouped_pallas(vis0, elem_rank, op_elem, op_rank, op_delta,
                             op_valid, chunk=64, interpret=False):
    """Drop-in for `list_rank.dominance_grouped` on TPU.  `interpret=True`
    runs the kernel in the Pallas interpreter (CPU-testable)."""
    W, L = vis0.shape
    T = op_elem.shape[1]
    K = chunk
    if T % K != 0:
        raise ValueError('T=%d must be a multiple of chunk=%d' % (T, K))
    if W % _ROWS != 0:
        raise ValueError('W=%d must be a multiple of %d' % (W, _ROWS))
    n_chunks = T // K

    spec_l = pl.BlockSpec((_ROWS, L), lambda o: (o, 0))
    spec_t = pl.BlockSpec((_ROWS, T), lambda o: (o, 0))
    return pl.pallas_call(
        functools.partial(_kernel, n_chunks=n_chunks, K=K, L=L),
        grid=(W // _ROWS,),
        out_shape=jax.ShapeDtypeStruct((W, T), jnp.int32),
        in_specs=[spec_l, spec_l, spec_t, spec_t, spec_t, spec_t],
        out_specs=spec_t,
        scratch_shapes=[pltpu.VMEM((_ROWS, L), jnp.float32)],
        interpret=interpret,
    )(vis0.astype(jnp.float32), elem_rank, op_elem, op_rank,
      op_delta.astype(jnp.int32), op_valid.astype(jnp.int32))


def _use_pallas():
    from .pallas_common import pallas_enabled
    return pallas_enabled()


def dominance_grouped_auto(vis0, elem_rank, op_elem, op_rank, op_delta,
                           op_valid, chunk=64):
    """Pallas on TPU when the lane tiling fits; XLA kernel otherwise.
    Both paths compute identical outputs (pinned by unit test)."""
    W, L = vis0.shape
    T = op_elem.shape[1]
    # The pallas path always chunks by 128: Mosaic requires lane-dimension
    # slice offsets provably 128-aligned, and chunk width changes only the
    # work grouping, never the result.  VMEM budget (~16 MiB/core): two
    # live [ROWS, 128, L] f32 chunk temporaries plus six [ROWS, T] i32
    # timeline blocks must fit with headroom.
    PK = 128
    vmem_bytes = 2 * _ROWS * PK * L * 4 + 6 * _ROWS * T * 4
    if (_use_pallas() and L % 128 == 0 and T % PK == 0
            and W % _ROWS == 0 and vmem_bytes <= 10 * 2 ** 20):
        return dominance_grouped_pallas(
            vis0, elem_rank, op_elem, op_rank, op_delta, op_valid,
            chunk=PK)
    return list_rank.dominance_grouped(
        vis0, elem_rank, op_elem, op_rank, op_delta, op_valid, chunk=chunk)
