"""Pallas TPU kernel for sliding-window register resolution.

Same contract as `registers.resolve_registers` (reference semantics:
partition register ops into overwritten vs concurrent, winner = max
actor, `/root/reference/backend/op_set.js:188-231`), restricted to the
sorted sliding-window form: after the host's (group, time) sort, the
candidate predecessors of sorted row i are exactly rows i-W..i-1, so
member formation is a stencil -- no gathers anywhere.

What Pallas buys over the XLA twin: the [B, W+1, A] one-hot and the
[B, W+1, W+1] pairwise concurrency/supersession intermediates live and
die in VMEM per 128-row block instead of materializing [T, W+1, A] /
[T, W+1, W+1] through HBM -- on a v5e the XLA formulation's HBM traffic
is ~(W+1)x the input volume, which is the whole cost of this
bandwidth-bound kernel (the MXU work is one tiny clock*onehot product
per block).

Ordering without argsort (Mosaic has no stable sort): survivor output
order is (actor desc, time desc) and times are unique, so each alive
member's output position is a PAIRWISE COUNT --
  pos(u) = #{v alive : actor_v > actor_u
                       or (actor_v == actor_u and time_v > time_u)}
-- and winner/conflicts scatter through a position one-hot.  Bit-equal
to the XLA twin's two stable argsorts (pinned by
tests/test_ops_kernels.py::TestPallasRegisters).

Auto-dispatch: `resolve_registers_auto` uses the Pallas kernel on TPU
when shapes fit (T % 128 == 0, VMEM budget, W <= 8) and falls back to
the XLA kernel otherwise -- including on ANY compile/lowering failure,
which latches the Pallas path off for the process (the tunneled-TPU
image cannot be compile-probed at import time).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import registers as xla_registers

_B = 128      # sorted rows per grid program
_PADW = 128   # front pad so halo loads stay 128-aligned


def _kernel(g_ref, t_ref, a_ref, q_ref, d_ref, c_ref, src_ref,
            winner_ref, conflicts_ref, alive_ref, vb_ref, ovf_ref,
            g_s, t_s, a_s, q_s, d_s, src_s, c_s, sems, *, W, A):
    b = pl.program_id(0)
    start = b * _B

    # halo DMA: rows [start, start + PADW + B) of each padded column
    cols = ((g_ref, g_s), (t_ref, t_s), (a_ref, a_s), (q_ref, q_s),
            (d_ref, d_s), (src_ref, src_s))
    dmas = []
    for i, (ref, scratch) in enumerate(cols):
        dmas.append(pltpu.make_async_copy(
            ref.at[pl.ds(start, _PADW + _B)], scratch, sems.at[i]))
    dmas.append(pltpu.make_async_copy(
        c_ref.at[pl.ds(start, _PADW + _B)], c_s, sems.at[len(cols)]))
    for d in dmas:
        d.start()
    for d in dmas:
        d.wait()

    def members(col):
        """[B, W+1]: slot 0 = self, slot w = w-th predecessor."""
        return jnp.stack(
            [jax.lax.slice_in_dim(col, _PADW - w, _PADW - w + _B, axis=0)
             for w in range(W + 1)], axis=1)

    m_g = members(g_s[:])
    m_t = members(t_s[:])
    m_a = members(a_s[:])
    m_q = members(q_s[:])
    m_d = members(d_s[:])
    m_src = members(src_s[:])
    g_cur = m_g[:, 0]
    m_valid = (m_g == g_cur[:, None]) & (g_cur >= 0)[:, None]   # [B, W+1]

    # member clocks: [B, W+1, A] slices of the halo clock block
    m_clk = jnp.stack(
        [jax.lax.slice_in_dim(c_s[:], _PADW - w, _PADW - w + _B, axis=0)
         for w in range(W + 1)], axis=1)

    # P[b, u, v] = clock_u[actor_v] via one-hot multiply-reduce (Mosaic
    # rejects batched dot_general; the temporaries stay in VMEM).  All
    # arithmetic stays int32: float32 would silently round seqs/clock
    # entries at 2^24, flipping supersession verdicts for long-lived
    # actors -- the XLA twin compares in int32.
    lanes = jax.lax.broadcasted_iota(jnp.int32, (_B, W + 1, A), 2)
    onehot = (lanes == m_a[:, :, None]).astype(jnp.int32)
    P = jnp.sum(m_clk[:, :, None, :] * onehot[:, None, :, :], axis=3)
    u_seq = m_q[:, :, None]
    v_seq = m_q[:, None, :]
    concurrent = (P < v_seq) & (jnp.swapaxes(P, 1, 2) < u_seq)
    later = (jax.lax.broadcasted_iota(jnp.int32, (W + 1, W + 1), 0) <
             jax.lax.broadcasted_iota(jnp.int32, (W + 1, W + 1), 1))
    supersedes = later[None] & ~concurrent \
        & m_valid[:, :, None] & m_valid[:, None, :]

    superseded = jnp.sum(supersedes.astype(jnp.int32), axis=1) > 0
    m_alive = m_valid & ~superseded & (m_d == 0)
    superseded_wo_self = \
        jnp.sum(supersedes[:, 1:, :].astype(jnp.int32), axis=1) > 0
    alive_before = m_valid & ~superseded_wo_self & (m_d == 0)
    vb_ref[:] = (jnp.sum(alive_before[:, 1:].astype(jnp.int32), axis=1)
                 > 0).astype(jnp.int32)
    alive_ref[:] = jnp.sum(m_alive.astype(jnp.int32), axis=1)

    # output position by pairwise count: (actor desc, time desc)
    a_u = m_a[:, :, None]
    a_v = m_a[:, None, :]
    t_u = m_t[:, :, None]
    t_v = m_t[:, None, :]
    precede = m_alive[:, None, :] & \
        ((a_v > a_u) | ((a_v == a_u) & (t_v > t_u)))           # v before u
    pos = jnp.sum(precede.astype(jnp.int32), axis=2)           # [B, W+1]

    winner_ref[:] = jnp.sum(
        jnp.where((pos == 0) & m_alive, m_src + 1, 0), axis=1) - 1
    kpos = jax.lax.broadcasted_iota(jnp.int32, (_B, W + 1, W), 2)
    poh = (pos[:, :, None] == kpos + 1) & m_alive[:, :, None]
    conflicts_ref[:] = jnp.sum(
        jnp.where(poh, (m_src + 1)[:, :, None], 0), axis=1) - 1

    window_full = jnp.sum(m_valid[:, 1:].astype(jnp.int32), axis=1) == W
    ovf_ref[:] = (window_full & (g_cur >= 0)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=('window', 'interpret'))
def resolve_registers_pallas(group, time, actor, seq, is_del, sort_idx,
                             clock_table, clock_idx, window=8,
                             interpret=False):
    """Drop-in for `registers.resolve_registers` (sliding-window mode).

    Same arguments as the XLA twin's (clock_table, clock_idx) form;
    `interpret=True` runs in the Pallas interpreter (CPU-testable).
    """
    T = group.shape[0]
    W = window
    A = clock_table.shape[1]
    if T % _B != 0:
        raise ValueError('T=%d must be a multiple of %d' % (T, _B))

    clock = clock_table[jnp.asarray(clock_idx)]
    g_s = jnp.asarray(group)[sort_idx]
    t_s = jnp.asarray(time)[sort_idx]
    a_s = jnp.asarray(actor)[sort_idx]
    q_s = jnp.asarray(seq)[sort_idx]
    c_s = clock[sort_idx]
    d_s = jnp.asarray(is_del).astype(jnp.int32)[sort_idx]
    src = jnp.asarray(sort_idx, jnp.int32)

    def pad(x, fill):
        return jnp.concatenate(
            [jnp.full((_PADW,) + x.shape[1:], fill, x.dtype), x])

    outs = pl.pallas_call(
        functools.partial(_kernel, W=W, A=A),
        grid=(T // _B,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 7,
        out_specs=[pl.BlockSpec((_B,), lambda b: (b,)),
                   pl.BlockSpec((_B, W), lambda b: (b, 0)),
                   pl.BlockSpec((_B,), lambda b: (b,)),
                   pl.BlockSpec((_B,), lambda b: (b,)),
                   pl.BlockSpec((_B,), lambda b: (b,))],
        out_shape=[jax.ShapeDtypeStruct((T,), jnp.int32),
                   jax.ShapeDtypeStruct((T, W), jnp.int32),
                   jax.ShapeDtypeStruct((T,), jnp.int32),
                   jax.ShapeDtypeStruct((T,), jnp.int32),
                   jax.ShapeDtypeStruct((T,), jnp.int32)],
        scratch_shapes=[pltpu.VMEM((_PADW + _B,), jnp.int32)
                        for _ in range(6)] +
                       [pltpu.VMEM((_PADW + _B, A), jnp.int32),
                        pltpu.SemaphoreType.DMA((7,))],
        interpret=interpret,
    )(pad(g_s, -2), pad(t_s, 0), pad(a_s, 0), pad(q_s, 0), pad(d_s, 0),
      pad(c_s, 0), pad(src, -1))
    winner_s, conflicts_s, alive_s, vb_s, ovf_s = outs

    # scatter back to original row order + the packed transfer summary
    # (same layout as the XLA twin)
    out = {
        'alive_after':
            jnp.zeros((T,), jnp.int32).at[sort_idx].set(alive_s),
        'winner': jnp.full((T,), -1, jnp.int32).at[sort_idx].set(winner_s),
        'conflicts':
            jnp.full((T, W), -1, jnp.int32).at[sort_idx].set(conflicts_s),
        'visible_before':
            jnp.zeros((T,), jnp.bool_).at[sort_idx].set(vb_s > 0),
        'overflow':
            jnp.zeros((T,), jnp.bool_).at[sort_idx].set(ovf_s > 0),
    }
    out['packed'] = xla_registers.pack_register_word(
        out['winner'], out['alive_after'], out['overflow'])
    return out


_pallas_broken = False
# first-call validation is per compiled shape: a new (T, window, A)
# triggers a fresh Mosaic compile whose runtime faults (DMA/VMEM at
# execution, not lowering) must be caught here, not at the async
# collect site
_pallas_validated_shapes = set()


def _use_pallas():
    from .pallas_common import pallas_enabled
    return not _pallas_broken and pallas_enabled()


def resolve_registers_auto(group, time, actor, seq, is_del, alive_in,
                           sort_idx, clock_table, clock_idx, window=8):
    """Pallas on TPU when shapes fit; the XLA kernel otherwise.  Both
    paths compute identical outputs (pinned by unit test).

    Failure handling: the FIRST Pallas call per compiled shape
    (T, window, A) blocks on its outputs inside the try, so
    deterministic lowering/runtime faults (Mosaic rejection, DMA fault,
    VMEM OOM) latch the path off and fall back to XLA with an
    observable metric (`report_latch`) instead of crashing every batch
    at the async collect site.  Once a shape is validated, later calls
    with that shape return lazily for normal async overlap.
    """
    global _pallas_broken
    T = group.shape[0]
    A = clock_table.shape[1]
    # VMEM budget: clock halo [256, A] + the [B, W+1, W+1, A] concurrency
    # temporary dominate
    vmem = 256 * A * 4 + _B * (window + 1) * (window + 1) * A * 4
    # the Pallas kernel hardcodes all-alive starting state; a caller
    # with a non-trivial alive_in mask must route to the XLA twin.  The
    # mask scan goes LAST in the conjunction: it may force a host sync
    # on a device-resident mask, so only pay it when the Pallas path
    # would otherwise engage.
    if (_use_pallas() and T % _B == 0 and window <= 8
            and vmem <= 10 * 2 ** 20
            and bool(np.all(np.asarray(alive_in)))):
        try:
            out = resolve_registers_pallas(
                group, time, actor, seq, is_del, sort_idx,
                clock_table, clock_idx, window=window)
            shape_key = (T, window, A)
            if shape_key not in _pallas_validated_shapes:
                jax.block_until_ready(out)
                _pallas_validated_shapes.add(shape_key)
            return out
        except Exception as e:
            _pallas_broken = True
            from .pallas_common import report_latch
            report_latch('registers', e)
    return xla_registers.resolve_registers(
        group, time, actor, seq, is_del=is_del, alive_in=alive_in,
        window=window, sort_idx=sort_idx, clock_table=clock_table,
        clock_idx=clock_idx)
