"""Shared Pallas dispatch gate for the TPU kernel twins.

One definition of "should a Pallas formulation run here": on-TPU check
cached once per process, `AMTPU_NO_PALLAS` kill switch re-read per call.
Per-kernel latches (e.g. lowering failures) layer on top in each
kernel's module.
"""

import functools
import sys

import jax
from ..utils.common import env_bool


def _on_tpu():
    try:
        return jax.devices()[0].platform == 'tpu'
    except Exception:
        return False


@functools.lru_cache(maxsize=1)
def on_tpu_cached():
    return _on_tpu()


def pallas_enabled():
    if env_bool('AMTPU_NO_PALLAS', False):
        return False
    return on_tpu_cached()


def report_latch(kernel, exc):
    """A Pallas kernel failed to lower/run and latched itself off: make
    that observable -- always-on metric (bench JSON surfaces it), trace
    counter, and one stderr line with the lost exception text."""
    from .. import trace
    trace.metric('fallback.pallas_%s_latch' % kernel)
    trace.count('pallas.%s_latch' % kernel)
    print('amtpu: pallas %s kernel latched off: %r' % (kernel, exc),
          file=sys.stderr)
