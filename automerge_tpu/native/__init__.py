"""Native host runtime bindings.

Loads `libamtpu_core.so` (built from /root/repo/native/) and exposes
`NativeDocPool`: the C++ host runtime driving the same JAX device kernels
as the Python `TPUDocPool`, with all per-op host stages (causal scheduling,
columnar encoding, patch emission, mirror maintenance) in C++ and
changes/patches crossing the boundary as msgpack bytes.

`NativeDocPool.apply_batch(dict)` round-trips through msgpack for drop-in
test parity with TPUDocPool; `apply_batch_bytes(bytes) -> bytes` is the
zero-Python wire path the sidecar serves.
"""

import ctypes
import os
import subprocess

import msgpack
import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_DIR)), 'native')
_LIB_PATH = os.path.join(_DIR, 'libamtpu_core.so')


def _build():
    subprocess.run(['make'], cwd=_SRC, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def _load():
    if not os.path.exists(_LIB_PATH) or (
            os.path.exists(os.path.join(_SRC, 'core.cpp')) and
            os.path.getmtime(os.path.join(_SRC, 'core.cpp')) >
            os.path.getmtime(_LIB_PATH)):
        _build()
    lib = ctypes.CDLL(_LIB_PATH)
    lib.amtpu_pool_new.restype = ctypes.c_void_p
    lib.amtpu_pool_free.argtypes = [ctypes.c_void_p]
    lib.amtpu_last_error.restype = ctypes.c_char_p
    lib.amtpu_last_error_kind.restype = ctypes.c_int
    lib.amtpu_begin.restype = ctypes.c_void_p
    lib.amtpu_begin.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int64]
    lib.amtpu_batch_free.argtypes = [ctypes.c_void_p]
    lib.amtpu_batch_dims.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_int64)]
    for name in ('g', 't', 'a', 's', 'clock', 'sort',
                 'obj', 'par', 'ctr', 'act', 'linsort'):
        fn = getattr(lib, 'amtpu_col_' + name)
        fn.restype = ctypes.POINTER(ctypes.c_int32)
        fn.argtypes = [ctypes.c_void_p]
    for name in ('d', 'val'):
        fn = getattr(lib, 'amtpu_col_' + name)
        fn.restype = ctypes.POINTER(ctypes.c_uint8)
        fn.argtypes = [ctypes.c_void_p]
    lib.amtpu_mid.restype = ctypes.c_int
    lib.amtpu_mid.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32)]
    lib.amtpu_dom_dims.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                   ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_dom_v0.restype = ctypes.POINTER(ctypes.c_float)
    lib.amtpu_dom_v0.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    for name in ('er', 'oe', 'orank', 'od'):
        fn = getattr(lib, 'amtpu_dom_' + name)
        fn.restype = ctypes.POINTER(ctypes.c_int32)
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.amtpu_dom_ov.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_dom_ov.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.amtpu_dom_set_indexes.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                          ctypes.POINTER(ctypes.c_int32)]
    lib.amtpu_finish.restype = ctypes.c_int
    lib.amtpu_finish.argtypes = [ctypes.c_void_p]
    lib.amtpu_result.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_result.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_get_patch.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_get_patch.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_get_missing_deps.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_get_missing_deps.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_get_missing_changes.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_get_missing_changes.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_buf_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    return lib


_lib = None


def lib():
    global _lib
    if _lib is None:
        _lib = _load()
    return _lib


def _np_view(ptr, shape, dtype):
    n = int(np.prod(shape))
    if n == 0:
        return np.zeros(shape, dtype)
    arr = np.ctypeslib.as_array(ptr, shape=(n,))
    return arr.reshape(shape).view(dtype) if arr.dtype != dtype else \
        arr.reshape(shape)


def _take_buf(ptr, length):
    try:
        return bytes(bytearray(ctypes.cast(
            ptr, ctypes.POINTER(ctypes.c_uint8 * length)).contents))
    finally:
        lib().amtpu_buf_free(ptr)


class NativeError(Exception):
    pass


def _raise_last():
    from ..errors import AutomergeError, RangeError
    msg = lib().amtpu_last_error().decode()
    kind = lib().amtpu_last_error_kind()
    raise (RangeError if kind == 1 else AutomergeError)(msg)


class NativeDocPool:
    """C++ host runtime + JAX kernels; drop-in for TPUDocPool."""

    #: window width of the register kernel (ops/registers.WINDOW)
    WINDOW = 8

    def __init__(self):
        self._pool = lib().amtpu_pool_new()

    def __del__(self):
        if getattr(self, '_pool', None):
            lib().amtpu_pool_free(self._pool)
            self._pool = None

    # -- wire path ------------------------------------------------------

    def apply_batch_bytes(self, payload):
        """msgpack {doc_id: [change...]} -> msgpack {doc_id: patch}."""
        L = lib()
        bh = L.amtpu_begin(self._pool, payload, len(payload))
        if not bh:
            _raise_last()
        try:
            dims = (ctypes.c_int64 * 8)()
            L.amtpu_batch_dims(bh, dims)
            T, Tp, A, Ap, Larena, Lp, n_blocks, max_obj = \
                [int(x) for x in dims]

            reg_out = self._run_register_kernel(L, bh, Tp, Ap)
            rank = self._run_linearize(L, bh, Lp, max_obj)

            win = ctypes.POINTER(ctypes.c_int32)
            if Tp > 0:
                winner = np.ascontiguousarray(reg_out['winner'], np.int32)
                conflicts = np.ascontiguousarray(reg_out['conflicts'],
                                                 np.int32)
                alive = np.ascontiguousarray(reg_out['alive_after'], np.int32)
                visible = np.ascontiguousarray(
                    reg_out['visible_before'], np.uint8)
                overflow = np.ascontiguousarray(reg_out['overflow'], np.uint8)
            else:
                winner = conflicts = alive = np.zeros(0, np.int32)
                visible = overflow = np.zeros(0, np.uint8)
            rank_arr = np.ascontiguousarray(rank, np.int32)

            def ip(a):
                return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

            def up(a):
                return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))

            if L.amtpu_mid(bh, ip(winner), ip(conflicts), self.WINDOW,
                           ip(alive), up(visible), up(overflow),
                           ip(rank_arr)) != 0:
                _raise_last()

            self._run_dominance(L, bh)

            if L.amtpu_finish(bh) != 0:
                _raise_last()
            out_len = ctypes.c_int64()
            ptr = L.amtpu_result(bh, ctypes.byref(out_len))
            return bytes(bytearray(ctypes.cast(
                ptr, ctypes.POINTER(
                    ctypes.c_uint8 * out_len.value)).contents)) \
                if out_len.value else b'\x80'
        finally:
            L.amtpu_batch_free(bh)

    # -- kernel dispatch ------------------------------------------------

    def _run_register_kernel(self, L, bh, Tp, Ap):
        if Tp == 0:
            return None
        from ..ops import registers as register_ops
        g = np.ctypeslib.as_array(L.amtpu_col_g(bh), shape=(Tp,))
        t = np.ctypeslib.as_array(L.amtpu_col_t(bh), shape=(Tp,))
        a = np.ctypeslib.as_array(L.amtpu_col_a(bh), shape=(Tp,))
        s = np.ctypeslib.as_array(L.amtpu_col_s(bh), shape=(Tp,))
        d = np.ctypeslib.as_array(L.amtpu_col_d(bh), shape=(Tp,))
        c = np.ctypeslib.as_array(L.amtpu_col_clock(bh), shape=(Tp, Ap))
        si = np.ctypeslib.as_array(L.amtpu_col_sort(bh), shape=(Tp,))
        out = register_ops.resolve_registers(
            g, t, a, s, c, d.astype(bool), np.ones((Tp,), bool),
            window=self.WINDOW, sort_idx=si)
        return {k: np.asarray(v) for k, v in out.items()}

    def _run_linearize(self, L, bh, Lp, max_obj_len):
        if Lp == 0:
            return np.zeros((0,), np.int32)
        from ..ops import list_rank
        obj = np.ctypeslib.as_array(L.amtpu_col_obj(bh), shape=(Lp,))
        par = np.ctypeslib.as_array(L.amtpu_col_par(bh), shape=(Lp,))
        ctr = np.ctypeslib.as_array(L.amtpu_col_ctr(bh), shape=(Lp,))
        act = np.ctypeslib.as_array(L.amtpu_col_act(bh), shape=(Lp,))
        val = np.ctypeslib.as_array(L.amtpu_col_val(bh), shape=(Lp,))
        si = np.ctypeslib.as_array(L.amtpu_col_linsort(bh), shape=(Lp,))
        # pointer-doubling depth: DFS chains never cross objects, so the
        # bound is the largest single arena, not the whole flat batch
        return np.asarray(list_rank.linearize(
            obj, par, ctr, act, val.astype(bool),
            n_iters=list_rank.ceil_log2(max(max_obj_len, 1)) + 1,
            sort_idx=si))

    def _run_dominance(self, L, bh):
        from ..ops import list_rank
        dims = (ctypes.c_int64 * 7)()
        L.amtpu_batch_dims(bh, dims)
        n_blocks = int(dims[6])
        bdims = (ctypes.c_int64 * 3)()
        for blk in range(n_blocks):
            L.amtpu_dom_dims(bh, blk, bdims)
            W, Lp, Tp = [int(x) for x in bdims]
            v0 = np.ctypeslib.as_array(L.amtpu_dom_v0(bh, blk),
                                       shape=(W, Lp))
            er = np.ctypeslib.as_array(L.amtpu_dom_er(bh, blk),
                                       shape=(W, Lp))
            oe = np.ctypeslib.as_array(L.amtpu_dom_oe(bh, blk),
                                       shape=(W, Tp))
            orank = np.ctypeslib.as_array(L.amtpu_dom_orank(bh, blk),
                                          shape=(W, Tp))
            od = np.ctypeslib.as_array(L.amtpu_dom_od(bh, blk),
                                       shape=(W, Tp))
            ov = np.ctypeslib.as_array(L.amtpu_dom_ov(bh, blk),
                                       shape=(W, Tp))
            idx = np.ascontiguousarray(np.asarray(list_rank.dominance_grouped(
                v0, er, oe, orank, od, ov.astype(bool),
                chunk=64)), np.int32)
            L.amtpu_dom_set_indexes(
                bh, blk, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))

    # -- dict-level API (test parity with TPUDocPool) -------------------

    @staticmethod
    def _doc_key(doc_id):
        return doc_id if isinstance(doc_id, str) else 'i:%d' % doc_id

    def apply_batch(self, changes_by_doc):
        keyed = {self._doc_key(d): chs for d, chs in changes_by_doc.items()}
        payload = msgpack.packb(keyed, use_bin_type=True)
        out = msgpack.unpackb(self.apply_batch_bytes(payload),
                              raw=False, strict_map_key=False)
        return {d: out[self._doc_key(d)] for d in changes_by_doc}

    def apply_changes(self, doc_id, changes):
        return self.apply_batch({doc_id: changes})[doc_id]

    def get_patch(self, doc_id):
        out_len = ctypes.c_int64()
        ptr = lib().amtpu_get_patch(
            self._pool, self._doc_key(doc_id).encode(),
            ctypes.byref(out_len))
        if not ptr:
            _raise_last()
        return msgpack.unpackb(_take_buf(ptr, out_len.value), raw=False)

    def get_missing_deps(self, doc_id):
        out_len = ctypes.c_int64()
        ptr = lib().amtpu_get_missing_deps(
            self._pool, self._doc_key(doc_id).encode(),
            ctypes.byref(out_len))
        if not ptr:
            _raise_last()
        return msgpack.unpackb(_take_buf(ptr, out_len.value), raw=False)

    def get_missing_changes(self, doc_id, have_deps):
        have = msgpack.packb(dict(have_deps), use_bin_type=True)
        out_len = ctypes.c_int64()
        ptr = lib().amtpu_get_missing_changes(
            self._pool, self._doc_key(doc_id).encode(), have, len(have),
            ctypes.byref(out_len))
        if not ptr:
            _raise_last()
        return msgpack.unpackb(_take_buf(ptr, out_len.value), raw=False)
