"""Native host runtime bindings.

Loads `libamtpu_core.so` (built from /root/repo/native/) and exposes
`NativeDocPool`: the C++ host runtime driving the same JAX device kernels
as the Python `TPUDocPool`, with all per-op host stages (causal scheduling,
columnar encoding, patch emission, mirror maintenance) in C++ and
changes/patches crossing the boundary as msgpack bytes.

`NativeDocPool.apply_batch(dict)` round-trips through msgpack for drop-in
test parity with TPUDocPool; `apply_batch_bytes(bytes) -> bytes` is the
zero-Python wire path the sidecar serves.
"""

import ctypes
import os
import re
import subprocess
import threading
import time

import msgpack
import numpy as np

from .. import faults, telemetry, trace
from ..telemetry import attribution, recorder
from ..utils.common import (doc_key, env_bool, env_int, env_raw, env_str,
                            parse_mesh_env)
from ..utils.wire import map_header as _map_header
from ..utils.wire import read_map_header as _read_map_header

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_DIR)), 'native')
# AMTPU_NATIVE_LIB loads an alternate build of the SAME ABI -- the asan
# gate (tools/asan_check.py) points it at the -fsanitize=address,
# undefined .so; an override is trusted as-is (no mtime rebuild)
_LIB_OVERRIDE = env_str('AMTPU_NATIVE_LIB', '')
_LIB_PATH = _LIB_OVERRIDE or os.path.join(_DIR, 'libamtpu_core.so')


def _build():
    subprocess.run(['make'], cwd=_SRC, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def _load():
    if not _LIB_OVERRIDE and (not os.path.exists(_LIB_PATH) or (
            os.path.exists(os.path.join(_SRC, 'core.cpp')) and
            os.path.getmtime(os.path.join(_SRC, 'core.cpp')) >
            os.path.getmtime(_LIB_PATH))):
        _build()
    lib = ctypes.CDLL(_LIB_PATH)
    lib.amtpu_pool_new.restype = ctypes.c_void_p
    lib.amtpu_pool_free.argtypes = [ctypes.c_void_p]
    lib.amtpu_doc_count.restype = ctypes.c_int64
    lib.amtpu_doc_count.argtypes = [ctypes.c_void_p]
    lib.amtpu_last_error.restype = ctypes.c_char_p
    lib.amtpu_last_error_kind.restype = ctypes.c_int
    lib.amtpu_begin.restype = ctypes.c_void_p
    lib.amtpu_begin.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int64]
    lib.amtpu_begin_local.restype = ctypes.c_void_p
    lib.amtpu_begin_local.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_int64]
    lib.amtpu_batch_free.argtypes = [ctypes.c_void_p]
    lib.amtpu_batch_rollback.restype = ctypes.c_int
    lib.amtpu_batch_rollback.argtypes = [ctypes.c_void_p]
    lib.amtpu_batch_dims.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_int64)]
    for name in ('g', 't', 'a', 's', 'clocktab', 'clockidx', 'sort',
                 'obj', 'par', 'ctr', 'act', 'linsort', 'memidx'):
        fn = getattr(lib, 'amtpu_col_' + name)
        fn.restype = ctypes.POINTER(ctypes.c_int32)
        fn.argtypes = [ctypes.c_void_p]
    for name in ('d', 'val', 'hostovf'):
        fn = getattr(lib, 'amtpu_col_' + name)
        fn.restype = ctypes.POINTER(ctypes.c_uint8)
        fn.argtypes = [ctypes.c_void_p]
    lib.amtpu_mid.restype = ctypes.c_int
    lib.amtpu_mid.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int]
    lib.amtpu_dom_dims.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                   ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_dom_v0.restype = ctypes.POINTER(ctypes.c_float)
    lib.amtpu_dom_v0.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    for name in ('er', 'oe', 'orank', 'od'):
        fn = getattr(lib, 'amtpu_dom_' + name)
        fn.restype = ctypes.POINTER(ctypes.c_int32)
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.amtpu_dom_ov.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_dom_ov.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.amtpu_dom_set_indexes.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                          ctypes.POINTER(ctypes.c_int32)]
    lib.amtpu_fused_dims.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_int64)]
    for name in ('ersrc', 'oranksrc', 'domsrc'):
        fn = getattr(lib, 'amtpu_fdom_' + name)
        fn.restype = ctypes.POINTER(ctypes.c_int32)
        fn.argtypes = [ctypes.c_void_p]
    lib.amtpu_mid_fused.restype = ctypes.c_int
    lib.amtpu_mid_fused.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32)]
    lib.amtpu_esc_dims.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_esc_group_meta.restype = ctypes.POINTER(ctypes.c_int64)
    lib.amtpu_esc_group_meta.argtypes = [ctypes.c_void_p]
    lib.amtpu_esc_rows.restype = ctypes.POINTER(ctypes.c_int32)
    lib.amtpu_esc_rows.argtypes = [ctypes.c_void_p]
    lib.amtpu_esc_mem_off.restype = ctypes.POINTER(ctypes.c_int64)
    lib.amtpu_esc_mem_off.argtypes = [ctypes.c_void_p]
    lib.amtpu_esc_mem.restype = ctypes.POINTER(ctypes.c_int32)
    lib.amtpu_esc_mem.argtypes = [ctypes.c_void_p]
    lib.amtpu_resclk_info.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_latch_defaults.argtypes = [ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_resclk_tab.restype = ctypes.POINTER(ctypes.c_int32)
    lib.amtpu_resclk_tab.argtypes = [ctypes.c_void_p]
    lib.amtpu_resclk_batch_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_mid_packed.restype = ctypes.c_int
    lib.amtpu_mid_packed.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
    lib.amtpu_finish.restype = ctypes.c_int
    lib.amtpu_finish.argtypes = [ctypes.c_void_p]
    lib.amtpu_host_dominance.restype = ctypes.c_int
    lib.amtpu_host_dominance.argtypes = [ctypes.c_void_p]
    lib.amtpu_mid_hostreg.restype = ctypes.c_int
    lib.amtpu_mid_hostreg.argtypes = [ctypes.c_void_p]
    lib.amtpu_pool_set_hostfull.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.amtpu_batch_trace.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_double)]
    lib.amtpu_sched_counts.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_dom_obj_meta.restype = ctypes.c_int64
    lib.amtpu_dom_obj_meta.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                       ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_batch_doc_id.restype = ctypes.c_char_p
    lib.amtpu_batch_doc_id.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.amtpu_intern_str.restype = ctypes.c_char_p
    lib.amtpu_intern_str.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.amtpu_arena_raw.restype = ctypes.c_int64
    lib.amtpu_arena_raw.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint32)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    lib.amtpu_result.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_result.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_get_patch.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_get_patch.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_save.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_save.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_truncate_history.restype = ctypes.c_int64
    lib.amtpu_truncate_history.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int64]
    lib.amtpu_get_missing_clock.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_get_missing_clock.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_history_bytes.restype = ctypes.c_int64
    lib.amtpu_history_bytes.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.amtpu_drop_doc.restype = ctypes.c_int64
    lib.amtpu_drop_doc.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.amtpu_get_clock.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_get_clock.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_get_missing_deps.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_get_missing_deps.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_get_missing_changes.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_get_missing_changes.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_buf_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.amtpu_get_register.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_get_register.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_get_changes_for_actor.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_get_changes_for_actor.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_columnar_encode.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_columnar_encode.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_columnar_decode.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_columnar_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_begin_columnar.restype = ctypes.c_void_p
    lib.amtpu_begin_columnar.argtypes = [ctypes.c_void_p,
                                         ctypes.c_char_p, ctypes.c_int64]
    lib.amtpu_fold_settled.restype = ctypes.c_int64
    lib.amtpu_fold_settled.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int64]
    lib.amtpu_fold_clocks.restype = ctypes.c_int64
    lib.amtpu_fold_clocks.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int64, ctypes.c_int64]
    lib.amtpu_clock_pairs.restype = ctypes.c_int64
    lib.amtpu_clock_pairs.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.amtpu_op_count.restype = ctypes.c_int64
    lib.amtpu_op_count.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.amtpu_doc_ids.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_doc_ids.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_doc_stats.restype = ctypes.c_int64
    lib.amtpu_doc_stats.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_int64),
                                    ctypes.c_int64]
    lib.amtpu_doc_shard.restype = ctypes.c_uint32
    lib.amtpu_doc_shard.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_int]
    lib.amtpu_shard_split.restype = ctypes.c_void_p
    lib.amtpu_shard_split.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                      ctypes.c_int]
    lib.amtpu_shard_buf.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.amtpu_shard_buf.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.POINTER(ctypes.c_int64)]
    lib.amtpu_shard_free.argtypes = [ctypes.c_void_p]
    return lib


_lib = None


def lib():
    global _lib
    if _lib is None:
        _lib = _load()
    return _lib


def _np_view(ptr, shape, dtype):
    n = int(np.prod(shape))
    if n == 0:
        return np.zeros(shape, dtype)
    arr = np.ctypeslib.as_array(ptr, shape=(n,))
    return arr.reshape(shape).view(dtype) if arr.dtype != dtype else \
        arr.reshape(shape)


def _take_buf(ptr, length):
    try:
        return ctypes.string_at(ptr, length)
    finally:
        lib().amtpu_buf_free(ptr)


# ---------------------------------------------------------------------------
# native columnar codec bindings (ISSUE 14; storage/columnar.py
# dispatches here under AMTPU_STORAGE_NATIVE)
# ---------------------------------------------------------------------------


def columnar_encode_native(raws):
    """C++ columnar encode: list of raw change bytes ->
    (blob, n_changes, n_residual).  Blob bytes are identical to the
    Python encoder's (the fuzz parity lane pins it).  Raws cross the
    boundary BIN-wrapped -- element boundaries must be explicit, a
    residual raw with trailing bytes is not re-delimitable by msgpack
    skip.  Raises on any native error; the columnar.py dispatch falls
    back to the Python codec then."""
    payload = msgpack.packb([bytes(r) for r in raws],
                            use_bin_type=True)
    out_len = ctypes.c_int64()
    stats = (ctypes.c_int64 * 2)()
    ptr = lib().amtpu_columnar_encode(payload, len(payload),
                                      ctypes.byref(out_len), stats)
    if not ptr:
        _raise_last()
    return _take_buf(ptr, out_len.value), int(stats[0]), int(stats[1])


def columnar_decode_native(blob):
    """C++ columnar decode: blob -> list of raw change bytes, byte-
    identical to the encode input (BIN-wrapped across the boundary, as
    in `columnar_encode_native`).  Corruption raises ValueError
    (decode_columnar's contract; the C++ side reports it as kind 1)."""
    out_len = ctypes.c_int64()
    ptr = lib().amtpu_columnar_decode(blob, len(blob),
                                      ctypes.byref(out_len))
    if not ptr:
        raise ValueError('corrupt columnar blob: %s'
                         % lib().amtpu_last_error().decode())
    return msgpack.unpackb(_take_buf(ptr, out_len.value), raw=False)


# ---------------------------------------------------------------------------
# batch-handle accounting: every amtpu_begin* success increments, every
# free decrements -- the assertion hook tests use to prove a phase-a
# failure cannot leak the C++ batch handle (each handle owns the whole
# decoded batch, so a leak under sustained error traffic is unbounded
# memory growth).
# ---------------------------------------------------------------------------

_live_lock = threading.Lock()
_live_batches = 0


def _track_begin():
    global _live_batches
    with _live_lock:
        _live_batches += 1


def _free_batch(bh):
    """The ONLY way batch handles are freed: pairs the counter with the
    C++ free so live_batch_handles() stays truthful."""
    global _live_batches
    lib().amtpu_batch_free(bh)
    with _live_lock:
        _live_batches -= 1


def live_batch_handles():
    """Currently allocated C++ batch handles (test/leak-audit hook)."""
    with _live_lock:
        return _live_batches


def _rollback_batch(bh, exc=None):
    """Best-effort pool rollback of a FAILED batch (pre-free).

    Success (returns True) means the pool is byte-identical to its
    pre-begin state: the failure is retryable/bisectable because re-
    applying the same changes is not swallowed by seq dedup.  Failure
    means emit already ran; the exception is marked
    ``amtpu_state_suspect`` so `resilience` refuses to re-apply those
    docs (the pre-resilience whole-batch raise is the only safe
    outcome there).
    """
    if lib().amtpu_batch_rollback(bh) != 0:
        if exc is not None:
            exc.amtpu_state_suspect = True
        telemetry.metric('resilience.rollback_unavailable')
        recorder.record('batch.rollback', detail='state_suspect')
        return False
    telemetry.metric('resilience.rollback')
    recorder.record('batch.rollback',
                    detail=type(exc).__name__ if exc is not None
                    else None)
    return True


def _batch_docs(bh, payload):
    """Doc keys of a begun batch -- fault-pinning lookups only (the
    disarmed fast path never calls this)."""
    if isinstance(payload, tuple):
        head = ctypes.string_at(payload[0], min(payload[1], 16))
    else:
        head = bytes(payload[:16])
    n = _read_map_header(head)[0]
    L = lib()
    return [L.amtpu_batch_doc_id(bh, i).decode() for i in range(n)]


def _packed_epilogue_on():
    """AMTPU_PACKED_EPILOGUE=0 forces the full-matrix member epilogue
    (the pre-packed readback path, kept as the parity A/B arm); default
    on.  Checked per batch, not latched."""
    return env_bool('AMTPU_PACKED_EPILOGUE', True)


def _conf_dense_thresh():
    """Dense-conflicts switch factor: the row-gather kernel saves
    nothing once `conf_rows * thresh > Tp` -- transfer the whole matrix
    and slice host-side instead.  AMTPU_CONF_DENSE_THRESH overrides the
    default factor 4 (0 disables the dense path entirely)."""
    return env_int('AMTPU_CONF_DENSE_THRESH', 4)


def _ctx_ready(ctx):
    """True when every device output phase b will block on has already
    resolved -- the ready-order collect predicate.  Host-only modes
    (hostreg) are always ready."""
    for arr in _ctx_pending_arrays(ctx):
        is_ready = getattr(arr, 'is_ready', None)
        if is_ready is not None and not is_ready():
            return False
    return True


def _ctx_pending_arrays(ctx):
    out = []
    combo = ctx.get('combo')
    if combo is not None:
        out.append(combo)
    elif ctx.get('reg_out') is not None:
        out.append(ctx['reg_out']['packed'])
    esc = ctx.get('esc')
    if esc:
        out.extend(t_out['packed'] for _w, _rows, t_out in esc[0])
    return out


def _run_phase_b_entry(key, pool, ctx, on_result=None, on_error=None):
    """Phase b of ONE (key, pool, ctx) entry, with the full failure
    protocol: drain in-flight kernels, roll the batch back, free the
    handle.  Shared by the serial ready-order collector below and the
    mesh pool's threaded collector (mesh_pool._collect_ready_parallel),
    so the two drivers cannot drift on error semantics."""
    try:
        result = pool._phase_b(ctx)
        if on_result is not None:
            on_result(key, result)
    except Exception as e:
        # drain in-flight kernels BEFORE rollback+free: a phase-b
        # failure (armed fault, device error) can leave dispatches
        # that zero-copied the C++ batch columns the free below is
        # about to delete -- the PR-4 alias class, same drain as
        # the wave phase-a unwind
        for arr in _ctx_pending_arrays(ctx):
            try:
                arr.block_until_ready()
            except Exception:
                pass    # already failing; kernel errors moot
        _rollback_batch(ctx['bh'], e)
        if on_error is not None:
            on_error(key, e)
        else:
            raise
    finally:
        _free_batch(ctx['bh'])


def _collect_ready_order(entries, on_result=None, on_error=None):
    """Drives phase b over (key, pool, ctx) entries READY-FIRST: each
    round picks the first entry whose dispatched device outputs have
    already resolved (jax.Array.is_ready) and runs its host mid/emit;
    only when nothing is ready does it block on the oldest submission.
    One slow shard then no longer stalls shards whose results are
    already sitting in host memory -- shard k's C++ mid/emit overlaps
    shard k+1's in-flight device wait (ISSUE 3 tentpole b).

    Every entry runs to completion regardless of earlier failures (their
    begins have committed state); errors go to `on_error(key, exc)`."""
    pending = list(entries)
    while pending:
        pick = None
        for i, (_key, _pool, ctx) in enumerate(pending):
            if _ctx_ready(ctx):
                pick = i
                break
        if pick is None:
            # nothing resolved yet: block on the oldest submission
            pick = 0
            trace.metric('collect.wait_in_order')
        elif pick > 0:
            trace.metric('collect.ready_reorder')
        key, pool, ctx = pending.pop(pick)
        _run_phase_b_entry(key, pool, ctx, on_result, on_error)


def apply_payloads_pipelined(pools_payloads):
    """Applies (NativeDocPool, payload_bytes) pairs with host/device
    overlap: every pool's begin + kernel dispatch runs first (phase a),
    then results collect and emit ready-first (phase b) -- pool k's
    device work overlaps pool k+1's host begin AND pool j's mid/emit,
    the same pattern ShardedNativePool uses across shards.  The PUBLIC
    entry for fanning a round of independent deliveries (replica
    catch-up) over many pools.

    Pools that already began successfully still run to completion when a
    later one fails; the first error is re-raised afterwards."""
    ctxs = []
    errors = []
    for pool, payload in pools_payloads:
        try:
            # overlapped: callers may pass the same pool more than once,
            # so a later begin must not donate a table an earlier
            # in-flight dispatch still reads
            ctxs.append((None, pool, pool._phase_a(payload,
                                                   overlapped=True)))
        except Exception as e:
            errors.append(e)
    _collect_ready_order(ctxs,
                         on_error=lambda _k, e: errors.append(e))
    if errors:
        raise errors[0]


#: fixed byte prefix of a v1 checkpoint; the remainder is the raw
#: changes array (the v2 columnar container lives in
#: automerge_tpu.storage -- this alias keeps the byte-splice loader
#: self-contained)
_CKPT_PREFIX = (b'\x82' + msgpack.packb('format') +
                msgpack.packb('amtpu-doc-v1') + msgpack.packb('changes'))


def _base_pool_of(pool, doc_id):
    """The NativeDocPool that actually owns `doc_id`'s state: sharded /
    mesh pools route per doc; a plain pool is its own base."""
    if hasattr(pool, '_shard_of'):
        return pool.pools[pool._shard_of(doc_id)]
    return pool


def _v2_adopt_info(pool, doc_id, key, adopts, frontier, chunks,
                   empty_pools):
    """Queues the post-apply snapshot re-adopt for a v2 container --
    ONLY into docs that are empty pre-load (see _load_batch's inline
    rationale: adopting over a live doc would discard newer compacted
    chunks).  `empty_pools` caches base-pool emptiness: a cold restart
    into a fresh pool (the 1M-doc case) skips the per-doc clock query
    entirely."""
    from .. import storage
    if frontier and chunks and storage.storage_format() != 'json':
        bp = _base_pool_of(pool, doc_id)
        empty = empty_pools.get(id(bp))
        if empty is None:
            empty = empty_pools[id(bp)] = bp.doc_count() == 0
        if not empty:
            pre = {}
            try:
                pre = pool.get_clock(doc_id).get('clock') or {}
            except Exception:
                pass
            if pre:
                return
        adopts.append((doc_id, key, frontier, chunks))


def _load_batch_native(pool, blobs):
    """Arena-direct restore (ISSUE 14 tentpole): v2 snapshot chunks +
    tail ship to C++ AS COLUMNAR BLOBS (`amtpu_begin_columnar`) -- the
    columns materialize straight into ChangeRec arena state with no
    Python change dicts and no per-change msgpack round trip; v1
    containers splice their raw changes array through the same entry.
    Docs group per base pool, so sharded/mesh drivers route exactly
    like the dict path.  Byte parity with the dict-replay path is the
    decode-parity test lane's contract (both exec modes)."""
    from .. import storage
    from ..errors import RangeError
    groups = {}          # id(base pool) -> (base pool, {key: [parts]})
    adopts = []          # (doc_id, key, frontier, chunks) post-apply
    empty_pools = {}     # id(base pool) -> was empty pre-load
    for doc_id, data in blobs.items():
        key = doc_key(doc_id)
        data = bytes(data)
        if data.startswith(_CKPT_PREFIX):
            doc_parts = [data[len(_CKPT_PREFIX):]]
        elif data.startswith(storage.CKPT_V2_PREFIX):
            try:
                frontier, chunks, tail_blob = \
                    storage.unpack_checkpoint_parts(data)
            except ValueError as e:
                raise RangeError('corrupt checkpoint for %r: %s'
                                 % (doc_id, e))
            doc_parts = list(chunks) + [tail_blob]
            _v2_adopt_info(pool, doc_id, key, adopts, frontier,
                           chunks, empty_pools)
        else:
            raise RangeError('not an amtpu-doc checkpoint: %r'
                             % (doc_id,))
        bp = _base_pool_of(pool, doc_id)
        groups.setdefault(id(bp), (bp, {}))[1][key] = doc_parts
    def apply_group(bp, keyed):
        try:
            bp._apply_columnar(msgpack.packb(keyed, use_bin_type=True))
        except RangeError as e:
            # corrupt-blob surface parity with the dict-replay arm: a
            # bad chunk/tail reports as a corrupt CHECKPOINT
            raise RangeError('corrupt checkpoint (docs %s): %s'
                             % (sorted(keyed), e))

    if len(groups) > 1:
        # sharded/mesh pools: drive the per-shard restores CONCURRENTLY
        # (ctypes releases the GIL around the C++ begin/emit), matching
        # the dict-replay arm's threaded shard runner.  Shards commit
        # independently -- the first error re-raises after every group
        # ran, the documented sharded-pool error contract.
        import concurrent.futures
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(len(groups), os.cpu_count() or 1)) \
                as pool_exec:
            futs = [pool_exec.submit(apply_group, bp, keyed)
                    for bp, keyed in groups.values()]
            errors = [f.exception() for f in futs
                      if f.exception() is not None]
        if errors:
            raise errors[0]
    else:
        for bp, keyed in groups.values():
            apply_group(bp, keyed)
    for doc_id, key, frontier, chunks in adopts:
        _base_pool_of(pool, doc_id)._adopt_snapshot(key, frontier,
                                                    chunks)


def _load_batch(pool, blobs):
    """Splices many save() checkpoints into ONE {doc: [changes]} payload
    and applies it as a single batch -- per-doc loads each pay a full
    device round trip; a whole DocSet restore should pay one.  v2
    columnar containers (docs/STORAGE.md) decode their snapshot chunks
    here and, post-apply, re-adopt them so a reloaded doc keeps its
    compacted cold-state economics.

    Under ``AMTPU_STORAGE_NATIVE`` (default on) the restore goes
    ARENA-DIRECT through `amtpu_begin_columnar` instead
    (`_load_batch_native`); this dict-replay body is the =0 parity
    oracle."""
    from .. import storage
    from ..errors import RangeError
    if faults.ARMED:
        faults.fire('checkpoint.load', [doc_key(d) for d in blobs])
    if storage.storage_native_on():
        return _load_batch_native(pool, blobs)
    parts = [_map_header(len(blobs))]
    adopts = []          # (doc_id, key, frontier, chunks) post-apply
    for doc_id, data in blobs.items():
        key = doc_key(doc_id)
        if data.startswith(_CKPT_PREFIX):
            parts.append(msgpack.packb(key, use_bin_type=True))
            parts.append(memoryview(data)[len(_CKPT_PREFIX):])
            continue
        if not data.startswith(storage.CKPT_V2_PREFIX):
            raise RangeError('not an amtpu-doc checkpoint: %r'
                             % (doc_id,))
        try:
            frontier, chunks, tail = \
                storage.unpack_checkpoint(bytes(data))
            raws = []
            for chunk in chunks:
                raws.extend(storage.decode_columnar(chunk))
        except ValueError as e:
            # the RangeError contract covers corrupt containers too --
            # whatever the columnar decoder tripped on internally
            raise RangeError('corrupt checkpoint for %r: %s'
                             % (doc_id, e))
        raws.extend(tail)
        parts.append(msgpack.packb(key, use_bin_type=True))
        parts.append(storage.join_changes_array(raws))
        if frontier and chunks and storage.storage_format() != 'json':
            # adopt ONLY into docs that are empty pre-load: loading an
            # (older) checkpoint into a LIVE doc replays as seq-deduped
            # no-ops, and overwriting that doc's storage state with the
            # checkpoint's would discard newer compacted chunks (changes
            # then live in neither arena nor snapshot) -- and the
            # checkpoint's application-order prefix need not be a
            # prefix of the live doc's.  A live target just stays on
            # its own (possibly uncompacted) state.
            pre = {}
            try:
                pre = pool.get_clock(doc_id).get('clock') or {}
            except Exception:
                pass
            if not pre:
                adopts.append((doc_id, key, frontier, chunks))
    pool.apply_batch_bytes(b''.join(parts))
    for doc_id, key, frontier, chunks in adopts:
        _base_pool_of(pool, doc_id)._adopt_snapshot(key, frontier,
                                                    chunks)


def _restore_threads():
    """``AMTPU_RESTORE_THREADS``: restore fan-out width (0 = auto, one
    worker per core capped at 8; 1 = serial -- the A/B arm the
    coldstart gate compares against)."""
    n = env_int('AMTPU_RESTORE_THREADS', 0)
    if n <= 0:
        n = min(8, os.cpu_count() or 1)
    return n


def restore_from_store(pool, store, doc_ids=None, batch=None,
                       threads=None):
    """Parallel arena-direct restore straight off a ColdStore's durable
    manifest (ISSUE 17 tentpole): walks the store's doc inventory, reads
    + checksums blobs, and fans per-shard doc groups across a thread
    pool where each shard runs its own `amtpu_begin_columnar` decode +
    apply with the GIL released -- the 1M-doc cold-start entry point.

    * **Sharding.** Docs group by base pool (`_base_pool_of`); each
      group restores on its own worker, serially batched
      (``AMTPU_RESTORE_BATCH``, default 8192 docs) -- a single
      NativeDocPool applies single-threaded by contract, so the
      parallel axis is the shard, exactly like the dict-replay arm's
      threaded shard runner.  Within a group, the next batch's blob
      reads prefetch on a side thread while the current batch applies
      (I/O overlaps decode even at one shard).
    * **Failure isolation.** A corrupt blob (checksum mismatch --
      `ColdStoreCorrupt`) quarantines THAT doc: typed per-doc error in
      the summary + ``storage.restore.corrupt``, never a whole-restore
      failure.  A failed batch apply falls back to per-doc application
      (the `DocEvictor.ensure_resident` pattern); docs that still fail
      land in the summary as resilience error envelopes +
      ``storage.restore.failed``.
    * **Progress.** ``storage.restore.{docs,bytes,batches}`` advance
      per applied batch (scrapable mid-restore) and the flight recorder
      logs start/finish + every quarantined doc.

    Returns a summary dict: ``{'docs', 'bytes', 'batches', 'corrupt':
    {doc: error}, 'failed': {doc: error}, 'elapsed_s'}``.
    """
    from ..storage.coldstore import ColdStoreCorrupt
    from .. import resilience
    t0 = time.perf_counter()
    if doc_ids is None:
        doc_ids = sorted(store.doc_ids())
    else:
        doc_ids = list(doc_ids)
    if batch is None:
        batch = max(1, env_int('AMTPU_RESTORE_BATCH', 8192))
    if threads is None:
        threads = _restore_threads()
    recorder.record('restore.start', n=len(doc_ids),
                    detail='threads=%d batch=%d' % (threads, batch))
    groups = {}          # id(base pool) -> (base pool, [doc ids])
    if hasattr(pool, '_shard_of'):
        pool.pools     # materialize the lazy shard list on THIS thread
    for d in doc_ids:
        bp = _base_pool_of(pool, d)
        groups.setdefault(id(bp), (bp, []))[1].append(d)
    lock = threading.Lock()
    summary = {'docs': 0, 'bytes': 0, 'batches': 0,
               'corrupt': {}, 'failed': {}}

    def read_blobs(ids):
        """One batch's blobs off the store, checksums verified; corrupt
        docs quarantine here (typed, counted, skipped)."""
        blobs = {}
        for d in ids:
            try:
                blobs[d] = store.get(d)
            except ColdStoreCorrupt as e:
                telemetry.metric('storage.restore.corrupt')
                recorder.record('restore.corrupt', doc=doc_key(d),
                                detail=str(e))
                with lock:
                    summary['corrupt'][d] = resilience.error_envelope(e)
            except KeyError:
                pass   # dropped between inventory walk and read
        return blobs

    def apply_blobs(bp, blobs):
        if not blobs:
            return
        try:
            _load_batch(bp, blobs)
        except Exception as batch_exc:
            # per-doc isolation (the ensure_resident pattern): one
            # poison blob must not fail the other docs of its batch
            for d, data in blobs.items():
                try:
                    _load_batch(bp, {d: data})
                except Exception as e:
                    telemetry.metric('storage.restore.failed')
                    recorder.record('restore.failed', doc=doc_key(d),
                                    detail=str(e))
                    with lock:
                        summary['failed'][d] = \
                            resilience.error_envelope(e)
            del batch_exc
        with lock:
            summary['docs'] += len(blobs)
            summary['bytes'] += sum(len(v) for v in blobs.values())
            summary['batches'] += 1
        telemetry.metric('storage.restore.docs', len(blobs))
        telemetry.metric('storage.restore.bytes',
                         sum(len(v) for v in blobs.values()))
        telemetry.metric('storage.restore.batches')

    def run_group(bp, ids):
        import concurrent.futures
        chunks = [ids[i:i + batch] for i in range(0, len(ids), batch)]
        # single-reader prefetch: batch k+1's store reads overlap batch
        # k's decode+apply (reads release the GIL around file I/O)
        with concurrent.futures.ThreadPoolExecutor(1) as reader:
            pending = reader.submit(read_blobs, chunks[0]) \
                if chunks else None
            for i in range(len(chunks)):
                blobs = pending.result()
                pending = reader.submit(read_blobs, chunks[i + 1]) \
                    if i + 1 < len(chunks) else None
                apply_blobs(bp, blobs)

    group_list = [g for g in groups.values() if g[1]]
    if len(group_list) > 1 and threads > 1:
        import concurrent.futures
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(threads, len(group_list))) as ex:
            futs = [ex.submit(run_group, bp, ids)
                    for bp, ids in group_list]
            errors = [f.exception() for f in futs
                      if f.exception() is not None]
        if errors:
            raise errors[0]
    else:
        for bp, ids in group_list:
            run_group(bp, ids)
    summary['elapsed_s'] = round(time.perf_counter() - t0, 3)
    recorder.record('restore.done', n=summary['docs'],
                    detail='%.3fs corrupt=%d failed=%d'
                           % (summary['elapsed_s'],
                              len(summary['corrupt']),
                              len(summary['failed'])))
    return summary


def _apply_batch_dicts(pool, changes_by_doc):
    """Shared dict-level apply_batch: msgpack round trip through the
    pool's RESILIENT wire path (pool is any object with
    apply_batch_bytes_resilient) -- a device/native-path failure is
    retried, bisected, and at worst quarantined per doc instead of
    failing every doc in the batch (automerge_tpu.resilience)."""
    keyed = {NativeDocPool._doc_key(d): chs
             for d, chs in changes_by_doc.items()}
    payload = msgpack.packb(keyed, use_bin_type=True)
    out = msgpack.unpackb(pool.apply_batch_bytes_resilient(payload),
                          raw=False, strict_map_key=False)
    # the op counter lives here because this is where changes exist as
    # decoded dicts (the bytes path can't count ops without paying a
    # decode it otherwise avoids; docs it counts itself from the map
    # header), and AFTER the apply so a failed batch doesn't inflate it;
    # counts submitted ops of committed batches -- duplicates/queued
    # changes included (the engine path counts exact causally-applied
    # ops)
    telemetry.OPS.inc(sum(len(c.get('ops', ()))
                          for chs in changes_by_doc.values() for c in chs))
    return {d: out[NativeDocPool._doc_key(d)] for d in changes_by_doc}


def _raise_if_quarantined(doc_id, result):
    """Single-doc entry points keep their raise contract: a one-doc
    batch has nothing to isolate FROM, so a quarantine envelope there
    surfaces as the exception it stands for.  The message embeds
    ``resilience.QUARANTINE_RAISE_MARKER`` -- the gateway's fan-out
    recognizes this surface to keep its 'envelope, not silence'
    promise to subscribers."""
    from ..resilience import QUARANTINE_RAISE_MARKER, is_quarantined
    if is_quarantined(result):
        from ..errors import AutomergeError
        raise AutomergeError('doc %r%s%s] %s'
                             % (doc_id, QUARANTINE_RAISE_MARKER,
                                result['errorType'], result['error']))


def _raise_last():
    from ..errors import AutomergeError, RangeError
    msg = lib().amtpu_last_error().decode()
    kind = lib().amtpu_last_error_kind()
    if kind == 2:
        raise TypeError(msg)
    raise (RangeError if kind == 1 else AutomergeError)(msg)


def _pipeline_depth():
    """Cross-batch staging depth of the double-buffered wave pipeline
    (AMTPU_PIPELINE_DEPTH, default 2; 0/1 disables).  Each wave is a
    doc-disjoint slice of the payload begun while earlier waves' device
    kernels are still in flight -- wave k+1's C++ decode/begin (GIL
    released) overlaps wave k's XLA compute, the cross-BATCH extension
    of the cross-shard overlap `_collect_ready_order` already drives."""
    return env_int('AMTPU_PIPELINE_DEPTH', 2)


def _pipeline_min_docs():
    """Smallest doc count worth splitting into waves: below this the
    per-wave fixed cost (split pass, extra dispatch, jit shape) beats
    the overlap.  AMTPU_PIPELINE_MIN_DOCS overrides (default 64)."""
    return env_int('AMTPU_PIPELINE_MIN_DOCS', 64)


def _devtime_on():
    """AMTPU_DEVTIME=1 turns on synchronous per-dispatch device timing
    (checked per call, not latched -- bench.py flips it for one pass).
    Single definition in telemetry so the engine and native paths can't
    drift."""
    return telemetry.devtime_on()


def _host_dom_on():
    """Host-Fenwick dominance instead of the device kernel.

    The [L]x[L,K] dominance mask products are the right formulation on
    an accelerator (MXU work, stays fused with resolve+linearize) but
    O(T*L) scalar work on the CPU backend, where they dominate
    single-big-doc latency.  Default: host path on CPU, device path on
    accelerators; AMTPU_HOST_DOM=1/0 forces either way (checked per
    batch, not latched)."""
    env = env_raw('AMTPU_HOST_DOM')
    if env is not None:
        return env not in ('', '0')
    import jax
    return jax.default_backend() == 'cpu'


#: resident-mode knobs that BIND at a process's first batch: C++ static
#: latches (core.cpp resident_enabled_pre / resclk_enabled) + jit cache
#: shapes.  AMTPU_HOST_FULL is deliberately absent -- it is re-read per
#: batch (the exec-mode A/B tests flip it in-process).
# AMTPU_MESH is latched like the resident knobs: the pool factory's
# choice and each chip's device binding are fixed at construction, so a
# later env flip must warn, not silently serve the old topology.  (The
# sp-fence threshold AMTPU_MESH_SP_MIN is deliberately NOT here -- the
# fence reads it live per dispatch, so flips genuinely apply.)
_RESIDENT_LATCH_KEYS = ('AMTPU_RESIDENT', 'AMTPU_RESIDENT_MIN',
                        'AMTPU_RESIDENT_CLK', 'AMTPU_RESCLK_MAX_ACTORS',
                        'AMTPU_RESCLK_MAX_ROWS', 'AMTPU_TRIVIAL_HOST',
                        'AMTPU_MESH')
# flips of the mesh-topology knob count under mesh.*; everything else
# stays resident.latch_flip_ignored
_LATCH_COUNTER_NS = {'AMTPU_MESH': 'mesh'}
_resident_latch = None          # first-batch snapshot
_latch_flips_warned = set()     # (key, new value) pairs already warned


def _atoi(s):
    """C atoi: leading integer or 0 -- the parse the C++ latches use."""
    m = re.match(r'\s*[-+]?\d+', s or '')
    return int(m.group()) if m else 0


_latch_defaults_cache = None


def _latch_defaults():
    """(resident_min, resclk_max_actors, resclk_max_rows) defaults read
    through the ABI (amtpu_latch_defaults): the flip guard's effective
    values can never drift from the constants core.cpp latches on."""
    global _latch_defaults_cache
    if _latch_defaults_cache is None:
        out = (ctypes.c_int64 * 3)()
        lib().amtpu_latch_defaults(out)
        _latch_defaults_cache = tuple(int(v) for v in out)
    return _latch_defaults_cache


def _latch_snapshot():
    """(raw, effective) views of the latch knobs.  Effective values
    mirror each knob's actual consumers, so a semantically no-op env
    change (e.g. exporting a numeric knob's default) does not warn:

    * AMTPU_RESIDENT stays raw -- the Python arena/dominance gates
      distinguish unset (backend-dependent) from any set value;
    * AMTPU_RESIDENT_CLK's only consumer is core.cpp's resclk_enabled:
      atoi(CLK, falling back to RESIDENT) != 0, default on;
    * the numeric knobs compare as parsed integers with the C++
      defaults filled in;
    * AMTPU_TRIVIAL_HOST mirrors core.cpp's trivial_host static:
      atoi != 0, default on;
    * AMTPU_MESH compares as the normalized (dp, sp) the pool factory
      parses (malformed values compare raw -- they never built a
      mesh)."""
    raw = tuple(env_raw(k) for k in _RESIDENT_LATCH_KEYS)
    res, rmin, clk, amax, arows, triv, mesh = raw
    clk_src = clk if clk is not None else res
    d_rmin, d_amax, d_arows = _latch_defaults()
    try:
        mesh_eff = parse_mesh_env()
    except ValueError:
        mesh_eff = mesh
    eff = (res,
           _atoi(rmin) if rmin is not None else d_rmin,
           True if clk_src is None else _atoi(clk_src) != 0,
           _atoi(amax) if amax is not None else d_amax,
           _atoi(arows) if arows is not None else d_arows,
           True if triv is None else _atoi(triv) != 0,
           mesh_eff)
    return raw, eff


def _check_resident_latch():
    """Enforce the latch-at-first-batch contract instead of silently
    ignoring flips (ISSUE 6): the first batch snapshots the
    AMTPU_RESIDENT* knobs; a later divergence warns once per (key,
    value) and counts ``resident.latch_flip_ignored``.  The flipped env
    stays ignored exactly as before -- the C++ statics latched and the
    jit caches already compiled against the first-batch values; only a
    process restart can apply it (bench.py's subprocess-per-config
    protocol exists for this reason)."""
    global _resident_latch
    cur = _latch_snapshot()
    if _resident_latch is None:
        _resident_latch = cur
        return
    if cur[1] == _resident_latch[1]:    # effective values decide
        return
    import warnings
    for key, was, now, was_eff, now_eff in zip(
            _RESIDENT_LATCH_KEYS, _resident_latch[0], cur[0],
            _resident_latch[1], cur[1]):
        if was_eff == now_eff:
            continue
        trace.metric('%s.latch_flip_ignored'
                     % _LATCH_COUNTER_NS.get(key, 'resident'))
        if (key, now) not in _latch_flips_warned:
            _latch_flips_warned.add((key, now))
            warnings.warn(
                '%s changed %r -> %r after the first batch; resident-'
                'mode knobs latch at first use, so the flip is IGNORED '
                '(restart the process to apply it)' % (key, was, now),
                RuntimeWarning, stacklevel=3)


def _host_full_on():
    """Full host path: no kernel dispatch at all -- C++ resolves
    registers in-emit and list indexes via an in-emit Fenwick sweep.

    The right default on the CPU backend, where the XLA kernels share
    the single host core the C++ engine runs on and every dispatch is
    pure overhead.  Accelerators keep the kernel path (that is the
    point of the framework); a forced AMTPU_RESIDENT=1 also keeps it,
    so the resident tests and the multichip dryrun still drive the
    device-resident dispatch on CPU.  AMTPU_HOST_FULL=1/0 forces."""
    env = env_raw('AMTPU_HOST_FULL')
    if env is not None:
        return env not in ('', '0')
    # any truthy AMTPU_RESIDENT forces the resident kernel path -- same
    # parse as the C++ gate (atoi != 0), not just the literal '1'
    res = env_raw('AMTPU_RESIDENT')
    if res is not None and res not in ('', '0'):
        return False
    import jax
    return jax.default_backend() == 'cpu'


def _raise_shard_errors(errors):
    """Per-shard error reporting: a single failure re-raises with its
    shard identified; multiple failures aggregate every shard's message
    so no diagnosis is lost (healthy shards have already committed)."""
    if not errors:
        return
    if len(errors) == 1:
        shard, err = errors[0]
        err.args = ('[shard %d] %s' % (shard, err.args[0] if err.args
                                       else err),) + err.args[1:]
        raise err
    # aggregate, but keep the concrete exception class when every shard
    # failed the same way so callers' except clauses behave identically
    # whether one shard or all of them raised (e.g. all-ValueError must
    # surface as ValueError, same as the single-failure path above)
    from ..errors import AutomergeError
    types = {type(e) for _, e in errors}
    cls = types.pop() if len(types) == 1 else AutomergeError
    try:
        probe = cls('probe')          # must accept a lone message arg
    except Exception:
        cls, probe = AutomergeError, None
    if probe is not None and not isinstance(probe, Exception):
        cls = AutomergeError
    raise cls(
        '%d shards failed: ' % len(errors) +
        '; '.join('[shard %d] %s: %s' % (s, type(e).__name__, e)
                  for s, e in errors)) from errors[0][1]


class NativeDocPool:
    """C++ host runtime + JAX kernels; drop-in for TPUDocPool."""

    #: window width of the register kernel (ops/registers.WINDOW)
    WINDOW = 8
    #: entries amtpu_batch_dims writes -- must match core.cpp exactly
    #: (an undersized ctypes buffer is silent heap corruption)
    N_DIMS = 14

    def __init__(self):
        self._pool = lib().amtpu_pool_new()
        self._mode_set = False
        from .batch_resident import PoolClockCache
        from .resident import ResidentCache
        self._resident = ResidentCache()
        self._resclk = PoolClockCache()
        # per-doc settled-history snapshots (ISSUE 10, docs/STORAGE.md):
        # doc key -> {'frontier': {actor: seq}, 'chunks': [columnar
        # blob, ...]}.  The chunks hold exactly the changes <= frontier
        # in application order; the C++ arena holds only the tail.
        # Driven single-threaded under the callers' pool serialization
        # (the gateway's pool lock), like every other pool mutation.
        self._storage = {}

    @staticmethod
    def _backend_is_cpu():
        import jax
        return jax.default_backend() == 'cpu'

    def _ensure_mode_flags(self):
        # resolved lazily at the first batch (jax backend init is heavy
        # and pools are built in sharded bulk); re-checked never -- the
        # backend cannot change within a process
        if not self._mode_set:
            lib().amtpu_pool_set_hostfull(
                self._pool, 1 if _host_full_on() else 0)
            self._mode_set = True

    def __del__(self):
        # read the module global directly: at interpreter shutdown the
        # lib() accessor may already have been torn down
        if getattr(self, '_pool', None) and _lib is not None:
            _lib.amtpu_pool_free(self._pool)
            self._pool = None

    def doc_count(self):
        """Number of materialized docs (tests assert queries on unknown
        ids never create phantom state)."""
        return lib().amtpu_doc_count(self._pool)

    # -- wire path ------------------------------------------------------

    def apply_batch_bytes(self, payload):
        """msgpack {doc_id: [change...]} -> msgpack {doc_id: patch}."""
        t0 = time.perf_counter()
        if isinstance(payload, (bytes, bytearray)):
            try:
                docs = _read_map_header(payload)[0]
            except (ValueError, IndexError):
                # malformed header: skip pipelining and let C++ begin
                # raise its typed validation error (the resilience and
                # sidecar layers classify on that type)
                docs = 0
        else:
            # shard sub-call: never pipelined (the sharded driver
            # overlaps across shards itself) and the top level already
            # counted docs for telemetry -- no header parse needed
            docs = 0
        recorder.record('batch.begin', n=docs)
        if self._should_pipeline(payload, docs):
            try:
                out = self._apply_waves(payload, docs)
            except Exception as e:
                if getattr(e, 'amtpu_state_suspect', False):
                    raise
                # every begun wave rolled back pre-emit, so a serial
                # replay is safe -- and it restores the unpipelined
                # contract that a multi-error payload surfaces its
                # FIRST error in application order (C++ begin), which
                # wave hash-order begin would otherwise change with
                # AMTPU_PIPELINE_DEPTH
                trace.metric('pipeline.serial_replay')
                out = self._apply_unpipelined(payload)
        else:
            out = self._apply_unpipelined(payload)
        telemetry.observe_batch('native', time.perf_counter() - t0,
                                docs=docs)
        return out

    def _apply_unpipelined(self, payload):
        """One whole-payload phase a + b: the non-wave batch body.
        The always-on attribution seams split the wall at the phase
        boundary: `dispatch` = host begin + async device dispatch,
        `collect` = blocking on device outputs + host mid/emit."""
        t0 = time.perf_counter()
        ctx = self._phase_a(payload)
        t1 = time.perf_counter()
        attribution.note_flush_phase('dispatch', t1 - t0)
        try:
            return self._phase_b(ctx)
        except Exception as e:
            _rollback_batch(ctx['bh'], e)
            raise
        finally:
            attribution.note_flush_phase('collect',
                                         time.perf_counter() - t1)
            _free_batch(ctx['bh'])

    def _should_pipeline(self, payload, docs):
        """Wave pipelining engages only where the overlap is real and the
        semantics unchanged: enough docs to split, a device kernel to
        overlap (the full host path has no async device work -- C++
        begin and emit already saturate the core), no armed fault sites
        (chaos lanes pin exact single-batch rollback semantics), and not
        already inside a sharded driver's sub-call (tuple payloads),
        which pipelines across shards itself."""
        if isinstance(payload, tuple):
            return False
        if docs < max(2, _pipeline_min_docs()) or _pipeline_depth() < 2:
            return False
        if faults.ARMED:
            return False
        self._ensure_mode_flags()
        return not _host_full_on()

    def _apply_waves(self, payload, docs):
        """Double-buffered cross-batch staging INSIDE one pool: the
        payload splits into doc-disjoint waves (the same FNV doc hash as
        the shard splitter), every wave's C++ begin + async kernel
        dispatch runs before any wave blocks on results, and phase b
        drains ready-first (`_collect_ready_order`) -- so wave k+1's
        decode/begin/encode overlaps wave k's in-flight device compute
        on the SAME NativeDocPool.  Doc-disjointness is what makes the
        interleaved begins sound: the begin journal, register mirrors,
        member windows, and arenas are all doc-scoped, and the pool-
        global intern/clock tables are append-only.

        Failure semantics: any phase-a error rolls back every begun wave
        in reverse begin order -- nothing has emitted yet, so the call
        stays atomic exactly like the unpipelined path (validation/
        protocol errors all raise at begin).  A phase-b error
        (unreachable for well-formed pools; fault injection disables
        pipelining) rolls back the failed wave while healthy waves still
        run to completion -- the sharded driver's semantics -- and the
        re-raised exception is marked ``amtpu_state_suspect`` when any
        wave committed, so the resilience layer refuses a blind
        whole-payload re-apply instead of double-applying committed
        docs."""
        L = lib()
        depth = min(_pipeline_depth(), docs)
        # bytes only: _should_pipeline rejects shard sub-call views, and
        # waves must never nest inside a shard split (doc-disjointness
        # and failure semantics are reasoned per top-level payload)
        assert isinstance(payload, (bytes, bytearray))
        with trace.span('pipeline.split'):
            # the splitter copies doc sub-payloads into its own buffers,
            # so `payload` only needs to outlive this call
            sp = L.amtpu_shard_split(payload, len(payload), depth)
            if not sp:
                _raise_last()
        try:
            subs = []
            for s in range(depth):
                sub_len = ctypes.c_int64()
                ptr = L.amtpu_shard_buf(sp, s, ctypes.byref(sub_len))
                if sub_len.value > 1:
                    subs.append((ctypes.cast(ptr, ctypes.c_char_p),
                                 sub_len.value))
            ctxs = []
            t_loop0 = time.perf_counter()
            t_a0 = t_loop0
            try:
                for i, sub in enumerate(subs):
                    ctx = self._phase_a(sub, overlapped=True)
                    ctxs.append((i, self, ctx))
                    if i == 0:
                        t_a0 = time.perf_counter()
            except Exception as e:
                # atomic unwind: reverse begin order, nothing emitted.
                # Drain each wave's in-flight kernels BEFORE freeing:
                # their dispatch zero-copied the C++ batch columns the
                # free is about to delete (the PR-4 alias class).
                for _i, _p, ctx in reversed(ctxs):
                    for arr in _ctx_pending_arrays(ctx):
                        try:
                            arr.block_until_ready()
                        except Exception:
                            pass    # already unwinding; kernel errors moot
                    _rollback_batch(ctx['bh'], e)
                    _free_batch(ctx['bh'])
                raise
            if len(ctxs) > 1:
                # host begin time of waves >0: the decode/begin work
                # that ran while wave 0's kernels were already in flight
                trace.metric('collect.overlap_s',
                             time.perf_counter() - t_a0)
            trace.metric('pipeline.batches')
            trace.metric('pipeline.waves', len(ctxs))
            t_disp = time.perf_counter()
            attribution.note_flush_phase('dispatch', t_disp - t_loop0)
            recorder.record('wave.dispatch', n=len(ctxs))
            results = [None] * len(ctxs)
            errors = []

            def keep(i, result):
                results[i] = result

            _collect_ready_order(
                ctxs, on_result=keep,
                on_error=lambda i, e: errors.append((i, e)))
            attribution.note_flush_phase('collect',
                                         time.perf_counter() - t_disp)
            recorder.record('wave.collect', n=len(ctxs))
            if errors:
                _i, err = errors[0]
                # suspect if any wave committed OR any other wave's
                # failure was itself marked suspect (post-emit rollback
                # failure): the marker must survive raising errors[0]
                if (any(r is not None for r in results)
                        or any(getattr(e, 'amtpu_state_suspect', False)
                               for _j, e in errors)):
                    err.amtpu_state_suspect = True
                raise err
            total = 0
            bodies = []
            for r in results:
                cnt, off = _read_map_header(r)
                total += cnt
                bodies.append(memoryview(r)[off:])
            return _map_header(total) + b''.join(bodies)
        finally:
            L.amtpu_shard_free(sp)

    def _phase_a(self, payload, overlapped=False):
        """Host begin + async device dispatch.  Returns a context dict;
        the caller MUST pass it to `_phase_b` and then free ctx['bh'].
        `overlapped=True` (the wave-pipelined driver) forbids donating
        the previous resident clock table: an earlier wave's in-flight
        kernels may still read it.

        `payload` is msgpack bytes, or a zero-copy (ctypes char pointer,
        length) pair -- the sharded driver passes views into the C++
        splitter's buffers; amtpu_begin copies what it keeps, so the
        buffer only needs to outlive this call.

        Splitting here lets a sharded driver overlap shard k+1's host
        `begin` with shard k's in-flight device work on a single thread
        (jax dispatches are async; the transfer is started with
        copy_to_host_async and collected in phase b)."""
        L = lib()
        if isinstance(payload, tuple):
            data, n = payload
        else:
            data, n = payload, len(payload)
        _check_resident_latch()
        self._ensure_mode_flags()
        with trace.span('host.begin'):
            bh = L.amtpu_begin(self._pool, data, n)
        if not bh:
            _raise_last()
        _track_begin()
        fault_docs = None
        if faults.ARMED:
            fault_docs = _batch_docs(bh, payload)
            try:
                faults.fire('native.begin', fault_docs)
            except Exception as e:
                # semantics: "begin failed" -- the pool must look
                # untouched, exactly like a real begin-phase throw
                _rollback_batch(bh, e)
                _free_batch(bh)
                raise
        return self._phase_a_rest(bh, fault_docs, overlapped=overlapped)

    def _phase_a_rest(self, bh, fault_docs=None, overlapped=False):
        """Post-begin half of phase a: read batch dims and dispatch the
        device kernels.  Shared by the batch and local-change entries."""
        L = lib()
        ctx = {'bh': bh, 'fault_docs': fault_docs}
        try:
            dims = (ctypes.c_int64 * self.N_DIMS)()
            L.amtpu_batch_dims(bh, dims)
            (T, Tp, A, Ap, Larena, Lp, n_blocks, max_obj, CTp,
             use_members, any_ovf, max_group, pre_ovf, host_full) = \
                [int(x) for x in dims]
            # 6 slots -- must match what amtpu_fused_dims writes exactly
            # (an undersized ctypes buffer is silent heap corruption)
            fdims = (ctypes.c_int64 * 6)()
            L.amtpu_fused_dims(bh, fdims)
            (fused_ok, W, dLp, dTp, resident_ok,
             res_clock) = [int(x) for x in fdims]
            trace.count('ops.register_rows', T)
            trace.count('ops.arena_elems', Larena)
            # member-window mode (hot keys): explicit candidate indexes +
            # host-computed overflow flags replace the sliding window
            mem = hovf = None
            if use_members and Tp > 0:
                mem = np.ctypeslib.as_array(L.amtpu_col_memidx(bh),
                                            shape=(Tp, self.WINDOW))
                hovf = np.ctypeslib.as_array(L.amtpu_col_hostovf(bh),
                                             shape=(Tp,))
            # Dynamic sliding-window width: the (W+1)^2 pairwise
            # intermediates of the register kernel dominate its cost,
            # and most batches never have more than 2-3 rows per
            # register (text: one set + maybe one delete per elemId).
            # A window covering the batch's widest group is EXACT --
            # saturation (the overflow->oracle fallback) needs a group
            # wider than the window, which cannot happen here.  Member
            # mode keeps the C++-built width.
            if use_members or max_group > self.WINDOW:
                weff = self.WINDOW
            else:
                weff = 2
                while weff < max_group:
                    weff *= 2
            wenv = env_raw('AMTPU_WEFF')
            if wenv and not use_members:
                # test-only: force a narrower window so the overflow
                # branch is REACHABLE (the dynamic sizing above makes
                # saturation impossible by construction); parity still
                # holds because flagged groups escalate through exact
                # wider kernel tiers (or the host oracle under
                # AMTPU_ESCALATE=0).  tests/test_native.py uses this to
                # pin the fallback paths under both dominance modes.
                weff = min(self.WINDOW, max(2, int(wenv)))
            ctx.update(dims=(T, Tp, A, Ap, Larena, Lp, n_blocks, max_obj,
                             CTp), mem=mem, hovf=hovf, weff=weff,
                       resident_ok=bool(resident_ok))

            if host_full:
                # full host path (CPU backend): C++ skipped the register
                # rows at begin; emit resolves registers + list indexes
                # itself (host_resolve_step + in-emit Fenwick)
                trace.count('hostfull.batches')
                trace.metric('hostfull.batches')
                ctx.update(mode='hostreg')
                return ctx

            # Host-register mode: when a map-only batch's register rows
            # mostly sit in groups wider than the member window, emit can
            # resolve each register against the live mirror in one O(w)
            # merge (no sort) with no dispatch at all.  That only beats
            # the kernel on the CPU backend, where XLA shares the host
            # core; on accelerators the escalation ladder keeps the
            # resolution on device (one wider dispatch per tier), so
            # hostreg engages only when the ladder is unavailable.  The
            # 64-writer replica catch-up shape (BASELINE config 5) is
            # the canonical CPU case.
            from ..ops.registers import escalation_enabled
            if (use_members and n_blocks == 0 and 2 * pre_ovf >= T
                    and env_bool('AMTPU_HOST_REG', True)
                    and (not escalation_enabled()
                         or self._backend_is_cpu())):
                trace.count('hostreg.batches')
                trace.metric('hostreg.batches')
                ctx.update(mode='hostreg')
                return ctx

            if res_clock and Tp > 0:
                # pool-resident clock table (tentpole a): sync the
                # device copy -- usually a delta upload of just this
                # batch's appended rows -- and stamp per-batch hit
                # accounting.  Computed only on the kernel paths (the
                # hostreg returns above never stage clocks).
                ctx['ctab_dev'] = self._resclk.table(
                    L, self._pool, donate_ok=not overlapped)
                stats = (ctypes.c_int64 * 2)()
                L.amtpu_resclk_batch_stats(bh, stats)
                if stats[0]:
                    trace.metric('resident.batch_hit_rows',
                                 int(stats[0]))
            elif not res_clock:
                # actor cap crossed mid-pool: release the (possibly
                # huge) device table the moment C++ disables the cache
                self._resclk.drop_if_disabled(L, self._pool)
            if faults.ARMED:
                faults.fire('device.dispatch', ctx['fault_docs'])
            devtime = _devtime_on()
            t0 = time.perf_counter() if devtime else 0.0
            if fused_ok:
                with trace.span('device.dispatch'):
                    self._dispatch_fused(L, ctx, Tp, Ap, CTp, Lp, max_obj,
                                         n_blocks, W, dLp, dTp)
            else:
                trace.count('fused.fallback_layout')
                trace.metric('fallback.layout_batches')
                with trace.span('device.dispatch'):
                    reg_out, rank = self._run_resolver(
                        L, bh, Tp, Ap, CTp, Lp, max_obj, mem,
                        weff=ctx['weff'],
                        ctab_dev=ctx.get('ctab_dev'))
                ctx.update(mode='old', reg_out=reg_out, rank=rank)
                # member-mode overflow flags are HOST-computed, so the
                # escalation tiers dispatch here -- async, overlapping
                # the pipeline's other host work -- and collect in
                # phase b (kernel-decided overflow, e.g. AMTPU_WEFF,
                # stays synchronous in _escalate)
                if hovf is not None and hovf.any():
                    from ..ops import registers as register_ops
                    if register_ops.escalation_enabled():
                        ctx['esc'] = self._escalation_dispatch(
                            L, ctx, hovf.astype(bool))
            if devtime:
                # AMTPU_DEVTIME=1: block on the dispatched outputs and
                # record the synchronous dispatch+compute time.  This
                # serializes the shard pipeline, so bench.py measures it
                # in a dedicated extra pass, never in the timed runs.
                outs = [v for v in (ctx.get('combo'), ctx.get('reg_out'),
                                    ctx.get('rank')) if v is not None]
                if outs:                 # Tp == 0 batches dispatch nothing
                    import jax
                    jax.block_until_ready(outs)
                    trace.metric('device.dispatch_sync_s',
                                 time.perf_counter() - t0)
                    trace.metric('device.dispatches')
            return ctx
        except Exception as e:
            # phase-a failure frees its OWN handle (callers only see an
            # exception, never a ctx to free); the live-handle counter
            # stays balanced -- tests assert live_batch_handles() == 0
            # after forced phase-a errors.  Rollback first: begin already
            # committed schedule state, and a retry/bisect is only byte-
            # safe against the pre-begin pool.
            _rollback_batch(bh, e)
            _free_batch(bh)
            raise

    def _register_views(self, L, bh, Tp, Ap, CTp, ctab_dev=None):
        """ctypes views of the register columns (single source of truth
        for their shapes/dtypes).  `ctab_dev` (the pool-resident device
        clock table) replaces the batch-local table view when the batch
        was encoded against pool-global clock rows (CTp == 0)."""
        if ctab_dev is not None:
            ctab = ctab_dev
        else:
            ctab = np.ctypeslib.as_array(L.amtpu_col_clocktab(bh),
                                         shape=(CTp, Ap))
        return dict(
            g=np.ctypeslib.as_array(L.amtpu_col_g(bh), shape=(Tp,)),
            t=np.ctypeslib.as_array(L.amtpu_col_t(bh), shape=(Tp,)),
            a=np.ctypeslib.as_array(L.amtpu_col_a(bh), shape=(Tp,)),
            s=np.ctypeslib.as_array(L.amtpu_col_s(bh), shape=(Tp,)),
            d=np.ctypeslib.as_array(L.amtpu_col_d(bh), shape=(Tp,)),
            ctab=ctab,
            cidx=np.ctypeslib.as_array(L.amtpu_col_clockidx(bh),
                                       shape=(Tp,)),
            si=np.ctypeslib.as_array(L.amtpu_col_sort(bh), shape=(Tp,)))

    def _arena_views(self, L, bh, Lp):
        """ctypes views of the arena columns."""
        return dict(
            obj=np.ctypeslib.as_array(L.amtpu_col_obj(bh), shape=(Lp,)),
            par=np.ctypeslib.as_array(L.amtpu_col_par(bh), shape=(Lp,)),
            ctr=np.ctypeslib.as_array(L.amtpu_col_ctr(bh), shape=(Lp,)),
            act=np.ctypeslib.as_array(L.amtpu_col_act(bh), shape=(Lp,)),
            val=np.ctypeslib.as_array(L.amtpu_col_val(bh), shape=(Lp,)),
            lsi=np.ctypeslib.as_array(L.amtpu_col_linsort(bh),
                                      shape=(Lp,)))

    def _dispatch_fused(self, L, ctx, Tp, Ap, CTp, Lp, max_obj, n_blocks,
                        W, dLp, dTp):
        from ..ops import list_rank, registers as register_ops
        bh = ctx['bh']
        if Tp == 0:
            # no register ops: nothing to resolve, and without list-assign
            # ops there are no dominance timelines either -- no dispatch
            ctx.update(mode='fused', combo=None, reg_out=None, rank=None)
            return
        r = self._register_views(L, bh, Tp, Ap, CTp,
                                 ctab_dev=ctx.get('ctab_dev'))
        mem = ctx.get('mem')

        def dispatch_registers_only(hostdom=False):
            # register resolution alone: either there is no list-assign
            # work at all (n_blocks == 0) or dominance indexes come from
            # the C++ Fenwick sweep (hostdom) -- rank is consumed by
            # nothing on the host in both cases
            if mem is not None:
                reg_out = register_ops.resolve_registers_members(
                    r['t'], r['a'], r['s'], mem, r['d'].astype(bool),
                    r['ctab'], r['cidx'], window=ctx['weff'],
                    want_visible_before=False)
            else:
                # Pallas stencil kernel on TPU (VMEM-resident pairwise
                # temporaries), XLA twin elsewhere -- bit-equal outputs
                from ..ops.pallas_registers import resolve_registers_auto
                reg_out = resolve_registers_auto(
                    r['g'], r['t'], r['a'], r['s'], r['d'].astype(bool),
                    np.ones((Tp,), bool), r['si'], r['ctab'], r['cidx'],
                    window=ctx['weff'])
            combo = reg_out['packed']
            combo.copy_to_host_async()
            ctx.update(mode='fused', combo=combo, reg_out=reg_out,
                       rank=None, hostdom=hostdom)

        if n_blocks == 0:
            dispatch_registers_only()
            return
        if ctx.get('resident_ok') and mem is None and \
                self._dispatch_resident(L, ctx, Tp, Ap, CTp, max_obj,
                                        dLp, dTp):
            return
        if _host_dom_on():
            # CPU backend: dispatch ONLY register resolution; ranks and
            # dominance indexes come from the C++ Fenwick sweep in
            # phase b (amtpu_host_dominance) instead of the quadratic
            # device kernel.  See _host_dom_on for the rationale.
            dispatch_registers_only(hostdom=True)
            trace.count('hostdom.dispatch')
            return
        e = self._arena_views(L, bh, Lp)
        n_iters = list_rank.ceil_log2(max(max_obj, 1)) + 1
        v0 = np.ctypeslib.as_array(L.amtpu_dom_v0(bh, 0), shape=(W, dLp))
        er_src = np.ctypeslib.as_array(L.amtpu_fdom_ersrc(bh),
                                       shape=(W, dLp))
        oe = np.ctypeslib.as_array(L.amtpu_dom_oe(bh, 0), shape=(W, dTp))
        orank_src = np.ctypeslib.as_array(L.amtpu_fdom_oranksrc(bh),
                                          shape=(W, dTp))
        dom_src = np.ctypeslib.as_array(L.amtpu_fdom_domsrc(bh),
                                        shape=(W, dTp))
        ov = np.ctypeslib.as_array(L.amtpu_dom_ov(bh, 0), shape=(W, dTp))
        reg_out, rank, combo = register_ops.resolve_rank_dominate(
            r['g'], r['t'], r['a'], r['s'], r['ctab'], r['cidx'],
            r['d'].astype(bool), np.ones((Tp,), bool), r['si'],
            e['obj'], e['par'], e['ctr'], e['act'], e['val'].astype(bool),
            e['lsi'], n_iters,
            v0, er_src, oe, orank_src, dom_src, ov.astype(bool),
            window=ctx['weff'], mem_idx=mem)
        combo.copy_to_host_async()
        ctx.update(mode='fused', combo=combo, reg_out=reg_out, rank=rank)

    def _dispatch_resident(self, L, ctx, Tp, Ap, CTp, max_obj, dLp, dTp):
        """Fused dispatch over the DEVICE-RESIDENT arena (single big
        list object): uploads only per-batch deltas; the arena columns,
        visibility vector, and in-graph sibling sort live on device
        between batches (SURVEY hard part 5).  Returns False to fall
        back to the standard fused path (C++ refills the skipped
        layout arrays lazily)."""
        from ..ops import list_rank
        from .resident import _jit_kernel
        # Residency trades per-batch H2D of the whole arena for an
        # in-graph sibling sort: a clear win over a real device link,
        # a loss on the CPU backend where "transfers" are memcpys.
        # Default: on for accelerators, off for CPU; AMTPU_RESIDENT=1/0
        # overrides either way (C++ skips its O(arena) layout fills
        # optimistically and refills lazily when Python declines).
        env = env_raw('AMTPU_RESIDENT')
        if env is None:
            import jax
            if jax.default_backend() == 'cpu':
                return False
        bh = ctx['bh']
        meta = (ctypes.c_int64 * 4)()
        L.amtpu_dom_obj_meta(bh, 0, meta)
        doc_idx, obj_sid, base, n_now = [int(x) for x in meta]
        if base != 0 or n_now <= 0 or n_now > dLp:
            return False
        doc_id = L.amtpu_batch_doc_id(bh, doc_idx)
        entry = self._resident.get_entry(L, self._pool, doc_id, obj_sid,
                                         n_now, dLp)
        if entry is None:
            return False
        r = self._register_views(L, bh, Tp, Ap, CTp,
                                 ctab_dev=ctx.get('ctab_dev'))
        oe = np.ctypeslib.as_array(L.amtpu_dom_oe(bh, 0), shape=(1, dTp))
        dom_src = np.ctypeslib.as_array(L.amtpu_fdom_domsrc(bh),
                                        shape=(1, dTp))
        ov = np.ctypeslib.as_array(L.amtpu_dom_ov(bh, 0), shape=(1, dTp))
        n_iters = list_rank.ceil_log2(max(max_obj, 1)) + 1
        # entry.dirty until the post-emit visibility sync lands: a batch
        # that errors in between leaves the device ev unsynced
        entry.dirty = True
        from .resident import (_jit_kernel_sharded, _sp_device_cap,
                               _sp_sharding)
        if _sp_sharding(dLp, count_fenced=True) is not None:
            # multi-device with a capacity the mesh divides AND past the
            # sp fence's long-list crossover: element axis sharded over
            # sp -- the quadratic dominance stage splits across devices
            # (the promoted AMTPU_BENCH_C1_MESH path)
            fn = _jit_kernel_sharded(n_iters, ctx['weff'], 64,
                                     _sp_device_cap())
            trace.count('resident.sharded_dispatch')
            trace.metric('mesh.sp_engaged')
        else:
            fn = _jit_kernel(n_iters, ctx['weff'], 64)
        reg_out, rank, combo = fn(
            r['g'], r['t'], r['a'], r['s'], r['ctab'], r['cidx'],
            r['d'].astype(bool), np.ones((Tp,), bool), r['si'],
            entry.par, entry.ctr, entry.act, entry.ev,
            np.int32(n_now), oe, dom_src, ov.astype(bool))
        combo.copy_to_host_async()
        touched = np.unique(oe[0][(ov[0] != 0) & (oe[0] >= 0)])
        ctx.update(mode='fused', combo=combo, reg_out=reg_out, rank=rank,
                   resident=(entry, doc_id, obj_sid, n_now,
                             touched.astype(np.int32)))
        trace.count('resident.dispatch')
        # always-on (not AMTPU_TRACE-gated): a bench line labeled
        # `mode: resident` must be able to show residency actually
        # engaged, not silently fell back to the standard fused path
        trace.metric('resident.dispatches')
        return True

    def _mark_resident_stale(self, L, ctx):
        """Invalidates resident entries for every list object this
        (non-resident) batch touched -- its emit updated C++ visibility
        without a device sync."""
        bh = ctx['bh']
        n_blocks = ctx['dims'][6]
        for blk in range(n_blocks):
            bdims = (ctypes.c_int64 * 3)()
            L.amtpu_dom_dims(bh, blk, bdims)
            W = int(bdims[0])
            meta = (ctypes.c_int64 * (4 * W))()
            n_objs = int(L.amtpu_dom_obj_meta(bh, blk, meta))
            for o in range(n_objs):
                doc_idx, obj_sid = int(meta[o * 4]), int(meta[o * 4 + 1])
                doc_id = L.amtpu_batch_doc_id(bh, doc_idx)
                entry = self._resident.entries.get((doc_id, obj_sid))
                if entry is not None:
                    entry.dirty = True
                    trace.count('resident.cross_path_invalidation')

    def _phase_b(self, ctx):
        """Collect device results, run host mid+emit, return patch bytes."""
        L = lib()
        bh = ctx['bh']
        if faults.ARMED:
            # both sites fire BEFORE their phase mutates anything, so a
            # rollback + re-apply reproduces the fault-free byte stream
            if ctx['mode'] != 'hostreg':
                faults.fire('device.collect', ctx.get('fault_docs'))
            faults.fire('native.mid', ctx.get('fault_docs'))
        T, Tp, A, Ap, Larena, Lp, n_blocks, max_obj, CTp = ctx['dims']

        def ip(a):
            return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

        def up(a):
            return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))

        if ctx['mode'] == 'hostreg':
            with trace.span('host.mid'):
                if L.amtpu_mid_hostreg(bh) != 0:
                    _raise_last()
        elif ctx['mode'] == 'fused':
            with trace.span('device.collect'):
                if ctx['combo'] is None:
                    packed = dom_idx = np.zeros(0, np.int32)
                    fallback = False
                    conf_rows = np.zeros(0, np.int32)
                    conf_vals = np.zeros(0, np.int32)
                else:
                    from ..ops import registers as register_ops
                    combo = np.asarray(ctx['combo'])
                    packed = np.ascontiguousarray(combo[:Tp])
                    dom_idx = np.ascontiguousarray(combo[Tp:], np.int32)
                    fallback = bool(
                        (packed >> register_ops.PACKED_OVF_SHIFT
                         & 1).any())
                    if not fallback:
                        # conflicts stay SPARSE: only rows whose register
                        # kept >1 member carry a conflict list (the
                        # dense-workload switch lives in
                        # _fetch_conflict_rows)
                        conf_rows = np.nonzero(
                            (packed >> register_ops.PACKED_ALIVE_SHIFT
                             & register_ops.PACKED_ALIVE_MASK)
                            > 1)[0].astype(np.int32)
                        conf_vals = self._fetch_conflict_rows(
                            ctx['reg_out'], conf_rows, Tp)
            if fallback:
                # >window concurrent writers on some register: re-fetch
                # the full outputs + rank, escalate the flagged groups
                # through wider kernel tiers, and hand only what the
                # ladder could not hold (fallback.oracle) to the C++
                # oracle replay
                trace.count('fused.fallback_overflow')
                trace.metric('fallback.overflow_batches')
                trace.metric('fallback.overflow_rows',
                             int((packed >> register_ops.PACKED_OVF_SHIFT
                                  & 1).sum()))
                trace.metric('collect.full_matrix_readback')
                reg_out = ctx['reg_out']
                winner = np.ascontiguousarray(reg_out['winner'], np.int32)
                conflicts = np.ascontiguousarray(reg_out['conflicts'],
                                                 np.int32)
                alive = np.ascontiguousarray(reg_out['alive_after'],
                                             np.int32)
                overflow = np.ascontiguousarray(reg_out['overflow'],
                                                np.uint8)
                winner, conflicts, alive, overflow = self._escalate(
                    L, ctx, winner, conflicts, alive, overflow)
                rank_arr = (np.ascontiguousarray(ctx['rank'], np.int32)
                            if ctx['rank'] is not None
                            else np.zeros(0, np.int32))
                hostdom = ctx.get('hostdom')
                with trace.span('host.mid'):
                    if L.amtpu_mid(bh, ip(winner), ip(conflicts),
                                   self._mid_window(ctx, conflicts),
                                   ip(alive), up(overflow),
                                   None if hostdom else ip(rank_arr),
                                   1 if hostdom else 0) != 0:
                        _raise_last()
                if hostdom:
                    with trace.span('host.dominance'):
                        if L.amtpu_host_dominance(bh) != 0:
                            _raise_last()
                else:
                    t0 = time.perf_counter() if _devtime_on() else 0.0
                    with trace.span('device.dominance'):
                        self._run_dominance(L, bh)
                    if t0:
                        trace.metric('device.dispatch_sync_s',
                                     time.perf_counter() - t0)
                        trace.metric('device.dispatches')
            else:
                hostdom = ctx.get('hostdom')
                conf_offs = np.arange(conf_rows.size + 1,
                                      dtype=np.int32) * ctx['weff']
                with trace.span('host.mid'):
                    if L.amtpu_mid_packed(
                            bh, ip(packed), ctx['weff'], ip(conf_rows),
                            ip(conf_offs), ip(conf_vals), len(conf_rows),
                            None, None, None if hostdom else ip(dom_idx),
                            1 if hostdom else 0) != 0:
                        _raise_last()
                if hostdom:
                    with trace.span('host.dominance'):
                        if L.amtpu_host_dominance(bh) != 0:
                            _raise_last()
        else:
            reg_out, rank = ctx['reg_out'], ctx['rank']
            # Packed member epilogue (ISSUE 3 tentpole a): member-mode
            # batches transfer ONE i32 per register row + a sparse CSR
            # conflict gather instead of the full O(Tp x W) matrices;
            # escalation-tier results merge into the packed word, and
            # only the ladder's residue rides the C++ oracle replay.
            if (Tp > 0 and ctx.get('hovf') is not None
                    and Tp < (1 << 24) and _packed_epilogue_on()):
                with trace.span('device.collect'):
                    (packed, conf_rows, conf_offs, conf_vals,
                     residual) = self._collect_member_packed(
                        ctx, reg_out, Tp)
                    rank_arr = np.ascontiguousarray(rank, np.int32)
                trace.metric('collect.packed_member_batches')
                with trace.span('host.mid'):
                    if L.amtpu_mid_packed(
                            bh, ip(packed), ctx['weff'], ip(conf_rows),
                            ip(conf_offs), ip(conf_vals), len(conf_rows),
                            None if residual is None else up(residual),
                            ip(rank_arr), None, 0) != 0:
                        _raise_last()
            else:
                with trace.span('device.collect'):
                    if Tp > 0:
                        trace.metric('collect.full_matrix_readback')
                        winner, conflicts, alive, overflow = \
                            self._unpack_register_out(reg_out, Tp)
                        if ctx.get('hovf') is not None:
                            # member mode: overflow is host-decided
                            # (>WINDOW concurrent streams / same-change
                            # dup assigns)
                            overflow = np.array(ctx['hovf'], np.uint8)
                            n_ovf = int(overflow.sum())
                            if n_ovf:
                                trace.metric(
                                    'fallback.member_overflow_rows',
                                    n_ovf)
                                trace.metric('fallback.overflow_batches')
                        if overflow.any():
                            winner, conflicts, alive, overflow = \
                                self._escalate(L, ctx, winner, conflicts,
                                               alive, overflow)
                    else:
                        winner = conflicts = alive = np.zeros(0, np.int32)
                        overflow = np.zeros(0, np.uint8)
                    rank_arr = np.ascontiguousarray(rank, np.int32)
                with trace.span('host.mid'):
                    if L.amtpu_mid(bh, ip(winner), ip(conflicts),
                                   self._mid_window(ctx, conflicts),
                                   ip(alive), up(overflow),
                                   ip(rank_arr), 0) != 0:
                        _raise_last()
            t0 = time.perf_counter() if _devtime_on() else 0.0
            with trace.span('device.dominance'):
                self._run_dominance(L, bh)
            if t0:
                trace.metric('device.dispatch_sync_s',
                             time.perf_counter() - t0)
                trace.metric('device.dispatches')

        with trace.span('host.finish'):
            if L.amtpu_finish(bh) != 0:
                _raise_last()
        if ctx.get('resident') is not None:
            # post-emit visibility sync from the C++ arena ground truth
            entry, doc_id, obj_sid, n_now, touched = ctx['resident']
            self._resident.sync_after_emit(L, self._pool, entry, doc_id,
                                           obj_sid, n_now, touched)
        elif self._resident.entries:
            # a NON-resident batch may have flipped visibility on arenas
            # the cache holds (multi-object batches, member-window mode,
            # overflow); mark every overlapping entry stale
            self._mark_resident_stale(L, ctx)
        if trace.ENABLED:
            tr = (ctypes.c_double * 6)()
            L.amtpu_batch_trace(bh, tr)
            for name, val in zip(('decode', 'schedule', 'encode',
                                  'mid', 'emit', 'domlay'), tr):
                trace.add('cxx.' + name, float(val))
            sc = (ctypes.c_int64 * 4)()
            L.amtpu_sched_counts(bh, sc)
            trace.count('sched.fast_path', int(sc[0]))
            trace.count('sched.queued', int(sc[1]))
            if sc[2]:
                trace.count('sched.trivial_rows', int(sc[2]))
                trace.count('sched.trivial_groups', int(sc[3]))
        out_len = ctypes.c_int64()
        ptr = L.amtpu_result(bh, ctypes.byref(out_len))
        return ctypes.string_at(ptr, out_len.value) \
            if out_len.value else b'\x80'

    @staticmethod
    def _mid_window(ctx, conflicts):
        """Conflicts-matrix width handed to amtpu_mid: the escalation
        merge may have widened it beyond the dispatch window."""
        return int(conflicts.shape[1]) if conflicts.ndim == 2 \
            else ctx['weff']

    def _esc_layout_groups(self, L, bh):
        """CSR group records from the C++ escalation layout
        (amtpu_esc_*), built at begin for member-mode overflow --
        replaces the host-side window re-derivation.  None when the
        batch carries no layout (sliding-mode overflow, AMTPU_WEFF)."""
        dims = (ctypes.c_int64 * 3)()
        L.amtpu_esc_dims(bh, dims)
        n_groups, R, M = [int(x) for x in dims]
        if n_groups == 0:
            return None
        meta = np.ctypeslib.as_array(L.amtpu_esc_group_meta(bh),
                                     shape=(n_groups, 3))
        rows_all = np.ctypeslib.as_array(L.amtpu_esc_rows(bh), shape=(R,))
        off = np.ctypeslib.as_array(L.amtpu_esc_mem_off(bh),
                                    shape=(R + 1,))
        vals_all = np.ctypeslib.as_array(L.amtpu_esc_mem(bh),
                                         shape=(M,)) if M else \
            np.zeros(0, np.int32)
        groups = []
        for gi in range(n_groups):
            rs, k, width = (int(meta[gi, 0]), int(meta[gi, 1]),
                            int(meta[gi, 2]))
            groups.append((rows_all[rs:rs + k],
                           np.diff(off[rs:rs + k + 1]),
                           vals_all[off[rs]:off[rs + k]], width))
        return groups

    def _escalation_dispatch(self, L, ctx, flagged):
        """Tier-ladder dispatch for this batch's flagged rows: prefers
        the C++-prebuilt member layout; falls back to the generic host
        window build (sliding-mode overflow has no layout)."""
        from ..ops import registers as register_ops
        Tp, Ap = ctx['dims'][1], ctx['dims'][3]
        CTp = ctx['dims'][8]
        r = self._register_views(L, ctx['bh'], Tp, Ap, CTp,
                                 ctab_dev=ctx.get('ctab_dev'))
        groups = self._esc_layout_groups(L, ctx['bh'])
        if groups is not None:
            return register_ops.escalate_dispatch_groups(
                groups, r['t'], r['a'], r['s'], r['d'].astype(bool),
                r['ctab'], r['cidx'], want_visible_before=False)
        return register_ops.escalate_overflow_dispatch(
            r['g'], r['t'], r['a'], r['s'], r['d'].astype(bool),
            r['ctab'], r['cidx'], flagged, want_visible_before=False)

    def _escalate(self, L, ctx, winner, conflicts, alive, overflow):
        """Tiered escalation ladder over the batch's register columns:
        collects the tier dispatches (pre-dispatched async in phase a
        when the flags were host-computed, dispatched here otherwise)
        and merges the results, clearing the flags of resolved rows.
        Rows still flagged afterwards -- groups wider than every tier /
        over the scratch budget, or all of them under AMTPU_ESCALATE=0
        -- take the C++ oracle replay in amtpu_mid and are counted as
        fallback.oracle."""
        from ..ops import registers as register_ops
        esc = ctx.pop('esc', None)
        if esc is None and register_ops.escalation_enabled():
            esc = self._escalation_dispatch(L, ctx,
                                            overflow.astype(bool))
        if esc is not None:
            chunks = register_ops.escalate_overflow_collect_arrays(esc[0])
            if chunks:
                winner = np.array(winner, np.int32)
                conflicts = np.array(conflicts, np.int32)
                alive = np.array(alive, np.int32)
                overflow = np.array(overflow, np.uint8)
                winner, conflicts, alive, overflow = \
                    register_ops.merge_escalated_arrays(
                        winner, conflicts, alive, overflow, chunks)
        n_oracle = int(np.asarray(overflow, bool).sum())
        if n_oracle:
            trace.metric('fallback.oracle', n_oracle)
        return winner, conflicts, alive, overflow

    def _collect_member_packed(self, ctx, reg_out, Tp):
        """Packed member epilogue (ISSUE 3 tentpole a): ONE [Tp] i32
        word + a sparse CSR conflict gather cross the device boundary
        instead of the full winner/conflicts/alive/overflow matrices
        (_unpack_register_out).  Escalation-tier results merge INTO the
        packed word host-side -- their conflicts ride the same CSR at
        tier width -- and rows the ladder could not resolve stay flagged
        in the returned residual vector for the C++ oracle replay
        (fallback.oracle).

        Returns (packed [Tp] i32, conf_rows, conf_offs, conf_vals,
        residual u8 [Tp] | None)."""
        from ..ops import registers as register_ops
        flagged = np.asarray(ctx['hovf']).astype(bool)
        residual = None
        esc_parts = []            # (global rows, global conflicts) pairs
        esc = None
        if flagged.any():
            trace.metric('fallback.member_overflow_rows',
                         int(flagged.sum()))
            trace.metric('fallback.overflow_batches')
            esc = ctx.pop('esc', None)
            if esc is None and register_ops.escalation_enabled():
                # flags are host-computed, so phase a normally
                # pre-dispatched the tiers; dispatch late if it could not
                esc = self._escalation_dispatch(lib(), ctx, flagged)
        # Device-side tier merge (ISSUE 6 tentpole b): scatter each tier
        # chunk's packed words into the base word ON DEVICE -- tier-local
        # winners translate to global rows through the chunk's row map --
        # so the ONE packed transfer below returns the word already
        # resolved for every tier-escalated row; the host's remaining
        # merge work is the residual vector + sparse conflicts.
        dev_merge = (esc is not None and len(esc[0]) > 0
                     and register_ops.device_merge_on())
        if dev_merge:
            base = reg_out['packed']
            for _W, sub_rows, out in esc[0]:
                Tn = int(out['packed'].shape[0])
                rows_p = np.full(Tn, Tp, np.int32)       # Tp = dropped
                rows_p[:len(sub_rows)] = sub_rows
                sub_p = np.zeros(Tn, np.int32)
                sub_p[:len(sub_rows)] = sub_rows
                base = register_ops.merge_packed_rows(
                    base, rows_p, out['packed'], sub_p)
            trace.metric('collect.device_merge_chunks', len(esc[0]))
            packed = np.asarray(base)
        else:
            packed = np.asarray(reg_out['packed'])
        if flagged.any():
            if not dev_merge:
                packed = np.array(packed)        # writable copy
            residual = np.array(np.asarray(ctx['hovf']), np.uint8)
            if esc is not None:
                for ch in register_ops.escalate_overflow_collect_arrays(
                        esc[0], need_winner=not dev_merge):
                    if not dev_merge:
                        packed[ch.rows] = register_ops.pack_register_word(
                            ch.winner, ch.alive)
                    residual[ch.rows] = 0
                    if ch.conf_rows.size:
                        esc_parts.append((ch.rows[ch.conf_rows],
                                          ch.conflicts))
            n_oracle = int(residual.sum())
            if n_oracle:
                trace.metric('fallback.oracle', n_oracle)
            else:
                residual = None
        # base sparse conflicts: rows OUTSIDE flagged groups that kept
        # more than one member (flagged groups' base-kernel output is
        # invalid -- they re-resolved in the tiers or the oracle replay)
        base_mask = ((packed >> register_ops.PACKED_ALIVE_SHIFT)
                     & register_ops.PACKED_ALIVE_MASK) > 1
        if flagged.any():
            base_mask &= ~flagged
        conf_rows_b = np.nonzero(base_mask)[0].astype(np.int32)
        conf_vals_b = self._fetch_conflict_rows(reg_out, conf_rows_b, Tp)
        weff = ctx['weff']
        if not esc_parts:
            conf_offs = np.arange(conf_rows_b.size + 1,
                                  dtype=np.int32) * weff
            conf_vals = np.ascontiguousarray(conf_vals_b, np.int32) \
                .reshape(-1)
            return packed, conf_rows_b, conf_offs, conf_vals, residual
        rows_parts = [conf_rows_b]
        vals_parts = [np.ascontiguousarray(conf_vals_b,
                                           np.int32).reshape(-1)]
        lens = [np.full(conf_rows_b.size, weff, np.int32)]
        for rows_g, conf_g in esc_parts:
            rows_parts.append(np.ascontiguousarray(rows_g, np.int32))
            vals_parts.append(np.ascontiguousarray(conf_g,
                                                   np.int32).reshape(-1))
            lens.append(np.full(rows_g.size, conf_g.shape[1], np.int32))
        conf_rows = np.ascontiguousarray(np.concatenate(rows_parts),
                                         np.int32)
        conf_offs = np.zeros(conf_rows.size + 1, np.int32)
        np.cumsum(np.concatenate(lens), out=conf_offs[1:])
        conf_vals = np.ascontiguousarray(np.concatenate(vals_parts),
                                         np.int32)
        return packed, conf_rows, conf_offs, conf_vals, residual

    def _fetch_conflict_rows(self, reg_out, conf_rows, Tp):
        """Sparse-vs-dense conflicts fetch: the device row gather wins
        while >1-member rows are rare; once `conf_rows * thresh > Tp`
        (AMTPU_CONF_DENSE_THRESH, default 4; 0 disables the dense path)
        the whole [Tp, W] matrix transfers once and slices host-side
        instead.  Each choice is counted: collect.conflict_sparse /
        collect.conflict_dense."""
        thresh = _conf_dense_thresh()
        if thresh and conf_rows.size * thresh > Tp:
            trace.metric('collect.conflict_dense')
            allconf = np.asarray(reg_out['conflicts'])
            return np.ascontiguousarray(allconf[conf_rows], np.int32)
        if conf_rows.size:
            trace.metric('collect.conflict_sparse')
        return self._gather_conflict_rows(reg_out, conf_rows)

    def _gather_conflict_rows(self, reg_out, rows):
        """Lazy conflicts fetch: only registers that kept >1 member have
        conflict rows worth transferring.  Returns [n, WINDOW] i32."""
        from ..ops import registers as register_ops
        if not rows.size:
            return np.zeros(0, np.int32)
        pad = 1
        while pad < rows.size:
            pad *= 2
        rows_p = np.zeros((pad,), np.int32)
        rows_p[:rows.size] = rows
        got = np.asarray(register_ops.gather_rows(
            reg_out['conflicts'], rows_p))[:rows.size]
        return np.ascontiguousarray(got, np.int32)

    def _gather_conflicts(self, reg_out, alive, Tp):
        """Dense [Tp, W] conflicts (fallback paths); width follows the
        kernel's conflicts output (the dynamic window)."""
        width = int(reg_out['conflicts'].shape[1])
        conflicts = np.full((Tp, width), -1, np.int32)
        rows = np.nonzero(alive > 1)[0].astype(np.int32)
        got = self._gather_conflict_rows(reg_out, rows)
        if rows.size:
            conflicts[rows] = got
        return conflicts

    # -- kernel dispatch ------------------------------------------------

    def _run_resolver(self, L, bh, Tp, Ap, CTp, Lp, max_obj_len,
                      mem=None, weff=None, ctab_dev=None):
        """Register resolution + linearization, fused into one dispatch
        when both are needed (halves blocking round trips on the
        high-latency device link).  Returns (reg_out device dict | None,
        rank np.int32 [Lp])."""
        from ..ops import list_rank, registers as register_ops
        if Tp > 0:
            r = self._register_views(L, bh, Tp, Ap, CTp,
                                     ctab_dev=ctab_dev)
        if Lp > 0:
            e = self._arena_views(L, bh, Lp)
            # doubling depth: DFS chains never cross objects
            n_iters = list_rank.ceil_log2(max(max_obj_len, 1)) + 1
        if Tp > 0 and Lp > 0:
            reg_out, rank = register_ops.resolve_and_rank(
                r['g'], r['t'], r['a'], r['s'], r['ctab'], r['cidx'],
                r['d'].astype(bool), np.ones((Tp,), bool), r['si'],
                e['obj'], e['par'], e['ctr'], e['act'],
                e['val'].astype(bool), e['lsi'], n_iters,
                window=weff, mem_idx=mem)
            return reg_out, np.asarray(rank)
        if Tp > 0:
            if mem is not None:
                reg_out = register_ops.resolve_registers_members(
                    r['t'], r['a'], r['s'], mem, r['d'].astype(bool),
                    r['ctab'], r['cidx'], window=weff,
                    want_visible_before=False)
            else:
                reg_out = register_ops.resolve_registers(
                    r['g'], r['t'], r['a'], r['s'],
                    is_del=r['d'].astype(bool),
                    alive_in=np.ones((Tp,), bool), window=weff,
                    sort_idx=r['si'], clock_table=r['ctab'],
                    clock_idx=r['cidx'])
            return reg_out, np.zeros((0,), np.int32)
        if Lp > 0:
            rank = np.asarray(list_rank.linearize(
                e['obj'], e['par'], e['ctr'], e['act'],
                e['val'].astype(bool), n_iters, sort_idx=e['lsi']))
            return None, rank
        return None, np.zeros((0,), np.int32)

    def _unpack_register_out(self, reg_out, Tp):
        """One packed [Tp] i32 transfer for winner/alive/overflow plus a
        lazy row-gather of conflicts only where a register kept >1 member
        (D2H over the device link is the scarce resource, not compute)."""
        from ..ops import registers as register_ops
        if Tp >= 1 << 24:    # packed winner field width exceeded
            winner = np.ascontiguousarray(reg_out['winner'], np.int32)
            conflicts = np.ascontiguousarray(reg_out['conflicts'], np.int32)
            alive = np.ascontiguousarray(reg_out['alive_after'], np.int32)
            overflow = np.ascontiguousarray(reg_out['overflow'], np.uint8)
            return winner, conflicts, alive, overflow
        packed = np.asarray(reg_out['packed'])
        winner, alive, overflow = self._unpack_packed(packed)
        conflicts = self._gather_conflicts(reg_out, alive, Tp)
        return winner, conflicts, alive, overflow

    @staticmethod
    def _unpack_packed(packed):
        """Splits the packed [T] i32 register summary (24-bit winner,
        PACKED_WINNER_NONE = none | 6-bit alive, saturated at
        PACKED_ALIVE_MAX | overflow in bit PACKED_OVF_SHIFT) -- the
        decode twin of ops/registers.pack_register_word; both sides read
        the layout from the shared PACKED_* constants."""
        from ..ops import registers as register_ops
        winner = np.ascontiguousarray(
            packed & register_ops.PACKED_WINNER_MASK, np.int32)
        winner[winner == register_ops.PACKED_WINNER_NONE] = -1
        alive = np.ascontiguousarray(
            (packed >> register_ops.PACKED_ALIVE_SHIFT)
            & register_ops.PACKED_ALIVE_MASK, np.int32)
        overflow = np.ascontiguousarray(
            (packed >> register_ops.PACKED_OVF_SHIFT) & 1, np.uint8)
        return winner, alive, overflow

    def _run_dominance(self, L, bh):
        """Fallback-path dominance: per size-class device dispatches using
        the host-filled er/orank/od mirrors (after amtpu_mid).  Blocks are
        one-per-class since begin; classes too wide for one dispatch are
        sliced along the object axis here (numpy views are cheap)."""
        from ..ops.pallas_dominance import dominance_grouped_auto
        dims = (ctypes.c_int64 * self.N_DIMS)()
        L.amtpu_batch_dims(bh, dims)
        n_blocks = int(dims[6])
        bdims = (ctypes.c_int64 * 3)()
        CAP = 256 << 20
        for blk in range(n_blocks):
            L.amtpu_dom_dims(bh, blk, bdims)
            W, Lp, Tp = [int(x) for x in bdims]
            v0 = np.ctypeslib.as_array(L.amtpu_dom_v0(bh, blk),
                                       shape=(W, Lp))
            er = np.ctypeslib.as_array(L.amtpu_dom_er(bh, blk),
                                       shape=(W, Lp))
            oe = np.ctypeslib.as_array(L.amtpu_dom_oe(bh, blk),
                                       shape=(W, Tp))
            orank = np.ctypeslib.as_array(L.amtpu_dom_orank(bh, blk),
                                          shape=(W, Tp))
            od = np.ctypeslib.as_array(L.amtpu_dom_od(bh, blk),
                                       shape=(W, Tp))
            ov = np.ctypeslib.as_array(L.amtpu_dom_ov(bh, blk),
                                       shape=(W, Tp))
            w_cap = max(1, min(CAP // (Lp * 64 * 4), CAP // (Tp * 4)))
            if W <= w_cap:
                idx = np.asarray(dominance_grouped_auto(
                    v0, er, oe, orank, od, ov.astype(bool), chunk=64))
            else:
                idx = np.empty((W, Tp), np.int32)
                for s in range(0, W, w_cap):
                    hi = min(W, s + w_cap)
                    n = hi - s

                    def pad(x, fill):
                        if n == w_cap:
                            return x[s:hi]
                        out = np.full((w_cap,) + x.shape[1:], fill,
                                      x.dtype)
                        out[:n] = x[s:hi]
                        return out

                    got = np.asarray(dominance_grouped_auto(
                        pad(v0, 0.0), pad(er, -1), pad(oe, -1),
                        pad(orank, -1), pad(od, 0),
                        pad(ov, 0).astype(bool), chunk=64))
                    idx[s:hi] = got[:n]
            idx = np.ascontiguousarray(idx, np.int32)
            L.amtpu_dom_set_indexes(
                bh, blk, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))

    # -- dict-level API (test parity with TPUDocPool) -------------------

    _doc_key = staticmethod(doc_key)

    def apply_batch_bytes_resilient(self, payload):
        """`apply_batch_bytes` behind the resilience layer: transient
        failures retry with backoff, persistent ones bisect down to the
        poison doc(s), which quarantine as per-doc error envelopes while
        every healthy doc commits (docs/RESILIENCE.md)."""
        from .. import resilience
        return resilience.apply_payload(self, payload)

    def apply_batch(self, changes_by_doc):
        return _apply_batch_dicts(self, changes_by_doc)

    def apply_changes(self, doc_id, changes):
        out = self.apply_batch({doc_id: changes})[doc_id]
        _raise_if_quarantined(doc_id, out)
        return out

    def apply_local_change(self, doc_id, request):
        """Applies one local change request with the reference's undo
        semantics (backend/index.js:175-197): requestType 'change' records
        inverse ops on the per-doc undo stack; 'undo'/'redo' execute the
        stacks.  Returns the patch (incl. actor/seq and real
        canUndo/canRedo)."""
        key = self._doc_key(doc_id)
        payload = msgpack.packb(request, use_bin_type=True)
        # local changes latch the C++ statics / jit caches exactly like
        # batches do, so they must take (or check) the same snapshot --
        # a gateway that serves local changes first would otherwise
        # baseline the latch on post-flip values
        _check_resident_latch()
        self._ensure_mode_flags()
        with trace.span('host.begin'):
            bh = lib().amtpu_begin_local(self._pool, key.encode(), payload,
                                         len(payload))
        if not bh:
            _raise_last()
        _track_begin()
        if faults.ARMED:
            try:
                faults.fire('native.begin', [key])
            except Exception as e:
                _rollback_batch(bh, e)
                _free_batch(bh)
                raise
        ctx = self._phase_a_rest(bh, [key] if faults.ARMED else None)
        try:
            out = self._phase_b(ctx)
        except Exception as e:
            _rollback_batch(bh, e)
            raise
        finally:
            _free_batch(bh)
        return msgpack.unpackb(out, raw=False, strict_map_key=False)[key]

    def get_patch(self, doc_id):
        out_len = ctypes.c_int64()
        ptr = lib().amtpu_get_patch(
            self._pool, self._doc_key(doc_id).encode(),
            ctypes.byref(out_len))
        if not ptr:
            _raise_last()
        return msgpack.unpackb(_take_buf(ptr, out_len.value), raw=False)

    def get_clock(self, doc_id):
        """{'clock': ..., 'deps': ...} without materializing the doc --
        the cheap per-round query replica catch-up gossips."""
        out_len = ctypes.c_int64()
        ptr = lib().amtpu_get_clock(
            self._pool, self._doc_key(doc_id).encode(),
            ctypes.byref(out_len))
        if not ptr:
            _raise_last()
        return msgpack.unpackb(_take_buf(ptr, out_len.value), raw=False)

    def _tail_raws(self, key):
        """Raw msgpack bytes of the changes the C++ arena still holds
        for `key` (the post-truncation tail), application order."""
        from .. import storage
        out_len = ctypes.c_int64()
        ptr = lib().amtpu_save(self._pool, key.encode(),
                               ctypes.byref(out_len))
        if not ptr:
            _raise_last()
        raw_v1 = _take_buf(ptr, out_len.value)
        return storage.split_changes_array(
            memoryview(raw_v1)[len(_CKPT_PREFIX):])

    def _snapshot_raws(self, st):
        from .. import storage
        out = []
        for chunk in st['chunks']:
            out.extend(storage.decode_columnar(chunk))
        return out

    def save(self, doc_id):
        """Checkpoint one doc as msgpack bytes: by default the v2
        COLUMNAR container (settled snapshot chunks + delta/RLE-encoded
        tail, docs/STORAGE.md) -- compacted docs reuse their cached
        snapshot bytes, so save cost is O(tail), not O(history).
        ``AMTPU_STORAGE_FORMAT=json`` emits the PR-4 v1 container (raw
        change history, the parity oracle).  Load with `load()` on any
        pool; both formats restore byte-identically (the reference's
        save serializes opSet.history, src/automerge.js:45-52)."""
        from .. import storage
        key = self._doc_key(doc_id)
        st = self._storage.get(key)
        tail = self._tail_raws(key)
        if storage.storage_format() == 'json':
            if not st or not st['chunks']:
                return storage.pack_checkpoint_v1(tail)
            # parity-oracle arm of a doc compacted earlier (format
            # flipped mid-process / v2 blob loaded): reconstruct the
            # full v1 history
            return storage.pack_checkpoint_v1(
                self._snapshot_raws(st) + tail)
        frontier = dict(st['frontier']) if st else {}
        chunks = list(st['chunks']) if st else []
        return storage.pack_checkpoint(frontier, chunks, tail)

    def load(self, doc_id, data):
        """Restores a `save()` checkpoint (either container format) as
        ONE batched replay (the reference replays scalar, O(history)
        through a fresh backend -- here the whole history resolves in a
        single kernel pass).  A v2 container's settled snapshot is re-
        adopted, so a reloaded doc stays compacted.  Returns the doc's
        whole-state patch."""
        from .. import storage
        if not storage.is_checkpoint(data):
            from ..errors import RangeError
            raise RangeError('not an amtpu-doc checkpoint')
        _load_batch(self, {doc_id: data})
        return self.get_patch(doc_id)

    def load_batch(self, blobs):
        """Restores MANY save() checkpoints in one batched replay
        ({doc_id: bytes}); the whole DocSet resolves in a single kernel
        pass instead of one device round trip per doc."""
        _load_batch(self, blobs)

    def restore_from_store(self, store, doc_ids=None, batch=None,
                           threads=None):
        """Restores the store's whole manifest inventory into this pool
        (module-level `restore_from_store`; a single pool applies
        serially with the next batch's blob reads prefetching)."""
        return restore_from_store(self, store, doc_ids=doc_ids,
                                  batch=batch, threads=threads)

    def get_missing_deps(self, doc_id):
        out_len = ctypes.c_int64()
        ptr = lib().amtpu_get_missing_deps(
            self._pool, self._doc_key(doc_id).encode(),
            ctypes.byref(out_len))
        if not ptr:
            _raise_last()
        return msgpack.unpackb(_take_buf(ptr, out_len.value), raw=False)

    def _missing_clock(self, key, have_deps):
        """The transitively-closed {actor: from_seq} clock the C++
        missing-changes walk serves from (the same closure, exposed)."""
        have = msgpack.packb(dict(have_deps), use_bin_type=True)
        out_len = ctypes.c_int64()
        ptr = lib().amtpu_get_missing_clock(
            self._pool, key.encode(), have, len(have),
            ctypes.byref(out_len))
        if not ptr:
            _raise_last()
        return msgpack.unpackb(_take_buf(ptr, out_len.value), raw=False)

    def _missing_changes_raw(self, key, have_deps):
        have = msgpack.packb(dict(have_deps), use_bin_type=True)
        out_len = ctypes.c_int64()
        ptr = lib().amtpu_get_missing_changes(
            self._pool, key.encode(), have, len(have),
            ctypes.byref(out_len))
        if not ptr:
            _raise_last()
        return _take_buf(ptr, out_len.value)

    def get_missing_changes(self, doc_id, have_deps):
        """Changes the requester is missing given its `have_deps`
        clock.  A doc compacted behind the settled frontier serves a
        straggler (whose closure reaches into the snapshot) by merging
        snapshot-decoded changes with the C++ tail, in exactly the
        order the untruncated walk would have produced -- byte parity
        is the GC-frontier test lane's contract (docs/STORAGE.md)."""
        from .. import storage
        key = self._doc_key(doc_id)
        st = self._storage.get(key)
        if st and st['chunks']:
            from_clock = self._missing_clock(key, have_deps)
            if any(from_clock.get(a, 0) < s
                   for a, s in st['frontier'].items()):
                telemetry.metric('storage.snapshot_backfills')
                raws = self._merged_missing_raws(key, st, from_clock)
                return [msgpack.unpackb(r, raw=False,
                                        strict_map_key=False)
                        for r in raws]
        return msgpack.unpackb(self._missing_changes_raw(key, have_deps),
                               raw=False)

    def _merged_missing_raws(self, key, st, from_clock):
        """Snapshot + tail merge: per actor in first-seen application
        order, changes with seq > from_clock[actor], seq ascending --
        the exact emission order of the C++ walk over full history."""
        from .. import storage
        full = []
        for chunk in st['chunks']:
            full.extend(storage.decode_columnar_meta(chunk))
        for raw in self._tail_raws(key):
            c = msgpack.unpackb(raw, raw=False, strict_map_key=False)
            full.append((raw, c.get('actor'), c.get('seq')))
        actor_order, per_actor = [], {}
        for raw, actor, seq in full:
            if actor not in per_actor:
                actor_order.append(actor)
                per_actor[actor] = []
            per_actor[actor].append((seq, raw))
        out = []
        for actor in actor_order:
            frm = from_clock.get(actor, 0)
            out.extend(raw for seq, raw in per_actor[actor]
                       if seq is not None and seq > frm)
        return out

    def get_register(self, doc_id, obj, key):
        """Current field ops of one (obj, key), winner first -- the
        Backend.getFieldOps query undo/redo capture reads."""
        out_len = ctypes.c_int64()
        ptr = lib().amtpu_get_register(
            self._pool, self._doc_key(doc_id).encode(), obj.encode(),
            key.encode(), ctypes.byref(out_len))
        if not ptr:
            _raise_last()
        return msgpack.unpackb(_take_buf(ptr, out_len.value), raw=False)

    def get_changes_for_actor(self, doc_id, actor, after_seq=0):
        """(parity: op_set.js:347-357)"""
        return msgpack.unpackb(
            self.get_changes_for_actor_bytes(doc_id, actor, after_seq),
            raw=False)

    def get_changes_for_actor_bytes(self, doc_id, actor, after_seq=0):
        """Raw msgpack array of changes -- the zero-decode shipping path
        replica catch-up uses (change bytes pass sender -> receiver
        without ever becoming Python objects).  Compacted docs splice
        snapshot-decoded raws ahead of the C++ tail (decode_columnar is
        byte-lossless, so the shipped bytes are identical either way)."""
        from .. import storage
        key = self._doc_key(doc_id)
        out_len = ctypes.c_int64()
        ptr = lib().amtpu_get_changes_for_actor(
            self._pool, key.encode(), actor.encode(),
            after_seq, ctypes.byref(out_len))
        if not ptr:
            _raise_last()
        buf = _take_buf(ptr, out_len.value)
        st = self._storage.get(key)
        if not st or not st['chunks'] \
                or after_seq >= st['frontier'].get(actor, 0):
            return buf
        telemetry.metric('storage.snapshot_backfills')
        head = []
        for chunk in st['chunks']:
            for raw, a, seq in storage.decode_columnar_meta(chunk):
                if a == actor and seq is not None and seq > after_seq:
                    head.append(raw)
        return storage.join_changes_array(
            head + storage.split_changes_array(buf))

    def _apply_columnar(self, payload):
        """One arena-direct columnar batch (`amtpu_begin_columnar`):
        payload is msgpack {doc_key: [part, ...]} where each part is a
        columnar blob or a raw msgpack changes array.  The batch is
        pinned host-full in C++, so phase b is the hostreg driver
        regardless of exec mode (host/kernel byte parity is pinned by
        the differential suites)."""
        L = lib()
        _check_resident_latch()
        self._ensure_mode_flags()
        t0 = time.perf_counter()
        with trace.span('host.begin'):
            bh = L.amtpu_begin_columnar(self._pool, payload,
                                        len(payload))
        if not bh:
            _raise_last()
        _track_begin()
        telemetry.metric('storage.native_loads')
        ctx = self._phase_a_rest(bh)
        t1 = time.perf_counter()
        attribution.note_flush_phase('dispatch', t1 - t0)
        try:
            return self._phase_b(ctx)
        except Exception as e:
            _rollback_batch(ctx['bh'], e)
            raise
        finally:
            attribution.note_flush_phase('collect',
                                         time.perf_counter() - t1)
            _free_batch(ctx['bh'])

    # -- settled-history GC + cold-doc eviction (ISSUE 10) ---------------

    def _adopt_snapshot(self, key, frontier, chunks):
        """Installs a checkpoint's settled snapshot for `key` and
        truncates the C++ arena behind its frontier (reload keeps the
        compacted economics; docs/STORAGE.md).  Op-state folding rides
        along, so a reloaded doc's op arena stays as lean as the one it
        checkpointed from."""
        self._storage[key] = {'frontier': dict(frontier),
                              'chunks': list(chunks)}
        self._truncate(key, frontier)
        self._fold_settled(key, frontier)
        self._fold_clocks(key, frontier)

    def _truncate(self, key, frontier):
        fb = msgpack.packb(dict(frontier), use_bin_type=True)
        freed = lib().amtpu_truncate_history(self._pool, key.encode(),
                                             fb, len(fb))
        if freed < 0:
            _raise_last()
        telemetry.metric('storage.gc.bytes_freed', freed)
        return freed

    def compact(self, doc_id, frontier=None, min_changes=0):
        """Folds the causally-settled PREFIX of the doc's history into
        its columnar snapshot and truncates the arena behind it.

        `frontier` is the settled {actor: seq} clock (every peer's
        acked coverage -- the gateway passes the fan-out engine's
        pointwise-min believed clock); None means no external
        constraint (no live subscribers), i.e. everything applied is
        settled.  Only the longest history PREFIX at or behind the
        frontier folds: application order is part of the materialize
        contract (concurrent changes resolve key order by arrival), so
        the snapshot must stay an exact order-preserving prefix.
        Returns the number of changes folded (0 = nothing to do;
        ``AMTPU_STORAGE_FORMAT=json`` makes this a no-op, the parity
        -oracle arm)."""
        from .. import storage
        key = self._doc_key(doc_id)
        if storage.storage_format() == 'json':
            telemetry.metric('storage.gc.skipped_json')
            return 0
        clock = self.get_clock(doc_id).get('clock') or {}
        if not clock:
            return 0
        if frontier is None:
            limit = dict(clock)
        else:
            limit = {}
            for a, s in frontier.items():
                s = min(int(s), int(clock.get(a, 0)))
                if s > 0:
                    limit[a] = s
            if not limit:
                return 0
        tail = self._tail_raws(key)
        fold, prefix_clock = [], {}
        for raw in tail:
            c = msgpack.unpackb(raw, raw=False, strict_map_key=False)
            actor, seq = c.get('actor'), c.get('seq', 0)
            if seq > limit.get(actor, 0):
                break            # first unsettled change ends the prefix
            fold.append(raw)
            prefix_clock[actor] = max(prefix_clock.get(actor, 0), seq)
        if not fold or len(fold) < min_changes:
            return 0
        st = self._storage.setdefault(key, {'frontier': {},
                                            'chunks': []})
        st['chunks'].append(storage.encode_columnar(fold))
        for a, s in prefix_clock.items():
            st['frontier'][a] = max(st['frontier'].get(a, 0), s)
        self._truncate(key, st['frontier'])
        self._fold_settled(key, st['frontier'])
        self._fold_clocks(key, st['frontier'])
        self._maybe_rechunk(key, st)
        telemetry.metric('storage.gc.compactions')
        telemetry.metric('storage.gc.changes_folded', len(fold))
        return len(fold)

    def _fold_settled(self, key, frontier):
        """Op-state folding (ISSUE 14 tentpole): settled changes at or
        behind `frontier` free their op records / deps / message in the
        C++ arena -- registers and list arenas already hold their final
        values, and the columnar snapshot holds their replay bytes, so
        the arena stops growing with history under settled-overwrite
        churn.  ``AMTPU_STORAGE_FOLD=0`` is the no-fold A/B arm the
        folding lane compares against (byte-identical patches and
        straggler backfills either way)."""
        if not frontier or not env_bool('AMTPU_STORAGE_FOLD', True):
            return 0
        fb = msgpack.packb(dict(frontier), use_bin_type=True)
        n = lib().amtpu_fold_settled(self._pool, key.encode(), fb,
                                     len(fb))
        if n < 0:
            _raise_last()
        if n:
            telemetry.metric('storage.gc.ops_folded', n)
        return int(n)

    def _fold_clocks(self, key, frontier):
        """Clock-vector folding (ISSUE 17 tentpole): settled changes at
        or behind `frontier` move their sparse per-change ``all_deps``
        vector clocks into the doc's densified C++ fold table (or a
        zero-byte sentinel for empty / linear-history shapes) and free
        the vectors -- the last per-history memory term goes O(live
        frontier) instead of O(changes).  Causal queries (straggler
        closure walks, `get_missing_clock`, conflict concurrency) keep
        answering through the folded rows -- the clock-fold parity
        suite pins them against an unfolded twin.
        ``AMTPU_STORAGE_FOLD_CLOCKS=0`` is the unfolded A/B arm;
        ``AMTPU_FOLDCLK_MAX_ACTORS`` (default 256) caps the per-doc
        folded actor population (row width is the doc's actor count --
        past the cap, non-trivial vectors stay sparse)."""
        if not frontier or \
                not env_bool('AMTPU_STORAGE_FOLD_CLOCKS', True):
            return 0
        fb = msgpack.packb(dict(frontier), use_bin_type=True)
        n = lib().amtpu_fold_clocks(
            self._pool, key.encode(), fb, len(fb),
            env_int('AMTPU_FOLDCLK_MAX_ACTORS', 256))
        if n < 0:
            _raise_last()
        if n:
            telemetry.metric('storage.gc.clocks_folded', n)
        return int(n)

    def clock_pairs(self, doc_id=None):
        """Retained sparse all_deps clock pairs (one doc, or the whole
        pool), walked fresh in C++ -- the reconciliation oracle the
        clock-fold lane gates against `doc_stats`'s incrementally-
        maintained ``clk_pairs`` column."""
        key = '' if doc_id is None else self._doc_key(doc_id)
        n = lib().amtpu_clock_pairs(self._pool, key.encode())
        if n < 0:
            _raise_last()
        return int(n)

    def resclk_row_bytes(self):
        """Bytes one pool-resident clock-table row costs (padded actor
        width x int32) -- converts `doc_stats`'s ``resclk_rows`` count
        into the byte tier the capacity cost vector reports."""
        info = (ctypes.c_int64 * 4)()
        lib().amtpu_resclk_info(self._pool, info)
        return int(info[1]) * 4

    def _maybe_rechunk(self, key, st):
        """Chunk re-compaction (ISSUE 14): a long-lived doc accumulates
        one snapshot chunk per GC fold; past ``AMTPU_STORAGE_CHUNK_MAX``
        chunks (default 8; 0 disables) they merge into one columnar
        blob on the same `_storage_upkeep` cadence that triggered the
        fold.  Decode is byte-lossless, so the merged chunk replays and
        backfills byte-identically."""
        from .. import storage
        cap = env_int('AMTPU_STORAGE_CHUNK_MAX', 8)
        if cap <= 0 or len(st['chunks']) < cap:
            return 0
        raws = []
        for chunk in st['chunks']:
            raws.extend(storage.decode_columnar(chunk))
        st['chunks'] = [storage.encode_columnar(raws)]
        telemetry.metric('storage.gc.rechunks')
        return len(raws)

    def op_count(self, doc_id=None):
        """Retained op records in the C++ arena (applied states + the
        causal queue; one doc or the whole pool) -- the growth measure
        the op-state folding lane gates flat."""
        key = '' if doc_id is None else self._doc_key(doc_id)
        n = lib().amtpu_op_count(self._pool, key.encode())
        if n < 0:
            _raise_last()
        return int(n)

    def drop_doc(self, doc_id):
        """Cold-doc eviction: removes the doc's entire state from the
        pool (checkpoint it FIRST -- `save()` -> disk; reload is
        `load()`).  Returns True if the doc existed."""
        key = self._doc_key(doc_id)
        found = lib().amtpu_drop_doc(self._pool, key.encode())
        if found < 0:
            _raise_last()
        self._storage.pop(key, None)
        return bool(found)

    def history_bytes(self, doc_id=None):
        """Retained raw-change bytes in the C++ arena (one doc, or the
        whole pool) -- the measure the storage gate bounds."""
        key = '' if doc_id is None else self._doc_key(doc_id)
        n = lib().amtpu_history_bytes(self._pool, key.encode())
        if n < 0:
            _raise_last()
        return int(n)

    #: amtpu_doc_stats columns, in ABI order (core.cpp has the
    #: authoritative comment); telemetry/capacity.py reads these names
    DOC_STAT_COLS = ('hist_bytes', 'ops', 'folded_ops', 'changes',
                     'queued', 'resclk_rows', 'clk_pairs',
                     'foldclk_bytes')

    def doc_stats(self):
        """Per-doc resource accounting in ONE C call for the whole pool
        (ISSUE 15): returns ``(doc_keys, stats)`` where `stats` is an
        int64 ndarray of shape (n_docs, len(DOC_STAT_COLS)) in the same
        first-seen doc order as `doc_keys`.  Column totals reconcile
        bit-exactly with `history_bytes()` / `op_count()` -- the
        capacity tests and `make capacity-check` pin it."""
        L = lib()
        n = int(L.amtpu_doc_count(self._pool))
        ncols = len(self.DOC_STAT_COLS)
        if n <= 0:
            return [], np.zeros((0, ncols), np.int64)
        buf = (ctypes.c_int64 * (n * ncols))()
        rows = L.amtpu_doc_stats(self._pool, buf, n * ncols)
        if rows < 0:
            _raise_last()
        ln = ctypes.c_int64()
        ptr = L.amtpu_doc_ids(self._pool, ctypes.byref(ln))
        if not ptr:
            _raise_last()
        ids = msgpack.unpackb(_take_buf(ptr, ln.value), raw=False)
        rows = int(rows)
        stats = np.frombuffer(buf, dtype=np.int64,
                              count=rows * ncols).reshape(rows, ncols)
        # a private copy: `buf` dies with this frame
        return ids[:rows], stats.copy()


class ShardedNativePool:
    """S independent native pools, driven pipelined or threaded.

    Document-level independence is the framework's data-parallel axis
    (SURVEY.md section 2); on the host it also shards the C++ runtime.
    Two drive modes (AMTPU_SHARD_MODE=pipeline|threads; default picks by
    core count):

    * pipeline -- single thread, async device dispatch: all shards run
      host `begin` + kernel dispatch first (phase a), then results are
      collected and emitted in order (phase b).  jax dispatches are
      async, so shard k's device work and d->h transfer overlap shard
      k+1's host begin and shard k-1's emit.  Strictly better on a
      1-core host, where extra threads only add contention.
    * threads -- one thread per shard; ctypes releases the GIL around
      native calls, so on multi-core hosts begin/emit of shards run
      truly concurrently on top of the same async device overlap.

    Doc -> shard routing uses the same FNV-1a hash as the C++ payload
    splitter.  API-compatible with NativeDocPool for apply_batch /
    apply_batch_bytes and the per-doc queries.

    Error semantics: shards commit independently; if one shard's batch
    fails, other shards may already have applied their sub-batches.  The
    first shard error is re-raised; callers needing atomicity must keep
    doc groups within one shard (route by doc id).
    """

    @staticmethod
    def resolve_mode(mode=None):
        cores = os.cpu_count() or 1
        if mode is None:
            mode = env_str('AMTPU_SHARD_MODE', '')
        if not mode:
            mode = 'pipeline' if cores == 1 else 'threads'
        if mode not in ('pipeline', 'threads'):
            raise ValueError('unknown shard mode %r' % (mode,))
        return mode

    @classmethod
    def default_shards(cls, mode=None):
        """Mode-aware shard-count default, without building any pools.

        Keys on the RESOLVED mode: pipelining overlaps async device work
        with host begin/emit, so more shards than cores helps (finer
        overlap granularity, smaller per-shard pads; 20 measured best on
        the 1-core headline bench, BASELINE.md round 3).  Threads mode
        runs shards truly concurrently, so one per core (capped) avoids
        oversubscription and unbounded per-shard state.

        Full host path (CPU backend, round 4): there is no device work
        to overlap, so the pipeline's extra shards are pure per-shard
        fixed cost -- ONE shard measured ~6% faster than 20 on the
        headline config (and skips the payload splitter entirely).
        """
        if _host_full_on():
            return 1
        mode = cls.resolve_mode(mode)
        return 20 if mode == 'pipeline' else min(8, os.cpu_count() or 1)

    def __init__(self, n_shards=None, mode=None):
        mode = self.resolve_mode(mode)
        self.mode = mode
        if n_shards is not None and n_shards < 1:
            raise ValueError('n_shards must be >= 1, got %r' % (n_shards,))
        # None = resolve lazily at first use: default_shards() keys on
        # _host_full_on(), which initializes the jax backend -- on a
        # host with a wedged device tunnel that can block indefinitely,
        # and merely CONSTRUCTING a pool must never hang (same lazy
        # convention as NativeDocPool._ensure_mode_flags)
        self._n_shards = n_shards        # guarded-by(w): self._pools_lock
        self._pools = None               # guarded-by(w): self._pools_lock
        # materialization lock: ANY entry point may be the first to touch
        # the lazy properties from concurrent threads; without it two
        # racers could each build a pool list and apply shards to pools
        # the losing assignment discards.  Reads stay lock-free (the
        # double-checked publish pattern: a reference load is atomic
        # under the GIL), so the guarded-by annotation covers WRITES --
        # `make static-check` enforces it (docs/ANALYSIS.md).
        import threading
        self._pools_lock = threading.Lock()

    @property
    def n_shards(self):
        if self._n_shards is None:
            with self._pools_lock:
                if self._n_shards is None:
                    self._n_shards = self.default_shards(self.mode)
        return self._n_shards

    @property
    def pools(self):
        # double-checked under the lock so every concurrent first-toucher
        # observes the SAME pool list (no call site needs to pre-touch)
        if self._pools is None:
            # resolve n_shards BEFORE taking the lock: it acquires the
            # same (non-reentrant) lock for its own lazy materialization
            n = self.n_shards
            with self._pools_lock:
                if self._pools is None:
                    self._pools = [NativeDocPool() for _ in range(n)]
        return self._pools

    def _shard_of(self, doc_id):
        key = NativeDocPool._doc_key(doc_id).encode()
        return int(lib().amtpu_doc_shard(key, len(key), self.n_shards))

    def apply_batch_bytes(self, payload):
        L = lib()
        t_batch = time.perf_counter()
        # warm the lazy pool list on THIS thread (the property itself is
        # now lock-guarded, so this is an optimization -- jax backend
        # resolution happens once here instead of inside a worker)
        self.pools
        with trace.span('shard.split'):
            sp = L.amtpu_shard_split(payload, len(payload), self.n_shards)
            if not sp:
                _raise_last()
        try:
            # zero-copy: shard sub-payloads stay in the C++ splitter's
            # buffers; begin() copies what it keeps, so the ShardSplit
            # only needs to outlive the begin calls (freed below)
            subs = []
            for s in range(self.n_shards):
                n = ctypes.c_int64()
                ptr = L.amtpu_shard_buf(sp, s, ctypes.byref(n))
                subs.append((ctypes.cast(ptr, ctypes.c_char_p), n.value)
                            if n.value > 1 else None)
            with trace.span('shard.run'):
                results, errors = self._run(subs)
            if errors:
                # poison-batch isolation at SHARD granularity: a failed
                # shard rolled its pool back, so its whole sub-payload
                # re-applies through the resilience layer (retry ->
                # bisect -> quarantine) while the healthy shards'
                # results stand (docs/RESILIENCE.md)
                errors = self._retry_failed_shards(subs, results, errors)
            _raise_shard_errors(errors)
        finally:
            L.amtpu_shard_free(sp)
        # merge the per-shard {doc: patch} maps at the byte level: sum the
        # map headers, splice the bodies -- no decode of patch contents
        total = 0
        bodies = []
        for r in results:
            if r is None:
                continue
            n, off = _read_map_header(r)
            total += n
            bodies.append(memoryview(r)[off:])   # no intermediate copy
        out = _map_header(total) + b''.join(bodies)
        # whole-batch series; shard sub-batches land under pool="native"
        # (threads mode) or not at all (pipeline mode drives _phase_a/b
        # directly), so the two label values never double-count one level
        telemetry.observe_batch(self._batch_label,
                                time.perf_counter() - t_batch,
                                docs=_read_map_header(payload)[0])
        return out

    #: batch-latency series label (`MeshDocPool` overrides with 'mesh'
    #: so its lines are attributable; `telemetry.collect_share` knows
    #: every value)
    _batch_label = 'sharded'

    def _run(self, subs):
        """Drive-mode dispatch for one split payload; subclasses (the
        mesh pool) override with their own drive."""
        if self.mode == 'pipeline':
            return self._run_pipelined(subs)
        return self._run_threaded(subs)

    def _run_pipelined(self, subs):
        """Phase a for every shard, then phase b READY-FIRST: shards
        whose device outputs already resolved collect and emit before a
        slow shard that happens to sit earlier in submission order
        (_collect_ready_order).  A shard error must NOT leave *other*
        shards half-applied (their begin has already committed state),
        so every healthy shard still runs to completion and the first
        error is re-raised afterwards -- matching the threads-mode
        semantics."""
        ctxs = []
        results = [None] * self.n_shards
        errors = []
        for s in range(self.n_shards):
            if subs[s] is None:
                continue
            try:
                ctxs.append((s, self.pools[s], self.pools[s]._phase_a(
                    subs[s])))
            except Exception as e:
                errors.append((s, e))

        def keep(s, result):
            results[s] = result

        _collect_ready_order(ctxs, on_result=keep,
                             on_error=lambda s, e: errors.append((s, e)))
        return results, errors

    def _run_threaded(self, subs):
        results = [None] * self.n_shards
        errors = []

        def run(s):
            try:
                if subs[s] is not None:
                    results[s] = self.pools[s].apply_batch_bytes(subs[s])
            except Exception as e:         # re-raised on the caller thread
                errors.append((s, e))

        import threading
        threads = [threading.Thread(target=run, args=(s,))
                   for s in range(self.n_shards)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return results, errors

    def _retry_failed_shards(self, subs, results, errors):
        """Re-applies each failed shard's sub-payload through the
        resilience layer on that shard's own pool; returns the errors
        resilience must not isolate (they re-raise, as before)."""
        from .. import resilience
        remaining = []
        for s, e in errors:
            if not resilience.should_isolate(e):
                remaining.append((s, e))
                continue
            try:
                results[s] = resilience.apply_payload(
                    self.pools[s], subs[s], first_exc=e)
            except Exception as e2:
                remaining.append((s, e2))
        return remaining

    def apply_batch_bytes_resilient(self, payload):
        """Alias for `apply_batch_bytes`: the sharded driver already
        isolates failures per shard internally."""
        return self.apply_batch_bytes(payload)

    def apply_batch(self, changes_by_doc):
        return _apply_batch_dicts(self, changes_by_doc)

    def apply_changes(self, doc_id, changes):
        return self.pools[self._shard_of(doc_id)].apply_changes(
            doc_id, changes)   # quarantine raises inside (single doc)

    def apply_local_change(self, doc_id, request):
        return self.pools[self._shard_of(doc_id)].apply_local_change(
            doc_id, request)

    def get_patch(self, doc_id):
        return self.pools[self._shard_of(doc_id)].get_patch(doc_id)

    def get_clock(self, doc_id):
        return self.pools[self._shard_of(doc_id)].get_clock(doc_id)

    def save(self, doc_id):
        return self.pools[self._shard_of(doc_id)].save(doc_id)

    def load(self, doc_id, data):
        return self.pools[self._shard_of(doc_id)].load(doc_id, data)

    def load_batch(self, blobs):
        """One batched replay for many checkpoints (the payload splitter
        routes docs to their shards)."""
        _load_batch(self, blobs)

    def restore_from_store(self, store, doc_ids=None, batch=None,
                           threads=None):
        """Parallel per-shard restore off the store's durable manifest:
        each shard's doc group decodes + applies on its own thread with
        the GIL released (module-level `restore_from_store`)."""
        return restore_from_store(self, store, doc_ids=doc_ids,
                                  batch=batch, threads=threads)

    def get_missing_deps(self, doc_id):
        return self.pools[self._shard_of(doc_id)].get_missing_deps(doc_id)

    def get_missing_changes(self, doc_id, have_deps):
        return self.pools[self._shard_of(doc_id)].get_missing_changes(
            doc_id, have_deps)

    def get_register(self, doc_id, obj, key):
        return self.pools[self._shard_of(doc_id)].get_register(
            doc_id, obj, key)

    def get_changes_for_actor(self, doc_id, actor, after_seq=0):
        return self.pools[self._shard_of(doc_id)].get_changes_for_actor(
            doc_id, actor, after_seq)

    def get_changes_for_actor_bytes(self, doc_id, actor, after_seq=0):
        return self.pools[self._shard_of(doc_id)] \
            .get_changes_for_actor_bytes(doc_id, actor, after_seq)

    def compact(self, doc_id, frontier=None, min_changes=0):
        return self.pools[self._shard_of(doc_id)].compact(
            doc_id, frontier, min_changes)

    def drop_doc(self, doc_id):
        return self.pools[self._shard_of(doc_id)].drop_doc(doc_id)

    def history_bytes(self, doc_id=None):
        if doc_id is not None:
            return self.pools[self._shard_of(doc_id)] \
                .history_bytes(doc_id)
        return sum(p.history_bytes() for p in self.pools)

    def op_count(self, doc_id=None):
        if doc_id is not None:
            return self.pools[self._shard_of(doc_id)].op_count(doc_id)
        return sum(p.op_count() for p in self.pools)

    def clock_pairs(self, doc_id=None):
        if doc_id is not None:
            return self.pools[self._shard_of(doc_id)].clock_pairs(doc_id)
        return sum(p.clock_pairs() for p in self.pools)

    def resclk_row_bytes(self):
        """Widest shard's row cost: shards serve one doc population, so
        actor widths track each other -- the capacity tier wants a
        stable per-row conversion, not per-shard precision."""
        return max(p.resclk_row_bytes() for p in self.pools)

    DOC_STAT_COLS = NativeDocPool.DOC_STAT_COLS

    def doc_stats(self):
        """Per-doc stats across every shard (one C call per shard),
        concatenated in shard order -- same (doc_keys, (N, cols) int64
        ndarray) contract as `NativeDocPool.doc_stats`."""
        ids, mats = [], []
        for p in self.pools:
            pids, pstats = p.doc_stats()
            ids.extend(pids)
            if len(pids):
                mats.append(pstats)
        if not mats:
            return ids, np.zeros((0, len(self.DOC_STAT_COLS)), np.int64)
        return ids, np.concatenate(mats, axis=0)


def make_pool():
    """The execution-mode-aware pool factory (ISSUE 7): `MeshDocPool`
    when ``AMTPU_MESH=dp[,sp]`` requests mesh execution, else a plain
    `NativeDocPool`.  The sidecar backend and the CI gates construct
    through this, so flipping one env var moves a whole serving stack
    (gateway, resilience, sidecar) onto the device mesh unchanged."""
    mesh = parse_mesh_env()
    if mesh is None:
        return NativeDocPool()
    from .mesh_pool import MeshDocPool
    return MeshDocPool(dp=mesh[0], sp=mesh[1])


def __getattr__(name):
    # lazy so importing the native driver never drags the mesh module
    # (and through it jax device enumeration) into processes that only
    # serve single-device traffic
    if name in ('MeshDocPool', 'MeshChipPool'):
        from . import mesh_pool
        return getattr(mesh_pool, name)
    raise AttributeError('module %r has no attribute %r'
                         % (__name__, name))
