"""Device-resident arena cache -- incremental state across batches.

SURVEY hard part 5: the reference keeps its opSet state incrementally
between calls (`/root/reference/backend/op_set.js:310-322`); the TPU
analogue is arena columns that LIVE ON DEVICE between `apply_batch`
calls, with the host uploading only per-batch deltas (appended elements,
per-op arrays, register rows) instead of re-encoding and re-uploading
O(arena) bytes every batch.

The cache keys on (doc id, object sid).  Entries hold four long-lived
device arrays -- parent/ctr/actor-rank (i32) and visibility (f32) -- at
the dom block's padded capacity.  Consistency contract:

* Appends are detected by length: rows [cached_n, current_n) upload as
  one scatter; a shrink (batch rollback) or capacity change (pow2 bucket
  growth) triggers a full re-upload.
* Visibility is synced AFTER emit from the C++ arena's own `visible`
  column (only the batch's touched elements -- O(batch)); the C++ state
  is ground truth, so overflow fallbacks and undo flows stay exact.
* Element actor ranks must preserve actor-STRING order across batches
  (linearize tie-breaks siblings by actor descending), so ranks come
  from a pool-lifetime sorted registry; an actor whose name sorts into
  the middle of the known set shifts existing ranks and drops the cache
  (rare -- one full re-upload).
* An entry whose batch failed between dispatch and sync is `dirty` and
  re-uploads in full on next touch.
"""

import bisect
import ctypes
from functools import lru_cache, partial

import numpy as np

from .. import trace


class ResidentArena:
    __slots__ = ('capacity', 'n', 'par', 'ctr', 'act', 'ev', 'dirty')

    def __init__(self, capacity):
        self.capacity = capacity
        self.n = 0
        self.par = None
        self.ctr = None
        self.act = None
        self.ev = None
        self.dirty = False


@lru_cache(maxsize=None)
def _jit_scatter():
    import jax

    @jax.jit
    def scatter(col, idx, vals):
        # pad slots carry idx == capacity (out of bounds) and drop
        return col.at[idx].set(vals, mode='drop')
    return scatter


@lru_cache(maxsize=None)
def _jit_kernel(n_iters, window, chunk):
    import jax

    from ..ops import registers as register_ops
    return jax.jit(partial(register_ops.resolve_rank_dominate_resident,
                           n_iters=n_iters, window=window, chunk=chunk))


def _bucket_pow2(n, floor=16):
    size = floor
    while size < n:
        size *= 2
    return size


class ResidentCache:
    def __init__(self):
        self.entries = {}        # (doc_id bytes, obj_sid) -> ResidentArena
        self.actor_order = []    # sorted actor strings (bytes)
        self.sid_str = {}        # sid -> actor string

    # -- actor ranks ----------------------------------------------------

    def _rank_of_sids(self, L, pool, sids):
        """Vector of string-order ranks for actor sids; registering a
        middle-sorting new actor invalidates every cached eact column.

        Two passes: ALL new sids register first, THEN ranks compute --
        interleaving them would hand out ranks that a later insert in
        the same call shifts (colliding eact values, divergent sibling
        tie-breaks)."""
        for sid in sids:
            if sid in self.sid_str:
                continue
            s = L.amtpu_intern_str(pool, sid)
            self.sid_str[sid] = s
            pos = bisect.bisect_left(self.actor_order, s)
            if pos != len(self.actor_order):
                # ranks of later actors shift: resident eact stale
                self.entries.clear()
                trace.count('resident.actor_invalidation')
            self.actor_order.insert(pos, s)
        out = np.empty(len(sids), np.int32)
        for i, sid in enumerate(sids):
            out[i] = bisect.bisect_left(self.actor_order,
                                        self.sid_str[sid])
        return out

    # -- entry acquisition ---------------------------------------------

    def _read_raw(self, L, pool, doc_id, obj_sid):
        ctr = ctypes.POINTER(ctypes.c_int32)()
        act = ctypes.POINTER(ctypes.c_uint32)()
        par = ctypes.POINTER(ctypes.c_int32)()
        vis = ctypes.POINTER(ctypes.c_uint8)()
        n = L.amtpu_arena_raw(pool, doc_id, obj_sid,
                              ctypes.byref(ctr), ctypes.byref(act),
                              ctypes.byref(par), ctypes.byref(vis))
        if n == 0:
            return 0, None, None, None, None
        shape = (n,)
        return (n,
                np.ctypeslib.as_array(ctr, shape=shape),
                np.ctypeslib.as_array(act, shape=shape),
                np.ctypeslib.as_array(par, shape=shape),
                np.ctypeslib.as_array(vis, shape=shape))

    def get_entry(self, L, pool, doc_id, obj_sid, n_now, capacity):
        """Returns a ResidentArena whose device columns reflect the
        arena's current rows [0, n_now), uploading as little as the
        consistency contract allows; None when the raw arena is
        unavailable."""
        import jax.numpy as jnp

        n_raw, ctr, act, par, vis = self._read_raw(L, pool, doc_id,
                                                   obj_sid)
        if n_raw < n_now:
            return None
        key = (doc_id, obj_sid)
        entry = self.entries.get(key)
        need_full = (entry is None or entry.dirty or
                     entry.capacity != capacity or entry.n > n_now)

        if need_full:
            lo = 0
        else:
            lo = entry.n
        if need_full or n_now > lo:
            # rank mapping may clear self.entries (middle-sorting actor);
            # compute ranks FIRST, then re-check the entry
            ranks = self._rank_of_sids(L, pool,
                                       act[lo:n_now].tolist())
            entry2 = self.entries.get(key)
            if entry2 is not entry or (entry2 is not None and
                                       entry2.dirty):
                need_full = True
                lo = 0
                ranks = self._rank_of_sids(L, pool, act[:n_now].tolist())
            entry = entry2 if not need_full else None

        if need_full:
            entry = ResidentArena(capacity)
            pad = capacity - n_now

            def up(a, dtype, fill):
                return jnp.asarray(np.pad(
                    np.ascontiguousarray(a[:n_now], dtype),
                    (0, pad), constant_values=fill))
            entry.par = up(par, np.int32, -1)
            entry.ctr = up(ctr, np.int32, 0)
            entry.act = jnp.asarray(np.pad(ranks, (0, pad),
                                           constant_values=0))
            entry.ev = up(vis, np.float32, 0.0)
            entry.n = n_now
            self.entries[key] = entry
            trace.count('resident.full_upload_rows', n_now)
        elif n_now > lo:
            k = n_now - lo
            kp = _bucket_pow2(k)
            idx = np.full(kp, capacity, np.int32)   # capacity = dropped
            idx[:k] = np.arange(lo, n_now, dtype=np.int32)
            scatter = _jit_scatter()

            def pad(a, dtype):
                out = np.zeros(kp, dtype)
                out[:k] = a
                return out
            entry.par = scatter(entry.par, idx,
                                pad(par[lo:n_now], np.int32))
            entry.ctr = scatter(entry.ctr, idx,
                                pad(ctr[lo:n_now], np.int32))
            entry.act = scatter(entry.act, idx, pad(ranks, np.int32))
            entry.ev = scatter(entry.ev, idx,
                               pad(vis[lo:n_now], np.float32))
            entry.n = n_now
            trace.count('resident.delta_upload_rows', k)
        else:
            trace.count('resident.no_upload')
        return entry

    def sync_after_emit(self, L, pool, entry, doc_id, obj_sid, n_now,
                        touched_eidx):
        """Post-emit visibility refresh from the C++ ground truth: only
        the batch's touched elements re-upload (O(batch))."""
        n_raw, _ctr, _act, _par, vis = self._read_raw(L, pool, doc_id,
                                                      obj_sid)
        if n_raw < n_now:          # rollback after dispatch: drop
            entry.dirty = True
            return
        if touched_eidx.size:
            kp = _bucket_pow2(touched_eidx.size)
            idx = np.full(kp, entry.capacity, np.int32)
            idx[:touched_eidx.size] = touched_eidx
            vals = np.zeros(kp, np.float32)
            vals[:touched_eidx.size] = vis[touched_eidx]
            entry.ev = _jit_scatter()(entry.ev, idx, vals)
        entry.n = n_now
        entry.dirty = False
