"""Device-resident arena cache -- incremental state across batches.

SURVEY hard part 5: the reference keeps its opSet state incrementally
between calls (`/root/reference/backend/op_set.js:310-322`); the TPU
analogue is arena columns that LIVE ON DEVICE between `apply_batch`
calls, with the host uploading only per-batch deltas (appended elements,
per-op arrays, register rows) instead of re-encoding and re-uploading
O(arena) bytes every batch.

The cache keys on (doc id, object sid).  Entries hold four long-lived
device arrays -- parent/ctr/actor-rank (i32) and visibility (f32) -- at
the dom block's padded capacity.  Consistency contract:

* Appends are detected by length: rows [cached_n, current_n) upload as
  one scatter; a shrink (batch rollback) or capacity change (pow2 bucket
  growth) triggers a full re-upload.
* Visibility is synced AFTER emit from the C++ arena's own `visible`
  column (only the batch's touched elements -- O(batch)); the C++ state
  is ground truth, so overflow fallbacks and undo flows stay exact.
* Element actor ranks must preserve actor-STRING order across batches
  (linearize tie-breaks siblings by actor descending), so ranks come
  from a pool-lifetime sorted registry; an actor whose name sorts into
  the middle of the known set shifts existing ranks and drops the cache
  (rare -- one full re-upload).
* An entry whose batch failed between dispatch and sync is `dirty` and
  re-uploads in full on next touch.
"""

import bisect
import ctypes
from functools import lru_cache, partial

import numpy as np

from .. import trace
from ..utils.common import env_int, parse_mesh_env

#: Default long-list crossover for the sp (sequence-parallel) axis, in
#: arena elements (ISSUE 7 satellite: sp-axis triage).  Below this the
#: linearization all-gather + per-device dispatch overhead outweighs
#: the sharded dominance win and sp REGRESSES hard -- measured on the
#: 2-core CI stand-in (steady-state resident edit batches, sp=2,
#: interleaved A/B; bench.py --multichip re-records the probe per
#: host): 3.4x slower at 8k elements, ~2x at 32k, ~1.3x at 64k,
#: break-even (0.85-1.1x, noise-dominated) at 128k+.  The stand-in can
#: never show a WIN -- its virtual devices share the two cores XLA's
#: intra-op parallelism already saturates at sp=1 -- so the default
#: threshold marks where sharding stops HURTING; real multi-chip
#: hardware (where sp buys actual extra silicon and O(L/sp) resident
#: memory) is expected to move it down, and the hardware-day run
#: re-measures it.  AMTPU_MESH_SP_MIN overrides; arenas below the
#: threshold stay on the single-chip resident kernel and count
#: ``mesh.sp_fenced``.
SP_CROSSOVER_ELEMS = 1 << 17


def _sp_min():
    """Element threshold under which sp sharding is fenced off."""
    return env_int('AMTPU_MESH_SP_MIN', SP_CROSSOVER_ELEMS)


def _sp_device_cap():
    """How many devices the sp axis may claim: None = every local
    device (legacy auto policy, no AMTPU_MESH set), 0 = fenced off
    entirely, else the explicit sp extent of ``AMTPU_MESH=dp,sp``.

    With dp > 1 every device belongs to a dp chip, and a global sp
    mesh would shard one chip's arena across devices other chips own
    -- so mesh mode enables sp only for the dp=1 topology (the
    single-big-doc showcase the sp axis exists for); composing per-
    chip sp sub-meshes is deferred until the path validates on real
    hardware."""
    try:
        env = parse_mesh_env()
    except ValueError:
        return 0          # malformed AMTPU_MESH: never shard on a typo
    if env is None:
        return None
    dp, sp = env
    if sp <= 1 or dp > 1:
        return 0
    return sp


class ResidentArena:
    __slots__ = ('capacity', 'n', 'par', 'ctr', 'act', 'ev', 'dirty')

    def __init__(self, capacity):
        self.capacity = capacity
        self.n = 0
        self.par = None
        self.ctr = None
        self.act = None
        self.ev = None
        self.dirty = False


@lru_cache(maxsize=None)
def _jit_scatter(sharding=None):
    import jax

    @partial(jax.jit, out_shardings=sharding)
    def scatter(col, idx, vals):
        # pad slots carry idx == capacity (out of bounds) and drop
        return col.at[idx].set(vals, mode='drop')
    return scatter


@lru_cache(maxsize=None)
def _jit_kernel(n_iters, window, chunk):
    import jax

    from ..ops import registers as register_ops
    return jax.jit(partial(register_ops.resolve_rank_dominate_resident,
                           n_iters=n_iters, window=window, chunk=chunk))


@lru_cache(maxsize=None)
def _sp_mesh(n_cap=None):
    """A 1-D ('sp',) mesh over the largest power-of-two subset of local
    devices (capped at `n_cap` when the AMTPU_MESH topology reserves
    devices for dp chips), or None single-device.  The pool's resident
    dispatch shards big arenas over it -- the promotion of the
    AMTPU_BENCH_C1_MESH showcase path into the default pool entry point
    (VERDICT r2 #4).  Power-of-two so the pow2-bucketed arena
    capacities divide evenly."""
    import jax
    devices = jax.devices()
    limit = len(devices) if n_cap is None else min(n_cap, len(devices))
    n = 1
    while n * 2 <= limit:
        n *= 2
    if n < 2:
        return None
    from jax.sharding import Mesh
    return Mesh(np.array(devices[:n]), ('sp',))


def _sp_sharding(capacity=None, count_fenced=False):
    """Element-axis sharding for a resident column of `capacity` rows,
    or None when sharding is unavailable/indivisible -- or FENCED (the
    caller then keeps the column replicated and uses the unsharded
    kernel).  The fence is the sp-axis triage (ISSUE 7): sp>1 routes
    only past the measured long-list crossover (`_sp_min`), and only
    over devices the AMTPU_MESH topology has not claimed for dp chips
    (`_sp_device_cap`).  `count_fenced` records a fenced would-be
    sharding as ``mesh.sp_fenced`` -- passed ONLY by the dispatch
    decision site, so fenced counts one per dispatch exactly like its
    ``mesh.sp_engaged`` counterpart (placement/sync callers would
    otherwise inflate it 3-4x)."""
    cap = _sp_device_cap()
    if cap == 0:
        return None
    mesh = _sp_mesh(cap)
    if mesh is None:
        return None
    if capacity is not None and capacity % mesh.size != 0:
        return None
    if capacity is not None and capacity < _sp_min():
        if count_fenced:
            trace.metric('mesh.sp_fenced')
        return None
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec('sp'))


@lru_cache(maxsize=None)
def _jit_kernel_sharded(n_iters, window, chunk, n_cap=None):
    """The resident resolver with the arena element axis SHARDED over the
    sp mesh: linearize all-gathers the (tiny) parent/ctr/act columns for
    pointer doubling, while the quadratic dominance stage -- the dominant
    cost for long lists -- computes only each device's local partial
    counts, completed with one psum (`ops/list_rank.dominance_indexes`
    sequence-parallel mode, same formulation as parallel/mesh.py).
    `n_cap` keys the cache on the AMTPU_MESH device cap so the compiled
    mesh always matches the sharding decision that routed here."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops import list_rank
    from ..ops import registers as register_ops
    from ..parallel.mesh import shard_map

    mesh = _sp_mesh(n_cap)
    rep = P()
    shd = P('sp')
    reg_spec = {k: rep for k in ('winner', 'conflicts', 'alive_after',
                                 'visible_before', 'overflow', 'packed')}

    def step(g, t, a, s, ctab, cidx, d, alive, si, par, ctr, act, ev,
             n_elems, oe, dom_src, ov):
        reg = register_ops._resolve(g, t, a, s, ctab, cidx, d, alive,
                                    si, None, window)
        par_f = jax.lax.all_gather(par, 'sp', tiled=True)
        ctr_f = jax.lax.all_gather(ctr, 'sp', tiled=True)
        act_f = jax.lax.all_gather(act, 'sp', tiled=True)
        C = par_f.shape[0]
        valid_f = jnp.arange(C, dtype=jnp.int32) < n_elems
        rank = list_rank.linearize(jnp.zeros((C,), jnp.int32), par_f,
                                   ctr_f, act_f, valid_f, n_iters)
        Ll = par.shape[0]
        off = jax.lax.axis_index('sp') * Ll
        er_local = jax.lax.dynamic_slice_in_dim(rank, off, Ll)
        oe1, ds1, ov1 = oe[0], dom_src[0], ov[0]
        orank, od = register_ops.dominance_op_inputs(reg, rank, oe1,
                                                     ds1, ov1)
        oobj = jnp.where(ov1, 0, -2)
        idx = list_rank.dominance_indexes(
            jnp.zeros((Ll,), jnp.int32), er_local, ev, oe1, oobj, orank,
            od, ov1, chunk=chunk, axis_name='sp', l_offset=off)
        combo = jnp.concatenate([reg['packed'], idx])
        return reg, rank, combo

    stepped = shard_map(
        step, mesh,
        in_specs=(rep,) * 9 + (shd, shd, shd, shd) + (rep,) * 4,
        out_specs=(reg_spec, rep, rep))
    return jax.jit(stepped)


def _bucket_pow2(n, floor=16):
    size = floor
    while size < n:
        size *= 2
    return size


class ResidentCache:
    def __init__(self):
        self.entries = {}        # (doc_id bytes, obj_sid) -> ResidentArena
        self.actor_order = []    # sorted actor strings (bytes)
        self.sid_str = {}        # sid -> actor string

    # -- actor ranks ----------------------------------------------------

    def _rank_of_sids(self, L, pool, sids):
        """Vector of string-order ranks for actor sids; registering a
        middle-sorting new actor invalidates every cached eact column.

        Two passes: ALL new sids register first, THEN ranks compute --
        interleaving them would hand out ranks that a later insert in
        the same call shifts (colliding eact values, divergent sibling
        tie-breaks)."""
        for sid in sids:
            if sid in self.sid_str:
                continue
            s = L.amtpu_intern_str(pool, sid)
            self.sid_str[sid] = s
            pos = bisect.bisect_left(self.actor_order, s)
            if pos != len(self.actor_order):
                # ranks of later actors shift: resident eact stale
                self.entries.clear()
                trace.count('resident.actor_invalidation')
            self.actor_order.insert(pos, s)
        out = np.empty(len(sids), np.int32)
        for i, sid in enumerate(sids):
            out[i] = bisect.bisect_left(self.actor_order,
                                        self.sid_str[sid])
        return out

    # -- entry acquisition ---------------------------------------------

    def _read_raw(self, L, pool, doc_id, obj_sid):
        ctr = ctypes.POINTER(ctypes.c_int32)()
        act = ctypes.POINTER(ctypes.c_uint32)()
        par = ctypes.POINTER(ctypes.c_int32)()
        vis = ctypes.POINTER(ctypes.c_uint8)()
        n = L.amtpu_arena_raw(pool, doc_id, obj_sid,
                              ctypes.byref(ctr), ctypes.byref(act),
                              ctypes.byref(par), ctypes.byref(vis))
        if n == 0:
            return 0, None, None, None, None
        shape = (n,)
        return (n,
                np.ctypeslib.as_array(ctr, shape=shape),
                np.ctypeslib.as_array(act, shape=shape),
                np.ctypeslib.as_array(par, shape=shape),
                np.ctypeslib.as_array(vis, shape=shape))

    def get_entry(self, L, pool, doc_id, obj_sid, n_now, capacity):
        """Returns a ResidentArena whose device columns reflect the
        arena's current rows [0, n_now), uploading as little as the
        consistency contract allows; None when the raw arena is
        unavailable."""
        import jax.numpy as jnp

        n_raw, ctr, act, par, vis = self._read_raw(L, pool, doc_id,
                                                   obj_sid)
        if n_raw < n_now:
            return None
        key = (doc_id, obj_sid)
        entry = self.entries.get(key)
        need_full = (entry is None or entry.dirty or
                     entry.capacity != capacity or entry.n > n_now)

        if need_full:
            lo = 0
        else:
            lo = entry.n
        if need_full or n_now > lo:
            # rank mapping may clear self.entries (middle-sorting actor);
            # compute ranks FIRST, then re-check the entry
            ranks = self._rank_of_sids(L, pool,
                                       act[lo:n_now].tolist())
            entry2 = self.entries.get(key)
            if entry2 is not entry or (entry2 is not None and
                                       entry2.dirty):
                need_full = True
                lo = 0
                ranks = self._rank_of_sids(L, pool, act[:n_now].tolist())
            entry = entry2 if not need_full else None

        if need_full:
            import jax
            entry = ResidentArena(capacity)
            pad = capacity - n_now
            sharding = _sp_sharding(capacity)

            def up(a, dtype, fill):
                arr = jnp.asarray(np.pad(
                    np.ascontiguousarray(a[:n_now], dtype),
                    (0, pad), constant_values=fill))
                return (jax.device_put(arr, sharding)
                        if sharding is not None else arr)
            entry.par = up(par, np.int32, -1)
            entry.ctr = up(ctr, np.int32, 0)
            entry.act = up(ranks, np.int32, 0)
            entry.ev = up(vis, np.float32, 0.0)
            entry.n = n_now
            self.entries[key] = entry
            trace.count('resident.full_upload_rows', n_now)
        elif n_now > lo:
            k = n_now - lo
            kp = _bucket_pow2(k)
            idx = np.full(kp, capacity, np.int32)   # capacity = dropped
            idx[:k] = np.arange(lo, n_now, dtype=np.int32)
            scatter = _jit_scatter(_sp_sharding(capacity))

            def pad(a, dtype):
                out = np.zeros(kp, dtype)
                out[:k] = a
                return out
            entry.par = scatter(entry.par, idx,
                                pad(par[lo:n_now], np.int32))
            entry.ctr = scatter(entry.ctr, idx,
                                pad(ctr[lo:n_now], np.int32))
            entry.act = scatter(entry.act, idx, pad(ranks, np.int32))
            entry.ev = scatter(entry.ev, idx,
                               pad(vis[lo:n_now], np.float32))
            entry.n = n_now
            trace.count('resident.delta_upload_rows', k)
        else:
            trace.count('resident.no_upload')
        return entry

    def sync_after_emit(self, L, pool, entry, doc_id, obj_sid, n_now,
                        touched_eidx):
        """Post-emit visibility refresh from the C++ ground truth: only
        the batch's touched elements re-upload (O(batch))."""
        n_raw, _ctr, _act, _par, vis = self._read_raw(L, pool, doc_id,
                                                      obj_sid)
        if n_raw < n_now:          # rollback after dispatch: drop
            entry.dirty = True
            return
        if touched_eidx.size:
            kp = _bucket_pow2(touched_eidx.size)
            idx = np.full(kp, entry.capacity, np.int32)
            idx[:touched_eidx.size] = touched_eidx
            vals = np.zeros(kp, np.float32)
            vals[:touched_eidx.size] = vis[touched_eidx]
            entry.ev = _jit_scatter(
                _sp_sharding(entry.capacity))(entry.ev, idx, vals)
        entry.n = n_now
        entry.dirty = False
