"""First-class mesh execution: the doc-partitioned device-mesh pool
(ISSUE 7 tentpole; docs/ARCHITECTURE.md mesh section).

`MeshDocPool` promotes the multichip dryrun into a production execution
mode: document batches partition across a real device mesh's **dp**
axis (the FNV doc hash the payload splitter already uses), each dp
shard served by a `MeshChipPool` -- a `NativeDocPool` whose every
kernel dispatch, pool-resident clock table, and escalation tier is
pinned to ONE mesh device via `jax.default_device` (thread-local, so
concurrent chips never fight over placement).  The pool speaks the
same `apply_batch`/`apply_batch_bytes` + patches contract as
`NativeDocPool`, so the scheduler gateway, the resilience
retry/bisect/quarantine path, and the sidecar serve it unchanged
(select with ``AMTPU_MESH=dp[,sp]`` through `native.make_pool`).

The dryrun's scaling losses are attacked structurally:

* host-side decode/begin runs in one thread PER CHIP (ctypes releases
  the GIL around the C++ runtime), so per-step host work -- the
  dominant cost on the CPU stand-in -- stops serializing
  (``mesh.encode_shard_skew_s`` records the per-chip begin imbalance);
* PR 6's device-resident pool state is per chip by construction: each
  chip pool owns its own `PoolClockCache`/`ResidentCache`, created
  under the chip's device context, with per-chip generation tracking
  and delta scatters (donated off-CPU, exactly the single-device
  rules); escalation tiers dispatch on the chip that owns the
  overflowing docs instead of re-gathering to device 0;
* there is NO barrier between the phases: every chip thread, after
  publishing its own phase-a context, joins a shared ready-first
  collector (`_collect_one_ready_first`) that claims chips whose
  device outputs already resolved (jax.Array.is_ready) -- one slow
  chip neither serializes nor barriers the others
  (``mesh.collective_wait_s`` records time blocked with nothing
  ready);
* the sp (sequence-parallel) axis is FENCED: `resident._sp_sharding`
  routes element-axis sharding only past a measured long-list
  crossover (AMTPU_MESH_SP_MIN) and only for the ``AMTPU_MESH=1,sp``
  topology -- the dryrun's 2.2x sp=2 regression can no longer ship
  silently (ISSUE 7 satellite; the crossover probe is recorded in the
  MULTICHIP bench line).

Error semantics are the sharded pool's: chips commit independently; a
failed chip's sub-payload re-applies through the resilience layer on
that chip alone (retry -> bisect -> per-doc quarantine), healthy
chips' results stand.
"""

import ctypes
import threading
import time
import warnings

from .. import trace
from ..utils.common import env_raw, parse_mesh_env
from ..utils.jaxenv import ensure_cpu_devices
from . import (NativeDocPool, ShardedNativePool, _ctx_pending_arrays,
               _ctx_ready, _run_phase_b_entry, _read_map_header, lib)


class MeshChipPool(NativeDocPool):
    """One dp shard of the mesh: a `NativeDocPool` pinned to a device.

    Placement rides `jax.default_device` (thread-local config) around
    both phases, so everything the batch path stages -- register
    columns, the resident clock table, escalation tier chunks, the
    resident arena -- lands on this chip, and donation/delta rules
    apply per chip exactly as on a single device.

    The chip forces the KERNEL path: the mesh exists to use the
    devices, so the CPU backend's full-host default would reduce
    ``AMTPU_MESH`` to plain host sharding with idle chips.  An
    explicit ``AMTPU_HOST_FULL=1`` still wins (parity A/B arms)."""

    def __init__(self, device):
        super().__init__()
        self.device = device

    def _device_ctx(self):
        import jax
        return jax.default_device(self.device)

    def _ensure_mode_flags(self):
        if not self._mode_set:
            env = env_raw('AMTPU_HOST_FULL')
            host_full = env is not None and env not in ('', '0')
            lib().amtpu_pool_set_hostfull(self._pool,
                                          1 if host_full else 0)
            self._mode_set = True

    def _phase_a(self, payload, overlapped=False):
        with self._device_ctx():
            return super()._phase_a(payload, overlapped=overlapped)

    def _phase_b(self, ctx):
        with self._device_ctx():
            return super()._phase_b(ctx)

    def apply_local_change(self, doc_id, request):
        with self._device_ctx():
            return super().apply_local_change(doc_id, request)


def _collect_one_ready_first(produced, state, cv, on_result, on_error):
    """One claim+collect round of the shared mesh collector: under the
    condition variable, wait for a produced entry (or for production to
    end), claim the first READY one (jax.Array.is_ready; oldest when
    nothing resolved yet), then -- outside the lock -- wait out its
    device outputs if needed and run phase b through the SAME
    `_run_phase_b_entry` protocol as the serial collector.  Returns
    False when there is nothing left to collect."""
    with cv:
        while not produced and state['outstanding'] > 0:
            cv.wait()
        if not produced:
            return False
        pick = None
        for i, (_k, _p, ctx) in enumerate(produced):
            if _ctx_ready(ctx):
                pick = i
                break
        if pick is None:
            pick = 0
            trace.metric('collect.wait_in_order')
        elif pick > 0:
            trace.metric('collect.ready_reorder')
        key, pool, ctx = produced.pop(pick)
    if not _ctx_ready(ctx):
        # device still computing: block OUTSIDE the lock so other chip
        # threads keep draining ready entries, and account the block as
        # collective/device wait
        t0 = time.perf_counter()
        for arr in _ctx_pending_arrays(ctx):
            try:
                arr.block_until_ready()
            except Exception:
                pass    # phase b will surface the real error
        trace.metric('mesh.collective_wait_s', time.perf_counter() - t0)
    _run_phase_b_entry(key, pool, ctx, on_result, on_error)
    return True


class MeshDocPool(ShardedNativePool):
    """Doc-partitioned pool over a device mesh: dp chips, each a
    device-pinned `MeshChipPool`; drop-in for `NativeDocPool` on the
    batch/query surface (see module docstring for the drive)."""

    _batch_label = 'mesh'

    def __init__(self, dp=None, sp=None):
        env = parse_mesh_env()
        if dp is None:
            if env is None:
                raise ValueError(
                    'MeshDocPool needs dp (constructor arg or '
                    'AMTPU_MESH=dp[,sp])')
            dp, sp = env
        if sp is None:
            sp = 1
        if dp < 1 or sp < 1:
            raise ValueError('mesh axes must be >= 1, got dp=%r sp=%r'
                             % (dp, sp))
        # reserve the virtual CPU devices BEFORE anything initializes a
        # backend: on jax without the jax_num_cpu_devices option the
        # XLA flag parses exactly once, at first backend init.  Device
        # ENUMERATION stays lazy (construction must never hang on a
        # wedged device tunnel).
        ensure_cpu_devices(dp * sp)
        super().__init__(n_shards=dp)
        self.dp = dp
        self.sp = sp
        self._devices = None

    def _resolve_devices(self):
        """One device per dp chip, resolved at first use.  A device
        shortfall (backend initialized before the pool could reserve
        enough) degrades to round-robin placement -- parity is
        unaffected (placement is a performance property), but it is
        counted and warned so an under-provisioned mesh cannot
        masquerade as the real thing."""
        if self._devices is None:
            import jax
            devs = jax.devices()
            want = self.dp * self.sp
            if len(devs) < want:
                trace.metric('mesh.device_shortfall')
                warnings.warn(
                    'AMTPU_MESH wants %d devices (dp=%d x sp=%d) but '
                    'only %d are available; chips share devices '
                    'round-robin (parity holds, scaling will not)'
                    % (want, self.dp, self.sp, len(devs)),
                    RuntimeWarning, stacklevel=3)
            # chip s owns devices [s*sp, (s+1)*sp); its primary device
            # (kernel placement) is the first -- the rest belong to the
            # chip's sp sub-mesh when the sp fence routes a long list
            self._devices = [devs[(s * self.sp) % len(devs)]
                             for s in range(self.dp)]
        return self._devices

    @property
    def pools(self):
        if self._pools is None:
            n = self.n_shards
            devices = self._resolve_devices()
            with self._pools_lock:
                if self._pools is None:
                    self._pools = [MeshChipPool(devices[s])
                                   for s in range(n)]
        return self._pools

    def _run(self, subs):
        """The mesh drive: one thread per chip runs that chip's phase a
        (parallel C++ decode/begin + the chip's async kernel dispatch),
        publishes the context, and immediately joins a SHARED ready-
        first collector -- no barrier between the phases, so an early
        chip's host mid/emit overlaps a late chip's begin and a slow
        chip's device wait (ISSUE 7 tentpole a+c).  Ready-order claims
        use the same jax.Array.is_ready predicate and phase-b failure
        protocol as the single-device pipelined collector."""
        pools = self.pools
        results = [None] * self.n_shards
        errors = []
        live = [s for s in range(self.n_shards) if subs[s] is not None]
        trace.metric('mesh.batches')
        trace.metric('mesh.shards', len(live))
        chip_docs = []
        for s in live:
            try:
                head = ctypes.string_at(subs[s][0], min(subs[s][1], 16))
                chip_docs.append(_read_map_header(head)[0])
            except (ValueError, IndexError):
                chip_docs.append(0)
        if chip_docs:
            trace.metric('mesh.chip_docs', sum(chip_docs))
            trace.metric('mesh.occupancy_skew',
                         max(chip_docs) - min(chip_docs))

        produced = []                    # phase-a outputs awaiting collect
        state = {'outstanding': len(live)}
        cv = threading.Condition()
        t_a = {}

        def keep(s, result):
            results[s] = result          # per-slot writes: no lock

        def err(s, e):
            with cv:
                errors.append((s, e))

        def chip(s):
            try:
                t0 = time.perf_counter()
                ctx = pools[s]._phase_a(subs[s])
                t_a[s] = time.perf_counter() - t0
            except Exception as e:
                with cv:
                    errors.append((s, e))
                    state['outstanding'] -= 1
                    cv.notify_all()
            else:
                with cv:
                    produced.append((s, pools[s], ctx))
                    state['outstanding'] -= 1
                    cv.notify_all()
            while _collect_one_ready_first(produced, state, cv, keep,
                                           err):
                pass

        if len(live) <= 1:
            for s in live:
                chip(s)
        else:
            threads = [threading.Thread(target=chip, args=(s,))
                       for s in live]
            with trace.span('mesh.drive'):
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
        if len(t_a) > 1:
            trace.metric('mesh.encode_shard_skew_s',
                         max(t_a.values()) - min(t_a.values()))
        return results, errors
