"""Device-resident POOL state for the batch register path (ISSUE 6
tentpole a).

`resident.py` keeps a single big list object's arena columns on device
between batches; this module does the same for the state every *batch*
re-stages through the register/clock path -- starting with the pool-
resident clock table that `native/core.cpp` persists across batches
(struct ResClock): densified all_deps rows keyed (doc, actor, seq) are
immutable once their change is applied, so the device copy only ever
needs the rows appended since the last batch.

Consistency rides the C++ generation counter:

* (gen, Ap) unchanged and n_rows grew  ->  delta-upload rows
  [cached_n, n_rows) with one scatter (pow2-bucketed index padding, the
  `resident.py` pattern);
* gen bumped (rollback, new actor registered, row-cap restart) or Ap
  grown (actor capacity)  ->  full re-upload at the new pow2 capacity;
* n_rows outgrew the pow2 row capacity with gen/Ap unchanged  ->  the
  table grows ON DEVICE (device-to-device copy into the next bucket)
  and the batch still delta-uploads only its appended rows;
* n_rows unchanged  ->  no upload at all: the steady-state batch whose
  changes all dedup into persisted rows pays ZERO clock traffic
  (`resident.batch_hits`).

The device table is handed to the register kernels in place of the
batch-local `amtpu_col_clocktab` view; batch `clock_idx` columns
reference pool-global rows, so the kernel maths is unchanged and byte
parity with the non-resident path holds by construction (pinned by the
A/B lanes in tests/test_resident.py and the adversarial fuzz suite).
"""

import ctypes
from functools import lru_cache

import numpy as np

from .. import trace
from ..analysis import sanitize
from .resident import _bucket_pow2


@lru_cache(maxsize=None)
def _jit_row_scatter(donate):
    import jax

    def scatter(tab, idx, rows):
        # pad slots carry idx == capacity (out of bounds) and drop
        return tab.at[idx].set(rows, mode='drop')
    if donate:
        # accelerators: reuse the prior table's device buffer for the
        # output instead of allocating per delta (donate_argnums is
        # proven on the tier staging path, ops/registers.py); on CPU
        # "transfers" are memcpys and donation buys nothing
        jitted = jax.jit(scatter, donate_argnums=(0,))
    else:
        jitted = jax.jit(scatter)

    def dispatch(tab, idx, rows):
        # jax zero-copies 64B-aligned numpy inputs on CPU and even
        # jnp.array's "copy" can be deferred past dispatch (measured on
        # jax 0.4.37: mutating the source after dispatch corrupts the
        # in-flight scatter -- the PR-4 alias class).  Hand the
        # computation PRIVATE synchronous host copies instead: jax may
        # alias them freely because no caller ever sees them, so the
        # staging arrays are reusable the moment dispatch returns.
        out = jitted(tab, np.array(idx), np.array(rows))
        # AMTPU_SANITIZE=1: poison the caller-visible staging arrays the
        # moment dispatch returns -- if the private-copy contract above
        # ever regresses (jax aliasing idx/rows), the in-flight scatter
        # reads sentinel garbage and the parity lanes fail loudly
        # instead of shipping silent corruption (docs/ANALYSIS.md)
        sanitize.poison(idx, rows)
        return out
    return dispatch


class PoolClockCache:
    """Device-resident copy of one pool's ResClock table."""

    __slots__ = ('tab', 'gen', 'n', 'ap', 'cap')

    def __init__(self):
        self.tab = None
        self.gen = -1
        self.n = 0
        self.ap = 0
        self.cap = 0

    def table(self, L, pool, donate_ok=True):
        """Returns the device clock table [cap, Ap] covering the pool's
        current rows, uploading as little as the generation contract
        allows.  Call once per batch, AFTER begin (the batch's rows are
        appended by then).

        `donate_ok=False` disables buffer donation on the delta scatter:
        the wave-pipelined driver hands the PREVIOUS table version to a
        batch whose kernels are still in flight when the next wave's
        delta runs, so donating would recycle a buffer an enqueued
        computation may still read."""
        import jax
        import jax.numpy as jnp

        info = (ctypes.c_int64 * 4)()
        L.amtpu_resclk_info(pool, info)
        n, ap, gen = int(info[0]), int(info[1]), int(info[2])
        need_full = (self.tab is None or gen != self.gen
                     or ap != self.ap or n < self.n)
        if not need_full and n > self.cap:
            # capacity growth WITHOUT invalidation: the persisted rows
            # are already on device, so grow there (device-to-device
            # copy into the next pow2 bucket) instead of re-staging the
            # whole table from host -- the steady-state cost of crossing
            # a pow2 boundary is one device copy, not O(n) host traffic
            cap = _bucket_pow2(n, floor=64)
            self.tab = jnp.zeros((cap, max(ap, 1)),
                                 self.tab.dtype).at[:self.cap].set(self.tab)
            self.cap = cap
            trace.metric('resident.batch_grow_uploads')
        if need_full:
            if gen != self.gen and self.tab is not None:
                trace.metric('resident.batch_gen_invalidation')
            cap = _bucket_pow2(max(n, 1), floor=64)
            host = np.zeros((cap, max(ap, 1)), np.int32)
            if n:
                src = np.ctypeslib.as_array(L.amtpu_resclk_tab(pool),
                                            shape=(n, ap))
                host[:n] = src
            self.tab = jnp.asarray(host)
            trace.metric('resident.batch_full_uploads')
            trace.metric('resident.batch_full_upload_rows', n)
            self.cap = cap
        elif n > self.n:
            k = n - self.n
            kp = _bucket_pow2(k, floor=16)
            idx = np.full(kp, self.cap, np.int32)    # cap = dropped
            idx[:k] = np.arange(self.n, n, dtype=np.int32)
            rows = np.zeros((kp, ap), np.int32)
            src = np.ctypeslib.as_array(L.amtpu_resclk_tab(pool),
                                        shape=(n, ap))
            rows[:k] = src[self.n:n]
            donate = donate_ok and jax.default_backend() != 'cpu'
            self.tab = _jit_row_scatter(donate)(self.tab, idx, rows)
            trace.metric('resident.batch_hits')
            trace.metric('resident.batch_delta_rows', k)
        else:
            # every clock row of this batch was already resident
            trace.metric('resident.batch_hits')
            trace.metric('resident.batch_noop')
        self.gen, self.n, self.ap = gen, n, ap
        return self.tab

    def drop_if_disabled(self, L, pool):
        """Release the device table once C++ permanently disabled the
        pool's resident cache (actor population past
        AMTPU_RESCLK_MAX_ACTORS): the buffer can be pool-lifetime large
        (up to row-cap x Ap x 4 bytes) and will never be read again."""
        if self.tab is None:
            return
        info = (ctypes.c_int64 * 4)()
        L.amtpu_resclk_info(pool, info)
        if int(info[3]):
            self.tab = None
            self.gen = -1
            self.n = self.ap = self.cap = 0
            trace.metric('resident.batch_cache_dropped')
