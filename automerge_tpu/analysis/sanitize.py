"""Runtime alias sanitizer (``AMTPU_SANITIZE=1``; docs/ANALYSIS.md).

The static dispatch-alias checker sees lexical reuse; this is the
dynamic net for everything else.  It generalizes the hostile-mutation
wrapper tests/test_resident.py pins the wave pipeline with: every
staging buffer a dispatch site hands to jax is POISONED (filled with a
sentinel) the moment the dispatch returns.  The dispatch contract says
jax received either a private copy or a buffer nobody will touch
again, so poisoning is invisible -- unless some path aliased a
caller-visible array into the async computation, in which case the
in-flight kernel reads sentinel garbage and the parity/fuzz lanes fail
LOUDLY instead of shipping silent corruption (exactly how the PR-4 and
PR-6 alias bugs would have surfaced at CI time).

Usage at a staging call site (wired today through the pool-resident
delta scatter, `native/batch_resident.py` -- the one dispatch whose
contract is "jax received private copies"; the escalation tier staging
hands its fresh buffers OVER to jax instead, so poisoning there would
corrupt legitimately aliased memory):

    out = jitted(tab, np.array(idx), np.array(rows))
    sanitize.poison(idx, rows)      # no-op unless AMTPU_SANITIZE=1
    return out

`poison` costs one module-attribute check when disarmed (the
trace.ENABLED shim pattern), so it is free on the hot path.
"""

import numpy as np

from ..utils.common import env_bool

#: sentinel byte pattern: 0x5B per byte -> int32 0x5B5B5B5B, a value no
#: workload emits, so corrupted output is unmistakable in a diff
POISON_BYTE = 0x5B

#: armed flag, refreshed from AMTPU_SANITIZE at import and via
#: refresh() -- tests arm it per subprocess
ARMED = env_bool('AMTPU_SANITIZE', False)

_poisoned = 0


def refresh():
    """Re-reads AMTPU_SANITIZE (subprocess lanes set it before import;
    in-process tests flip the env then call this)."""
    global ARMED
    ARMED = env_bool('AMTPU_SANITIZE', False)
    return ARMED


def poison(*arrays):
    """Overwrites each writable numpy array with the sentinel pattern
    when armed.  Call it on the HOST staging buffers right after the
    dispatch that consumed them returns."""
    if not ARMED:
        return
    global _poisoned
    n = 0
    for a in arrays:
        if isinstance(a, np.ndarray) and a.flags.writeable and a.size:
            if a.flags.c_contiguous:
                a.view(np.uint8).fill(POISON_BYTE)
            else:
                # strided view: byte reinterpretation is illegal; the
                # elementwise sentinel still poisons every slot
                a.fill(POISON_BYTE)
            n += 1
    if n:
        _poisoned += n
        from .. import trace
        trace.count('sanitize.poisoned_buffers', n)


def poisoned_count():
    """Total buffers poisoned since import (test observability)."""
    return _poisoned
