"""automerge_tpu.analysis -- project-specific static analysis (ISSUE 8).

Every hardening round since PR 4 re-caught the same four bug classes by
manual review; this package turns them into CI failures (`make
static-check`, docs/ANALYSIS.md):

  * **env-latch** (`check_env`): one machine-readable spec of every
    ``AMTPU_*`` flag (`env_spec.ENV_FLAGS`) cross-verified against the
    call-site defaults, raw ``os.environ`` reads, the C++ ``getenv``
    sites, the ``amtpu_latch_defaults`` ABI, the latch-flip-guard key
    list, and the env rows in docs/OBSERVABILITY.md.
  * **telemetry-key** (`check_telemetry`): every statically reachable
    flat-counter key must be pre-seeded in its ``KNOWN_*_KEYS`` block
    and documented; documented keys with no emit site are dead.
  * **dispatch-alias** (`check_alias`): host numpy buffers handed to a
    jax dispatch and then mutated in the same scope -- the PR-4/PR-6
    zero-copy alias class.  `sanitize.py` is the runtime sibling
    (``AMTPU_SANITIZE=1`` poisons staging buffers after dispatch).
  * **lock-discipline** (`check_locks`): ``# guarded-by: <lock>``
    attribute annotations enforced -- annotated attributes may only be
    touched inside ``with <lock>``.

The engine (`engine.py`) parses each file once and hands the shared
sources to every checker; `tools/static_check.py` is the CLI.
"""

from .engine import Finding, run_checks  # noqa: F401
