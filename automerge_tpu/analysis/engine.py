"""The checker engine: parse once, check many (docs/ANALYSIS.md).

A `Source` bundles everything a checker wants about one Python file --
the text, the AST, and the per-line comments (AST drops comments, so
they come from `tokenize`; the lock checker's ``# guarded-by:`` and the
suppression markers live there).  `run_checks` walks the scanned roots
once, builds the sources, and hands the same list to every registered
checker, so adding a checker never adds a parse pass.

Suppression: a finding is dropped when its source line carries
``# static-ok: <checker>`` (or a bare ``# static-ok``).  Suppressions
are for reviewed, deliberate exceptions -- the marker is greppable.
"""

import ast
import io
import os
import tokenize

#: package subtrees scanned by default (tools/tests/bench stay out:
#: they run OUTSIDE the serving process, and their harness knobs are
#: covered by the env spec's harness prefixes)
DEFAULT_SCAN_DIRS = ('automerge_tpu',)

SUPPRESS_MARK = 'static-ok'


class Finding(object):
    """One checker hit, formatted `path:line: [checker] code: message`."""

    __slots__ = ('checker', 'code', 'path', 'line', 'message')

    def __init__(self, checker, code, path, line, message):
        self.checker = checker
        self.code = code
        self.path = path
        self.line = line
        self.message = message

    def format(self, root=None):
        path = self.path
        if root and path.startswith(root.rstrip(os.sep) + os.sep):
            path = path[len(root.rstrip(os.sep)) + 1:]
        return '%s:%d: [%s] %s: %s' % (path, self.line, self.checker,
                                       self.code, self.message)

    def __repr__(self):
        return '<Finding %s>' % self.format()


class Source(object):
    """One parsed Python file shared by every checker."""

    __slots__ = ('path', 'relpath', 'text', 'lines', 'tree', 'comments')

    def __init__(self, path, relpath, text):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.comments = self._extract_comments(text)

    @staticmethod
    def _extract_comments(text):
        """{line_number: comment text (without '#')} -- logical-line
        comments AND trailing comments both land on their physical
        line."""
        out = {}
        try:
            toks = tokenize.generate_tokens(io.StringIO(text).readline)
            for tok in toks:
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string.lstrip('#').strip()
        except tokenize.TokenError:
            pass
        return out

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ''

    def suppressed(self, lineno, checker):
        c = self.comments.get(lineno, '')
        if SUPPRESS_MARK not in c:
            return False
        tail = c.split(SUPPRESS_MARK, 1)[1].lstrip(': ').strip()
        return not tail or checker in tail.split(',')


#: name -> callable(sources, ctx) -> iterable[Finding]
CHECKERS = {}


def register(name):
    def deco(fn):
        CHECKERS[name] = fn
        return fn
    return deco


def iter_py_files(root, scan_dirs=DEFAULT_SCAN_DIRS):
    for sub in scan_dirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != '__pycache__']
            for fn in sorted(filenames):
                if fn.endswith('.py'):
                    yield os.path.join(dirpath, fn)


def load_sources(root, scan_dirs=DEFAULT_SCAN_DIRS, extra_files=()):
    """(sources, parse_findings): a file that does not parse becomes a
    `syntax-error` finding instead of aborting the whole gate -- every
    other file's checkers still run and report."""
    sources, broken = [], []
    for path in list(iter_py_files(root, scan_dirs)) + list(extra_files):
        with open(path, encoding='utf-8') as f:
            text = f.read()
        try:
            sources.append(Source(path, os.path.relpath(path, root),
                                  text))
        except SyntaxError as e:
            broken.append(Finding('engine', 'syntax-error', path,
                                  e.lineno or 0, str(e)))
    return sources, broken


class Context(object):
    """Cross-file context the checkers share: the repo root plus lazily
    loaded artifacts (docs text, the native ABI)."""

    def __init__(self, root):
        self.root = root
        self._docs = {}

    def doc_text(self, relpath):
        """Text of a docs/ file (cached; '' when absent)."""
        if relpath not in self._docs:
            path = os.path.join(self.root, relpath)
            try:
                with open(path, encoding='utf-8') as f:
                    self._docs[relpath] = f.read()
            except OSError:
                self._docs[relpath] = ''
        return self._docs[relpath]


def run_checks(root, checkers=None, scan_dirs=DEFAULT_SCAN_DIRS,
               extra_files=()):
    """Runs the selected checkers (default: all registered) over the
    scan roots; returns the suppression-filtered findings sorted by
    (path, line)."""
    # import for side effect: checker registration
    from . import check_alias, check_env, check_locks, check_telemetry  # noqa: F401
    unknown = sorted(set(checkers or ()) - set(CHECKERS))
    if unknown:
        raise ValueError('unknown checker(s) %s; known: %s'
                         % (', '.join(unknown),
                            ', '.join(sorted(CHECKERS))))
    sources, findings = load_sources(root, scan_dirs, extra_files)
    by_path = {s.path: s for s in sources}
    ctx = Context(root)
    for name in (checkers or sorted(CHECKERS)):
        for f in CHECKERS[name](sources, ctx):
            src = by_path.get(f.path)
            if src is not None and src.suppressed(f.line, f.checker):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings
