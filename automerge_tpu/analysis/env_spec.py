"""The ONE machine-readable spec of every ``AMTPU_*`` environment flag.

Each flag records its type (which `utils/common` helper reads it), its
default (cross-checked against the literal at every call site AND, for
the C++ latches, against the ``amtpu_latch_defaults`` ABI), whether it
LATCHES at the process's first batch (cross-checked against
``native._RESIDENT_LATCH_KEYS`` -- the flip-guard list), and its
consumers.  `check_env` fails `make static-check` when any of those
drifts, and when a flag here is missing from the env-variable table in
docs/OBSERVABILITY.md (or vice versa).

Registering a new flag (docs/ANALYSIS.md has the walkthrough):
  1. add an `EnvFlag` row here;
  2. read it ONLY through the `utils/common` helper matching its type;
  3. add its row to docs/OBSERVABILITY.md's env table;
  4. if it latches at first batch, add it to `_RESIDENT_LATCH_KEYS`.
`make static-check` verifies you did all four.
"""

import collections

#: type -> the utils/common helper that must read it.  `raw` flags are
#: tri-state (consumers distinguish unset from any value); `special`
#: flags have a dedicated parser (AMTPU_MESH -> parse_mesh_env).
EnvFlag = collections.namedtuple(
    'EnvFlag', ('name', 'type', 'default', 'latched', 'consumer'))

ENV_FLAGS = (
    # -- observability ------------------------------------------------------
    EnvFlag('AMTPU_TRACE', 'bool', False, False, 'telemetry/spans.py'),
    EnvFlag('AMTPU_TRACE_FILE', 'str', '', False, 'telemetry/spans.py'),
    EnvFlag('AMTPU_TRACE_FILE_MAX_MB', 'int', 256, False,
            'telemetry/spans.py (keep-1 rotation cap; <=0 disables)'),
    EnvFlag('AMTPU_TRACE_WIRE', 'bool', True, False,
            'sidecar/client.py (stamp the wire trace context on every '
            'outbound request; read once per client)'),
    EnvFlag('AMTPU_REPLICA_ID', 'str', '', False,
            'telemetry/__init__.py (fleet replica identity; empty -> '
            'hostname:pid)'),
    EnvFlag('AMTPU_RECORDER_EVENTS', 'int', 4096, False,
            'telemetry/recorder.py (ring size; read once at import)'),
    EnvFlag('AMTPU_RECORDER_DIR', 'str', '', False,
            'telemetry/recorder.py (dump dir; empty -> per-process '
            'tempdir)'),
    EnvFlag('AMTPU_RECORDER_MIN_DUMP_S', 'float', 5.0, False,
            'telemetry/recorder.py (per-reason dump rate limit)'),
    EnvFlag('AMTPU_SLOW_MS', 'float', 250.0, False,
            'telemetry/attribution.py (exemplar-trace threshold)'),
    EnvFlag('AMTPU_SLO_P99_MS', 'float', 100.0, False,
            'telemetry/attribution.py (p99 target the burn rates '
            'measure against)'),
    EnvFlag('AMTPU_EXEMPLAR_MIN_S', 'float', 0.05, False,
            'telemetry/attribution.py (min interval between exemplar '
            'emissions; bounds the tail sampler under error storms)'),
    EnvFlag('AMTPU_DEVTIME', 'bool', False, False, 'telemetry/__init__.py'),
    EnvFlag('AMTPU_DEGRADED_WINDOW_S', 'float', 300.0, False,
            'telemetry/__init__.py'),
    EnvFlag('AMTPU_SIDECAR_RESTARTS', 'int', 0, False,
            'telemetry/__init__.py (exported by sidecar/client.py)'),
    EnvFlag('AMTPU_METRICS_PORT', 'int', -1, False, 'sidecar/server.py'),
    EnvFlag('AMTPU_METRICS_HOST', 'str', '127.0.0.1', False,
            'sidecar/server.py'),
    # -- per-doc capacity accounting + headroom (ISSUE 15) ------------------
    EnvFlag('AMTPU_MEM_BUDGET_MB', 'int', 0, False,
            'telemetry/capacity.py (memory budget the headroom '
            'estimator measures against; 0 = unbudgeted)'),
    EnvFlag('AMTPU_MEM_PRESSURE_EVICT', 'float', 0.85, False,
            'telemetry/capacity.py (pressure fraction past which the '
            'gateway evicts cold docs proactively; <=0 disables)'),
    EnvFlag('AMTPU_PRESSURE_EVICT_DOCS', 'int', 16, False,
            'storage/coldstore.py (max LRU docs one pressure-eviction '
            'pass checkpoints out)'),
    EnvFlag('AMTPU_PRESSURE_EVICT_COOLDOWN_S', 'float', 30.0, False,
            'telemetry/capacity.py (min seconds between pressure '
            'passes: a stuck-high RSS signal must not evict per flush)'),
    EnvFlag('AMTPU_CAPACITY_TOPK', 'int', 10, False,
            'telemetry/capacity.py (hot-doc table depth)'),
    EnvFlag('AMTPU_CAPACITY_REFRESH_S', 'float', 1.0, False,
            'telemetry/capacity.py (min seconds between native per-doc '
            'stats passes; scrapes + pressure checks share one)'),
    EnvFlag('AMTPU_CAPACITY_SKETCH', 'int', 128, False,
            'telemetry/capacity.py (space-saver sketch capacity for '
            'the streaming fanned/egress tiers)'),
    # -- kernel path --------------------------------------------------------
    EnvFlag('AMTPU_PACKED_EPILOGUE', 'bool', True, False,
            'native/__init__.py'),
    EnvFlag('AMTPU_CONF_DENSE_THRESH', 'int', 4, False,
            'native/__init__.py'),
    EnvFlag('AMTPU_HOST_DOM', 'raw', None, False, 'native/__init__.py'),
    EnvFlag('AMTPU_HOST_FULL', 'raw', None, False,
            'native/__init__.py, native/mesh_pool.py'),
    EnvFlag('AMTPU_HOST_REG', 'bool', True, False, 'native/__init__.py'),
    EnvFlag('AMTPU_WEFF', 'raw', None, False,
            'native/__init__.py (test-only window narrowing)'),
    EnvFlag('AMTPU_SHARD_MODE', 'str', '', False, 'native/__init__.py'),
    EnvFlag('AMTPU_NO_PALLAS', 'bool', False, False,
            'ops/pallas_common.py'),
    EnvFlag('AMTPU_ESCALATE', 'bool', True, False, 'ops/registers.py'),
    EnvFlag('AMTPU_MAX_TIER', 'int', 1024, False, 'ops/registers.py'),
    EnvFlag('AMTPU_ESCALATE_BUDGET_MB', 'int', -1, False,
            'ops/registers.py (unset -> built-in 256MB; explicit 0 '
            'forces the oracle)'),
    EnvFlag('AMTPU_ESC_CHUNK', 'int', 32768, False, 'ops/registers.py'),
    EnvFlag('AMTPU_DEVICE_MERGE', 'bool', True, False, 'ops/registers.py'),
    EnvFlag('AMTPU_PIPELINE_DEPTH', 'int', 2, False, 'native/__init__.py'),
    EnvFlag('AMTPU_PIPELINE_MIN_DOCS', 'int', 64, False,
            'native/__init__.py'),
    EnvFlag('AMTPU_NATIVE_LIB', 'str', '', False,
            'native/__init__.py (alternate .so path; the asan gate)'),
    # -- resident-state latches (C++ statics; bind at first batch) ----------
    EnvFlag('AMTPU_RESIDENT', 'raw', None, True,
            'native/__init__.py, native/core.cpp'),
    EnvFlag('AMTPU_RESIDENT_MIN', 'int', 16384, True, 'native/core.cpp'),
    EnvFlag('AMTPU_RESIDENT_CLK', 'raw', None, True, 'native/core.cpp'),
    EnvFlag('AMTPU_RESCLK_MAX_ACTORS', 'int', 512, True,
            'native/core.cpp'),
    EnvFlag('AMTPU_RESCLK_MAX_ROWS', 'int', 1048576, True,
            'native/core.cpp'),
    EnvFlag('AMTPU_TRIVIAL_HOST', 'bool', True, True, 'native/core.cpp'),
    EnvFlag('AMTPU_TRACE_BEGIN', 'raw', None, False,
            'native/core.cpp (per-begin debug trace)'),
    # -- mesh ---------------------------------------------------------------
    EnvFlag('AMTPU_MESH', 'special', None, True,
            'utils/common.py parse_mesh_env (factory + fence + guard)'),
    EnvFlag('AMTPU_MESH_SP_MIN', 'int', 131072, False,
            'native/resident.py (default SP_CROSSOVER_ELEMS)'),
    EnvFlag('AMTPU_MESH_CONNECT_DEADLINE_S', 'float', 60, False,
            'sync/distributed.py'),
    # -- resilience / faults ------------------------------------------------
    EnvFlag('AMTPU_RESILIENCE', 'bool', True, False, 'resilience.py'),
    EnvFlag('AMTPU_RETRY_MAX', 'int', 3, False, 'resilience.py'),
    EnvFlag('AMTPU_RETRY_BACKOFF_S', 'float', 0.05, False,
            'resilience.py'),
    EnvFlag('AMTPU_DEGRADE', 'bool', False, False, 'resilience.py'),
    EnvFlag('AMTPU_FAULT', 'str', '', False, 'faults.py'),
    EnvFlag('AMTPU_FAULT_SEED', 'raw', None, False, 'faults.py'),
    # -- columnar storage / cold-state tier (ISSUE 10, 14) ------------------
    EnvFlag('AMTPU_STORAGE_FORMAT', 'str', 'columnar', False,
            'storage/__init__.py (json = v1 parity-oracle arm)'),
    EnvFlag('AMTPU_STORAGE_NATIVE', 'bool', True, False,
            'storage/columnar.py (0 = Python codec + dict-replay load, '
            'the parity-oracle arm; checked per call)'),
    EnvFlag('AMTPU_STORAGE_FOLD', 'bool', True, False,
            'native/__init__.py (0 = no op-state folding, the A/B arm '
            'of the folding lane)'),
    EnvFlag('AMTPU_STORAGE_CHUNK_MAX', 'int', 8, False,
            'native/__init__.py (snapshot chunks per doc before '
            're-compaction merges them; 0 disables)'),
    EnvFlag('AMTPU_STORAGE_DURABLE', 'bool', False, False,
            'storage/coldstore.py (fsync + per-dir manifest: the '
            'crash-safe replica-handoff transport)'),
    EnvFlag('AMTPU_STORAGE_DIR', 'str', '', False,
            'storage/coldstore.py (empty -> fresh tempdir)'),
    EnvFlag('AMTPU_STORAGE_GC_MIN', 'int', 256, False,
            'storage/coldstore.py (mutations per doc between settled '
            '-history folds; 0 disables GC)'),
    EnvFlag('AMTPU_RESIDENT_DOCS_MAX', 'int', 0, False,
            'storage/coldstore.py (0 = no cold-doc eviction)'),
    # -- clock folding + parallel restore (ISSUE 17) ------------------------
    EnvFlag('AMTPU_STORAGE_FOLD_CLOCKS', 'bool', True, False,
            'native/__init__.py (0 = keep per-change all_deps clock '
            'vectors sparse, the unfolded A/B-oracle arm)'),
    EnvFlag('AMTPU_FOLDCLK_MAX_ACTORS', 'int', 256, False,
            'native/__init__.py (per-doc actor-population cap for the '
            'densified clock-fold table; busier docs stay sparse)'),
    EnvFlag('AMTPU_RESTORE_THREADS', 'int', 0, False,
            'native/__init__.py (restore_from_store fan-out; 0 = auto '
            'min(8, cores), 1 = the serial A/B arm)'),
    EnvFlag('AMTPU_RESTORE_BATCH', 'int', 8192, False,
            'native/__init__.py (docs per decode+apply batch during '
            'restore_from_store)'),
    # -- sidecar client -----------------------------------------------------
    EnvFlag('AMTPU_WAL_COMPACT', 'int', 32, False, 'sidecar/client.py'),
    EnvFlag('AMTPU_WAL_MAX_BYTES', 'int', 67108864, False,
            'sidecar/client.py (log-byte compaction trigger; <=0 '
            'disables the byte bound)'),
    EnvFlag('AMTPU_SIDECAR_DEADLINE_S', 'float', 0, False,
            'sidecar/client.py (0 -> no deadline)'),
    EnvFlag('AMTPU_SIDECAR_HEARTBEAT_S', 'float', 0, False,
            'sidecar/client.py (0 -> no heartbeat)'),
    EnvFlag('AMTPU_SIDECAR_MAX_RESPAWNS', 'int', 3, False,
            'sidecar/client.py'),
    EnvFlag('AMTPU_SIDECAR_RESPAWN_DEADLINE_S', 'float', 30.0, False,
            'sidecar/client.py'),
    # -- serve gateway ------------------------------------------------------
    EnvFlag('AMTPU_GATEWAY', 'bool', True, False, 'sidecar/server.py'),
    EnvFlag('AMTPU_FLUSH_DEADLINE_MS', 'float', 2.0, False,
            'scheduler/queue.py'),
    EnvFlag('AMTPU_MAX_BATCH_DOCS', 'int', 256, False,
            'scheduler/queue.py'),
    EnvFlag('AMTPU_MAX_BATCH_OPS', 'int', 2048, False,
            'scheduler/queue.py'),
    EnvFlag('AMTPU_QUEUE_MAX_OPS', 'int', 4096, False,
            'scheduler/queue.py'),
    EnvFlag('AMTPU_QUEUE_LOW_FRAC', 'float', 0.5, False,
            'scheduler/queue.py'),
    # -- bounded egress / backpressure (ISSUE 13) ---------------------------
    EnvFlag('AMTPU_EGRESS_MAX_BYTES', 'int', 1048576, False,
            'scheduler/egress.py (per-conn queued-byte bound before '
            'tier-1 event shedding)'),
    EnvFlag('AMTPU_EGRESS_WEDGE_S', 'float', 10.0, False,
            'scheduler/egress.py (zero-progress seconds before tier-3 '
            'wedge eviction)'),
    EnvFlag('AMTPU_EGRESS_RESYNC_SHEDS', 'int', 3, False,
            'scheduler/egress.py (consecutive sheds before tier-2 '
            'drop-to-resubscribe)'),
    # -- batched sync fan-out -----------------------------------------------
    EnvFlag('AMTPU_FANOUT', 'bool', True, False, 'scheduler/gateway.py'),
    EnvFlag('AMTPU_FANOUT_VECTOR', 'bool', True, False,
            'sync/fanout.py (0 = per-peer scalar loop; A/B + oracle)'),
    EnvFlag('AMTPU_FANOUT_PRESENCE', 'bool', True, False,
            'sync/fanout.py'),
    # -- analysis / sanitizer ----------------------------------------------
    EnvFlag('AMTPU_SANITIZE', 'bool', False, False,
            'analysis/sanitize.py (poisons staging buffers post-dispatch)'),
    # -- fleet router / rebalancer (ISSUE 18) ------------------------------
    EnvFlag('AMTPU_ROUTE_VNODES', 'int', 64, False,
            'router/ring.py (virtual nodes per replica on the '
            'consistent-hash ring)'),
    EnvFlag('AMTPU_ROUTE_REDIRECTS', 'int', 3, False,
            'router/gateway.py + sidecar/client.py (max WrongReplica '
            'redirect hops per request before the error surfaces)'),
    EnvFlag('AMTPU_ROUTE_HANDOFF_DIR', 'str', '', False,
            'router/rebalance.py (root dir for durable migration '
            'handoff stores; empty -> per-process tempdir)'),
    EnvFlag('AMTPU_REBALANCE_INTERVAL_S', 'float', 5.0, False,
            'router/rebalance.py (seconds between rebalancer scrape '
            'passes)'),
    EnvFlag('AMTPU_REBALANCE_TOPK', 'int', 4, False,
            'router/rebalance.py (max hot-doc victims one rebalance '
            'pass migrates)'),
    EnvFlag('AMTPU_REBALANCE_MIN_SKEW', 'float', 0.5, False,
            'router/rebalance.py (relative occupancy spread '
            '(max-min)/mean below which the fleet counts as balanced)'),
    EnvFlag('AMTPU_REBALANCE_PRESSURE', 'float', 0.8, False,
            'router/rebalance.py (memory pressure on any replica past '
            'which a rebalance triggers regardless of skew)'),
    # -- fleet failover (ISSUE 19) ------------------------------------------
    EnvFlag('AMTPU_FLEET_HEARTBEAT_S', 'float', 0.5, False,
            'router/health.py (seconds between heartbeat probe sweeps '
            'over the ring members)'),
    EnvFlag('AMTPU_FLEET_DEADLINE_S', 'float', 0.5, False,
            'router/health.py (per-probe answer deadline; a hung '
            'replica counts as a miss)'),
    EnvFlag('AMTPU_FLEET_MISS_MAX', 'int', 3, False,
            'router/health.py (consecutive misses before a suspect '
            'member is declared dead and failed over)'),
    EnvFlag('AMTPU_FLEET_PARK_S', 'float', 10.0, False,
            'router/gateway.py (max seconds a frame parks for a '
            'suspect/dead member before the retryable envelope)'),
    EnvFlag('AMTPU_FLEET_PARK_MB', 'int', 8, False,
            'router/gateway.py (byte budget across all fleet-parked '
            'frames; overflow answers the retryable envelope)'),
    EnvFlag('AMTPU_FLEET_FLAP_MAX', 'int', 3, False,
            'router/supervisor.py (lineage deaths before respawns '
            'stop and the member is quarantined)'),
    EnvFlag('AMTPU_STORAGE_SYNC', 'bool', False, False,
            'scheduler/gateway.py (write-through checkpoint every '
            'acked mutation into the durable store pre-ack; the '
            'failover byte-parity guarantee rests on it)'),
    # -- read path (patch shipping / replicas / snapshots) ------------------
    EnvFlag('AMTPU_READ_PATCH', 'bool', True, False,
            'sync/fanout.py (0 refuses mode:"patch" subscriptions '
            'with a typed RangeError; change-mode fan-out unaffected)'),
    EnvFlag('AMTPU_READ_SNAPSHOT_CACHE', 'int', 64, False,
            'readview/snapshot.py (max resident frontier-clock-keyed '
            'container blobs, LRU)'),
    EnvFlag('AMTPU_READ_STALENESS_SLO_S', 'float', 5.0, False,
            'readview/replica.py (seconds a replica doc may lag the '
            'upstream frontier before a forced catch-up)'),
    EnvFlag('AMTPU_READ_RESYNC_S', 'float', 2.0, False,
            'readview/replica.py (staleness probe cadence against the '
            'upstream get_clock frontier)'),
)

SPEC = {f.name: f for f in ENV_FLAGS}

#: the three numeric C++ latch defaults exposed through the
#: `amtpu_latch_defaults` ABI, in ABI order -- check_env compares the
#: spec rows against the built library so a core.cpp constant bump
#: cannot drift past this table (or the flip guard reading the ABI)
ABI_LATCH_DEFAULTS = ('AMTPU_RESIDENT_MIN', 'AMTPU_RESCLK_MAX_ACTORS',
                      'AMTPU_RESCLK_MAX_ROWS')

#: bench/tools harness knob families: allowed in the docs env table and
#: in harness code without individual spec rows (they configure the
#: measurement harnesses, not the serving process)
HARNESS_PREFIXES = ('AMTPU_BENCH_', 'AMTPU_TCHECK_', 'AMTPU_MESHCHECK_',
                    'AMTPU_MC_', 'AMTPU_MULTICHIP_', 'AMTPU_DRYRUN_',
                    'AMTPU_SMOKE_')
