"""lock-discipline checker: `# guarded-by:` annotations enforced
(docs/ANALYSIS.md).

Annotation grammar (trailing comment on the attribute's assignment,
conventionally in ``__init__``):

    self._items = []          # guarded-by: self._lock
    self._resp = {}           # guarded-by: self._resp_cond
    self.depth_ops = 0        # guarded-by: self._lock|self._work
    self._pools = None        # guarded-by(w): self._pools_lock

* ``lock|lock`` lists alternates that guard the same state (a
  `threading.Condition` built ON a lock is the canonical case).
* ``guarded-by(w)`` checks WRITES only -- the double-checked publish
  pattern (racy read, locked construct-and-assign) stays legal.

Enforcement: inside the annotating class, every load/store of an
annotated ``self.<attr>`` must sit lexically inside ``with <lock>:``
(any alternate), except:

  * the method that carries the annotation (``__init__``: the object
    is not shared yet);
  * methods whose ``def`` line carries ``# holds-lock: <lock>`` (the
    caller owns the lock -- documented at the def, checked at the
    sites);
  * lines carrying ``# static-ok: lock-discipline`` (reviewed benign
    races -- say why in the comment).

The checker is lexical and per class: cross-object access (another
object's attributes) and dynamic lock juggling are out of scope -- the
annotated hot-path state (gateway queue, sidecar demux, mesh chip
pools, telemetry registry) is exactly the surface the mesh/fleet work
keeps growing.
"""

import ast
import re

from .engine import Finding, register

CHECKER = 'lock-discipline'

_GUARD_RE = re.compile(r'guarded-by(\((?P<mode>w)\))?:\s*(?P<locks>[^#]+)')
_HOLDS_RE = re.compile(r'holds-lock:\s*(?P<locks>[^#]+)')


def _norm(expr):
    return expr.replace(' ', '').strip()


def _parse_locks(text):
    return tuple(_norm(p) for p in text.split('|') if p.strip())


def _self_attr_of_assign(stmt):
    """The attribute name when `stmt` assigns (only) to self.<attr>."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == 'self':
            return t.attr
    return None


def _collect_annotations(src, cls):
    """{attr: (locks, writes_only, method_name)} from trailing
    guarded-by comments on self.<attr> assignments in `cls`."""
    out = {}
    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
            continue
        for stmt in ast.walk(method):
            if not isinstance(stmt, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                continue
            attr = _self_attr_of_assign(stmt)
            if attr is None:
                continue
            for line in range(stmt.lineno,
                              (stmt.end_lineno or stmt.lineno) + 1):
                m = _GUARD_RE.search(src.comments.get(line, ''))
                if m:
                    out[attr] = (_parse_locks(m.group('locks')),
                                 m.group('mode') == 'w', method.name)
                    break
    return out


def _holds_locks(src, method):
    """Locks a method's def-line comment declares as already held."""
    for line in range(method.lineno, method.body[0].lineno + 1):
        m = _HOLDS_RE.search(src.comments.get(line, ''))
        if m:
            return _parse_locks(m.group('locks'))
    return ()


class _Visitor(ast.NodeVisitor):
    """Walks one method tracking the lexical `with` stack.

    Nested defs/lambdas are NOT descended into: a closure created under
    `with lock:` typically runs LATER on another thread (executor
    submit, callback), so treating it as lock-held would be wrong --
    and visiting it with an empty stack would flag helpers whose every
    caller holds the lock.  Deferred-closure discipline is out of this
    checker's lexical scope; the runtime sanitizer and the chaos lanes
    stay the net there."""

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def __init__(self, src, method, annotations, held, findings):
        self.src = src
        self.method = method
        self.annotations = annotations
        self.held = list(held)
        self.findings = findings

    def visit_With(self, node):
        exprs = [_norm(ast.unparse(item.context_expr))
                 for item in node.items]
        self.held.extend(exprs)
        for stmt in node.body:
            self.visit(stmt)
        # also walk the context expressions themselves (unguarded)
        del self.held[len(self.held) - len(exprs):]
        for item in node.items:
            self.visit(item.context_expr)

    def visit_Attribute(self, node):
        if isinstance(node.value, ast.Name) and node.value.id == 'self' \
                and node.attr in self.annotations:
            locks, writes_only, _home = self.annotations[node.attr]
            is_store = isinstance(node.ctx, (ast.Store, ast.Del))
            if (is_store or not writes_only) \
                    and not any(lk in self.held for lk in locks):
                kind = 'store' if is_store else 'load'
                self.findings.append(Finding(
                    CHECKER, 'unguarded-access', self.src.path,
                    node.lineno,
                    'self.%s (%s) is guarded by %s but this %s is '
                    'outside any `with %s:` block'
                    % (node.attr, 'guarded-by(w)' if writes_only
                       else 'guarded-by', '|'.join(locks), kind,
                       locks[0])))
        self.generic_visit(node)


@register(CHECKER)
def check(sources, ctx):
    findings = []
    for src in sources:
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            annotations = _collect_annotations(src, cls)
            if not annotations:
                continue
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                # the annotating method (construction) is exempt for
                # exactly the attrs it annotates
                active = {a: spec for a, spec in annotations.items()
                          if spec[2] != method.name}
                if not active:
                    continue
                held = _holds_locks(src, method)
                v = _Visitor(src, method, active, held, findings)
                for stmt in method.body:
                    v.visit(stmt)
    return findings
