"""dispatch-alias checker: post-dispatch mutation of staged host
buffers (docs/ANALYSIS.md) -- the PR-4 / PR-6 regression class.

jax zero-copies 64-byte-aligned numpy inputs on the CPU backend, and
even `jnp.array`'s "copy" can defer past dispatch (measured on jax
0.4.37), so a host array handed to an async dispatch is NOT reusable
when the call returns: mutating it corrupts the in-flight computation.
The safe idioms are a PRIVATE synchronous copy at the call site
(`np.array(x)` / `x.copy()` / `np.ascontiguousarray(x)`) or simply
never touching the buffer again.

This checker flags, per function scope:

  * a bare name passed to a dispatch-like call (`jnp.array`,
    `jnp.asarray`, `device_put`, a jitted callable -- any `_jit*` /
    `*jitted*` name, including `_jit_foo(...)(args)` factories) that is
    later MUTATED in the same scope (`x[...] = ...`, `x += ...` on a
    subscript, `x.fill/sort/put/partition/resize(...)`, `np.copyto(x,
    ...)`, or an `out=x` keyword);
  * thread-local staging reuse: an attribute read from a `*_tls` /
    `*local*` holder passed to a dispatch without a private-copy wrap
    (the tier-staging bug PR 4 fixed and PR 6 re-found).

Rebinding (`x = ...`) releases the capture -- a fresh object is not the
staged buffer.  A dispatch INSIDE a loop additionally flags mutations
of its captured names anywhere in the same loop body, even on earlier
lines: `for chunk: buf[:n] = chunk; jitted(tab, buf)` refills the
buffer iteration k's async dispatch may still be reading (the exact
PR-6 tier-staging shape) -- unless the name is rebound inside the loop
body (a fresh buffer per iteration is safe by construction).
`# static-ok: dispatch-alias` suppresses a reviewed line.  The runtime
sibling is `analysis.sanitize` (AMTPU_SANITIZE=1), which poisons
staging buffers after dispatch so any alias the static scan cannot see
fails parity loudly in tests.
"""

import ast
import re

from .engine import Finding, register

CHECKER = 'dispatch-alias'

#: callee names (terminal identifier) treated as a device dispatch
DISPATCH_NAMES = {'array', 'asarray', 'device_put', 'frombuffer'}
#: terminal names counted as dispatch only when the VALUE is jnp/jax
DISPATCH_MODULES = {'jnp', 'jax'}
#: local callables that are jitted dispatches by convention
JIT_NAME_RE = re.compile(r'(^_?jit)|jitted|dispatch$')
#: safe private-copy wrappers at the call site
COPY_WRAPPERS = {'array', 'copy', 'ascontiguousarray', 'copyto'}
#: mutating method calls on a captured buffer
MUTATING_METHODS = {'fill', 'sort', 'put', 'partition', 'resize',
                    'setfield', 'itemset'}
#: attribute holders that mark a value as thread-local staging
TLS_NAME_RE = re.compile(r'(_tls|_local\b|threadlocal)', re.I)


def _terminal_name(func):
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_dispatch_call(node):
    """True when `node` (a Call) submits work to the device."""
    func = node.func
    name = _terminal_name(func)
    if name is None:
        # `_jit_row_scatter(donate)(tab, idx, rows)`: func is a Call
        if isinstance(func, ast.Call):
            inner = _terminal_name(func.func)
            return inner is not None and bool(JIT_NAME_RE.search(inner))
        return False
    if isinstance(func, ast.Attribute):
        base = func.value
        base_name = base.id if isinstance(base, ast.Name) else None
        if name in DISPATCH_NAMES:
            # device_put is unambiguous on any base; the np-shared
            # names (array/asarray/...) only count on jnp/jax
            return base_name in DISPATCH_MODULES or name == 'device_put'
        return bool(JIT_NAME_RE.search(name))
    if name in DISPATCH_NAMES:
        return False            # bare np-style array() is host work
    return bool(JIT_NAME_RE.search(name))


def _is_copy_wrapped(arg):
    """np.array(x) / x.copy() / np.ascontiguousarray(x) at the call."""
    if not isinstance(arg, ast.Call):
        return False
    name = _terminal_name(arg.func)
    return name in COPY_WRAPPERS


def _captured_names(node):
    """Names a dispatch call captures: bare-Name positional args."""
    out = []
    for arg in node.args:
        if isinstance(arg, ast.Name):
            out.append(arg.id)
    return out


def _tls_args(node):
    """Attribute args whose holder looks thread-local (self._tls.buf)."""
    out = []
    for arg in node.args:
        if isinstance(arg, ast.Attribute):
            chain = []
            cur = arg
            while isinstance(cur, ast.Attribute):
                chain.append(cur.attr)
                cur = cur.value
            if isinstance(cur, ast.Name):
                chain.append(cur.id)
            if any(TLS_NAME_RE.search(part) for part in chain):
                out.append(ast.unparse(arg))
    return out


def _scope_statements(fn):
    """Every statement in the function in source order (nested defs
    stay separate scopes and are walked on their own)."""
    stmts = []

    def walk(body):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stmts.append(stmt)
            for field in ('body', 'orelse', 'finalbody', 'handlers'):
                sub = getattr(stmt, field, None)
                if sub:
                    for h in sub:
                        if isinstance(h, ast.excepthandler):
                            walk(h.body)
                    if not isinstance(sub[0], ast.excepthandler):
                        walk(sub)
    walk(fn.body)
    return stmts


def _mutations_of(stmt, name):
    """Line numbers where `stmt` mutates the buffer bound to `name`."""
    hits = []
    for node in ast.walk(stmt):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == name:
                    hits.append(node.lineno)
        elif isinstance(node, ast.Call):
            fname = _terminal_name(node.func)
            if fname in MUTATING_METHODS \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == name:
                hits.append(node.lineno)
            elif fname == 'copyto' and node.args \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id == name:
                hits.append(node.lineno)
            for kw in node.keywords:
                if kw.arg == 'out' and isinstance(kw.value, ast.Name) \
                        and kw.value.id == name:
                    hits.append(node.lineno)
    return hits


def _rebound(stmt, name):
    for node in ast.walk(stmt):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return True
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                return True
    return False


def _enclosing_loops(fn):
    """{loop_node: set(statements lexically inside it)} for every
    for/while in `fn`'s own scope (nested defs excluded)."""
    loops = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            continue
        if isinstance(node, (ast.For, ast.While)):
            body = set()
            for sub in ast.walk(node):
                body.add(sub)
            loops[node] = body
    return loops


def _bound_in(nodes, name):
    """True when `name` is (re)bound by a plain assignment within the
    node set -- a fresh object per iteration, not the staged buffer."""
    for node in nodes:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return True
    return False


def _check_function(src, fn, findings):
    stmts = _scope_statements(fn)
    loops = _enclosing_loops(fn)
    # nested statements appear both via their parent (ast.walk) and as
    # their own stmts entry, so findings dedupe on (code, site, line)
    seen = set()

    def emit(code, line, message):
        key = (code, line, message)
        if key not in seen:
            seen.add(key)
            findings.append(Finding(CHECKER, code, src.path, line,
                                    message))

    for i, stmt in enumerate(stmts):
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call) \
                    or not _is_dispatch_call(node):
                continue
            for attr_src in _tls_args(node):
                emit('tls-staging', node.lineno,
                     'thread-local staging buffer %s passed to a '
                     'dispatch without a private synchronous copy '
                     '(np.array(...)) -- jax may still be reading it '
                     'when the slot is reused' % attr_src)
            for name in _captured_names(node):
                released = False
                for later in stmts[i:]:
                    if later.lineno < node.lineno:
                        continue
                    for mline in _mutations_of(later, name):
                        if mline > node.lineno and not released:
                            emit('post-dispatch-mutation', mline,
                                 '%r was passed to a dispatch at line '
                                 '%d and is mutated here -- jax may '
                                 'alias the buffer past dispatch; hand '
                                 'the call np.array(%s) or drop the '
                                 'mutation' % (name, node.lineno, name))
                    if later.lineno > node.lineno \
                            and _rebound(later, name):
                        released = True
                        break
                # dispatch inside a loop: a refill ANYWHERE in the same
                # loop body mutates the buffer an earlier iteration's
                # async dispatch may still read -- unless the name is
                # rebound fresh inside the loop
                for loop, body in loops.items():
                    if node not in body or _bound_in(body, name):
                        continue
                    for body_stmt in loop.body:
                        for mline in _mutations_of(body_stmt, name):
                            if mline <= node.lineno:
                                emit('loop-staging-reuse', mline,
                                     '%r is refilled here and '
                                     'dispatched at line %d inside the '
                                     'same loop -- iteration k+1\'s '
                                     'fill races iteration k\'s async '
                                     'dispatch; allocate a fresh '
                                     'buffer per iteration or hand the '
                                     'dispatch np.array(%s)'
                                     % (name, node.lineno, name))


@register(CHECKER)
def check(sources, ctx):
    findings = []
    for src in sources:
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _check_function(src, node, findings)
    return findings
