"""telemetry-key checker: counter spec/docs lockstep (docs/ANALYSIS.md).

Collects every statically reachable telemetry emit in the package:

  * flat always-on counters -- `trace.metric` / `telemetry.metric`
    call sites (string literals; `%`/f-string/`+` formats become
    wildcard patterns, so `'fallback.escalated.w%d' % W` still counts);
  * phase counters and spans -- `trace.count` / `phase_count` /
    `trace.span` names (they satisfy doc rows but are not pre-seeded);
    flight-recorder event stamps (`recorder.record`) count the same
    way, so the docs' event catalog stays in lockstep with the sites;
  * registry families -- `registry.counter/gauge/histogram('amtpu_*')`.

Then enforces three invariants:

  1. every literal flat key whose prefix owns a ``KNOWN_*_KEYS`` block
     (fallback/collect/resilience/scheduler/resident/pipeline/mesh)
     must be pre-seeded there -- a gate reading the bench block must
     see an explicit zero, not a missing key.  Dynamic keys must match
     a declared `DYNAMIC_KEY_PATTERNS` family;
  2. every flat key and registry family must have a glossary row in
     docs/OBSERVABILITY.md or docs/RESILIENCE.md (digit runs collapse
     to `N`, so `fallback.escalated.w16` matches the documented
     `fallback.escalated.wN`);
  3. pre-seeded and documented keys with NO emit site are dead --
     flagged so the spec and the docs shrink with the code.
"""

import ast
import os
import re

from .engine import Finding, register

CHECKER = 'telemetry-key'

#: flat-counter prefix -> the telemetry/__init__.py KNOWN tuple that
#: pre-seeds it into every bench_block / healthz payload.  Prefixes may
#: span multiple dot segments (`sync.fanout`); the LONGEST matching
#: prefix owns a key, and the seeded suffix is what follows it.
PRESEED_BLOCKS = {
    'fallback': 'KNOWN_FALLBACK_REASONS',
    'collect': 'KNOWN_COLLECT_KEYS',
    'resident': 'KNOWN_RESIDENT_BATCH_KEYS',
    'pipeline': 'KNOWN_PIPELINE_KEYS',
    'mesh': 'KNOWN_MESH_KEYS',
    'resilience': 'KNOWN_RESILIENCE_KEYS',
    'scheduler': 'KNOWN_SCHEDULER_KEYS',
    'sync.fanout': 'KNOWN_FANOUT_KEYS',
    'egress': 'KNOWN_EGRESS_KEYS',
    'storage': 'KNOWN_STORAGE_KEYS',
    'recorder': 'KNOWN_RECORDER_KEYS',
    'slo': 'KNOWN_SLO_KEYS',
    'capacity': 'KNOWN_CAPACITY_KEYS',
    'trace': 'KNOWN_TRACE_KEYS',
    'fleet': 'KNOWN_FLEET_KEYS',
    'router': 'KNOWN_ROUTER_KEYS',
    'migrate': 'KNOWN_MIGRATE_KEYS',
    'failover': 'KNOWN_FAILOVER_KEYS',
    'readview': 'KNOWN_READVIEW_KEYS',
}


def _preseed_ns_of(key):
    """The longest PRESEED_BLOCKS prefix owning `key`, or None."""
    best = None
    for ns in PRESEED_BLOCKS:
        if key.startswith(ns + '.') and (best is None
                                         or len(ns) > len(best)):
            best = ns
    return best

#: dynamic key families that are deliberately NOT pre-seeded row by row
#: (`*` matches within and across dots); everything else formatted at
#: runtime must land on a pre-seeded literal
DYNAMIC_KEY_PATTERNS = (
    'fallback.escalated.w*',        # tier ladder: one key per width
    'fallback.pallas_*_latch',      # per-kernel pallas latch-off
    'resilience.fault_injected.*',  # per-site subkeys (base is seeded)
    '*.latch_flip_ignored',         # resident./mesh. via namespace map
)

#: counter namespaces whose doc glossary rows are checked for deadness
#: (first dot segment of each preseed prefix, plus the un-seeded ones)
DOC_NAMESPACES = tuple(sorted({ns.split('.')[0]
                               for ns in PRESEED_BLOCKS})) + (
    'sched', 'sidecar', 'device', 'host', 'hostfull', 'hostreg',
    'sanitize', 'pallas', 'ops')

#: flat keys that feed derived exposition families instead of a
#: glossary row of their own (documented as amtpu_device_*_total)
UNDOCUMENTED_OK = {'device.dispatch_sync_s', 'device.dispatches'}

_TOKEN_RE = re.compile(r'`([A-Za-z0-9_./*%\[\]]+)`')
_KEY_RE = re.compile(r'^[a-z][a-z0-9_]*(\.[a-zA-Z0-9_.*]+)+$')
_BARE_RE = re.compile(r'^\.?[a-z][a-zA-Z0-9_]*$')


def _pattern_of(node):
    """(literal, regex) for a key expression: literal keys return
    (key, None); formatted keys return (None, compiled_regex); opaque
    expressions return (None, None)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, None
    lit = None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod) \
            and isinstance(node.left, ast.Constant) \
            and isinstance(node.left.value, str):
        lit = re.sub(r'%[-#0-9.]*[sdifrxX]', '*', node.left.value)
    elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add) \
            and isinstance(node.left, ast.Constant) \
            and isinstance(node.left.value, str):
        lit = node.left.value + '*'
    elif isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append('*')
        lit = ''.join(parts)
    if lit is None:
        return None, None
    return None, _glob_re(lit)


def _glob_re(glob):
    return re.compile('^' + '.*'.join(re.escape(p)
                                      for p in glob.split('*')) + '$')


def _terminal_name(func):
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _collect_emits(sources):
    """(flat_literals, flat_patterns, phase_names, families) --
    flat_literals: {key: (path, line)}; flat_patterns: [(regex, path,
    line)]; phase_names: set of span/count names; families: {name:
    (path, line)}."""
    flats, patterns, phases, families = {}, [], set(), {}
    pkg_self = os.path.join('automerge_tpu', 'analysis') + os.sep
    for src in sources:
        if src.relpath.startswith(pkg_self) \
                and os.path.basename(src.path) != 'sanitize.py':
            # the CHECKER modules quote key literals in messages and
            # pattern tables; sanitize.py is product runtime whose
            # emits count like any other
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = _terminal_name(node.func)
            if name == 'metric':
                lit, pat = _pattern_of(node.args[0])
                if lit is not None:
                    flats.setdefault(lit, (src.path, node.lineno))
                elif pat is not None:
                    patterns.append((pat, src.path, node.lineno))
            elif name in ('count', 'phase_count', 'span', 'phase_add',
                          'span_with_context', 'fire', 'arm', 'record'):
                lit, pat = _pattern_of(node.args[0])
                if lit is not None:
                    phases.add(lit)
                elif pat is not None:
                    patterns.append((pat, src.path, node.lineno))
            elif name in ('counter', 'gauge', 'histogram'):
                lit, _ = _pattern_of(node.args[0])
                if lit is not None and lit.startswith('amtpu_'):
                    families.setdefault(lit, (src.path, node.lineno))
    return flats, patterns, phases, families


def _parse_known_blocks(sources):
    """{tuple_name: (set_of_keys, path, line)} from telemetry/__init__."""
    out = {}
    for src in sources:
        if not src.relpath.replace(os.sep, '/').endswith(
                'telemetry/__init__.py'):
            continue
        for node in src.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.startswith('KNOWN_') \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                keys = {e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)}
                out[node.targets[0].id] = (keys, src.path, node.lineno)
    return out


def _doc_tokens(ctx):
    """Documented counter keys from the two glossaries, with slash
    continuation: in `` `collect.conflict_sparse` / `conflict_dense` ``
    (or `` `sidecar.client.respawns` / `.transport_errors` ``) the
    continuation inherits the previous token's namespace -- but ONLY
    when separated by a bare slash, so prose backticks never fabricate
    keys.  A trailing ``[...]`` qualifier is stripped
    (`resilience.fault_injected[.site]`); tokens containing ``*`` are
    doc-side wildcard families."""
    tokens = {}
    gap_re = re.compile(r'^\s*/\s*$')
    for rel in ('docs/OBSERVABILITY.md', 'docs/RESILIENCE.md'):
        text = ctx.doc_text(rel)
        for ln, line in enumerate(text.splitlines(), 1):
            prefix, last_end = None, -1
            for m in _TOKEN_RE.finditer(line):
                tok = m.group(1).split('[')[0].rstrip('.')
                continues = prefix is not None and gap_re.match(
                    line[last_end:m.start()])
                if _KEY_RE.match(tok) and tok.split('.')[0] \
                        in DOC_NAMESPACES and not re.search(r'[A-Z]{2}',
                                                            tok):
                    tokens.setdefault(tok, (rel, ln))
                    prefix, last_end = tok.rsplit('.', 1)[0], m.end()
                elif continues and _BARE_RE.match(tok) \
                        and not tok.startswith('amtpu'):
                    full = prefix + tok if tok.startswith('.') \
                        else '%s.%s' % (prefix, tok)
                    tokens.setdefault(full, (rel, ln))
                    last_end = m.end()
                else:
                    prefix = None
    return tokens


def _canonical(key):
    """Digit runs collapse to N so `fallback.escalated.w16` matches the
    documented `fallback.escalated.wN`."""
    return re.sub(r'\d+', 'N', key)


def _emitted(key, flats, patterns, phases):
    if key in flats or key in phases:
        return True
    return any(pat.match(key) for pat, _p, _l in patterns)


@register(CHECKER)
def check(sources, ctx):
    findings = []
    flats, patterns, phases, families = _collect_emits(sources)
    known = _parse_known_blocks(sources)
    docs = _doc_tokens(ctx)
    doc_keys = {k for k in docs if '*' not in k}
    doc_globs = {k: _glob_re(k) for k in docs if '*' in k}
    # a whole-namespace glob (`resident.*`) keeps its row alive but is
    # too broad to DOCUMENT a key -- membership needs two literal
    # segments (`sidecar.client.*`)
    doc_globs_member = {k: g for k, g in doc_globs.items()
                        if k.split('*')[0].count('.') >= 2}
    doc_canon = {_canonical(k) for k in doc_keys}
    dynamic_res = [_glob_re(p) for p in DYNAMIC_KEY_PATTERNS]

    # 1. every literal flat emit with a pre-seeded prefix is in KNOWN
    for key, (path, line) in sorted(flats.items()):
        ns = _preseed_ns_of(key)
        block = PRESEED_BLOCKS.get(ns) if ns else None
        if block is not None:
            suffix = key[len(ns) + 1:]
            keys, _bp, _bl = known.get(block, (set(), None, 0))
            if suffix not in keys \
                    and not any(r.match(key) for r in dynamic_res):
                findings.append(Finding(
                    CHECKER, 'unseeded-key', path, line,
                    '%s is emitted but not pre-seeded in telemetry.%s '
                    '-- gates would see a missing key instead of an '
                    'explicit zero' % (key, block)))
        # 2. documented somewhere
        if key not in doc_keys and _canonical(key) not in doc_canon \
                and not any(g.match(key)
                            for g in doc_globs_member.values()) \
                and key not in UNDOCUMENTED_OK:
            findings.append(Finding(
                CHECKER, 'undocumented-key', path, line,
                '%s has no glossary row in docs/OBSERVABILITY.md or '
                'docs/RESILIENCE.md' % key))

    # formatted emits with a pre-seeded namespace must match a declared
    # dynamic family (otherwise the runtime key can never be seeded)
    for pat, path, line in patterns:
        glob = pat.pattern
        ns_m = re.match(r'\^([a-z_]+)\\\.', glob)
        if ns_m and ns_m.group(1) in PRESEED_BLOCKS:
            sample = glob[1:-1].replace('\\', '').replace('.*', 'X')
            if not any(r.match(sample) for r in dynamic_res):
                findings.append(Finding(
                    CHECKER, 'undeclared-dynamic-key', path, line,
                    'formatted %s.* key does not match any '
                    'DYNAMIC_KEY_PATTERNS family' % ns_m.group(1)))

    # 3a. pre-seeded keys with no emit site are dead
    for ns, block in sorted(PRESEED_BLOCKS.items()):
        keys, bpath, bline = known.get(block, (set(), None, 0))
        for suffix in sorted(keys):
            key = '%s.%s' % (ns, suffix)
            if not _emitted(key, flats, patterns, phases):
                findings.append(Finding(
                    CHECKER, 'dead-seed', bpath or '<telemetry>', bline,
                    '%s is pre-seeded in %s but nothing emits it'
                    % (key, block)))

    # 3b. documented keys with no emit site are dead rows
    emitted_canon = {_canonical(k) for k in flats} \
        | {_canonical(k) for k in phases}
    for tok, (rel, ln) in sorted(docs.items()):
        if '*' in tok:
            # a documented wildcard family is live when any emit lands
            # inside it
            glob = doc_globs[tok]
            if not any(glob.match(k) for k in flats) \
                    and not any(glob.match(k) for k in phases):
                findings.append(Finding(
                    CHECKER, 'dead-doc-row',
                    os.path.join(ctx.root, rel), ln,
                    '`%s` is documented but nothing emits inside the '
                    'family' % tok))
            continue
        if _emitted(tok, flats, patterns, phases):
            continue
        if _canonical(tok) in emitted_canon:
            continue
        if any(pat.match(_canonical(tok)) or pat.match(tok)
               for pat, _p, _l in patterns):
            continue
        findings.append(Finding(
            CHECKER, 'dead-doc-row', os.path.join(ctx.root, rel), ln,
            '`%s` is documented but nothing emits it' % tok))

    # registry families must be documented
    text = ctx.doc_text('docs/OBSERVABILITY.md') \
        + ctx.doc_text('docs/RESILIENCE.md')
    for fam, (path, line) in sorted(families.items()):
        if fam not in text:
            findings.append(Finding(
                CHECKER, 'undocumented-family', path, line,
                'registry family %s has no docs/OBSERVABILITY.md row'
                % fam))
    return findings
