"""env-latch checker: AMTPU_* flag discipline (docs/ANALYSIS.md).

Cross-verifies `env_spec.ENV_FLAGS` against five surfaces:

  1. **call sites** -- every `env_int/env_float/env_bool/env_str/
     env_raw('AMTPU_X', default)` call in the package must name a spec
     flag, use the helper matching the spec type, and pass the spec
     default (literals and same-module integer constants resolve);
  2. **raw reads** -- `os.environ` / `os.getenv` touching an AMTPU key
     anywhere but `utils/common.py` is a violation (that module IS the
     helper layer);
  3. **C++** -- `getenv("AMTPU_X")` sites in native/core.cpp must be
     spec flags naming core.cpp as a consumer, and vice versa;
  4. **the latch ABI + flip guard** -- spec rows marked `latched` must
     exactly match `native._RESIDENT_LATCH_KEYS` (the PR-6/7 flip
     guard), and the numeric latch defaults must match what the built
     library's `amtpu_latch_defaults` reports;
  5. **docs** -- every spec flag needs a row in docs/OBSERVABILITY.md's
     env-variable table, and every AMTPU token in that table must be a
     spec flag (harness-prefix knobs excepted).
"""

import ast
import ctypes
import os
import re

from .engine import Finding, register
from .env_spec import (ABI_LATCH_DEFAULTS, HARNESS_PREFIXES, SPEC)

CHECKER = 'env-latch'

#: helper name -> spec type it serves (underscore-prefixed aliases from
#: `from ..utils.common import env_float as _env_float` included)
HELPER_TYPES = {'env_int': 'int', 'env_float': 'float',
                'env_bool': 'bool', 'env_str': 'str', 'env_raw': 'raw'}

#: modules allowed to touch os.environ for AMTPU keys: the helper layer
#: itself (env_* + parse_mesh_env)
RAW_READ_ALLOWED = ('utils/common.py',)


def _terminal_name(func):
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _module_int_constants(tree):
    """{NAME: int} for simple module-level integer constants -- resolves
    defaults like env_int('AMTPU_MAX_TIER', DEFAULT_MAX_TIER)."""
    out = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, (int, float)) \
                and not isinstance(node.value.value, bool):
            out[node.targets[0].id] = node.value.value
    return out


def _is_environ(node):
    """True for the expression `os.environ`."""
    return (isinstance(node, ast.Attribute) and node.attr == 'environ'
            and isinstance(node.value, ast.Name)
            and node.value.id == 'os')


def _amtpu_key(node):
    """The literal AMTPU_* key of an expression, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith('AMTPU_'):
        return node.value
    return None


def _check_helper_calls(src, findings):
    consts = _module_int_constants(src.tree)
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        helper = HELPER_TYPES.get((name or '').lstrip('_'))
        if helper is None:
            continue
        # positional or keyword spellings both count (env_int('X', 7),
        # env_int(name='X', default=7), env_int('X', default=7))
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        args = list(node.args)
        key_node = args[0] if args else kw.get('name')
        dflt_node = args[1] if len(args) > 1 else kw.get('default')
        if key_node is None:
            continue
        key = _amtpu_key(key_node)
        if key is None:
            continue
        flag = SPEC.get(key)
        if flag is None:
            findings.append(Finding(
                CHECKER, 'unknown-flag', src.path, node.lineno,
                '%s is not in env_spec.ENV_FLAGS -- register it (and '
                'its OBSERVABILITY.md row) before reading it' % key))
            continue
        if helper == 'raw' or flag.type == 'special':
            # env_raw imposes no default/type semantics, so it is legal
            # for any flag (diagnostics, latch snapshots); parse_mesh_env
            # owns the 'special' flags
            continue
        if flag.type != helper:
            findings.append(Finding(
                CHECKER, 'type-drift', src.path, node.lineno,
                '%s is a %r flag but is read through env_%s'
                % (key, flag.type, helper)))
            continue
        if dflt_node is None:
            continue
        dflt = dflt_node
        value = None
        if isinstance(dflt, ast.Constant):
            value = dflt.value
        elif isinstance(dflt, ast.Name) and dflt.id in consts:
            value = consts[dflt.id]
        elif isinstance(dflt, ast.UnaryOp) \
                and isinstance(dflt.op, ast.USub) \
                and isinstance(dflt.operand, ast.Constant):
            value = -dflt.operand.value
        else:
            continue          # computed default: the spec can't compare
        if value != flag.default or (isinstance(value, bool)
                                     != isinstance(flag.default, bool)):
            findings.append(Finding(
                CHECKER, 'default-drift', src.path, node.lineno,
                '%s call-site default %r != spec default %r'
                % (key, value, flag.default)))


def _check_raw_reads(src, findings):
    allowed = src.relpath.replace(os.sep, '/').endswith(RAW_READ_ALLOWED)
    if allowed:
        return
    for node in ast.walk(src.tree):
        key = None
        if isinstance(node, ast.Subscript) and _is_environ(node.value):
            key = _amtpu_key(node.slice)
        elif isinstance(node, ast.Call):
            name = _terminal_name(node.func)
            if name == 'get' and isinstance(node.func, ast.Attribute) \
                    and _is_environ(node.func.value) and node.args:
                key = _amtpu_key(node.args[0])
            elif name == 'getenv' and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == 'os' and node.args:
                key = _amtpu_key(node.args[0])
        if key is not None:
            findings.append(Finding(
                CHECKER, 'direct-read', src.path, node.lineno,
                'direct os.environ read of %s -- route it through the '
                'utils/common env helpers' % key))


def _parse_latch_guard(sources):
    """The `_RESIDENT_LATCH_KEYS` tuple from native/__init__.py."""
    for src in sources:
        if not src.relpath.replace(os.sep, '/').endswith(
                'native/__init__.py'):
            continue
        for node in src.tree.body:
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == '_RESIDENT_LATCH_KEYS'
                            for t in node.targets) \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                keys = [e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)]
                return src, node.lineno, keys
    return None, 0, None


def _check_latch_guard(sources, findings):
    src, lineno, guard = _parse_latch_guard(sources)
    if guard is None:
        findings.append(Finding(
            CHECKER, 'guard-missing', '<native/__init__.py>', 0,
            'could not locate _RESIDENT_LATCH_KEYS'))
        return
    spec_latched = {f.name for f in SPEC.values() if f.latched}
    for key in sorted(spec_latched - set(guard)):
        findings.append(Finding(
            CHECKER, 'unguarded-latch', src.path, lineno,
            '%s is a first-batch latch in env_spec but missing from '
            '_RESIDENT_LATCH_KEYS -- post-batch flips would be '
            'silently ignored' % key))
    for key in sorted(set(guard) - spec_latched):
        findings.append(Finding(
            CHECKER, 'guard-drift', src.path, lineno,
            '%s is in _RESIDENT_LATCH_KEYS but env_spec does not mark '
            'it latched' % key))


def _check_cpp(ctx, findings):
    cpp_path = os.path.join(ctx.root, 'native', 'core.cpp')
    try:
        with open(cpp_path, encoding='utf-8') as f:
            cpp = f.read()
    except OSError:
        return
    seen = set()
    for m in re.finditer(r'getenv\("(AMTPU_[A-Z0-9_]+)"\)', cpp):
        key = m.group(1)
        seen.add(key)
        line = cpp.count('\n', 0, m.start()) + 1
        flag = SPEC.get(key)
        if flag is None:
            findings.append(Finding(
                CHECKER, 'unknown-flag', cpp_path, line,
                'C++ getenv(%s) is not in env_spec.ENV_FLAGS' % key))
        elif 'core.cpp' not in flag.consumer:
            findings.append(Finding(
                CHECKER, 'consumer-drift', cpp_path, line,
                '%s is read by core.cpp but its spec row does not name '
                'core.cpp as a consumer' % key))
    for flag in SPEC.values():
        if 'core.cpp' in flag.consumer and flag.name not in seen:
            findings.append(Finding(
                CHECKER, 'consumer-drift', cpp_path, 1,
                'env_spec names core.cpp as a consumer of %s but '
                'core.cpp never reads it' % flag.name))


def _check_abi_defaults(ctx, findings):
    lib_path = os.path.join(ctx.root, 'automerge_tpu', 'native',
                            'libamtpu_core.so')
    if not os.path.exists(lib_path):
        findings.append(Finding(
            CHECKER, 'abi-unavailable', lib_path, 0,
            'libamtpu_core.so is not built -- run `make native` first '
            '(the latch-default cross-check needs the ABI)'))
        return
    lib = ctypes.CDLL(lib_path)
    out = (ctypes.c_int64 * len(ABI_LATCH_DEFAULTS))()
    lib.amtpu_latch_defaults(out)
    for i, name in enumerate(ABI_LATCH_DEFAULTS):
        if int(out[i]) != SPEC[name].default:
            findings.append(Finding(
                CHECKER, 'abi-drift', lib_path, 0,
                'amtpu_latch_defaults reports %s=%d but env_spec says '
                '%r -- core.cpp and the spec drifted'
                % (name, int(out[i]), SPEC[name].default)))


def _env_table_tokens(ctx):
    """AMTPU tokens in OBSERVABILITY.md's env-variable table, with the
    table's starting line."""
    text = ctx.doc_text('docs/OBSERVABILITY.md')
    m = re.search(r'^## Environment variables$', text, re.M)
    if not m:
        return None, 0
    start_line = text.count('\n', 0, m.start()) + 1
    section = text[m.end():]
    nxt = re.search(r'^## ', section, re.M)
    if nxt:
        section = section[:nxt.start()]
    tokens = set(re.findall(r'AMTPU_[A-Z0-9_]+', section))
    return tokens, start_line


def _check_docs(ctx, findings):
    doc_path = os.path.join(ctx.root, 'docs', 'OBSERVABILITY.md')
    tokens, line = _env_table_tokens(ctx)
    if tokens is None:
        findings.append(Finding(
            CHECKER, 'docs-missing', doc_path, 0,
            'docs/OBSERVABILITY.md has no "## Environment variables" '
            'section'))
        return
    for name in sorted(SPEC):
        if name not in tokens:
            findings.append(Finding(
                CHECKER, 'undocumented-flag', doc_path, line,
                '%s (consumer: %s) has no row in the OBSERVABILITY.md '
                'env table' % (name, SPEC[name].consumer)))
    for tok in sorted(tokens - set(SPEC)):
        if not tok.startswith(HARNESS_PREFIXES):
            findings.append(Finding(
                CHECKER, 'dead-doc-row', doc_path, line,
                '%s is documented in the env table but is not a spec '
                'flag (stale row, or register it in env_spec)' % tok))


@register(CHECKER)
def check(sources, ctx):
    findings = []
    for src in sources:
        _check_helper_calls(src, findings)
        _check_raw_reads(src, findings)
    _check_latch_guard(sources, findings)
    _check_cpp(ctx, findings)
    _check_abi_defaults(ctx, findings)
    _check_docs(ctx, findings)
    return findings
