"""Typed client-side objects for fan-out event frames (ISSUE 20
satellite; docs/SERVING.md read path).

`SidecarClient.next_event()` historically returned raw frame dicts and
every consumer demuxed on ``ev['event']`` strings.  With patch mode the
frame zoo grew, so each frame kind gets a typed wrapper -- every class
here SUBCLASSES dict, so ``ev['event']``/``ev.get('doc')`` consumers
keep working unchanged while new code reads ``ev.doc`` / ``ev.patch``
/ ``isinstance(ev, PatchEvent)``.

`typed_event` is the factory the client pump applies on the way out;
an unrecognized ``event`` string stays a plain dict (forward
compatibility: an old client must not crash on a new server frame).
"""


class FanoutEvent(dict):
    """Base: a fan-out frame with the common fields as attributes."""

    @property
    def event(self):
        return self.get('event')

    @property
    def doc(self):
        return self.get('doc')

    @property
    def clock(self):
        return self.get('clock') or {}

    @property
    def trace(self):
        return self.get('trace')

    @property
    def is_resync_backfill(self):
        """True for the synthetic frames an auto-resubscribe surfaces
        (marked ``"resync": true``) so consumers can tell a live flush
        frame from catch-up history."""
        return bool(self.get('resync'))


class ChangeEvent(FanoutEvent):
    """``{"event": "change", ...}``: change bytes for a CRDT-capable
    subscriber (the classic mode)."""

    @property
    def changes(self):
        return self.get('changes') or []

    @property
    def presence(self):
        return self.get('presence') or {}


class PatchEvent(FanoutEvent):
    """``{"event": "patch", ...}``: a server-computed patch for a thin
    client (``mode: "patch"`` subscriptions).  ``full`` means the
    patch REPLACES the local view (straggler/resync recovery, or the
    subscribe backfill) rather than applying incrementally."""

    @property
    def patch(self):
        return self.get('patch')

    @property
    def full(self):
        return bool(self.get('full'))

    @property
    def presence(self):
        return self.get('presence') or {}


class PresenceEvent(FanoutEvent):
    """``{"event": "presence", ...}``: ephemeral per-peer state only."""

    @property
    def presence(self):
        return self.get('presence') or {}


class QuarantinedEvent(FanoutEvent):
    """``{"event": "quarantined", ...}``: the resilience envelope for a
    doc whose flush was refused (docs/RESILIENCE.md)."""

    @property
    def error(self):
        return self.get('error')

    @property
    def error_type(self):
        return self.get('errorType')


class ResyncEvent(FanoutEvent):
    """``{"event": "resync", ...}``: egress tier-2 drop-to-resubscribe
    (the client's auto-resubscribe machinery usually consumes this
    before the application sees it)."""

    @property
    def docs(self):
        return self.get('docs') or []

    @property
    def retry_after_ms(self):
        return self.get('retryAfterMs')


class Snapshot(dict):
    """A ``snapshot`` response: the doc's v2 container bytes plus the
    frontier clock they were built at (the cache key -- equal clocks
    mean byte-identical artifacts)."""

    @property
    def doc(self):
        return self.get('doc')

    @property
    def clock(self):
        return self.get('clock') or {}

    @property
    def data(self):
        """The container bytes (base64-decoded from the wire)."""
        raw = self.get('snapshot_b64')
        if raw is None:
            return None
        if isinstance(raw, bytes):
            return raw
        import base64
        return base64.b64decode(raw)


_EVENT_TYPES = {
    'change': ChangeEvent,
    'patch': PatchEvent,
    'presence': PresenceEvent,
    'quarantined': QuarantinedEvent,
    'resync': ResyncEvent,
    'resync_failed': ResyncEvent,
}


def typed_event(frame):
    """Wraps one raw frame dict in its typed class (identity for
    non-dicts and unknown ``event`` strings)."""
    if not isinstance(frame, dict):
        return frame
    cls = _EVENT_TYPES.get(frame.get('event'))
    return cls(frame) if cls is not None else frame
