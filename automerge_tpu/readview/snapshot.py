"""Frontier-clock-keyed snapshot cache (ISSUE 20 tentpole, piece c).

The ``snapshot`` protocol command serves a doc's v2 columnar container
bytes (the `pool.save` checkpoint -- docs/STORAGE.md) so a client cold
-opens from one CDN-able artifact instead of replaying history.  The
expensive half is the container build; this cache memoizes it keyed by
the doc's FRONTIER CLOCK, which `pool.get_clock` answers without
materializing anything: an unchanged doc serves the same bytes for
free across flushes (and across any number of cold-opening clients),
and any mutation invalidates the entry by value -- no TTLs, no
explicit invalidation hooks in the write path.

`AMTPU_READ_SNAPSHOT_CACHE` bounds the resident entries (LRU); the
cache never holds more than that many container blobs in memory.
"""

from collections import OrderedDict
import threading

from .. import telemetry
from ..utils.common import env_int


def _clock_key(clock):
    return tuple(sorted((clock or {}).items()))


class SnapshotCache(object):
    """LRU of {doc_id: (frontier-clock key, container bytes)}.

    Thread-safe; the builder callable runs OUTSIDE the cache lock --
    callers (the sidecar backend, the read replica) already serialize
    doc access under the pool lock, so this lock only guards the map
    itself."""

    def __init__(self, max_entries=None):
        if max_entries is None:
            max_entries = env_int('AMTPU_READ_SNAPSHOT_CACHE', 64)
        self.max_entries = max(1, max_entries)
        self._lock = threading.Lock()
        self._entries = OrderedDict()   # guarded-by: self._lock

    def get(self, doc_id, clock, build):
        """Container bytes for `doc_id` at frontier `clock`; `build`
        (-> bytes) runs only on a miss.  A stale entry (any mutation
        since it was built) can never serve: the key IS the clock."""
        key = _clock_key(clock)
        with self._lock:
            hit = self._entries.get(doc_id)
            if hit is not None and hit[0] == key:
                self._entries.move_to_end(doc_id)
                telemetry.metric('readview.snapshot_hits')
                return hit[1]
        data = build()
        telemetry.metric('readview.snapshot_builds')
        with self._lock:
            self._entries[doc_id] = (key, data)
            self._entries.move_to_end(doc_id)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
        return data

    def drop(self, doc_id):
        with self._lock:
            self._entries.pop(doc_id, None)

    def __len__(self):
        with self._lock:
            return len(self._entries)
