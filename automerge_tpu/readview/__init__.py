"""Read-path subsystem (ISSUE 20, ROADMAP #4; docs/SERVING.md read
path): the read tier over the write tier the fleet PRs built.

Three pieces:

  * **Server-side patch shipping** -- subscriptions with
    ``mode: "patch"`` receive the flush's server-computed patch (the
    pool's per-doc apply result, byte-identical to the serial frontend
    oracle) instead of change bytes, fanned through the existing
    encode-once FanoutEngine/egress tiers (`sync/fanout.py` +
    `scheduler/gateway.py` own the hot path; this package owns the
    client/replica halves).
  * **Materialized read replicas** (`replica.py`,
    `tools/amtpu_replica.py`) -- a subscriber-mode process consuming
    the fan-out stream into its own queryable pool, serving
    get_patch/snapshot/healthz on a read-only listener, with per-doc
    staleness as an SLO surface and resync-based catch-up.
  * **Snapshot serving** (`snapshot.py` + the ``snapshot`` protocol
    command) -- a doc's v2 container bytes, cache-keyed by frontier
    clock, as the CDN-able cold-open artifact.

`events.py` holds the typed client-side event objects
`SidecarClient.next_event()` demuxes into (dict subclasses, so
existing ``ev['event']`` consumers are untouched).
"""

from .events import (ChangeEvent, PatchEvent, PresenceEvent,  # noqa: F401
                     QuarantinedEvent, ResyncEvent, Snapshot,
                     typed_event)
from .snapshot import SnapshotCache  # noqa: F401
