"""Materialized read replica (ISSUE 20 tentpole, piece b;
docs/SERVING.md read path).

The 1-writer / 10k-readers shape: ONE subscriber-mode process consumes
the authoritative gateway's fan-out stream into its OWN queryable pool
and serves the read fleet from there -- `get_patch`, `snapshot`,
`healthz` -- on a read-only listener (`GatewayServer(read_only=True)`,
so a misdirected write gets a typed ``ReadOnly`` envelope instead of
silently forking the view).

Lifecycle:

  * **Bootstrap.** With a ColdStore directory the replica restores
    arena-direct off the durable manifest (PR 14/17:
    `pool.restore_from_store`) BEFORE subscribing -- instant cold
    start -- then subscribes each doc at its restored clock, so the
    subscribe backfill (straggler filter) ships only the tail it
    missed.  Without a store it subscribes at zero clocks and the
    backfill ships full history.
  * **Steady state.** A consumer thread applies every change frame
    into the pool under the listener's pool lock; the client's
    auto-resubscribe machinery (ISSUE 13) already heals egress-tier
    resyncs at the last-seen clock, surfacing backfill as synthetic
    change frames this same loop applies.
  * **Staleness SLO.** A prober thread polls the upstream's cheap
    ``get_clock`` frontier per followed doc and publishes the
    believed-vs-auth lag (missing seqs) plus how long the doc has been
    behind -- the healthz ``readview`` section.  A doc stale past
    ``AMTPU_READ_STALENESS_SLO_S`` is caught up by force: one
    ``get_missing_changes`` walk against the local clock
    (`resync_doc`), the same transitive-deps filter subscribe backfill
    uses, so a lost frame can make the replica LATE but never WRONG.

`tools/amtpu_replica.py` is the process entry point.
"""

import sys
import threading
import time

from .. import telemetry
from ..utils.common import env_float


class ReadReplica(object):
    """One materialized read replica over one upstream gateway."""

    def __init__(self, upstream, listen, docs=None, prefix=None,
                 store_dir=None, peer='replica', use_msgpack=False,
                 slo_s=None, probe_s=None):
        self.upstream_path = upstream
        self.listen_path = listen
        self.docs = list(docs or [])
        self.prefix = prefix
        self.store_dir = store_dir
        self.peer = peer
        self.use_msgpack = use_msgpack
        self.slo_s = env_float('AMTPU_READ_STALENESS_SLO_S', 5.0) \
            if slo_s is None else slo_s
        self.probe_s = env_float('AMTPU_READ_RESYNC_S', 2.0) \
            if probe_s is None else probe_s
        self.gw = None
        self.client = None
        self.backend = None
        self._threads = []
        self._stopping = False
        self._lock = threading.Lock()
        # doc -> {'lag': missing seqs vs upstream, 'since': first
        # perf_counter the doc was observed behind (None when caught
        # up), 'probed': last probe time}
        self._staleness = {}      # guarded-by: self._lock
        self._followed = set()    # guarded-by: self._lock

    # -- lifecycle ------------------------------------------------------

    def start(self):
        from ..scheduler import GatewayServer
        from ..sidecar.client import SidecarClient
        from ..sidecar.server import SidecarBackend
        self.backend = SidecarBackend()
        self.gw = GatewayServer(self.listen_path,
                                use_msgpack=self.use_msgpack,
                                backend=self.backend, read_only=True)
        restored = self._bootstrap()
        self.gw.start()
        telemetry.register_healthz_section('readview',
                                           self.healthz_section)
        self.client = SidecarClient(sock_path=self.upstream_path,
                                    use_msgpack=self.use_msgpack)
        with self._lock:
            self._followed.update(self.docs)
            self._followed.update(restored)
            follow = sorted(self._followed)
        for doc in follow:
            self._subscribe_doc(doc)
        if self.prefix is not None:
            res = self.client.subscribe(prefix=self.prefix,
                                        peer=self.peer)
            for d, r in (res.get('docs') or {}).items():
                with self._lock:
                    self._followed.add(d)
                self._apply_backfill(d, r)
        consumer = threading.Thread(target=self._consume_loop,
                                    name='amtpu-replica-consume',
                                    daemon=True)
        prober = threading.Thread(target=self._probe_loop,
                                  name='amtpu-replica-probe',
                                  daemon=True)
        self._threads = [consumer, prober]
        consumer.start()
        prober.start()
        return self

    def stop(self):
        self._stopping = True
        if self.client is not None:
            try:
                self.client.close()
            except Exception:
                pass
        if self.gw is not None:
            try:
                self.gw.stop()
            except Exception:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        telemetry.register_healthz_section('readview', None)

    def _bootstrap(self):
        """Arena-direct restore off a durable ColdStore manifest (the
        PR 14 cold-start path) -- returns the restored doc ids, each of
        which then subscribes at its RESTORED clock so upstream only
        backfills the tail."""
        if not self.store_dir:
            return []
        from ..storage.coldstore import ColdStore
        store = ColdStore(self.store_dir, durable=True)
        summary = self.backend.pool.restore_from_store(store)
        restored = [d for d in store.doc_ids()
                    if d not in summary.get('corrupt', {})
                    and d not in summary.get('failed', {})]
        telemetry.metric('readview.replica_bootstrap_docs',
                         len(restored))
        return restored

    def _local_clock(self, doc):
        with self.gw.pool_lock:
            try:
                return self.backend.pool.get_clock(doc) \
                    .get('clock') or {}
            except Exception:
                return {}

    def _subscribe_doc(self, doc):
        clock = self._local_clock(doc)
        res = self.client.subscribe(doc=doc, clock=clock,
                                    peer=self.peer)
        self._apply_backfill(doc, res)

    def _apply_backfill(self, doc, res):
        if isinstance(res, dict) and res.get('changes'):
            self._apply(doc, res['changes'])

    # -- the consumer (fan-out stream -> pool) --------------------------

    def _apply(self, doc, changes):
        try:
            with self.gw.pool_lock:
                self.backend.pool.apply_changes(doc, changes)
        except Exception as e:
            # a gapped/garbled frame must not kill the consumer: count
            # it and force a transitive-deps catch-up, which re-fetches
            # whatever the pool is actually missing
            telemetry.metric('readview.replica_apply_errors')
            print('replica: apply failed for %r: %s: %s'
                  % (doc, type(e).__name__, e), file=sys.stderr)
            self.resync_doc(doc)
            return 0
        telemetry.metric('readview.replica_changes', len(changes))
        return len(changes)

    def _consume_loop(self):
        while not self._stopping:
            try:
                ev = self.client.next_event(timeout=0.25)
            except ConnectionError:
                if not self._stopping:
                    time.sleep(0.25)
                    continue
                return
            if ev is None:
                continue
            telemetry.metric('readview.replica_events')
            kind = ev.get('event')
            doc = ev.get('doc')
            if kind == 'change' and doc is not None:
                with self._lock:
                    self._followed.add(doc)
                self._apply(doc, ev.get('changes') or [])
            elif kind == 'resync_failed' and doc is not None:
                # the auto-resubscribe budget ran out: the stream is
                # dead for this doc until we force a catch-up
                self.resync_doc(doc)
                try:
                    self._subscribe_doc(doc)
                except Exception:
                    pass

    # -- staleness SLO + forced catch-up --------------------------------

    def _probe_doc(self, doc, now):
        up = self.client.get_clock(doc).get('clock') or {}
        local = self._local_clock(doc)
        lag = sum(max(0, int(seq) - int(local.get(actor, 0)))
                  for actor, seq in up.items())
        with self._lock:
            st = self._staleness.setdefault(
                doc, {'lag': 0, 'since': None, 'probed': now})
            st['probed'] = now
            st['lag'] = lag
            if lag == 0:
                st['since'] = None
                return
            if st['since'] is None:
                st['since'] = now
            stale_s = now - st['since']
        if stale_s > self.slo_s:
            telemetry.metric('readview.replica_slo_breaches')
            self.resync_doc(doc)

    def _probe_loop(self):
        while not self._stopping:
            time.sleep(self.probe_s)
            if self._stopping:
                return
            with self._lock:
                follow = sorted(self._followed)
            for doc in follow:
                if self._stopping:
                    return
                try:
                    self._probe_doc(doc, time.perf_counter())
                    telemetry.metric('readview.replica_probes')
                except ConnectionError:
                    return
                except Exception:
                    continue

    def resync_doc(self, doc):
        """Forced catch-up: one transitive-deps missing-changes walk
        against the local clock, applied in one batch -- closes any
        gap (lost frames, a dead subscription) without a full-history
        refetch."""
        try:
            changes = self.client.get_missing_changes(
                doc, self._local_clock(doc))
        except Exception:
            return 0
        telemetry.metric('readview.replica_resyncs')
        if not changes:
            return 0
        try:
            with self.gw.pool_lock:
                self.backend.pool.apply_changes(doc, changes)
        except Exception:
            telemetry.metric('readview.replica_apply_errors')
            return 0
        telemetry.metric('readview.replica_changes', len(changes))
        with self._lock:
            st = self._staleness.get(doc)
            if st is not None:
                st['lag'] = 0
                st['since'] = None
        return len(changes)

    # -- observability --------------------------------------------------

    def staleness(self):
        """{doc: {'lag': missing seqs, 'stale_s': seconds behind}} as
        of the last probe (lag 0 <=> stale_s 0: caught up)."""
        now = time.perf_counter()
        with self._lock:
            return {doc: {'lag': st['lag'],
                          'stale_s': round(now - st['since'], 3)
                          if st['since'] is not None else 0.0}
                    for doc, st in self._staleness.items()}

    def healthz_section(self):
        st = self.staleness()
        stale = {d: s for d, s in st.items() if s['lag']}
        with self._lock:
            followed = len(self._followed)
        return {
            'upstream': self.upstream_path,
            'followed_docs': followed,
            'slo_s': self.slo_s,
            'stale_docs': len(stale),
            'max_lag': max((s['lag'] for s in st.values()), default=0),
            'max_stale_s': max((s['stale_s'] for s in st.values()),
                               default=0.0),
        }
