"""Proxy objects that make the document look like plain Python dicts/lists
inside a change() callback (reference: `/root/reference/frontend/proxies.js`).

`MapProxy` supports both item and attribute style access/assignment;
`ListProxy` exposes the full mutator surface (insert_at/delete_at/append/
pop/shift/unshift/splice/fill) plus read-only delegation, mirroring the
reference's Proxy traps and listMethods.
"""

from ..errors import RangeError
from ..models.table import Table
from ..models.text import Text
from ..utils.common import ROOT_ID


def parse_list_index(key):
    """(reference: proxies.js:6-15)"""
    if isinstance(key, str) and key.isdigit():
        key = int(key)
    if not isinstance(key, int) or isinstance(key, bool):
        raise TypeError('A list index must be a number, but you passed %r' % (key,))
    if key < 0:
        raise RangeError('A list index must be positive, but you passed %s' % key)
    return key


class MapProxy:
    """(reference: proxies.js:98-138)"""

    __slots__ = ('_context', '_objid')

    def __init__(self, context, object_id):
        object.__setattr__(self, '_context', context)
        object.__setattr__(self, '_objid', object_id)

    # -- reads ------------------------------------------------------------
    def __getitem__(self, key):
        return self._context.get_object_field(self._objid, key)

    def __getattr__(self, name):
        if name == '_objectId' or name == '_object_id':
            return self._objid
        if name == '_type':
            return 'map'
        if name == '_get':
            return lambda obj_id: self._context.instantiate_object(obj_id)
        if name == '_inspect':
            return _inspect_proxy(self)
        if name == '_conflicts':
            obj = self._context.get_object(self._objid)
            return obj._conflicts
        if name.startswith('_'):
            raise AttributeError(name)
        value = self._context.get_object_field(self._objid, name)
        return value

    def get(self, key, default=None):
        obj = self._context.get_object(self._objid)
        if key in obj:
            return self._context.get_object_field(self._objid, key)
        return default

    def keys(self):
        return list(self._context.get_object(self._objid).keys())

    def values(self):
        return [self[k] for k in self.keys()]

    def items(self):
        return [(k, self[k]) for k in self.keys()]

    def __contains__(self, key):
        return key in self._context.get_object(self._objid)

    def __iter__(self):
        return iter(self.keys())

    def __len__(self):
        return len(self._context.get_object(self._objid))

    # -- writes -----------------------------------------------------------
    def __setitem__(self, key, value):
        self._context.set_map_key(self._objid, 'map', key, value)

    def __setattr__(self, name, value):
        self._context.set_map_key(self._objid, 'map', name, value)

    def __delitem__(self, key):
        self._context.delete_map_key(self._objid, key)

    def __delattr__(self, name):
        self._context.delete_map_key(self._objid, name)

    def update(self, other):
        for key, value in other.items():
            self[key] = value

    def __repr__(self):
        return 'MapProxy(%r)' % (self._context.get_object(self._objid),)


class ListProxy:
    """(reference: proxies.js:140-195 + listMethods :17-96)"""

    __slots__ = ('_context', '_objid')

    def __init__(self, context, object_id):
        object.__setattr__(self, '_context', context)
        object.__setattr__(self, '_objid', object_id)

    def _obj(self):
        return self._context.get_object(self._objid)

    # -- reads ------------------------------------------------------------
    @property
    def _objectId(self):
        return self._objid

    @property
    def _object_id(self):
        return self._objid

    @property
    def _type(self):
        return 'list'

    def __getitem__(self, index):
        if isinstance(index, slice):
            return [self[i] for i in range(*index.indices(len(self)))]
        index = parse_list_index(index)
        return self._context.get_object_field(self._objid, index)

    def __len__(self):
        return len(self._obj())

    @property
    def length(self):
        return len(self._obj())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __contains__(self, value):
        return any(v == value for v in self)

    def index_of(self, value):
        for i, v in enumerate(self):
            if v == value:
                return i
        return -1

    indexOf = index_of

    def includes(self, value):
        return self.index_of(value) >= 0

    def slice(self, start=None, end=None):
        return list(self)[start:end]

    def map(self, fn):
        return [fn(v) for v in self]

    def filter(self, fn):
        return [v for v in self if fn(v)]

    def join(self, sep=','):
        return sep.join(str(v) for v in self)

    # -- writes (reference: listMethods, proxies.js:17-96) ----------------
    def __setitem__(self, index, value):
        self._context.set_list_index(self._objid, parse_list_index(index), value)

    def __delitem__(self, index):
        self._context.splice(self._objid, parse_list_index(index), 1, [])

    def delete_at(self, index, num_delete=None):
        self._context.splice(self._objid, parse_list_index(index),
                             num_delete if num_delete is not None else 1, [])
        return self

    deleteAt = delete_at

    def fill(self, value, start=0, end=None):
        length = len(self._obj())
        end = length if end is None else end
        for index in range(parse_list_index(start), parse_list_index(end)):
            self._context.set_list_index(self._objid, index, value)
        return self

    def insert_at(self, index, *values):
        self._context.splice(self._objid, parse_list_index(index), 0, list(values))
        return self

    insertAt = insert_at

    def insert(self, index, value):
        """Python-style single-element insert."""
        self._context.splice(self._objid, parse_list_index(index), 0, [value])

    def pop(self, index=None):
        lst = self._obj()
        if len(lst) == 0:
            return None
        if index is None:
            index = len(lst) - 1
        last = self._context.get_object_field(self._objid, index)
        self._context.splice(self._objid, index, 1, [])
        return last

    def push(self, *values):
        self._context.splice(self._objid, len(self._obj()), 0, list(values))
        return len(self._obj())

    def append(self, value):
        """Python-style alias of push()."""
        self.push(value)

    def extend(self, values):
        self.push(*values)

    def shift(self):
        lst = self._obj()
        if len(lst) == 0:
            return None
        first = self._context.get_object_field(self._objid, 0)
        self._context.splice(self._objid, 0, 1, [])
        return first

    def splice(self, start, delete_count=None, *values):
        lst = self._obj()
        start = parse_list_index(start)
        if delete_count is None:
            delete_count = len(lst) - start
        deleted = [self._context.get_object_field(self._objid, start + n)
                   for n in range(delete_count)]
        self._context.splice(self._objid, start, delete_count, list(values))
        return deleted

    def unshift(self, *values):
        self._context.splice(self._objid, 0, 0, list(values))
        return len(self._obj())

    def __repr__(self):
        return 'ListProxy(%r)' % (list(self),)


def _inspect_proxy(proxy):
    """Plain-data snapshot of a proxied object tree
    (reference: proxies.js:101,144)."""
    from .inspect_util import to_plain
    return to_plain(proxy._context.get_object(proxy._objid))


def map_proxy(context, object_id):
    return MapProxy(context, object_id)


def list_proxy(context, object_id):
    return ListProxy(context, object_id)


def instantiate_proxy(context, object_id):
    """Creates the right proxy flavor for an object
    (reference: proxies.js:210-219)."""
    obj = context.get_object(object_id)
    if isinstance(obj, (list, Text)):
        return list_proxy(context, object_id)
    elif isinstance(obj, Table):
        return obj.get_writeable(context)
    else:
        return map_proxy(context, object_id)


def root_object_proxy(context):
    """(reference: proxies.js:221-225)"""
    context.instantiate_object = lambda object_id: instantiate_proxy(context, object_id)
    return map_proxy(context, ROOT_ID)
