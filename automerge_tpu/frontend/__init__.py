"""Frontend -- document view + mutation capture
(reference: `/root/reference/frontend/index.js`, 450 LoC).

Keeps the frozen document tree with hidden metadata; turns change callbacks
into change requests; applies backend patches; rebases optimistically-applied
pending requests over incoming patches with a small operational transform
(the reference's admittedly-approximate OT, frontend/index.js:139-199).

The frontend and backend each keep their own state and may be version-skewed:
with an "immediate backend" (`options['backend']`) the round trip is
synchronous; without one, requests queue and rebase -- that queued mode is
exactly how the batched TPU engine drives thousands of frontends
asynchronously from one device pass.
"""

from ..errors import AutomergeError, RangeError
from ..models.table import Table
from ..models.text import Text
from ..utils.common import ROOT_ID, is_object
from ..utils.uuid import uuid
from .apply_patch import (apply_diffs, clone_root_object,
                          update_parent_objects)
from .context import Context
from .doc_objects import AmList, AmMap
from .proxies import root_object_proxy


def _freeze_all(updated):
    for object_id, obj in updated.items():
        obj._freeze()


def update_root_object(doc, updated, inbound, state):
    """Builds a new frozen root object incorporating `updated`
    (reference: frontend/index.js:16-46)."""
    new_doc = updated.get(ROOT_ID)
    if new_doc is None:
        new_doc = clone_root_object(doc._cache[ROOT_ID])
        updated[ROOT_ID] = new_doc

    new_doc._actor_id = get_actor_id(doc)
    new_doc._options = doc._options
    new_doc._cache = updated
    new_doc._inbound = inbound
    new_doc._state = state

    _freeze_all(updated)
    for object_id, obj in doc._cache.items():
        if object_id not in updated:
            updated[object_id] = obj
    return new_doc


def ensure_single_assignment(ops):
    """Keeps only the most recent assignment per (obj, key)
    (reference: frontend/index.js:53-71)."""
    assignments = {}
    result = []
    for op in reversed(ops):
        if op['action'] in ('set', 'del', 'link'):
            seen = assignments.setdefault(op['obj'], {})
            if op['key'] not in seen:
                seen[op['key']] = True
                result.append(op)
        else:
            result.append(op)
    result.reverse()
    return result


def make_change(doc, request_type, context, message):
    """Creates a change request; with an immediate backend the round trip is
    synchronous, otherwise the request queues with a `before` snapshot
    (reference: frontend/index.js:80-112)."""
    actor = get_actor_id(doc)
    if not actor:
        raise AutomergeError(
            'Actor ID must be initialized with set_actor_id() before making a change')
    state = dict(doc._state)
    state['seq'] += 1
    deps = dict(state['deps'])
    deps.pop(actor, None)

    request = {'requestType': request_type, 'actor': actor, 'seq': state['seq'],
               'deps': deps}
    if message is not None:
        request['message'] = message
    if context is not None:
        request['ops'] = ensure_single_assignment(context.ops)

    backend = doc._options.get('backend')
    if backend:
        backend_state, patch = backend.apply_local_change(
            state['backendState'], request)
        state['backendState'] = backend_state
        state['requests'] = []
        return apply_patch_to_doc(doc, patch, state, True), request
    else:
        queued = dict(request)
        queued['before'] = doc
        if context is not None:
            queued['diffs'] = context.diffs
        state['requests'] = state['requests'] + [queued]
        return (update_root_object(doc, context.updated if context else {},
                                   context.inbound if context else dict(doc._inbound),
                                   state),
                request)


def apply_patch_to_doc(doc, patch, state, from_backend):
    """(reference: frontend/index.js:121-136)"""
    actor = get_actor_id(doc)
    inbound = dict(doc._inbound)
    updated = {}
    # the optimistic replay of pending requests (from_backend=False) may
    # carry approximate-OT indexes; JS-array leniency applies there only
    apply_diffs(patch['diffs'], doc._cache, updated, inbound,
                lenient=not from_backend)
    update_parent_objects(doc._cache, updated, inbound)

    if from_backend:
        seq = (patch.get('clock') or {}).get(actor)
        if seq and seq > state['seq']:
            state['seq'] = seq
        state['deps'] = patch.get('deps', {})
        state['canUndo'] = patch.get('canUndo', False)
        state['canRedo'] = patch.get('canRedo', False)
    return update_root_object(doc, updated, inbound, state)


def transform_request(request, patch):
    """Transforms a pending local request past a remote patch -- a simple,
    deliberately approximate operational transform; the backend's answer
    replaces it when it arrives (reference: frontend/index.js:175-199)."""
    transformed = []
    for local in request['diffs']:
        local = dict(local)
        drop = False
        for remote in patch['diffs']:
            if (local['obj'] == remote['obj'] and local['type'] == 'list'
                    and local['action'] in ('insert', 'set', 'remove')):
                if remote['action'] == 'insert' and remote['index'] <= local['index']:
                    local['index'] += 1
                if remote['action'] == 'remove' and remote['index'] < local['index']:
                    local['index'] -= 1
                if remote['action'] == 'remove' and remote['index'] == local['index']:
                    if local['action'] == 'set':
                        local['action'] = 'insert'
                    if local['action'] == 'remove':
                        drop = True
                        break
        if not drop:
            transformed.append(local)
    request['diffs'] = transformed


def init(options=None):
    """Creates an empty document (reference: frontend/index.js:204-229)."""
    if isinstance(options, str):
        options = {'actorId': options}
    elif options is None:
        options = {}
    elif not isinstance(options, dict):
        raise TypeError('Unsupported value for init() options: %r' % (options,))
    if options.get('actorId') is None and not options.get('deferActorId'):
        options = dict(options, actorId=uuid())

    root = AmMap()
    cache = {ROOT_ID: root}
    state = {'seq': 0, 'requests': [], 'deps': {}, 'canUndo': False,
             'canRedo': False}
    if options.get('backend'):
        state['backendState'] = options['backend'].init()
    root._object_id = ROOT_ID
    root._options = options
    root._cache = cache
    root._inbound = {}
    root._state = state
    root._actor_id = options.get('actorId')
    root._freeze()
    return root


def change(doc, message=None, callback=None):
    """Mutates `doc` through a change callback; returns (new_doc, request)
    (reference: frontend/index.js:240-268)."""
    if doc._object_id != ROOT_ID:
        raise TypeError('The first argument to change must be the document root')
    if callable(message) and callback is None:
        message, callback = None, message
    if message is not None and not isinstance(message, str):
        raise TypeError('Change message must be a string')

    actor_id = get_actor_id(doc)
    if not actor_id:
        raise AutomergeError(
            'Actor ID must be initialized with set_actor_id() before making a change')
    context = Context(doc, actor_id)
    callback(root_object_proxy(context))

    if not context.updated:
        return doc, None
    update_parent_objects(doc._cache, context.updated, context.inbound)
    return make_change(doc, 'change', context, message)


def empty_change(doc, message=None):
    """A change that affects no data but adds a causal acknowledgment
    (reference: frontend/index.js:278-288)."""
    if message is not None and not isinstance(message, str):
        raise TypeError('Change message must be a string')
    actor_id = get_actor_id(doc)
    if not actor_id:
        raise AutomergeError(
            'Actor ID must be initialized with set_actor_id() before making a change')
    return make_change(doc, 'change', Context(doc, actor_id), message)


def apply_patch(doc, patch):
    """Applies a backend patch; matches it up with the pending-request queue
    and rebases the remainder (reference: frontend/index.js:296-331)."""
    state = dict(doc._state)

    if state['requests']:
        base_doc = state['requests'][0]['before']
        if patch.get('actor') == get_actor_id(doc) and patch.get('seq') is not None:
            if state['requests'][0]['seq'] != patch['seq']:
                raise RangeError(
                    'Mismatched sequence number: patch %s does not match next '
                    'request %s' % (patch['seq'], state['requests'][0]['seq']))
            state['requests'] = [dict(req) for req in state['requests'][1:]]
        else:
            state['requests'] = [dict(req) for req in state['requests']]
    else:
        base_doc = doc
        state['requests'] = []

    if doc._options.get('backend'):
        if 'state' not in patch:
            raise RangeError('When an immediate backend is used, a patch must '
                             'contain the new backend state')
        state['backendState'] = patch['state']
        state['requests'] = []
        return apply_patch_to_doc(doc, patch, state, True)

    new_doc = apply_patch_to_doc(base_doc, patch, state, True)
    for request in state['requests']:
        request['before'] = new_doc
        transform_request(request, patch)
        new_doc = apply_patch_to_doc(request['before'], request, state, False)
    return new_doc


def can_undo(doc):
    """(reference: frontend/index.js:337-339)"""
    return bool(doc._state['canUndo']) and not _is_undo_redo_in_flight(doc)


def _is_undo_redo_in_flight(doc):
    return any(req['requestType'] in ('undo', 'redo')
               for req in doc._state['requests'])


def undo(doc, message=None):
    """(reference: frontend/index.js:356-367)"""
    if message is not None and not isinstance(message, str):
        raise TypeError('Change message must be a string')
    if not doc._state['canUndo']:
        raise AutomergeError('Cannot undo: there is nothing to be undone')
    if _is_undo_redo_in_flight(doc):
        raise AutomergeError('Can only have one undo in flight at any one time')
    return make_change(doc, 'undo', None, message)


def can_redo(doc):
    """(reference: frontend/index.js:373-375)"""
    return bool(doc._state['canRedo']) and not _is_undo_redo_in_flight(doc)


def redo(doc, message=None):
    """(reference: frontend/index.js:386-397)"""
    if message is not None and not isinstance(message, str):
        raise TypeError('Change message must be a string')
    if not doc._state['canRedo']:
        raise AutomergeError('Cannot redo: there is no prior undo')
    if _is_undo_redo_in_flight(doc):
        raise AutomergeError('Can only have one redo in flight at any one time')
    return make_change(doc, 'redo', None, message)


def get_object_id(obj):
    """(reference: frontend/index.js:402-404)"""
    return getattr(obj, '_object_id', None)


def get_actor_id(doc):
    """(reference: frontend/index.js:409-411)"""
    return doc._state.get('actorId') or doc._options.get('actorId')


def set_actor_id(doc, actor_id):
    """(reference: frontend/index.js:417-420)"""
    state = dict(doc._state, actorId=actor_id)
    return update_root_object(doc, {}, dict(doc._inbound), state)


def get_conflicts(obj):
    """Conflict sets on any object in a document
    (reference: frontend/index.js:429-431)."""
    return obj._conflicts


def get_backend_state(doc):
    """(reference: frontend/index.js:437-439)"""
    return doc._state.get('backendState')


def get_element_ids(lst):
    """(reference: frontend/index.js:441-443)"""
    if isinstance(lst, Text):
        return [e['elemId'] for e in lst.elems]
    return lst._elem_ids


# camelCase aliases: the reference's public Frontend API
# (`/root/reference/frontend/index.js:445-450`)
emptyChange = empty_change
applyPatch = apply_patch
canUndo = can_undo
canRedo = can_redo
getObjectId = get_object_id
getActorId = get_actor_id
setActorId = set_actor_id
getConflicts = get_conflicts
getBackendState = get_backend_state
getElementIds = get_element_ids
