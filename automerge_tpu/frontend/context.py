"""Mutation context: records ops while a change() callback runs and keeps an
optimistically-updated local copy of the document
(reference: `/root/reference/frontend/context.js`, 277 LoC).
"""

from datetime import datetime

from ..errors import RangeError
from ..models.table import Table
from ..models.text import Text, get_elem_id
from ..utils.common import is_object
from ..utils.uuid import uuid
from .apply_patch import apply_diffs, timestamp_value

_MISSING = object()


def _same_value(current, value):
    """Mirrors the reference's `object[key] !== value` no-op check: strict
    (identity) for objects, value equality for primitives, with JS-style
    bool/number distinction."""
    if current is _MISSING:
        return False
    if is_object(value) or is_object(current):
        return current is value
    if isinstance(current, bool) != isinstance(value, bool):
        return False
    return current == value


class Context:
    def __init__(self, doc, actor_id):
        self.actor_id = actor_id
        self.cache = doc._cache
        self.updated = {}
        self.inbound = dict(doc._inbound)
        self.ops = []
        self.diffs = []
        # instantiate_object is attached by root_object_proxy()

    def add_op(self, operation):
        """(reference: context.js:27-29)"""
        self.ops.append(operation)

    def apply(self, diff):
        """Applies a local diff optimistically (reference: context.js:34-37)."""
        self.diffs.append(diff)
        apply_diffs([diff], self.cache, self.updated, self.inbound)

    def get_object(self, object_id):
        """(reference: context.js:42-45)"""
        obj = self.updated.get(object_id)
        if obj is None:
            obj = self.cache.get(object_id)
        if obj is None:
            raise RangeError('Target object does not exist: %s' % object_id)
        return obj

    def get_object_field(self, object_id, key):
        """(reference: context.js:52-60)"""
        obj = self.get_object(object_id)
        if isinstance(obj, (list, Text)):
            value = obj[key]
        else:
            value = obj.get(key)
        if is_object(value):
            return self.instantiate_object(value._object_id)
        return value

    def create_nested_objects(self, value):
        """Recursively creates Automerge objects for a nested Python value;
        returns the new object's ID (reference: context.js:67-105)."""
        if getattr(value, '_object_id', None):
            return value._object_id
        object_id = uuid()

        if isinstance(value, Text):
            if value.length > 0:
                raise RangeError('Assigning a non-empty Text object is not supported')
            self.apply({'action': 'create', 'type': 'text', 'obj': object_id})
            self.add_op({'action': 'makeText', 'obj': object_id})
        elif isinstance(value, Table):
            if value.count > 0:
                raise RangeError('Assigning a non-empty Table object is not supported')
            self.apply({'action': 'create', 'type': 'table', 'obj': object_id})
            self.add_op({'action': 'makeTable', 'obj': object_id})
            self.set_map_key(object_id, 'table', 'columns', value.columns)
        elif isinstance(value, list):
            self.apply({'action': 'create', 'type': 'list', 'obj': object_id})
            self.add_op({'action': 'makeList', 'obj': object_id})
            self.splice(object_id, 0, 0, value)
        else:
            self.apply({'action': 'create', 'type': 'map', 'obj': object_id})
            self.add_op({'action': 'makeMap', 'obj': object_id})
            for key in value.keys():
                self.set_map_key(object_id, 'map', key, value[key])
        return object_id

    def set_value(self, obj, key, value):
        """Normalizes an assigned value into op form: object reference
        -> {value: id, link: True}; datetime -> timestamp; primitive
        -> {value} (reference: context.js:114-136)."""
        if value is not None and not isinstance(
                value, (bool, int, float, str, dict, list, Text, Table, datetime)):
            raise TypeError('Unsupported type of value: %s' % type(value).__name__)

        if isinstance(value, datetime):
            ts = timestamp_value(value)
            self.add_op({'action': 'set', 'obj': obj, 'key': key, 'value': ts,
                         'datatype': 'timestamp'})
            return {'value': ts, 'datatype': 'timestamp'}
        elif is_object(value):
            child_id = self.create_nested_objects(value)
            self.add_op({'action': 'link', 'obj': obj, 'key': key,
                         'value': child_id})
            return {'value': child_id, 'link': True}
        else:
            self.add_op({'action': 'set', 'obj': obj, 'key': key, 'value': value})
            return {'value': value}

    def set_map_key(self, object_id, type_, key, value):
        """(reference: context.js:143-161)"""
        if not isinstance(key, str):
            raise RangeError('The key of a map entry must be a string, not %s'
                             % type(key).__name__)
        if key == '':
            raise RangeError('The key of a map entry must not be an empty string')
        if key.startswith('_'):
            raise RangeError(
                'Map entries starting with underscore are not allowed: %s' % key)

        obj = self.get_object(object_id)
        # Skip no-op assignment of an identical value with no conflict
        current = obj.get(key, _MISSING) if key in obj else _MISSING
        if not _same_value(current, value) or obj._conflicts.get(key):
            value_obj = self.set_value(object_id, key, value)
            diff = {'action': 'set', 'type': type_, 'obj': object_id, 'key': key}
            diff.update(value_obj)
            self.apply(diff)

    def delete_map_key(self, object_id, key):
        """(reference: context.js:166-172)"""
        obj = self.get_object(object_id)
        if key in obj:
            self.apply({'action': 'remove', 'type': 'map', 'obj': object_id,
                        'key': key})
            self.add_op({'action': 'del', 'obj': object_id, 'key': key})

    def insert_list_item(self, object_id, index, value):
        """(reference: context.js:178-193)"""
        lst = self.get_object(object_id)
        if index < 0 or index > len(lst):
            raise RangeError('List index %s is out of bounds for list of length %s'
                             % (index, len(lst)))

        max_elem = lst._max_elem + 1
        type_ = 'text' if isinstance(lst, Text) else 'list'
        prev_id = '_head' if index == 0 else get_elem_id(lst, index - 1)
        elem_id = '%s:%s' % (self.actor_id, max_elem)
        self.add_op({'action': 'ins', 'obj': object_id, 'key': prev_id,
                     'elem': max_elem})

        value_obj = self.set_value(object_id, elem_id, value)
        diff = {'action': 'insert', 'type': type_, 'obj': object_id,
                'index': index, 'elemId': elem_id}
        diff.update(value_obj)
        self.apply(diff)
        self.get_object(object_id)._max_elem = max_elem

    def set_list_index(self, object_id, index, value):
        """(reference: context.js:199-217)"""
        lst = self.get_object(object_id)
        if index == len(lst):
            self.insert_list_item(object_id, index, value)
            return
        if index < 0 or index > len(lst):
            raise RangeError('List index %s is out of bounds for list of length %s'
                             % (index, len(lst)))

        # The reference reads `list[index]` on a Text instance as undefined
        # (Text is not an array), so Text assignments always write.
        if isinstance(lst, Text):
            current, has_conflict = _MISSING, None
        else:
            current = lst[index]
            conflicts = lst._conflicts
            has_conflict = conflicts[index] if index < len(conflicts) else None
        if not _same_value(current, value) or has_conflict:
            elem_id = get_elem_id(lst, index)
            type_ = 'text' if isinstance(lst, Text) else 'list'
            value_obj = self.set_value(object_id, elem_id, value)
            diff = {'action': 'set', 'type': type_, 'obj': object_id,
                    'index': index}
            diff.update(value_obj)
            self.apply(diff)

    def splice(self, object_id, start, deletions, insertions):
        """(reference: context.js:224-246)"""
        lst = self.get_object(object_id)
        type_ = 'text' if isinstance(lst, Text) else 'list'

        if deletions > 0:
            if start < 0 or start > len(lst) - deletions:
                raise RangeError(
                    '%s deletions starting at index %s are out of bounds for '
                    'list of length %s' % (deletions, start, len(lst)))
            for i in range(deletions):
                self.add_op({'action': 'del', 'obj': object_id,
                             'key': get_elem_id(lst, start)})
                self.apply({'action': 'remove', 'type': type_,
                            'obj': object_id, 'index': start})
                if i == 0:
                    lst = self.get_object(object_id)

        for i, value in enumerate(insertions):
            self.insert_list_item(object_id, start + i, value)

    def add_table_row(self, object_id, row):
        """(reference: context.js:252-264)"""
        if not is_object(row):
            raise TypeError('A table row must be an object')
        if getattr(row, '_object_id', None):
            raise TypeError('Cannot reuse an existing object as table row')

        row_id = self.create_nested_objects(row)
        self.apply({'action': 'set', 'type': 'table', 'obj': object_id,
                    'key': row_id, 'value': row_id, 'link': True})
        self.add_op({'action': 'link', 'obj': object_id, 'key': row_id,
                     'value': row_id})
        return row_id

    def delete_table_row(self, object_id, row_id):
        """(reference: context.js:269-272)"""
        self.apply({'action': 'remove', 'type': 'table', 'obj': object_id,
                    'key': row_id})
        self.add_op({'action': 'del', 'obj': object_id, 'key': row_id})
