"""Plain-data snapshots of document trees (the analogue of the reference's
`JSON.parse(JSON.stringify(doc))`, `/root/reference/src/automerge.js:102-104`)."""

from datetime import datetime

from ..models.table import Table
from ..models.text import Text


def to_plain(value):
    """Recursively converts a document (sub)tree into plain dicts/lists/
    primitives.  Text becomes its string content; Table becomes
    {columns, rows: {id: row}}; datetime stays a datetime."""
    if isinstance(value, Text):
        return str(value)
    if isinstance(value, Table):
        return {
            'columns': to_plain(value.columns),
            'rows': {id_: to_plain(value.by_id(id_)) for id_ in value.ids},
        }
    if isinstance(value, dict):
        return {k: to_plain(v) for k, v in value.items()}
    if isinstance(value, list):
        return [to_plain(v) for v in value]
    if isinstance(value, datetime):
        return value
    return value
