"""Patch interpreter: applies backend diff lists to the frozen document tree
(reference: `/root/reference/frontend/apply_patch.js`, 464 LoC).

Per-type update functions clone the affected object copy-on-write, apply the
diff, then `update_parent_objects` rewrites the parent chain up to the root
and maintains the child->parent `inbound` index.  Consecutive text diffs are
batched into splices (reference: apply_patch.js:325-388).
"""

import re
from datetime import datetime, timezone

from ..errors import RangeError
from ..models.table import Table, instantiate_table
from ..models.text import Text
from ..utils.common import ROOT_ID, is_object
from .doc_objects import AmList, AmMap

_ELEM_ID_RE = re.compile(r'^(.*):(\d+)$')


def parse_elem_id(elem_id):
    """Splits 'actor:counter' into (counter, actor)
    (reference: apply_patch.js:11-17)."""
    m = _ELEM_ID_RE.match(elem_id or '')
    if not m:
        raise RangeError('Not a valid elemId: %s' % elem_id)
    return int(m.group(2)), m.group(1)


def get_value(diff, cache, updated):
    """Reconstructs a value from a diff (reference: apply_patch.js:22-35)."""
    if diff.get('link'):
        # explicit None checks: empty containers are falsy in Python, but a
        # just-created empty object must still resolve
        obj = updated.get(diff['value'])
        return obj if obj is not None else cache.get(diff['value'])
    elif diff.get('datatype') == 'timestamp':
        return datetime.fromtimestamp(diff['value'] / 1000.0, tz=timezone.utc)
    elif diff.get('datatype') is not None:
        raise TypeError('Unknown datatype: %s' % diff['datatype'])
    else:
        return diff.get('value')


def timestamp_value(dt):
    """Milliseconds since epoch for a datetime (the 'timestamp' datatype)."""
    return int(round(dt.timestamp() * 1000))


def child_references(obj, key):
    """objectIds of child objects under `key` incl. conflicts
    (reference: apply_patch.js:42-51)."""
    refs = {}
    if isinstance(obj, (list, AmList)):
        conflicts = (obj._conflicts[key] or {}) if key < len(obj._conflicts) else {}
        children = [obj[key] if key < len(obj) else None]
    else:
        conflicts = obj._conflicts.get(key) or {}
        children = [obj.get(key)]
    children.extend(conflicts.values())
    for child in children:
        if is_object(child) and hasattr(child, '_object_id'):
            refs[child._object_id] = True
    return refs


def update_inbound(object_id, refs_before, refs_after, inbound):
    """Maintains the child->parent index (reference: apply_patch.js:59-70)."""
    for ref in refs_before:
        if ref not in refs_after:
            inbound.pop(ref, None)
    for ref in refs_after:
        if ref in inbound and inbound[ref] != object_id:
            raise RangeError('Object %s has multiple parents' % ref)
        elif ref not in inbound:
            inbound[ref] = object_id


def clone_map_object(original, object_id):
    """Writable copy of a map object (reference: apply_patch.js:76-85)."""
    if original is not None and original._object_id != object_id:
        raise RangeError('cloneMapObject ID mismatch: %s != %s'
                         % (original._object_id, object_id))
    obj = AmMap(original if original is not None else {})
    obj._object_id = object_id
    obj._conflicts = dict(original._conflicts) if original is not None else {}
    return obj


def update_map_object(diff, cache, updated, inbound):
    """(reference: apply_patch.js:93-124)"""
    object_id = diff['obj']
    if object_id not in updated:
        updated[object_id] = clone_map_object(cache.get(object_id), object_id)
    obj = updated[object_id]
    conflicts = obj._conflicts
    refs_before, refs_after = {}, {}

    action = diff['action']
    if action == 'create':
        pass
    elif action == 'set':
        refs_before = child_references(obj, diff['key'])
        dict.__setitem__(obj, diff['key'], get_value(diff, cache, updated))
        if diff.get('conflicts'):
            conflicts[diff['key']] = {
                c['actor']: get_value(c, cache, updated)
                for c in diff['conflicts']
            }
        else:
            conflicts.pop(diff['key'], None)
        refs_after = child_references(obj, diff['key'])
    elif action == 'remove':
        refs_before = child_references(obj, diff['key'])
        dict.pop(obj, diff['key'], None)
        conflicts.pop(diff['key'], None)
    else:
        raise RangeError('Unknown action type: ' + action)

    update_inbound(object_id, refs_before, refs_after, inbound)


def parent_map_object(object_id, cache, updated):
    """Replaces updated children inside a parent map
    (reference: apply_patch.js:131-159)."""
    if object_id not in updated:
        updated[object_id] = clone_map_object(cache.get(object_id), object_id)
    obj = updated[object_id]

    for key in list(obj.keys()):
        value = obj[key]
        if is_object(value) and hasattr(value, '_object_id') \
                and value._object_id in updated:
            dict.__setitem__(obj, key, updated[value._object_id])

        conflicts = obj._conflicts.get(key) or {}
        conflicts_update = None
        for actor_id, value in conflicts.items():
            if is_object(value) and hasattr(value, '_object_id') \
                    and value._object_id in updated:
                if conflicts_update is None:
                    conflicts_update = dict(conflicts)
                    obj._conflicts[key] = conflicts_update
                conflicts_update[actor_id] = updated[value._object_id]


def update_table_object(diff, cache, updated, inbound):
    """(reference: apply_patch.js:167-194)"""
    object_id = diff['obj']
    if object_id not in updated:
        cached = cache.get(object_id)
        updated[object_id] = cached._clone() if cached is not None \
            else instantiate_table(object_id)
    obj = updated[object_id]
    refs_before, refs_after = {}, {}

    action = diff['action']
    if action == 'create':
        pass
    elif action == 'set':
        previous = obj.by_id(diff['key'])
        if is_object(previous):
            refs_before[previous._object_id] = True
        if diff.get('link'):
            child = updated.get(diff['value'])
            if child is None:
                child = cache.get(diff['value'])
            obj.set(diff['key'], child)
            refs_after[diff['value']] = True
        else:
            obj.set(diff['key'], diff.get('value'))
    elif action == 'remove':
        previous = obj.by_id(diff['key'])
        if is_object(previous):
            refs_before[previous._object_id] = True
        obj.remove(diff['key'])
    else:
        raise RangeError('Unknown action type: ' + action)

    update_inbound(object_id, refs_before, refs_after, inbound)


def parent_table_object(object_id, cache, updated):
    """(reference: apply_patch.js:201-213)"""
    if object_id not in updated:
        updated[object_id] = cache[object_id]._clone()
    table = updated[object_id]
    for key in list(table.entries.keys()):
        value = table.by_id(key)
        if is_object(value) and hasattr(value, '_object_id') \
                and value._object_id in updated:
            table.set(key, updated[value._object_id])


def clone_list_object(original, object_id):
    """Writable copy of a list object (reference: apply_patch.js:219-232)."""
    if original is not None and original._object_id != object_id:
        raise RangeError('cloneListObject ID mismatch: %s != %s'
                         % (original._object_id, object_id))
    lst = AmList(original if original is not None else [])
    lst._object_id = object_id
    lst._conflicts = list(original._conflicts) if original is not None else []
    lst._elem_ids = list(original._elem_ids) if original is not None else []
    lst._max_elem = original._max_elem if original is not None else 0
    return lst


def update_list_object(diff, cache, updated, inbound, lenient=False):
    """(reference: apply_patch.js:240-282)

    `lenient` applies JS-array index tolerance for the pending-request
    replay path ONLY: the frontend's operational transform is
    deliberately approximate (frontend/index.js:146-151 admits it), and
    the reference's transient optimistic state survives because JS
    splice/assignment silently clamp out-of-range indexes; the backend's
    patch replaces the transient state anyway.  Backend patches always
    carry valid indexes and use the strict mode."""
    object_id = diff['obj']
    if object_id not in updated:
        updated[object_id] = clone_list_object(cache.get(object_id), object_id)
    lst = updated[object_id]
    conflicts, elem_ids = lst._conflicts, lst._elem_ids
    value, conflict = None, None

    action = diff['action']
    if action in ('insert', 'set'):
        value = get_value(diff, cache, updated)
        if diff.get('conflicts'):
            conflict = {c['actor']: get_value(c, cache, updated)
                        for c in diff['conflicts']}

    index = diff.get('index')
    if lenient and index is not None:
        if action == 'remove' and index >= len(lst):
            return
        if action == 'set' and index >= len(lst):
            action = 'insert'
        if index > len(lst):
            index = len(lst)
        # the approximate OT can rewrite set->insert (remote remove at the
        # same index) without an elemId; the transient state just needs a
        # placeholder until the backend's patch replaces it
        if action == 'insert' and 'elemId' not in diff:
            diff = dict(diff, elemId='_transient:0')

    refs_before, refs_after = {}, {}
    if action == 'create':
        pass
    elif action == 'insert':
        lst._max_elem = max(lst._max_elem, parse_elem_id(diff['elemId'])[0])
        list.insert(lst, index, value)
        conflicts.insert(index, conflict)
        elem_ids.insert(index, diff['elemId'])
        refs_after = child_references(lst, index)
    elif action == 'set':
        refs_before = child_references(lst, index)
        list.__setitem__(lst, index, value)
        conflicts[index] = conflict
        refs_after = child_references(lst, index)
    elif action == 'remove':
        refs_before = child_references(lst, index)
        list.__delitem__(lst, index)
        del conflicts[index]
        del elem_ids[index]
    else:
        raise RangeError('Unknown action type: ' + action)

    update_inbound(object_id, refs_before, refs_after, inbound)


def parent_list_object(object_id, cache, updated):
    """(reference: apply_patch.js:289-317)"""
    if object_id not in updated:
        updated[object_id] = clone_list_object(cache.get(object_id), object_id)
    lst = updated[object_id]

    for index in range(len(lst)):
        value = lst[index]
        if is_object(value) and hasattr(value, '_object_id') \
                and value._object_id in updated:
            list.__setitem__(lst, index, updated[value._object_id])

        conflicts = (lst._conflicts[index] if index < len(lst._conflicts)
                     else None) or {}
        conflicts_update = None
        for actor_id, value in conflicts.items():
            if is_object(value) and hasattr(value, '_object_id') \
                    and value._object_id in updated:
                if conflicts_update is None:
                    conflicts_update = dict(conflicts)
                    lst._conflicts[index] = conflicts_update
                conflicts_update[actor_id] = updated[value._object_id]


def update_text_object(diffs, start_index, end_index, cache, updated):
    """Applies a run of text diffs, batching consecutive inserts/removes into
    splices (reference: apply_patch.js:325-388)."""
    object_id = diffs[start_index]['obj']
    if object_id not in updated:
        cached = cache.get(object_id)
        if cached is not None:
            updated[object_id] = Text(object_id, list(cached.elems),
                                      cached._max_elem)
        else:
            updated[object_id] = Text(object_id)

    text = updated[object_id]
    elems, max_elem = text.elems, text._max_elem
    splice_pos, deletions, insertions = -1, 0, []

    while start_index <= end_index:
        diff = diffs[start_index]
        action = diff['action']
        if action == 'create':
            pass
        elif action == 'insert':
            if splice_pos < 0:
                splice_pos = diff['index']
                deletions = 0
                insertions = []
            max_elem = max(max_elem, parse_elem_id(diff['elemId'])[0])
            insertions.append({'elemId': diff['elemId'],
                               'value': diff.get('value'),
                               'conflicts': diff.get('conflicts')})
            if (start_index == end_index
                    or diffs[start_index + 1]['action'] != 'insert'
                    or diffs[start_index + 1]['index'] != diff['index'] + 1):
                elems[splice_pos:splice_pos + deletions] = insertions
                splice_pos = -1
        elif action == 'set':
            elems[diff['index']] = {
                'elemId': elems[diff['index']]['elemId'],
                'value': diff.get('value'),
                'conflicts': diff.get('conflicts'),
            }
        elif action == 'remove':
            if splice_pos < 0:
                splice_pos = diff['index']
                deletions = 0
                insertions = []
            deletions += 1
            if (start_index == end_index
                    or diffs[start_index + 1]['action'] not in ('insert', 'remove')
                    or diffs[start_index + 1]['index'] != diff['index']):
                elems[splice_pos:splice_pos + deletions] = []
                splice_pos = -1
        else:
            raise RangeError('Unknown action type: ' + action)
        start_index += 1

    updated[object_id] = Text(object_id, elems, max_elem)


def update_parent_objects(cache, updated, inbound):
    """Propagates updated children into new parent versions up to the root
    (reference: apply_patch.js:398-418)."""
    affected = updated
    while affected:
        parents = {}
        for child_id in list(affected.keys()):
            parent_id = inbound.get(child_id)
            if parent_id:
                parents[parent_id] = True
        affected = parents

        for object_id in parents:
            target = updated.get(object_id)
            if target is None:
                target = cache.get(object_id)
            if isinstance(target, (list, AmList)):
                parent_list_object(object_id, cache, updated)
            elif isinstance(target, Table):
                parent_table_object(object_id, cache, updated)
            else:
                parent_map_object(object_id, cache, updated)


def apply_diffs(diffs, cache, updated, inbound, lenient=False):
    """Dispatches a diff list to the per-type updaters; text diffs for one
    object are handled as a run (reference: apply_patch.js:427-450).
    `lenient` is set only for the pending-request optimistic replay (see
    update_list_object)."""
    start_index = 0
    for end_index in range(len(diffs)):
        diff = diffs[end_index]
        type_ = diff['type']
        if type_ == 'map':
            update_map_object(diff, cache, updated, inbound)
            start_index = end_index + 1
        elif type_ == 'table':
            update_table_object(diff, cache, updated, inbound)
            start_index = end_index + 1
        elif type_ == 'list':
            update_list_object(diff, cache, updated, inbound, lenient)
            start_index = end_index + 1
        elif type_ == 'text':
            if (end_index == len(diffs) - 1
                    or diffs[end_index + 1]['obj'] != diff['obj']):
                update_text_object(diffs, start_index, end_index, cache, updated)
                start_index = end_index + 1
        else:
            raise TypeError('Unknown object type: %s' % type_)


def clone_root_object(root):
    """(reference: apply_patch.js:455-460)"""
    if root._object_id != ROOT_ID:
        raise RangeError('Not the root object: %s' % root._object_id)
    return clone_map_object(root, ROOT_ID)
