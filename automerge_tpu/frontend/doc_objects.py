"""Immutable document objects: the materialized view of a document.

The reference represents documents as frozen plain JS objects/arrays with
hidden metadata attached under Symbols (`/root/reference/frontend/index.js:16-46`,
`/root/reference/frontend/constants.js`).  The Python equivalents are dict/list
subclasses carrying the metadata as slot attributes, with a freeze flag that
turns all mutators into errors outside a change callback.
"""

from ..errors import AutomergeError


def _frozen_error():
    return AutomergeError(
        'This object is frozen; modify it inside a change() callback')


class AmMap(dict):
    """A frozen map object.  Keys are readable with both doc['key'] and
    doc.key.  Hidden metadata: _object_id, _conflicts; the root additionally
    carries _options, _cache, _inbound, _state, _actor_id."""

    _am_object = True
    __slots__ = ('_object_id', '_conflicts', '_options', '_cache', '_inbound',
                 '_state', '_actor_id', '_am_frozen')

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        object.__setattr__(self, '_am_frozen', False)
        object.__setattr__(self, '_conflicts', {})

    # -- attribute-style reads for non-underscore keys --------------------
    def __getattr__(self, name):
        if name.startswith('_'):
            raise AttributeError(name)
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name)

    def __setattr__(self, name, value):
        if name in AmMap.__slots__:
            object.__setattr__(self, name, value)
        else:
            raise _frozen_error()

    # -- freeze machinery -------------------------------------------------
    def _freeze(self):
        object.__setattr__(self, '_am_frozen', True)

    def _check(self):
        if getattr(self, '_am_frozen', False):
            raise _frozen_error()

    def __setitem__(self, key, value):
        self._check()
        super().__setitem__(key, value)

    def __delitem__(self, key):
        self._check()
        super().__delitem__(key)

    def update(self, *args, **kwargs):
        self._check()
        super().update(*args, **kwargs)

    def pop(self, *args):
        self._check()
        return super().pop(*args)

    def popitem(self):
        self._check()
        return super().popitem()

    def clear(self):
        self._check()
        super().clear()

    def setdefault(self, *args):
        self._check()
        return super().setdefault(*args)


class AmList(list):
    """A frozen list object.  Hidden metadata: _object_id, _conflicts
    (parallel list of conflict dicts or None), _elem_ids, _max_elem."""

    _am_object = True
    __slots__ = ('_object_id', '_conflicts', '_elem_ids', '_max_elem',
                 '_am_frozen')

    def __init__(self, *args):
        super().__init__(*args)
        object.__setattr__(self, '_am_frozen', False)

    def _freeze(self):
        object.__setattr__(self, '_am_frozen', True)

    def _check(self):
        if getattr(self, '_am_frozen', False):
            raise _frozen_error()

    def __setitem__(self, key, value):
        self._check()
        super().__setitem__(key, value)

    def __delitem__(self, key):
        self._check()
        super().__delitem__(key)

    def append(self, value):
        self._check()
        super().append(value)

    def extend(self, values):
        self._check()
        super().extend(values)

    def insert(self, index, value):
        self._check()
        super().insert(index, value)

    def pop(self, *args):
        self._check()
        return super().pop(*args)

    def remove(self, value):
        self._check()
        super().remove(value)

    def sort(self, **kwargs):
        self._check()
        super().sort(**kwargs)

    def reverse(self):
        self._check()
        super().reverse()

    def splice(self, index, deletions=0, *values):
        raise _frozen_error()
