"""Live doc migration + cost-driven rebalancing (ISSUE 18,
docs/SERVING.md migration section).

**MigrationExecutor** moves a set of docs from one replica to another
with no op lost, duplicated, or reordered:

  1. *park* -- the router marks the docs migrating; new frames
     touching them queue in per-doc FIFOs (`RouterGateway
     .begin_migration`), the same claim-order discipline the
     scheduler's admission queue applies per doc.
  2. *drain* -- wait until no already-forwarded op still touches the
     docs (`drain_docs`); the source replica still owns them, so
     in-flight ops complete and ack normally.
  3. *migrate_out* on the source: per-doc save -> durable
     ``ColdStore.put_many`` into a fresh handoff dir -> drop + mark
     disowned.  From this instant the source answers any straggler
     with the typed ``WrongReplica`` envelope.
  4. *migrate_in* on the target, RETRIED until a deadline: the handoff
     manifest is durable, and restore is idempotent (CRDT apply
     dedups), so a target that is SIGKILLed mid-restore simply
     restores again after restart -- the recovery arm
     `tools/route_check.py` exercises.
  5. *commit* -- ring overrides point the docs at the target (one
     version bump), the parked FIFOs release in arrival order to the
     new owner, and subscribed connections get the typed resync event
     so their subscription streams re-home.

**Rebalancer** is the watching thread: it scrapes each replica's
healthz ``capacity`` section through the router's control clients,
computes an occupancy score per replica from the cost totals, and when
the spread exceeds ``AMTPU_REBALANCE_MIN_SKEW`` (or any replica's
headroom pressure exceeds ``AMTPU_REBALANCE_PRESSURE``) moves the
hottest replica's top-K hot docs -- victims picked by cost vector from
the capacity hot-doc table -- to the coldest replica.
"""

import tempfile
import threading
import time

from .. import telemetry
from ..utils.common import env_float, env_int, env_str


class MigrationError(RuntimeError):
    """A migration step failed past recovery (docs remain parked-out
    in the durable handoff dir; `retry_in` can finish the move)."""


class MigrationExecutor(object):
    """Drives the park -> drain -> out -> in -> commit protocol through
    one RouterGateway.  `on_after_out` is a test seam called between
    migrate_out and migrate_in (the SIGKILL arm of route_check kills
    the target there)."""

    def __init__(self, router, handoff_dir=None, timeout_s=30.0,
                 on_after_out=None):
        self.router = router
        root = handoff_dir or env_str('AMTPU_ROUTE_HANDOFF_DIR', '')
        self.handoff_root = root or tempfile.mkdtemp(
            prefix='amtpu-handoff-')
        self.timeout_s = timeout_s
        self.on_after_out = on_after_out
        self._lock = threading.Lock()
        self._seq = 0             # guarded-by: self._lock

    def _next_handoff(self):
        """A FRESH subdir per migration: the ColdStore manifest is
        per-directory, so concurrent migrations never rewrite each
        other's."""
        import os
        with self._lock:
            self._seq += 1
            path = '%s/handoff-%03d' % (self.handoff_root, self._seq)
        os.makedirs(path, exist_ok=True)
        return path

    def migrate(self, docs, src, dst):
        """Moves `docs` from replica `src` to `dst`; returns
        ``{'docs', 'failed', 'src', 'dst', 'bytes', 'store_dir'}``.
        Raises MigrationError when the target never restores within
        the deadline (the handoff dir stays durable for `retry_in`)."""
        ring = self.router.ring
        docs = [d for d in docs
                if ring.owner(d) == src and src != dst]
        if not docs or dst not in self.router.replicas:
            return {'docs': [], 'failed': {}, 'src': src, 'dst': dst,
                    'bytes': 0, 'store_dir': None}
        store_dir = self._next_handoff()
        restored, failed, nbytes = [], {}, 0
        self.router.begin_migration(docs)
        try:
            if not self.router.drain_docs(docs,
                                          timeout_s=self.timeout_s):
                telemetry.metric('migrate.failed')
                raise MigrationError(
                    'in-flight ops on %r never drained' % (docs,))
            out = self.router.control_call(
                src, 'migrate_out', docs=list(docs),
                store_dir=store_dir, new_owner=dst,
                ring_version=ring.version + 1)
            failed.update(out.get('failed') or {})
            moved = out.get('migrated') or []
            nbytes = int(out.get('bytes') or 0)
            if self.on_after_out is not None:
                self.on_after_out(moved, store_dir)
            if moved:
                res = self.retry_in(moved, store_dir, dst)
                failed.update(res.get('failed') or {})
                restored = res.get('restored') or []
            if restored:
                ring.set_overrides({d: dst for d in restored})
                telemetry.metric('migrate.migrations', len(restored))
                # placement changed: journal it so a router restart
                # serves the post-migration placement (ISSUE 19)
                self.router._save_journal()
        finally:
            # parked frames release in arrival order even on failure:
            # ring placement decides where they go (committed moves ->
            # dst; failed moves still answer from wherever the ring
            # points, surfacing the error instead of wedging the FIFO)
            self.router.end_migration(docs)
        if restored:
            self.router.notify_migrated(restored)
        telemetry.recorder.record(
            'migrate.move', n=len(restored),
            detail={'src': src, 'dst': dst, 'failed': len(failed),
                    'bytes': nbytes})
        return {'docs': restored, 'failed': failed, 'src': src,
                'dst': dst, 'bytes': nbytes, 'store_dir': store_dir}

    def retry_in(self, docs, store_dir, dst):
        """migrate_in with retry-until-deadline.  Restore is
        idempotent, so retrying after a crash (or a torn first
        attempt) is safe; each retry reconnects because the control
        client is rebuilt on connection errors."""
        deadline = time.monotonic() + self.timeout_s
        last = None
        while True:
            try:
                return self.router.control_call(
                    dst, 'migrate_in', docs=list(docs),
                    store_dir=store_dir,
                    ring_version=self.router.ring.version + 1)
            except Exception as e:
                last = e
                if time.monotonic() > deadline:
                    telemetry.metric('migrate.failed')
                    raise MigrationError(
                        'migrate_in to %r never completed: %s'
                        % (dst, last))
                time.sleep(0.2)


def _occupancy(totals):
    """Scalar occupancy score from a capacity ``totals`` dict: arena
    bytes dominate (memory is what rebalancing protects), retained ops
    weigh in as write-load proxy."""
    return (int(totals.get('arena_bytes') or 0) +
            64 * int(totals.get('ops') or 0))


def _victim_score(row):
    """Cost-vector score for a hot-doc table row: prefer big, busy,
    watched docs -- the ones whose move buys the most headroom."""
    return (int(row.get('arena_bytes') or 0) +
            64 * int(row.get('ops') or 0) +
            4096 * int(row.get('subscribers') or 0))


class Rebalancer(object):
    """Background thread: scrape -> score -> (maybe) migrate.

    One pass (`scan`) scrapes every replica's healthz through the
    router's control clients, computes occupancy, and when the
    relative spread ``(max - min) / mean`` exceeds
    ``AMTPU_REBALANCE_MIN_SKEW`` -- or any replica's memory pressure
    exceeds ``AMTPU_REBALANCE_PRESSURE`` -- moves up to
    ``AMTPU_REBALANCE_TOPK`` victims from the hottest replica to the
    coldest, never more than half the observed gap (so a pass cannot
    overshoot and oscillate)."""

    def __init__(self, router, executor=None, interval_s=None,
                 topk=None, min_skew=None, pressure=None):
        self.router = router
        self.executor = executor or MigrationExecutor(router)
        self.interval_s = interval_s if interval_s is not None \
            else env_float('AMTPU_REBALANCE_INTERVAL_S', 5.0)
        self.topk = topk if topk is not None \
            else env_int('AMTPU_REBALANCE_TOPK', 4)
        self.min_skew = min_skew if min_skew is not None \
            else env_float('AMTPU_REBALANCE_MIN_SKEW', 0.5)
        self.pressure = pressure if pressure is not None \
            else env_float('AMTPU_REBALANCE_PRESSURE', 0.8)
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run,
                                        name='amtpu-rebalancer',
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.scan()
            except Exception as e:
                # a failed pass must not kill the thread; the next
                # interval re-scrapes from scratch
                telemetry.metric('migrate.errors')
                telemetry.recorder.record('migrate.scan_error',
                                          detail=str(e))

    def scrape(self):
        """{replica: healthz dict} via the router's control clients
        (unreachable replicas are skipped, not fatal)."""
        out = {}
        for r in sorted(self.router.replicas):
            try:
                out[r] = self.router.control_call(r, 'healthz')
            except Exception:
                continue
        return out

    def plan(self, scrapes):
        """(src, dst, victims) or None -- pure function of the scraped
        capacity sections, separated from `scan` so the route_check
        harness can drive it deterministically."""
        occ, tops, hot_pressure = {}, {}, 0.0
        for r, hz in scrapes.items():
            cap = (hz or {}).get('capacity') or {}
            occ[r] = _occupancy(cap.get('totals') or {})
            tops[r] = (cap.get('top') or {}).get('arena') or []
            headroom = cap.get('headroom') or {}
            hot_pressure = max(hot_pressure,
                               float(headroom.get('pressure') or 0.0))
        if len(occ) < 2:
            return None
        src = max(occ, key=occ.get)
        dst = min(occ, key=occ.get)
        gap = occ[src] - occ[dst]
        mean = sum(occ.values()) / float(len(occ))
        skew = gap / mean if mean > 0 else 0.0
        if skew < self.min_skew and hot_pressure < self.pressure:
            return None
        victims, moved_score = [], 0
        rows = sorted(tops[src], key=_victim_score, reverse=True)
        for row in rows[:self.topk]:
            score = _victim_score(row)
            if victims and moved_score + score > gap / 2.0:
                break          # never overshoot past the midpoint
            victims.append(row['doc'])
            moved_score += score
        if not victims:
            return None
        return src, dst, victims

    def scan(self):
        """One rebalance pass; returns the migration result (or None
        when the fleet is balanced)."""
        telemetry.metric('migrate.rebalance_passes')
        picked = self.plan(self.scrape())
        if picked is None:
            return None
        src, dst, victims = picked
        return self.executor.migrate(victims, src, dst)
