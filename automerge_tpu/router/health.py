"""Fleet health monitor (ISSUE 19, docs/RESILIENCE.md fleet
degradation tiers).

One state machine per ring member, fed by two signal paths that an
unplanned replica death can surface through:

  * **heartbeat probes** -- a monitor thread pings every member on its
    own dedicated probe socket each ``AMTPU_FLEET_HEARTBEAT_S``, with a
    hard per-probe deadline (``AMTPU_FLEET_DEADLINE_S``, enforced by a
    socket timeout so a hung-but-connected replica still counts as a
    miss).  The probe path carries the ``router.heartbeat`` fault site
    (member id as the doc scope), so chaos lanes drive the ladder
    deterministically.
  * **transport death** -- the router's per-connection upstream pumps
    report a died replica socket (`_upstream_dead`); that feeds the
    same machine as an immediate miss, so detection is not bounded by
    the probe period when real traffic notices first.

States::

    up --miss--> suspect --(misses >= AMTPU_FLEET_MISS_MAX)--> dead
        <--ok---         --(supervisor flap cap)--> quarantined

Consecutive-miss hysteresis: one miss only *suspects* a member (GC
pause, flush stall); while suspect, the router parks mutating frames
for that member's docs in the per-doc FIFOs instead of failing them
(bounded by ``AMTPU_FLEET_PARK_MB`` bytes and ``AMTPU_FLEET_PARK_S``
seconds -- the gateway enforces both).  A probe answering again
releases the parks in arrival order; ``AMTPU_FLEET_MISS_MAX``
consecutive misses declare the member dead and hand it to the failover
executor (``on_dead``), which runs on THIS monitor thread -- never on
a transport pump -- so fail-over never blocks the data path.

`dead` and `quarantined` are terminal for a member *id*: a supervised
respawn rejoins as a NEW member (router/supervisor.py), and this
monitor keeps the dead entry for the healthz ``fleet_health`` section
until it is forgotten.
"""

import json
import socket
import struct
import sys
import threading
import time

from .. import faults, telemetry
from ..utils.common import env_float, env_int

#: member states, in degradation order
UP, SUSPECT, DEAD, QUARANTINED = 'up', 'suspect', 'dead', 'quarantined'


class HealthMonitor(object):
    """Per-member up/suspect/dead state machine + heartbeat prober.

    ``on_dead(member)`` is the failover hook (typically
    ``FailoverExecutor.fail_over``); it is invoked from the monitor
    thread after the state transition is already visible, so the
    gateway's park checks and the executor never race the machine.
    """

    def __init__(self, router, heartbeat_s=None, deadline_s=None,
                 miss_max=None, on_dead=None):
        self.router = router
        self.heartbeat_s = heartbeat_s if heartbeat_s is not None \
            else env_float('AMTPU_FLEET_HEARTBEAT_S', 0.5)
        self.deadline_s = deadline_s if deadline_s is not None \
            else env_float('AMTPU_FLEET_DEADLINE_S', 0.5)
        self.miss_max = max(1, miss_max if miss_max is not None
                            else env_int('AMTPU_FLEET_MISS_MAX', 3))
        self.on_dead = on_dead
        self._lock = threading.Lock()
        self._members = {}       # guarded-by: self._lock
        self._pending_dead = []  # guarded-by: self._lock
        self._socks = {}         # probe sockets; monitor thread only
        self._hb_id = 0          # monitor thread only
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ------------------------------------------------------

    def start(self):
        telemetry.register_healthz_section('fleet_health',
                                           self._healthz_section)
        self.router.attach_health(self)
        self._thread = threading.Thread(target=self._run,
                                        name='amtpu-fleet-health',
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        for member in list(self._socks):
            self._drop_sock(member)
        telemetry.register_healthz_section('fleet_health', None)
        if getattr(self.router, '_health', None) is self:
            self.router.attach_health(None)

    # -- state machine --------------------------------------------------

    def _ensure(self, member):  # holds-lock: self._lock
        st = self._members.get(member)
        if st is None:
            st = {'state': UP, 'misses': 0,
                  'since': time.monotonic(),
                  'last_ok': time.monotonic()}
            self._members[member] = st
        return st

    def state(self, member):
        """The member's current state (an unseen member counts `up`)."""
        with self._lock:
            st = self._members.get(member)
            return st['state'] if st is not None else UP

    def is_parking(self, member):
        """While a member is suspect OR dead-but-not-yet-failed-over,
        mutating frames for its docs park instead of failing."""
        return self.state(member) in (SUSPECT, DEAD)

    def members(self):
        """Snapshot for rendering: {member: {state, misses, for_s}}."""
        now = time.monotonic()
        with self._lock:
            return {m: {'state': st['state'], 'misses': st['misses'],
                        'for_s': round(now - st['since'], 3)}
                    for m, st in self._members.items()}

    def note_ok(self, member):
        with self._lock:
            st = self._members.get(member)
            if st is None or st['state'] in (DEAD, QUARANTINED):
                return
            st['misses'] = 0
            st['last_ok'] = time.monotonic()
            recovered = st['state'] == SUSPECT
            if recovered:
                st['state'] = UP
                st['since'] = time.monotonic()
        if recovered:
            telemetry.metric('router.health.recoveries')
            self.router.release_member_parks(member)

    def note_miss(self, member, cause='probe'):
        now = time.monotonic()
        with self._lock:
            st = self._ensure(member)
            if st['state'] in (DEAD, QUARANTINED):
                return
            st['misses'] += 1
            suspected = st['state'] == UP
            if suspected:
                st['state'] = SUSPECT
                st['since'] = now
            died = st['misses'] >= self.miss_max
            if died:
                st['state'] = DEAD
                st['since'] = now
                self._pending_dead.append(member)
        telemetry.metric('router.health.misses')
        if suspected:
            telemetry.metric('router.health.suspects')
            telemetry.recorder.record('fleet.suspect', doc=member,
                                      n=1, detail=cause)
        if died:
            telemetry.metric('router.health.deaths')
            telemetry.recorder.record('fleet.dead', doc=member,
                                      n=1, detail=cause)

    def note_transport_death(self, member):
        """An upstream data socket died mid-stream -- stronger than a
        probe timeout (the kernel told us), so it suspects immediately
        without waiting for the next heartbeat tick."""
        self.note_miss(member, cause='transport')

    def mark_dead(self, member, cause='kill'):
        """Out-of-band kill detection (the supervisor watched the
        process exit): straight to dead, skipping hysteresis."""
        with self._lock:
            st = self._ensure(member)
            if st['state'] in (DEAD, QUARANTINED):
                return
            st['state'] = DEAD
            st['since'] = time.monotonic()
            self._pending_dead.append(member)
        telemetry.metric('router.health.deaths')
        telemetry.recorder.record('fleet.dead', doc=member, n=1,
                                  detail=cause)

    def quarantine(self, member):
        """Flap cap reached (router/supervisor.py): the member id is
        barred from the ring; only rendering distinguishes this from
        dead."""
        with self._lock:
            st = self._ensure(member)
            st['state'] = QUARANTINED
            st['since'] = time.monotonic()

    def forget(self, member):
        with self._lock:
            self._members.pop(member, None)
        self._drop_sock(member)

    # -- prober ---------------------------------------------------------

    def _run(self):
        while not self._stop.wait(self.heartbeat_s):
            for member in sorted(self.router.replicas):
                if self._stop.is_set():
                    return
                with self._lock:
                    st = self._ensure(member)
                    if st['state'] in (DEAD, QUARANTINED):
                        continue
                telemetry.metric('router.health.probes')
                if self._probe(member):
                    self.note_ok(member)
                else:
                    self.note_miss(member)
            self._fire_dead()
            self.router.sweep_parked()

    def _fire_dead(self):
        while True:
            with self._lock:
                if not self._pending_dead:
                    return
                member = self._pending_dead.pop(0)
            if self.on_dead is None:
                continue
            try:
                self.on_dead(member)
            except Exception as e:
                # a failed fail-over leaves the member dead and its
                # parks to expire via the sweep -- never kill the
                # monitor thread that detects everything else
                print('fleet-health: failover for %r failed: %s: %s'
                      % (member, type(e).__name__, e), file=sys.stderr)

    def _probe(self, member):
        """One deadline-bounded ping on the member's dedicated probe
        socket.  Runs only on the monitor thread, so the socket cache
        needs no lock."""
        try:
            if faults.ARMED:
                faults.fire('router.heartbeat', docs=(member,))
            sock = self._socks.get(member)
            if sock is None:
                path = self.router.replicas.get(member)
                if path is None:
                    return False
                sock = socket.socket(socket.AF_UNIX,
                                     socket.SOCK_STREAM)
                sock.settimeout(self.deadline_s)
                sock.connect(path)
                self._socks[member] = sock
            self._hb_id += 1
            req = {'id': '__amtpu_hb:%d' % self._hb_id, 'cmd': 'ping'}
            if self.router.use_msgpack:
                import msgpack
                body = msgpack.packb(req, use_bin_type=True)
                sock.sendall(struct.pack('>I', len(body)) + body)
                head = self._recv_exact(sock, 4)
                (n,) = struct.unpack('>I', head)
                resp = msgpack.unpackb(self._recv_exact(sock, n),
                                       raw=False, strict_map_key=False)
            else:
                sock.sendall((json.dumps(req) + '\n').encode())
                resp = json.loads(self._recv_line(sock))
            return isinstance(resp, dict) \
                and (resp.get('result') or {}).get('ok') is True
        except (OSError, ValueError, KeyError,
                faults.TransientFault, faults.PermanentFault):
            self._drop_sock(member)
            return False

    @staticmethod
    def _recv_exact(sock, n):
        buf = b''
        while len(buf) < n:
            got = sock.recv(n - len(buf))
            if not got:
                raise ConnectionError('probe socket closed')
            buf += got
        return buf

    @staticmethod
    def _recv_line(sock):
        buf = b''
        while not buf.endswith(b'\n'):
            got = sock.recv(4096)
            if not got:
                raise ConnectionError('probe socket closed')
            buf += got
        return buf

    def _drop_sock(self, member):
        sock = self._socks.pop(member, None)
        if sock is not None:
            try:
                sock.close()
            except Exception:
                pass

    # -- observability --------------------------------------------------

    def _healthz_section(self):
        out = {'members': self.members(),
               'heartbeat_s': self.heartbeat_s,
               'deadline_s': self.deadline_s,
               'miss_max': self.miss_max}
        out.update(self.router.park_stats())
        return out
