"""Consistent-hash placement ring (ISSUE 18, docs/SERVING.md routing
section).

Placement = hash ownership + an overrides table:

  * **Hash ownership.** Each replica contributes ``AMTPU_ROUTE_VNODES``
    virtual nodes (points on a 64-bit ring from sha1 of
    ``"<replica>#<k>"``); a doc belongs to the first point clockwise of
    ``sha1(doc_key)``.  Virtual nodes keep occupancy near-uniform and
    make membership changes *minimally disruptive*: adding or removing
    one replica of N remaps ~1/N of the doc space and nothing else.
  * **Overrides.** Live migration moves a doc OFF its hash owner, so
    placement consults a ``{doc: replica}`` overrides map first.  The
    map stays small (only migrated docs) and an override is dropped
    automatically when its target leaves the ring.

Every mutation bumps ``version`` -- the ring version the replicas echo
in their healthz ``routing`` section and the ``WrongReplica`` envelope
carries, so a scrape can tell which replicas have seen the latest
placement.  Thread model: read-heavy (every routed frame calls
``owner()``), mutated only by membership/rebalance events; one lock
guards all state (`make static-check` enforces the annotations).
"""

import bisect
import hashlib
import struct
import threading

from ..utils.common import doc_key, env_int


def _hash64(key):
    """Stable 64-bit ring coordinate (first 8 bytes of sha1)."""
    digest = hashlib.sha1(key.encode('utf-8')).digest()
    return struct.unpack('>Q', digest[:8])[0]


class HashRing(object):
    """Versioned consistent-hash ring with virtual nodes + overrides."""

    def __init__(self, members=(), vnodes=None):
        if vnodes is None:
            vnodes = env_int('AMTPU_ROUTE_VNODES', 64)
        self.vnodes = max(1, int(vnodes))
        self._lock = threading.Lock()
        self.version = 0          # guarded-by: self._lock
        self._members = set()     # guarded-by: self._lock
        self._points = []         # guarded-by: self._lock
        self._owners = []         # guarded-by: self._lock
        self._overrides = {}      # guarded-by: self._lock
        for m in members:
            self.add(m)

    def _rebuild(self):  # holds-lock: self._lock
        pts = []
        for m in self._members:
            for k in range(self.vnodes):
                pts.append((_hash64('%s#%d' % (m, k)), m))
        pts.sort()
        self._points = [p for p, _m in pts]
        self._owners = [m for _p, m in pts]

    def add(self, member):
        """Adds a replica (idempotent); bumps the ring version."""
        with self._lock:
            if member in self._members:
                return self.version
            self._members.add(member)
            self._rebuild()
            self.version += 1
            return self.version

    def add_pinned(self, member, placements):
        """Membership add + override batch in ONE atomic version bump:
        a (re)joining member must not implicitly remap docs that live
        elsewhere -- a request routed to the empty joiner would CREATE
        a fresh doc and fork history.  The caller pins every known doc
        to its pre-join owner (`placements`); pins matching the post
        -join hash owner drop (nothing remapped there), the rest hold
        the doc where its state is until the rebalancer migrates it
        over for real."""
        with self._lock:
            if member not in self._members:
                self._members.add(member)
                self._rebuild()
            self._apply_overrides(placements)
            self.version += 1
            return self.version

    def remove(self, member):
        """Removes a replica and every override pointing at it (its
        docs fall back to hash ownership); bumps the ring version."""
        with self._lock:
            if member not in self._members:
                return self.version
            self._members.discard(member)
            self._rebuild()
            for d in [d for d, m in self._overrides.items()
                      if m == member]:
                self._overrides.pop(d, None)
            self.version += 1
            return self.version

    def members(self):
        with self._lock:
            return sorted(self._members)

    def owner(self, doc):
        """The replica that owns `doc` (overrides first, then the first
        ring point clockwise of the doc's hash); None on an empty
        ring."""
        key = doc_key(doc)
        with self._lock:
            got = self._overrides.get(key)
            if got is not None:
                return got
            if not self._points:
                return None
            i = bisect.bisect_right(self._points, _hash64(key))
            if i >= len(self._points):
                i = 0
            return self._owners[i]

    def hash_owner(self, doc):
        """Pure hash placement, ignoring overrides (what `doc` falls
        back to if its override is dropped)."""
        key = doc_key(doc)
        with self._lock:
            if not self._points:
                return None
            i = bisect.bisect_right(self._points, _hash64(key))
            if i >= len(self._points):
                i = 0
            return self._owners[i]

    def _apply_overrides(self, placements):  # holds-lock: self._lock
        for doc, member in placements.items():
            key = doc_key(doc)
            i = bisect.bisect_right(self._points, _hash64(key)) \
                if self._points else 0
            home = self._owners[i % len(self._owners)] \
                if self._owners else None
            if member == home:
                self._overrides.pop(key, None)
            else:
                self._overrides[key] = member

    def set_overrides(self, placements):
        """Records migrated placements ({doc: replica}); an override
        matching the doc's hash owner is dropped instead of stored (the
        doc went home).  One version bump for the whole batch."""
        with self._lock:
            self._apply_overrides(placements)
            self.version += 1
            return self.version

    def overrides(self):
        with self._lock:
            return dict(self._overrides)

    def set_version_floor(self, version):
        """Monotonic floor for the membership epoch: the router's
        placement journal restores it across a restart, so a rebooted
        router never hands out an epoch older than the failovers it
        already committed (replicas compare epochs to spot stale
        placement)."""
        with self._lock:
            if int(version) > self.version:
                self.version = int(version)
            return self.version

    def stats(self):
        with self._lock:
            return {'version': self.version,
                    'members': sorted(self._members),
                    'vnodes': self.vnodes,
                    'overrides': len(self._overrides)}
