"""RouterGateway: the fleet's front door (ISSUE 18, docs/SERVING.md
routing section).

Speaks the sidecar's existing JSONL / length-prefixed-msgpack framing
on a unix socket, so ``SidecarClient`` connects to a router exactly as
it would to a single replica -- and behind it N shared-nothing
gateway+pool replicas split the doc space on a consistent-hash ring
(:mod:`automerge_tpu.router.ring`).

Data path (zero re-encode where it matters):

  * One reader thread per client connection decodes each frame ONLY to
    route it; the frame's **raw bytes** forward to the owner replica
    verbatim, and the replica's response / fan-out frames stream back
    verbatim through the client's bounded egress queue
    (:mod:`automerge_tpu.scheduler.egress` -- the same
    shed/resync/evict tiers as a replica's own connections).  Proxied
    single-owner traffic is therefore byte-identical to connecting to
    the replica directly.
  * Per (client connection, replica) the router keeps one dedicated
    upstream socket with a pump thread, so request ids pass through
    untranslated (each replica sees only this client's ids) and
    responses demultiplex trivially.
  * Requests spanning owners (a cross-owner ``apply_batch``, doc-set
    subscribe, or wildcard ``prefix`` subscribe) split into per-owner
    sub-requests under router-private ids and re-join into one
    response envelope under the original id.
  * ``ping/healthz/metrics/dump`` answer from the ROUTER process
    (its own telemetry, including the ``routing`` healthz section).

Migration safety (the part that makes live rebalancing lossless): the
executor parks a migrating doc's frames in a per-doc FIFO here, drains
the in-flight ops, and only then runs migrate_out/migrate_in -- see
:mod:`automerge_tpu.router.rebalance`.  Replicas answering a stale op
with the typed ``WrongReplica`` envelope get it re-forwarded to the
named owner (bounded by ``AMTPU_ROUTE_REDIRECTS``), and the envelope
teaches the ring the doc's true placement.

Failover (ISSUE 19, docs/RESILIENCE.md fleet degradation tiers): with
a :class:`~automerge_tpu.router.health.HealthMonitor` attached, an
unplanned replica death degrades instead of failing -- mutating frames
for a *suspect* member's docs park in the same per-doc FIFOs (bounded
by ``AMTPU_FLEET_PARK_MB`` / ``AMTPU_FLEET_PARK_S``), a *dead*
member's docs are re-placed onto survivors by the
:class:`~automerge_tpu.router.failover.FailoverExecutor` and the parks
replay to the new owners, and anything unrecoverable answers the typed
``ReplicaFailed`` envelope.  In-flight requests on a died upstream
answer the retryable ``ReplicaUnavailable`` envelope (read-only ones
park for one transparent post-failover retry instead).  Placement
survives a ROUTER restart through a small journal
(``journal_path``): membership + epoch + overrides, rewritten
atomically on every change, so a reboot never resurrects a dead
member's stale placement.
"""

import json
import os
import socket
import struct
import sys
import threading
import time

from .. import faults, telemetry
from ..scheduler.egress import EgressQueue
from ..scheduler.gateway import (BATCH_CMDS, EXEC_CMDS, FANOUT_CMDS,
                                 PURE_CMDS, ROUTER_CMDS, _op_docs)
from ..scheduler.queue import READ_CMDS
from ..sidecar.client import SidecarClient
from ..utils.common import doc_key, env_float, env_int
from .ring import HashRing

#: commands the router places by doc (everything the replica gateway
#: itself routes through `_op_docs`)
ROUTED_CMDS = BATCH_CMDS + EXEC_CMDS + FANOUT_CMDS + READ_CMDS

#: commands that mutate doc state -- the ones fleet-parked while their
#: owner is suspect (reads still forward: the process may well answer)
MUTATING_CMDS = BATCH_CMDS + EXEC_CMDS

#: the wildcard pseudo-doc prefix `_op_docs` mints for prefix
#: subscriptions -- routed by broadcast, never by hash
_PREFIX_KEY = 'prefix\x00'


def _is_prefix_key(doc):
    return isinstance(doc, str) and doc.startswith(_PREFIX_KEY)


class _Upstream(object):
    """One dedicated socket from a client connection to one replica:
    raw frames go up verbatim; a pump thread streams every frame the
    replica emits (responses AND fan-out events) back into the client
    connection's router-side demux."""

    def __init__(self, rconn, replica_id, sock_path):
        self.rconn = rconn
        self.replica_id = replica_id
        self.closed = False
        self._w_lock = threading.Lock()
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.connect(sock_path)
        self.rfile = self.sock.makefile('rb')
        self._thread = threading.Thread(
            target=self._pump,
            name='amtpu-router-up-%d-%s' % (rconn.cid, replica_id),
            daemon=True)
        self._thread.start()

    def send_raw(self, frame):
        with self._w_lock:
            self.sock.sendall(frame)

    def _pump(self):
        try:
            if self.rconn.router.use_msgpack:
                import msgpack
                while True:
                    head = self.rfile.read(4)
                    if len(head) < 4:
                        break
                    (n,) = struct.unpack('>I', head)
                    body = self.rfile.read(n)
                    if len(body) < n:
                        break
                    resp = msgpack.unpackb(body, raw=False,
                                           strict_map_key=False)
                    self.rconn.router._on_upstream(
                        self.rconn, self.replica_id, head + body, resp)
            else:
                for line in self.rfile:
                    if not line.strip():
                        continue
                    resp = json.loads(line)
                    self.rconn.router._on_upstream(
                        self.rconn, self.replica_id, line, resp)
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            self.close()
            self.rconn._upstream_dead(self.replica_id)

    def close(self):
        self.closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.rfile.close()
        except Exception:
            pass
        try:
            self.sock.close()
        except Exception:
            pass


class _RouterConn(object):
    """One accepted client connection: reader thread + bounded egress
    (every outbound frame stages; the writer thread drains), plus this
    connection's upstream sockets and pending-request table."""

    def __init__(self, sock, router, cid):
        self.sock = sock
        self.router = router
        self.cid = cid
        self.rfile = sock.makefile('rb')
        self.closed = False
        self.egress = EgressQueue(sock, label='router-conn-%d' % cid,
                                  on_overflow=self._egress_overflow,
                                  on_dead=self._egress_dead)
        self._lock = threading.Lock()
        self.upstreams = {}   # guarded-by: self._lock
        self.pending = {}     # guarded-by: self._lock
        self._sidx = 0        # guarded-by: self._lock

    # -- outbound ------------------------------------------------------

    def stage_raw(self, frame, kind='response'):
        if not self.closed:
            self.egress.stage(frame, kind=kind)

    def send_obj(self, obj, kind='response'):
        if self.closed:
            return
        try:
            frame = self.router._encode_frame(obj)
        except (TypeError, ValueError):
            return
        self.egress.stage(frame, kind=kind)

    def mint_sid(self):
        """Router-private sub-request id for split-join fan-out --
        a namespace client ids (ints, or any string a client picks)
        cannot collide with."""
        with self._lock:
            self._sidx += 1
            return '__amtpu_r:%d' % self._sidx

    # -- upstream management -------------------------------------------

    def upstream(self, replica_id):
        """The (lazily created) dedicated socket to `replica_id`."""
        with self._lock:
            up = self.upstreams.get(replica_id)
            if up is not None and not up.closed:
                return up
        up = _Upstream(self, replica_id,
                       self.router.replicas[replica_id])
        with self._lock:
            cur = self.upstreams.get(replica_id)
            if cur is not None and not cur.closed:
                up.close()          # lost the creation race
                return cur
            self.upstreams[replica_id] = up
        return up

    def _upstream_dead(self, replica_id):
        """A replica connection died mid-stream: the health machine is
        told (transport death suspects the member immediately), then
        every pending request routed there answers the RETRYABLE typed
        ``ReplicaUnavailable`` envelope (the op may not have executed;
        re-sending is exactly-once under seq-dedup, so the client's
        retry path -- not a silent drop -- decides).  Read-only
        requests park instead for ONE transparent retry once the
        failover (or recovery) re-places their docs.  The next frame
        for that replica reconnects lazily."""
        with self._lock:
            self.upstreams.pop(replica_id, None)
            dead = [(rid, e) for rid, e in self.pending.items()
                    if e['replica'] == replica_id]
            for rid, _e in dead:
                self.pending.pop(rid, None)
        if self.closed or self.router._stopping:
            return
        self.router._note_transport_death(replica_id)
        for _rid, entry in dead:
            telemetry.metric('router.upstream_errors')
            if self.router._park_read_retry(self, entry, replica_id):
                continue
            self.router._answer_entry(
                self, entry, self.router._replica_unavailable(
                    replica_id))

    # -- reader --------------------------------------------------------

    def run(self):
        try:
            if self.router.use_msgpack:
                self._run_msgpack()
            else:
                self._run_jsonl()
        except (BrokenPipeError, ConnectionError, OSError, ValueError):
            pass
        finally:
            self.close()
            self.router._conn_gone(self)

    def _run_jsonl(self):
        for line in self.rfile:
            if not line.strip():
                continue
            try:
                req = json.loads(line)
                if not isinstance(req, dict):
                    raise ValueError('request is not a map')
            except ValueError as e:
                self.send_obj({'id': None, 'error': 'bad json: %s' % e,
                               'errorType': 'RangeError'})
                continue
            self.router.route(self, line, req)

    def _run_msgpack(self):
        import msgpack
        while True:
            head = self.rfile.read(4)
            if len(head) < 4:
                break
            (n,) = struct.unpack('>I', head)
            body = self.rfile.read(n)
            if len(body) < n:
                break
            try:
                req = msgpack.unpackb(body, raw=False,
                                      strict_map_key=False)
                if not isinstance(req, dict):
                    raise ValueError('request is not a map')
            except Exception as e:
                self.send_obj({'id': None,
                               'error': 'bad msgpack: %s' % e,
                               'errorType': 'RangeError'})
                continue
            self.router.route(self, head + body, req)

    def _egress_overflow(self, _queue):
        """Tier-2 drop-to-resubscribe, router edition: tell the slow
        client to resync; its auto-resubscribe lands on the current
        owners through this same router."""
        docs = self.router._conn_sub_docs(self)
        telemetry.metric('egress.resyncs')
        self.send_obj({'event': 'resync', 'docs': docs,
                       'reason': 'slow-consumer', 'retryAfterMs': 100})

    def _egress_dead(self, reason):
        if reason == 'wedge':
            print('router: evicting wedged consumer conn-%d'
                  % self.cid, file=sys.stderr)
        self.close()
        self.router._conn_gone(self)

    def close(self):
        self.closed = True
        self.egress.close()
        with self._lock:
            ups = list(self.upstreams.values())
            self.upstreams.clear()
            self.pending.clear()
        for up in ups:
            up.close()
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.rfile.close()
        except Exception:
            pass
        try:
            self.sock.close()
        except Exception:
            pass


class RouterGateway(object):
    """Unix-socket fleet router over N replica gateways.

    `replicas` is ``{replica_id: replica_sock_path}`` (or an iterable
    of pairs) -- the membership seed a deployment derives from its
    fleet scrape (`telemetry/fleet.py`).  Embeddable like
    GatewayServer: ``start()`` returns, ``stop()`` tears down.
    """

    def __init__(self, sock_path, replicas, use_msgpack=False,
                 backlog=128, vnodes=None, journal_path=None):
        self.sock_path = sock_path
        self.use_msgpack = use_msgpack
        self.replicas = dict(replicas)
        self._vnodes = vnodes
        self.ring = HashRing(self.replicas, vnodes=vnodes)
        self.max_redirects = env_int('AMTPU_ROUTE_REDIRECTS', 3)
        self._srv = None
        self._accept_thread = None
        self._stopping = False
        self._conns = {}
        self._conns_lock = threading.Lock()
        self._next_cid = 0
        # migration parking + subscription registry (ISSUE 18): a doc
        # present in `_migrating` holds a FIFO of frames to re-route
        # once the move lands; `_subs` tracks which client connections
        # subscribed to which docs so a completed migration can stage
        # the handoff resync envelope
        self._park_lock = threading.Lock()
        self._migrating = {}      # guarded-by: self._park_lock
        self._subs = {}           # guarded-by: self._park_lock
        # fleet failover (ISSUE 19): `_park_meta` rides the SAME FIFOs
        # as migration parking but tags each fleet-parked doc with its
        # suspect member + park clock + byte share, so the health
        # sweep can expire and the failover executor can replay/fail
        # exactly the right queues
        self._park_meta = {}      # guarded-by: self._park_lock
        self._park_bytes = 0      # guarded-by: self._park_lock
        self.park_s = env_float('AMTPU_FLEET_PARK_S', 10.0)
        self.park_bytes_max = \
            env_int('AMTPU_FLEET_PARK_MB', 8) * (1 << 20)
        self._health = None       # HealthMonitor.start() attaches
        self.journal_path = journal_path
        # membership mutators (add/remove_member) serialize here and
        # replace `self.replicas` copy-on-write, so lock-free readers
        # (dispatch, the health prober) always see a coherent dict
        self._members_lock = threading.Lock()
        # router-owned control clients, one per replica (migrate/healthz
        # RPCs -- never the data path)
        self._control_lock = threading.Lock()
        self._control = {}        # guarded-by: self._control_lock

    # -- lifecycle ------------------------------------------------------

    def start(self):
        self._load_journal()
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(self.sock_path)
        self._srv.listen(128)
        telemetry.register_healthz_section('routing',
                                           self._routing_section)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name='amtpu-router-accept',
            daemon=True)
        self._accept_thread.start()
        return self

    def stop(self):
        self._stopping = True
        srv, self._srv = self._srv, None
        if srv is not None:
            try:
                srv.close()
            except Exception:
                pass
        if os.path.exists(self.sock_path):
            try:
                os.unlink(self.sock_path)
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns.values())
        for conn in conns:
            conn.close()
        with self._control_lock:
            controls = list(self._control.values())
            self._control.clear()
        for cli in controls:
            try:
                cli.close()
            except Exception:
                pass
        telemetry.register_healthz_section('routing', None)

    def _accept_loop(self):
        while not self._stopping:
            try:
                sock, _ = self._srv.accept()
            except OSError:
                break
            with self._conns_lock:
                self._next_cid += 1
                conn = _RouterConn(sock, self, self._next_cid)
                self._conns[conn.cid] = conn
            threading.Thread(target=conn.run,
                             name='amtpu-router-conn-%d' % conn.cid,
                             daemon=True).start()

    def _conn_gone(self, conn):
        with self._conns_lock:
            self._conns.pop(conn.cid, None)
        with self._park_lock:
            for d in list(self._subs):
                self._subs[d].pop(conn, None)
                if not self._subs[d]:
                    del self._subs[d]

    def _encode_frame(self, obj):
        if self.use_msgpack:
            import msgpack
            body = msgpack.packb(obj, use_bin_type=True)
            return struct.pack('>I', len(body)) + body
        return (json.dumps(obj) + '\n').encode()

    # -- request routing ------------------------------------------------

    def route(self, conn, raw, req):
        """Places one decoded client frame: local answer (pure cmds),
        forward to the owner replica, split across owners, or park
        behind a live migration."""
        cmd = req.get('cmd')
        rid = req.get('id')
        if cmd in PURE_CMDS:
            telemetry.metric('router.local')
            conn.send_obj(self._pure(cmd, rid))
            return
        if cmd in ROUTER_CMDS:
            # migration is the REBALANCER's control plane; a client
            # driving it through the router would race the parking
            # protocol
            conn.send_obj({'id': rid,
                           'error': '%s is replica control plane; '
                                    'drive migration through the '
                                    'rebalancer' % cmd,
                           'errorType': 'RangeError'})
            return
        docs = _op_docs(cmd, req)
        if docs is None:
            if cmd in ROUTED_CMDS:
                hint = " (subscribe/unsubscribe also accept 'docs' " \
                       "or 'prefix')" if cmd in FANOUT_CMDS else ''
                msg = "missing or invalid routing field: 'doc'%s" % hint
            else:
                msg = 'Unknown command: %r' % (cmd,)
            conn.send_obj({'id': rid, 'error': msg,
                           'errorType': 'RangeError'})
            return
        if cmd == 'subscribe':
            # registry rows keep the CLIENT's doc form next to the
            # canonical key, so a migration resync names the doc the
            # way the client subscribed to it
            with self._park_lock:
                for d in docs:
                    if not _is_prefix_key(d):
                        self._subs.setdefault(doc_key(d), {})[conn] = d
        elif cmd == 'unsubscribe':
            with self._park_lock:
                for d in docs:
                    subs = self._subs.get(doc_key(d))
                    if subs is not None:
                        subs.pop(conn, None)
                        if not subs:
                            del self._subs[doc_key(d)]
        self._dispatch(conn, raw, req, docs)

    def _dispatch(self, conn, raw, req, docs, attempts=0, exclude=()):
        """Park-check then forward.  `exclude` lets the release path
        skip the very doc being drained (still marked migrating) while
        honouring parks on OTHER docs.  Park keys are canonical
        (`doc_key`): the rebalancer names victims by the pool's doc
        keys while clients may use raw ids, and both must collide
        here."""
        keys = tuple(doc_key(d) for d in docs)
        with self._park_lock:
            mig = next((k for k in keys
                        if k in self._migrating and k not in exclude),
                       None)
            if mig is not None:
                self._migrating[mig].append((conn, raw, req))
                telemetry.metric('router.parked')
                return
        owners = {}
        if len(docs) == 1 and _is_prefix_key(docs[0]):
            # wildcard subscription: every replica owns part of the
            # prefix space, so the request broadcasts and the backfills
            # merge
            for r in self.replicas:
                owners[r] = []
        else:
            for d in docs:
                owners.setdefault(self.ring.owner(d), []).append(d)
        if not owners or None in owners:
            conn.send_obj({'id': req.get('id'),
                           'error': 'no replicas on the ring',
                           'errorType': 'InternalError'})
            return
        if len(owners) == 1:
            owner = next(iter(owners))
            if self._health is not None \
                    and req.get('cmd') in MUTATING_CMDS \
                    and self._health.is_parking(owner):
                # suspect owner (ISSUE 19): hold the mutation in the
                # doc's FIFO -- a recovery releases it unchanged, a
                # failover replays it at the new owner.  Past the park
                # budget the retryable envelope answers instead.
                if self._fleet_park(owner, keys[0], conn, raw, req):
                    telemetry.metric('router.health.parked')
                    return
                telemetry.metric('router.health.park_overflow')
                conn.send_obj(self._replica_unavailable(
                    owner, rid=req.get('id')))
                return
            self._forward(conn, owner, raw, req, docs,
                          attempts=attempts)
        else:
            self._split(conn, req, owners)

    def _forward(self, conn, replica, raw, req, docs, attempts=0,
                 join=None):
        rid = req.get('id')
        entry = {'raw': raw, 'req': req,
                 'docs': tuple(doc_key(d) for d in docs),
                 'replica': replica, 'attempts': attempts,
                 'join': join, 'rid': rid}
        if rid is not None:
            with conn._lock:
                conn.pending[rid] = entry
        try:
            if faults.ARMED:
                # chaos site (docs/RESILIENCE.md): a fired fault takes
                # the same exit as a dead upstream socket below
                faults.fire('router.forward', docs=entry['docs'])
            conn.upstream(replica).send_raw(raw)
            telemetry.metric('router.requests')
        except (OSError, KeyError, faults.InjectedFault) as e:
            if rid is not None:
                with conn._lock:
                    conn.pending.pop(rid, None)
            telemetry.metric('router.upstream_errors')
            self._answer_entry(conn, entry, self._replica_unavailable(
                replica, detail=str(e)))

    def _split(self, conn, req, owners):
        """Cross-owner fan-out: per-owner sub-requests under router
        -private ids, re-joined into ONE response under the client's
        id.  (Split responses re-encode; byte-parity is a single-owner
        property.)"""
        telemetry.metric('router.split_ops')
        cmd = req.get('cmd')
        join = {'rid': req.get('id'), 'cmd': cmd, 'want': len(owners),
                'results': [], 'errors': []}
        parts = []
        for owner, ds in owners.items():
            sub = dict(req)
            sub['id'] = conn.mint_sid()
            if cmd == 'apply_batch':
                sub['docs'] = {d: req['docs'][d] for d in ds}
            elif ds and isinstance(req.get('docs'), list):
                sub['docs'] = list(ds)
            parts.append((owner, sub))
        for owner, sub in parts:
            self._forward(conn, owner, self._encode_frame(sub), sub,
                          _op_docs(cmd, sub) or (), join=join)

    def _pure(self, cmd, rid):
        """ping/healthz/metrics/dump answered from the ROUTER process
        -- its healthz carries the `routing` section (ring version,
        members, live migrations), which is what the fleet scrape
        gossips."""
        from ..telemetry import httpd as telemetry_httpd
        if cmd == 'ping':
            return {'id': rid, 'result': {'ok': True}}
        if cmd == 'healthz':
            return {'id': rid, 'result': telemetry.healthz()}
        if cmd == 'metrics':
            return {'id': rid, 'result': {
                'contentType': telemetry_httpd.CONTENT_TYPE,
                'body': telemetry.render_prometheus()}}
        out = telemetry.recorder.dump('request', force=True) \
            or {'path': None, 'events': 0, 'reason': 'request'}
        return {'id': rid, 'result': out}

    # -- upstream demux --------------------------------------------------

    def _on_upstream(self, conn, replica_id, raw, resp):
        """One frame from a replica on `conn`'s upstream: fan-out
        events pass through verbatim; responses resolve the pending
        entry (redirect on WrongReplica, join for splits, else raw
        pass-through)."""
        if not isinstance(resp, dict) or 'event' in resp:
            conn.stage_raw(raw, kind='event')
            return
        rid = resp.get('id')
        entry = None
        if rid is not None:
            with conn._lock:
                entry = conn.pending.pop(rid, None)
        if entry is None:
            conn.stage_raw(raw)
            return
        if resp.get('errorType') == 'WrongReplica':
            owner = resp.get('owner')
            if owner in self.replicas \
                    and entry['attempts'] < self.max_redirects:
                # the replica knows better than our ring: re-forward
                # the ORIGINAL raw frame to the named owner (the op was
                # not executed, so this is exactly-once), and teach the
                # ring so the next frame routes straight there
                telemetry.metric('router.redirects')
                if len(entry['docs']) == 1:
                    self.ring.set_overrides(
                        {entry['docs'][0]: owner})
                self._forward(conn, owner, entry['raw'], entry['req'],
                              entry['docs'],
                              attempts=entry['attempts'] + 1,
                              join=entry['join'])
                return
        self._answer_entry(conn, entry, resp, raw=raw)

    def _answer_entry(self, conn, entry, resp, raw=None):
        """Completes one pending entry: a split part feeds its join; a
        plain forward passes the replica's frame through verbatim (or
        re-encodes the synthesized envelope under the original id)."""
        if entry.get('join') is not None:
            self._join_step(conn, entry['join'], resp)
            return
        if raw is not None:
            conn.stage_raw(raw)
            return
        out = dict(resp)
        out['id'] = entry.get('rid')
        conn.send_obj(out)

    def _join_step(self, conn, join, resp):
        with conn._lock:
            if 'error' in resp:
                join['errors'].append(resp)
            else:
                join['results'].append(resp.get('result'))
            join['want'] -= 1
            done = join['want'] <= 0
        if not done:
            return
        if join['errors']:
            err = join['errors'][0]
            out = {'id': join['rid'], 'error': err.get('error'),
                   'errorType': err.get('errorType', 'InternalError')}
            for k in ('retryAfterMs', 'owner', 'ringVersion'):
                if k in err:
                    out[k] = err[k]
        else:
            out = {'id': join['rid'],
                   'result': self._merge_results(join['cmd'],
                                                 join['results'])}
        conn.send_obj(out)

    @staticmethod
    def _merge_results(cmd, results):
        if cmd == 'apply_batch':
            out = {}
            for r in results:
                if isinstance(r, dict):
                    out.update(r)
            return out
        if cmd == 'unsubscribe':
            return {'ok': True,
                    'removed': sum(int((r or {}).get('removed') or 0)
                                   for r in results
                                   if isinstance(r, dict))}
        # subscribe (doc-set / prefix): merge the per-doc backfills,
        # keep the first part's scalar fields
        out, per_doc = {}, {}
        for r in results:
            if not isinstance(r, dict):
                continue
            if isinstance(r.get('docs'), dict):
                per_doc.update(r['docs'])
            for k, v in r.items():
                if k != 'docs':
                    out.setdefault(k, v)
        out['docs'] = per_doc
        return out

    # -- migration support (rebalance.py drives these) -------------------

    def begin_migration(self, docs):
        """Marks docs migrating: every new frame touching them parks in
        arrival order until `end_migration`."""
        with self._park_lock:
            for d in docs:
                self._migrating.setdefault(doc_key(d), [])

    def pending_on_docs(self, docs):
        """Frames forwarded to replicas and not yet answered that touch
        `docs` -- the executor drains this to zero (replicas still own
        the docs, so in-flight ops complete normally) before issuing
        migrate_out."""
        docset = set(doc_key(d) for d in docs)
        n = 0
        with self._conns_lock:
            conns = list(self._conns.values())
        for c in conns:
            with c._lock:
                n += sum(1 for e in c.pending.values()
                         if any(d in docset for d in e['docs']))
        return n

    def drain_docs(self, docs, timeout_s=30.0):
        deadline = time.monotonic() + timeout_s
        while self.pending_on_docs(docs):
            if time.monotonic() > deadline:
                return False
            time.sleep(0.002)
        return True

    def end_migration(self, docs):
        """Releases each doc's parked FIFO in order, then unmarks it.
        Frames arriving DURING the release still append to the FIFO
        (the doc stays marked until its queue is observed empty under
        the lock), so claim order is never inverted.  Returns the
        number of frames released (the failover replay accounting)."""
        released = 0
        for d in docs:
            key = doc_key(d)
            while True:
                with self._park_lock:
                    q = self._migrating.get(key)
                    if q is None:
                        break
                    if not q:
                        del self._migrating[key]
                        self._drop_park_meta(key)
                        break
                    conn, raw, req = q.pop(0)
                if conn.closed:
                    continue
                released += 1
                dcs = _op_docs(req.get('cmd'), req) or ()
                self._dispatch(conn, raw, req, dcs, exclude=(key,))
        return released

    def notify_migrated(self, docs, reason='migrated'):
        """Stages the typed resync envelope to every connection
        subscribed to a migrated doc: the client's auto-resubscribe
        re-issues the subscription at its last-seen clock, which this
        router then routes to the NEW owner -- the subscription stream
        hands off without the client changing.  Failover passes
        ``reason='failover'`` (same recovery path, the envelope just
        says why)."""
        with self._park_lock:
            targets = {}
            for d in docs:
                for conn, orig in self._subs.get(doc_key(d),
                                                 {}).items():
                    targets.setdefault(conn, []).append(orig)
        for conn, ds in targets.items():
            if conn.closed:
                continue
            telemetry.metric('router.resyncs', len(ds))
            conn.send_obj({'event': 'resync', 'docs': ds,
                           'reason': reason})

    def _conn_sub_docs(self, conn):
        with self._park_lock:
            return sorted((subs[conn] for subs in self._subs.values()
                           if conn in subs), key=str)

    def subscribed_doc_keys(self):
        """Canonical keys of every doc any live connection is
        subscribed to (the failover executor resyncs the subset the
        dead member owned)."""
        with self._park_lock:
            return sorted(self._subs)

    # -- fleet membership + failover (ISSUE 19) --------------------------

    def attach_health(self, monitor):
        """HealthMonitor.start()/stop() wire themselves here; with no
        monitor attached the fleet-park and read-retry paths are
        inert and the router behaves exactly as PR 18 shipped it."""
        self._health = monitor

    def add_member(self, member, sock_path, pins=None):
        """Joins one replica to the membership + ring (copy-on-write,
        journalled).  A supervised respawn rejoins through this as a
        NEW member id; `pins` ({doc: current_owner}, typically
        `FailoverExecutor.join_pins()`) holds every known doc at its
        pre-join owner so the join remaps nothing implicitly -- the
        rebalancer drains docs onto the joiner via real migrations."""
        with self._members_lock:
            replicas = dict(self.replicas)
            replicas[member] = sock_path
            self.replicas = replicas
            if pins:
                self.ring.add_pinned(member, pins)
            else:
                self.ring.add(member)
            self._save_journal()

    def remove_member(self, member):
        """Drops one replica from the membership + ring (its overrides
        fall home), closes its cached control client, and journals the
        new epoch."""
        with self._members_lock:
            replicas = dict(self.replicas)
            replicas.pop(member, None)
            self.replicas = replicas
            self.ring.remove(member)
            self._save_journal()
        with self._control_lock:
            cli = self._control.pop(member, None)
        if cli is not None:
            try:
                cli.close()
            except Exception:
                pass

    def _note_transport_death(self, member):
        if self._health is not None:
            self._health.note_transport_death(member)

    def _replica_unavailable(self, member, rid=None, detail=None):
        """The retryable envelope for a member the router cannot reach
        right now (satellite of ISSUE 19): by ``retryAfterMs`` the
        health machine has either recovered it or failed it over."""
        retry_ms = 100
        if self._health is not None:
            retry_ms = max(retry_ms, int(1000 * self._health.deadline_s))
        return {'id': rid,
                'error': 'replica %r unavailable%s; retry'
                         % (member,
                            ' (%s)' % detail if detail else ''),
                'errorType': 'ReplicaUnavailable',
                'retryAfterMs': retry_ms}

    @staticmethod
    def _replica_failed(member, doc, rid=None):
        """The terminal per-doc envelope: the member died and failover
        could not recover this doc from anything durable."""
        return {'id': rid,
                'error': 'doc %r lost with replica %r (nothing '
                         'durable to restore)' % (doc, member),
                'errorType': 'ReplicaFailed', 'doc': doc}

    def _fleet_park(self, member, key, conn, raw, req):
        """Parks one frame in `key`'s FIFO on behalf of a suspect/dead
        `member`; False when the byte budget is exhausted (the caller
        answers the retryable envelope instead)."""
        with self._park_lock:
            if self._park_bytes + len(raw) > self.park_bytes_max:
                return False
            self._migrating.setdefault(key, []).append(
                (conn, raw, req))
            meta = self._park_meta.setdefault(
                key, {'since': time.monotonic(), 'bytes': 0,
                      'member': member})
            meta['bytes'] += len(raw)
            self._park_bytes += len(raw)
        return True

    def _park_read_retry(self, conn, entry, member):
        """A read-only request whose upstream died parks for ONE
        transparent retry after the failover (or recovery) re-places
        its doc -- the client never sees the blip.  Anything already
        retried, split, or doc-less answers the envelope instead."""
        if self._health is None \
                or entry['req'].get('cmd') not in READ_CMDS \
                or entry['attempts'] > 0 \
                or entry.get('join') is not None \
                or len(entry['docs']) != 1:
            return False
        if not self._health.is_parking(member):
            return False
        if not self._fleet_park(member, entry['docs'][0], conn,
                                entry['raw'], entry['req']):
            return False
        telemetry.metric('failover.retried_reads')
        return True

    def _drop_park_meta(self, key):  # holds-lock: self._park_lock
        meta = self._park_meta.pop(key, None)
        if meta is not None:
            self._park_bytes -= meta['bytes']

    def parked_docs_for(self, member):
        """Doc keys currently fleet-parked on behalf of `member`, in
        park order (the failover executor's replay/fail worklist)."""
        with self._park_lock:
            got = [(meta['since'], key)
                   for key, meta in self._park_meta.items()
                   if meta['member'] == member]
        return [key for _t, key in sorted(got)]

    def release_member_parks(self, member):
        """A suspect member recovered: replay its parked frames to it,
        in arrival order, unchanged."""
        return self.release_parked(self.parked_docs_for(member))

    def release_parked(self, docs):
        """Replays parked FIFOs through normal dispatch (post-failover
        the ring now names the new owners).  Returns frames released."""
        return self.end_migration(docs)

    def fail_parked(self, docs, member):
        """Flushes parked FIFOs with the terminal ``ReplicaFailed``
        envelope -- the docs were on `member` and nothing durable
        could restore them.  Returns frames answered."""
        failed = 0
        for key in docs:
            while True:
                with self._park_lock:
                    q = self._migrating.get(key)
                    if q is None:
                        break
                    if not q:
                        del self._migrating[key]
                        self._drop_park_meta(key)
                        break
                    conn, _raw, req = q.pop(0)
                failed += 1
                if not conn.closed:
                    conn.send_obj(self._replica_failed(
                        member, key, rid=req.get('id')))
        return failed

    def sweep_parked(self):
        """Expires fleet parks older than ``AMTPU_FLEET_PARK_S`` with
        the retryable envelope (the health monitor calls this each
        tick): a wedged failover must not hold client frames hostage
        forever."""
        now = time.monotonic()
        with self._park_lock:
            expired = [(key, meta['member'])
                       for key, meta in self._park_meta.items()
                       if now - meta['since'] > self.park_s]
        for key, member in expired:
            while True:
                with self._park_lock:
                    q = self._migrating.get(key)
                    if q is None:
                        break
                    if not q:
                        del self._migrating[key]
                        self._drop_park_meta(key)
                        break
                    conn, _raw, req = q.pop(0)
                telemetry.metric('router.health.park_expired')
                if not conn.closed:
                    conn.send_obj(self._replica_unavailable(
                        member, rid=req.get('id')))

    def park_stats(self):
        with self._park_lock:
            return {'parked_docs': len(self._park_meta),
                    'parked_bytes': self._park_bytes}

    # -- placement journal (ISSUE 19 satellite) --------------------------

    def _save_journal(self):
        """Atomically rewrites the placement journal: membership (with
        socket paths), epoch, overrides.  Cheap (one small JSON) and
        only on membership/placement changes, never the data path."""
        if self.journal_path is None:
            return
        data = {'epoch': self.ring.version,
                'members': dict(self.replicas),
                'overrides': self.ring.overrides()}
        tmp = self.journal_path + '.tmp'
        try:
            with open(tmp, 'w') as f:
                json.dump(data, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.journal_path)
        except OSError as e:
            print('router: journal write failed: %s' % e,
                  file=sys.stderr)

    def _load_journal(self):
        """Restores journalled placement at start(): the journal's
        membership REPLACES the constructor seed (a member failed over
        before the restart must stay gone), overrides re-apply, and
        the epoch floors the ring version so it stays monotonic across
        the reboot."""
        if self.journal_path is None \
                or not os.path.exists(self.journal_path):
            return
        try:
            with open(self.journal_path) as f:
                data = json.load(f)
            members = data.get('members')
            if not isinstance(members, dict) or not members:
                raise ValueError('no members in journal')
        except (OSError, ValueError) as e:
            print('router: ignoring unreadable journal %r: %s'
                  % (self.journal_path, e), file=sys.stderr)
            return
        self.replicas = dict(members)
        self.ring = HashRing(self.replicas, vnodes=self._vnodes)
        overrides = data.get('overrides')
        if isinstance(overrides, dict) and overrides:
            self.ring.set_overrides(overrides)
        self.ring.set_version_floor(int(data.get('epoch') or 0))

    # -- control plane ---------------------------------------------------

    def control(self, replica):
        """The router-owned SidecarClient to one replica (lazy; the
        migrate/healthz control plane, never the data path)."""
        with self._control_lock:
            cli = self._control.get(replica)
            if cli is None:
                cli = SidecarClient(sock_path=self.replicas[replica],
                                    use_msgpack=self.use_msgpack)
                self._control[replica] = cli
            return cli

    def control_call(self, replica, cmd, **kwargs):
        """One control RPC with a single reconnect retry -- the cached
        client may predate a replica restart (SIGKILL recovery)."""
        try:
            return self.control(replica).call(cmd, **kwargs)
        except (ConnectionError, OSError):
            with self._control_lock:
                cli = self._control.pop(replica, None)
            if cli is not None:
                try:
                    cli.close()
                except Exception:
                    pass
            return self.control(replica).call(cmd, **kwargs)

    # -- observability ---------------------------------------------------

    def _routing_section(self):
        with self._park_lock:
            migrating = len(self._migrating)
            subscribed = len(self._subs)
        stats = self.ring.stats()
        flat = telemetry.metrics_snapshot()
        with self._conns_lock:
            conns = len(self._conns)
        return {'role': 'router',
                'replica_id': telemetry.replica_id(),
                'ring_version': stats['version'],
                'members': stats['members'],
                'vnodes': stats['vnodes'],
                'overrides': stats['overrides'],
                'connections': conns,
                'migrating_docs': migrating,
                'subscribed_docs': subscribed,
                'migrations': int(flat.get('migrate.migrations', 0)),
                'redirects': int(flat.get('router.redirects', 0))}
