"""Fleet replica supervisor (ISSUE 19).

The PR-4 self-healing sidecar pattern lifted to fleet scope: the
router spawns its replica server subprocesses, watches them, and
brings killed ones back -- while the health monitor + failover
executor keep the doc space serveable in between.

Lifecycle of one supervised member::

    spawn('r1')  ->  member 'r1'   (gen 0, socket + durable store
                                    provisioned under base_dir)
    SIGKILL      ->  monitor sees the exit -> health.mark_dead('r1')
                     -> failover drains r1's docs to survivors
    respawn      ->  member 'r1-g1' joins the ring as a NEW member
                     (capped-backoff, waits for the failover to
                     finish removing the old id first); the
                     Rebalancer's normal skew trigger then drains
                     docs back onto the empty rejoiner

A member id never rejoins under its old name: the ring treats
generations as distinct members, so stale WrongReplica owners and the
placement journal stay unambiguous.  A lineage that keeps dying
(``AMTPU_FLEET_FLAP_MAX`` deaths) is quarantined -- no further
respawns, the health entry renders ``quarantined`` -- because a
crash-looping replica re-absorbing its docs just loses them again.

Each spawned replica gets its own durable store
(``AMTPU_STORAGE_DIR=<base_dir>/store-<member>``,
``AMTPU_STORAGE_DURABLE=1``) with write-through checkpointing
(``AMTPU_STORAGE_SYNC=1``), so an ack always implies a restorable
blob -- the property the failover byte-parity gate
(`tools/failover_check.py`) rests on.
"""

import os
import subprocess
import sys
import threading
import time

from .. import telemetry
from ..utils.common import env_int

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


class ReplicaSupervisor(object):
    """Spawns, watches, and respawns replica server subprocesses.

    ``health`` / ``failover`` are the ISSUE-19 detection + recovery
    hooks; without them the supervisor still respawns (standalone
    supervision), but nothing re-places docs in the gap.
    """

    def __init__(self, router, base_dir, health=None, failover=None,
                 flap_max=None, spawn_env=None, spawn_deadline_s=60.0):
        self.router = router
        self.base_dir = base_dir
        self.health = health
        self.failover = failover
        self.flap_max = max(1, flap_max if flap_max is not None
                            else env_int('AMTPU_FLEET_FLAP_MAX', 3))
        self.spawn_env = dict(spawn_env or {})
        self.spawn_deadline_s = spawn_deadline_s
        self._lock = threading.Lock()
        self._procs = {}     # {member: Popen}    guarded-by: self._lock
        self._lineage = {}   # {base: deaths}     guarded-by: self._lock
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._run,
                                        name='amtpu-fleet-supervisor',
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        with self._lock:
            procs = dict(self._procs)
            self._procs.clear()
        for member, proc in procs.items():
            self._teardown(proc)

    @staticmethod
    def _teardown(proc):
        """terminate -> wait -> kill, the route_check/PR-4 teardown
        ladder -- never leave a replica orphaned."""
        try:
            proc.terminate()
        except OSError:
            pass
        try:
            proc.wait(timeout=10)
        except Exception:
            try:
                proc.kill()
            except OSError:
                pass
            try:
                proc.wait(timeout=10)
            except Exception:
                pass

    # -- spawning -------------------------------------------------------

    @staticmethod
    def _member_name(base, gen):
        return base if gen == 0 else '%s-g%d' % (base, gen)

    @staticmethod
    def _parse(member):
        base, sep, gen = member.rpartition('-g')
        if sep and gen.isdigit():
            return base, int(gen)
        return member, 0

    def spawn(self, base, gen=0):
        """Provisions + spawns one member, waits for its socket, joins
        it to the ring, and registers its durable store with the
        failover executor.  Returns the member id."""
        member = self._member_name(base, gen)
        sock_path = os.path.join(self.base_dir, member + '.sock')
        store_dir = os.path.join(self.base_dir, 'store-' + member)
        os.makedirs(store_dir, exist_ok=True)
        env = dict(os.environ)
        env.update(self.spawn_env)
        env.update({'AMTPU_REPLICA_ID': member,
                    'AMTPU_STORAGE_DIR': store_dir,
                    'AMTPU_STORAGE_DURABLE': '1',
                    'AMTPU_STORAGE_SYNC': '1',
                    'PYTHONPATH': REPO_ROOT + os.pathsep
                    + env.get('PYTHONPATH', '')})
        proc = subprocess.Popen(
            [sys.executable, '-m', 'automerge_tpu.sidecar.server',
             '--socket', sock_path],
            env=env, stdin=subprocess.DEVNULL)
        deadline = time.monotonic() + self.spawn_deadline_s
        while not os.path.exists(sock_path):
            if proc.poll() is not None or time.monotonic() > deadline:
                self._teardown(proc)
                raise RuntimeError('replica %r did not come up (rc=%s)'
                                   % (member, proc.returncode))
            time.sleep(0.02)
        with self._lock:
            self._procs[member] = proc
            self._lineage.setdefault(self._parse(member)[0], 0)
        # pin existing docs to their current owners BEFORE the store
        # registration, so the joiner's own (possibly stale, gen-1)
        # blobs never pin anything
        pins = self.failover.join_pins() \
            if self.failover is not None and gen else None
        if self.failover is not None:
            self.failover.register_store(member, store_dir)
        self.router.add_member(member, sock_path, pins=pins)
        if gen:
            telemetry.metric('failover.rejoins')
            telemetry.recorder.record('fleet.rejoin', doc=member,
                                      n=gen)
        return member

    def spawn_fleet(self, n, prefix='r'):
        return [self.spawn('%s%d' % (prefix, i)) for i in range(n)]

    def proc(self, member):
        with self._lock:
            return self._procs.get(member)

    # -- the watcher ----------------------------------------------------

    def _run(self):
        while not self._stop.wait(0.05):
            with self._lock:
                procs = list(self._procs.items())
            for member, proc in procs:
                if proc.poll() is None or self._stop.is_set():
                    continue
                with self._lock:
                    self._procs.pop(member, None)
                self._on_exit(member, proc.returncode)

    def _on_exit(self, member, rc):
        """Kill detection: feed the health machine (whose monitor
        thread runs the failover), then respawn a new generation once
        the old id has left the ring."""
        cause = 'exit rc=%s' % rc
        if self.health is not None:
            self.health.mark_dead(member, cause=cause)
        elif self.failover is not None:
            self.failover.fail_over(member)
        base, gen = self._parse(member)
        with self._lock:
            self._lineage[base] = self._lineage.get(base, 0) + 1
            deaths = self._lineage[base]
        if deaths > self.flap_max:
            telemetry.metric('failover.quarantined')
            if self.health is not None:
                self.health.quarantine(member)
            print('supervisor: %r quarantined after %d deaths '
                  '(AMTPU_FLEET_FLAP_MAX=%d)'
                  % (base, deaths, self.flap_max), file=sys.stderr)
            return
        # wait for the failover to remove the dead id (bounded): a
        # rejoiner added mid-failover would skew the re-placement
        deadline = time.monotonic() + 30.0
        while member in self.router.replicas \
                and time.monotonic() < deadline \
                and not self._stop.is_set():
            time.sleep(0.02)
        # capped-backoff respawn, scaled by the lineage's death count
        delay = min(0.1 * (2 ** (deaths - 1)), 2.0)
        if self._stop.wait(delay):
            return
        telemetry.metric('failover.respawns')
        try:
            self.spawn(base, gen + 1)
        except Exception as e:
            print('supervisor: respawn of %r failed: %s: %s'
                  % (base, type(e).__name__, e), file=sys.stderr)
