"""Fleet failover executor (ISSUE 19, docs/RESILIENCE.md fleet
degradation tiers).

When the health monitor declares a member dead, this executor makes
its doc space serveable again without the member:

  1. **Capture interest.**  Before the ring changes: the doc keys the
     router parked for the member (mutating frames held during the
     suspect window) and the subscribed docs the member owned (their
     fan-out streams died with it).
  2. **Remove the member** from the ring + membership (one epoch bump,
     journalled -- a router restart must not resurrect the dead
     placement).
  3. **Re-place + restore.**  The dead member's durable doc inventory
     (its write-through / checkpoint ColdStore, registered by the
     supervisor or the deployment) is grouped by post-removal ring
     ownership -- rendezvous over the ring -- and each survivor
     restores its share via the existing ``migrate_in`` control RPC
     (`restore_from_store`, arena-direct; idempotent under the CRDT's
     (actor, seq) dedup, which is what keeps re-applied changes
     exactly-once).
  4. **Replay parked frames** in arrival order through the normal
     dispatch path -- they now route to the new owners.  Docs whose
     restore FAILED answer every parked frame the typed
     ``ReplicaFailed`` envelope instead; with no durable store at all,
     every parked mutating frame is unrecoverable by definition.
  5. **Resync subscribers** through the PR-13 resync envelope
     (``reason: "failover"``): each client auto-resubscribes at its
     last-seen clock and the backfill machinery closes the gap against
     the restored state.

A doc absent from the durable store but present in the parked/
subscribed interest set is treated as NEW, not lost: with write-through
(``AMTPU_STORAGE_SYNC``) every acked change is durable, so absence
means nothing acked ever existed and replaying its parked frames
simply creates it on the new owner.
"""

import os
import sys
import time

from .. import telemetry


class FailoverExecutor(object):
    """Re-places a dead member's docs onto ring survivors.

    ``store_dirs`` maps member id -> its durable ColdStore root (the
    supervisor registers these as it spawns; embedders pass their
    own).  Thread model: `fail_over` runs on the health monitor's
    thread, one member at a time.
    """

    def __init__(self, router, store_dirs=None):
        self.router = router
        self.store_dirs = dict(store_dirs or {})

    def register_store(self, member, store_dir):
        self.store_dirs[member] = store_dir

    def join_pins(self):
        """{doc: current_ring_owner} over every doc any registered
        durable store has ever checkpointed (dead members' stores
        included: their docs were re-placed onto survivors whose own
        sync stores may not hold them yet).  Passed to
        `router.add_member(..., pins=...)` so a (re)joining member
        remaps nothing that already lives somewhere."""
        router = self.router
        pins = {}
        for store_dir in self.store_dirs.values():
            for d in self._inventory(store_dir):
                if d in pins:
                    continue
                owner = router.ring.owner(d)
                if owner is not None:
                    pins[d] = owner
        return pins

    def fail_over(self, member):
        """Removes `member`, restores its durable docs on survivors,
        replays/fails its parked frames, resyncs its subscribers.
        Idempotent: a member already failed over is a no-op."""
        router = self.router
        if member not in router.replicas:
            return {'member': member, 'recovered': [], 'lost': [],
                    'replayed': 0, 'already': True}
        t0 = time.monotonic()
        parked = router.parked_docs_for(member)
        subscribed = [d for d in router.subscribed_doc_keys()
                      if router.ring.owner(d) == member]
        router.remove_member(member)
        store_dir = self.store_dirs.get(member)
        doc_ids = self._inventory(store_dir)
        recovered, lost = self._restore(store_dir, doc_ids)
        if store_dir is None:
            # nothing durable was ever registered for this member:
            # every parked mutation is unrecoverable by definition
            lost.extend(d for d in parked if d not in lost)
        router._save_journal()
        lostset = set(lost)
        replayed = router.fail_parked(
            [d for d in parked if d in lostset], member)
        replayed += router.release_parked(
            [d for d in parked if d not in lostset])
        router.notify_migrated(subscribed, reason='failover')
        wall_s = time.monotonic() - t0
        telemetry.metric('failover.failovers')
        telemetry.metric('failover.docs_recovered', len(recovered))
        telemetry.metric('failover.docs_lost', len(lost))
        telemetry.metric('failover.replayed', replayed)
        telemetry.recorder.record(
            'fleet.failover', doc=member, n=len(recovered),
            detail='lost=%d replayed=%d wall_ms=%d'
                   % (len(lost), replayed, int(wall_s * 1000)))
        return {'member': member, 'recovered': recovered,
                'lost': sorted(lostset), 'replayed': replayed,
                'wall_s': wall_s}

    # -- internals ------------------------------------------------------

    @staticmethod
    def _inventory(store_dir):
        """The dead member's durable doc keys -- everything its
        write-through / checkpoint store committed before the kill."""
        if not store_dir or not os.path.isdir(store_dir):
            return []
        from ..storage.coldstore import ColdStore
        try:
            return sorted(ColdStore(store_dir, durable=True).doc_ids())
        except Exception as e:
            print('failover: unreadable store %r: %s: %s'
                  % (store_dir, type(e).__name__, e), file=sys.stderr)
            return []

    def _restore(self, store_dir, doc_ids):
        """Restores `doc_ids` from `store_dir` grouped by post-removal
        ring ownership; returns (recovered, lost).  Per-group failures
        lose only that group -- the rest of the doc space still comes
        back."""
        router = self.router
        groups = {}
        for d in doc_ids:
            owner = router.ring.owner(d)
            if owner is None:
                return [], list(doc_ids)    # no survivors at all
            groups.setdefault(owner, []).append(d)
        recovered, lost = [], []
        for dst in sorted(groups):
            ds = groups[dst]
            try:
                res = router.control_call(
                    dst, 'migrate_in', docs=ds, store_dir=store_dir,
                    ring_version=router.ring.version)
                got = set(str(k) for k in (res.get('restored') or ()))
                for d in ds:
                    (recovered if str(d) in got else lost).append(d)
            except Exception as e:
                print('failover: restore of %d docs on %r failed: '
                      '%s: %s' % (len(ds), dst, type(e).__name__, e),
                      file=sys.stderr)
                lost.extend(ds)
        return recovered, lost
