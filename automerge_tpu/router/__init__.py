"""Fleet routing tier (ISSUE 18, docs/SERVING.md routing section).

One gateway over one pool cannot serve millions of users.  This
package fronts N shared-nothing gateway+pool replicas with a
:class:`~automerge_tpu.router.gateway.RouterGateway` speaking the
sidecar's existing JSONL/msgpack framing, places docs on a
consistent-hash ring (:mod:`automerge_tpu.router.ring`), and moves
hot docs between replicas live
(:mod:`automerge_tpu.router.rebalance`) without losing, duplicating,
or reordering a single op.

Failover (ISSUE 19): :mod:`automerge_tpu.router.health` detects
replica death (heartbeats + transport signals), :mod:`.failover`
re-places a dead member's docs onto ring survivors from durable
storage, and :mod:`.supervisor` respawns router-managed replicas with
capped backoff -- docs/RESILIENCE.md "fleet degradation tiers" is the
contract.
"""

from .ring import HashRing                      # noqa: F401
from .gateway import RouterGateway              # noqa: F401
from .rebalance import (MigrationExecutor,      # noqa: F401
                        Rebalancer)
from .health import HealthMonitor               # noqa: F401
from .failover import FailoverExecutor          # noqa: F401
from .supervisor import ReplicaSupervisor       # noqa: F401
