"""Fleet routing tier (ISSUE 18, docs/SERVING.md routing section).

One gateway over one pool cannot serve millions of users.  This
package fronts N shared-nothing gateway+pool replicas with a
:class:`~automerge_tpu.router.gateway.RouterGateway` speaking the
sidecar's existing JSONL/msgpack framing, places docs on a
consistent-hash ring (:mod:`automerge_tpu.router.ring`), and moves
hot docs between replicas live
(:mod:`automerge_tpu.router.rebalance`) without losing, duplicating,
or reordering a single op.
"""

from .ring import HashRing                      # noqa: F401
from .gateway import RouterGateway              # noqa: F401
from .rebalance import (MigrationExecutor,      # noqa: F401
                        Rebalancer)
