"""Shared constants and helpers (reference: `/root/reference/src/common.js`)."""

import os

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def env_int(name, default):
    """Integer env knob with the shared fallback semantics: unset,
    empty, or unparsable -> default (defined ONCE; the scheduler queue,
    the wave pipeline, and the escalation chunk cap all read through
    this)."""
    try:
        v = os.environ.get(name, '')
        return int(v) if v else default
    except ValueError:
        return default


def parse_mesh_env(raw=None):
    """Parses the mesh execution-mode knob ``AMTPU_MESH=dp[,sp]`` into
    ``(dp, sp)``, or None when unset/empty/zero (mesh mode off).  The
    ONE parse shared by the pool factory (`native.make_pool`), the
    sp-axis fence (`native.resident`), and the latch-flip guard -- the
    three consumers can never disagree on what a value means.

    Raises ValueError on malformed values: a typo'd topology silently
    serving single-device traffic is the failure mode this knob exists
    to prevent."""
    if raw is None:
        raw = os.environ.get('AMTPU_MESH')
    if raw is None or not raw.strip():
        return None
    parts = raw.split(',')
    try:
        dp = int(parts[0])
        sp = int(parts[1]) if len(parts) > 1 and parts[1].strip() else 1
        if len(parts) > 2:
            raise ValueError
    except ValueError:
        raise ValueError('AMTPU_MESH must be dp[,sp] (e.g. "4" or '
                         '"4,2"), got %r' % (raw,))
    if dp <= 0:
        return None
    return dp, max(sp, 1)


def is_object(value):
    """True for values that map to Automerge objects (dict/list/Text/Table)."""
    return isinstance(value, (dict, list)) or hasattr(value, '_am_object')


def less_or_equal(clock1, clock2):
    """True if every component of vector clock `clock1` is <= the matching
    component of `clock2` (reference: `/root/reference/src/common.js:14-18`)."""
    for key in set(clock1) | set(clock2):
        if clock1.get(key, 0) > clock2.get(key, 0):
            return False
    return True


def doc_key(doc_id):
    """Canonical wire key for a doc id (int ids map to 'i:<n>') -- the ONE
    definition shared by the pools, the payload splitter mirror, and the
    replica shipping path."""
    return doc_id if isinstance(doc_id, str) else 'i:%d' % doc_id
