"""Shared constants and helpers (reference: `/root/reference/src/common.js`)."""

import os

ROOT_ID = '00000000-0000-0000-0000-000000000000'

# ---------------------------------------------------------------------------
# Environment access.  Every `AMTPU_*` read in the package routes through
# these helpers (plus `parse_mesh_env` below); the env-latch checker
# (`automerge_tpu/analysis/check_env.py`, `make static-check`) fails any
# direct `os.environ` AMTPU read elsewhere and cross-checks the literal
# defaults at each call site against the one spec in
# `automerge_tpu/analysis/env_spec.py` -- a hardcoded default can no
# longer drift between consumers.
# ---------------------------------------------------------------------------


def env_int(name, default):
    """Integer env knob with the shared fallback semantics: unset,
    empty, or unparsable -> default (defined ONCE; the scheduler queue,
    the wave pipeline, and the escalation chunk cap all read through
    this)."""
    try:
        v = os.environ.get(name, '')
        return int(v) if v else default
    except ValueError:
        return default


def env_float(name, default):
    """Float env knob, same fallback semantics as :func:`env_int`."""
    try:
        v = os.environ.get(name, '')
        return float(v) if v else default
    except ValueError:
        return default


def env_bool(name, default):
    """Boolean env knob: unset -> `default`; set -> the shared truthy
    parse (anything but '' and '0' is on).  Matches the historical
    ``not in ('', '0')`` idiom at every boolean call site."""
    v = os.environ.get(name)
    if v is None:
        return default
    return v not in ('', '0')


def env_str(name, default):
    """String env knob: unset or empty -> `default`."""
    v = os.environ.get(name, '')
    return v if v else default


def env_raw(name):
    """Raw tri-state read: None when unset, else the verbatim string.
    For knobs whose consumers distinguish *unset* (backend-dependent
    default) from any set value (AMTPU_HOST_FULL / AMTPU_RESIDENT /
    AMTPU_HOST_DOM and the latch-guard snapshot)."""
    return os.environ.get(name)


def parse_mesh_env(raw=None):
    """Parses the mesh execution-mode knob ``AMTPU_MESH=dp[,sp]`` into
    ``(dp, sp)``, or None when unset/empty/zero (mesh mode off).  The
    ONE parse shared by the pool factory (`native.make_pool`), the
    sp-axis fence (`native.resident`), and the latch-flip guard -- the
    three consumers can never disagree on what a value means.

    Raises ValueError on malformed values: a typo'd topology silently
    serving single-device traffic is the failure mode this knob exists
    to prevent."""
    if raw is None:
        raw = os.environ.get('AMTPU_MESH')
    if raw is None or not raw.strip():
        return None
    parts = raw.split(',')
    try:
        dp = int(parts[0])
        sp = int(parts[1]) if len(parts) > 1 and parts[1].strip() else 1
        if len(parts) > 2:
            raise ValueError
    except ValueError:
        raise ValueError('AMTPU_MESH must be dp[,sp] (e.g. "4" or '
                         '"4,2"), got %r' % (raw,))
    if dp <= 0:
        return None
    return dp, max(sp, 1)


def is_object(value):
    """True for values that map to Automerge objects (dict/list/Text/Table)."""
    return isinstance(value, (dict, list)) or hasattr(value, '_am_object')


def less_or_equal(clock1, clock2):
    """True if every component of vector clock `clock1` is <= the matching
    component of `clock2` (reference: `/root/reference/src/common.js:14-18`)."""
    for key in set(clock1) | set(clock2):
        if clock1.get(key, 0) > clock2.get(key, 0):
            return False
    return True


def doc_key(doc_id):
    """Canonical wire key for a doc id (int ids map to 'i:<n>') -- the ONE
    definition shared by the pools, the payload splitter mirror, and the
    replica shipping path."""
    return doc_id if isinstance(doc_id, str) else 'i:%d' % doc_id
