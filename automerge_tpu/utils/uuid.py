"""UUID provider with a swappable factory, mirroring the reference's
deterministic-test hook (`/root/reference/src/uuid.js:1-12`)."""

import uuid as _uuid

_default_factory = lambda: str(_uuid.uuid4())
_factory = _default_factory


def uuid():
    return _factory()


def set_factory(factory):
    global _factory
    _factory = factory


def reset():
    global _factory
    _factory = _default_factory


# camelCase alias for API parity with the reference
setFactory = set_factory
