"""msgpack wire-format splice helpers.

The pools move change/patch payloads as raw msgpack and splice headers by
hand (merging shard results, stitching shipped change arrays, wrapping
checkpoints).  These four helpers are the ONE definition of that byte
surgery -- per-module mirrors drift (and a drifted map header corrupts a
spliced payload silently).
"""


def read_map_header(buf):
    """(n_entries, header_len) of a msgpack map."""
    b = buf[0]
    if (b & 0xf0) == 0x80:
        return b & 0x0f, 1
    if b == 0xde:
        return int.from_bytes(buf[1:3], 'big'), 3
    if b == 0xdf:
        return int.from_bytes(buf[1:5], 'big'), 5
    raise ValueError('expected msgpack map, got 0x%02x' % b)


def map_header(n):
    if n <= 15:
        return bytes([0x80 | n])
    if n <= 0xffff:
        return b'\xde' + n.to_bytes(2, 'big')
    return b'\xdf' + n.to_bytes(4, 'big')


def read_array_header(buf):
    """(n_elements, header_len) of a msgpack array."""
    b = buf[0]
    if (b & 0xf0) == 0x90:
        return b & 0x0f, 1
    if b == 0xdc:
        return int.from_bytes(buf[1:3], 'big'), 3
    if b == 0xdd:
        return int.from_bytes(buf[1:5], 'big'), 5
    raise ValueError('expected msgpack array, got 0x%02x' % b)


def array_header(n):
    if n <= 15:
        return bytes([0x90 | n])
    if n <= 0xffff:
        return b'\xdc' + n.to_bytes(2, 'big')
    return b'\xdd' + n.to_bytes(4, 'big')
