from .common import ROOT_ID, is_object, less_or_equal
from .uuid import uuid, set_factory, reset

__all__ = ['ROOT_ID', 'is_object', 'less_or_equal', 'uuid', 'set_factory', 'reset']
