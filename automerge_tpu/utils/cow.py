"""Copy-on-write "transient" containers for the oracle backend state.

The reference engine stores its state in Immutable.js persistent maps/lists
(`/root/reference/backend/op_set.js:310-322`), paying O(log n) path-copies on
every single operation.  The TPU-native rebuild takes a different stance: the
backend state is a *generation-stamped* tree of plain dicts/lists.  Forking a
state bumps a global generation counter; any container whose stamp differs
from the current state's generation is copied (shallowly) the first time it is
written in that generation.  Reads are plain dict/list reads.

This gives the same observable persistence semantics as Immutable.js (old
states stay valid after `applyChanges` returns a new one) at amortised O(1)
per write within a batch -- the Clojure "transients" trick, which is also what
lets the batched TPU path slurp the whole state into columnar arrays without
fighting a persistent-structure API.
"""

import itertools

_GEN = itertools.count(1)


def next_gen():
    """Returns a fresh, globally unique generation number."""
    return next(_GEN)


class D(dict):
    """A dict with a generation stamp."""
    __slots__ = ('gen',)

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.gen = 0

    def copy_with_gen(self, gen):
        c = D(self)
        c.gen = gen
        return c


class L(list):
    """A list with a generation stamp."""
    __slots__ = ('gen',)

    def __init__(self, *args):
        super().__init__(*args)
        self.gen = 0

    def copy_with_gen(self, gen):
        c = L(self)
        c.gen = gen
        return c


def own_key(parent, key, gen, factory=None):
    """Fetches `parent[key]`, ensuring the returned container is owned by
    `gen` (copying and storing back if needed).  `parent` must already be
    owned.  If the key is missing, `factory()` supplies a fresh container."""
    child = parent.get(key)
    if child is None:
        child = factory()
        child.gen = gen
        parent[key] = child
        return child
    if child.gen != gen:
        child = child.copy_with_gen(gen)
        parent[key] = child
    return child
