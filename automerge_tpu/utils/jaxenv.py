"""JAX platform pinning shared by every CPU-only entry point.

The image's sitecustomize registers an accelerator plugin and PREPENDS it
to ``jax_platforms``, overriding a ``JAX_PLATFORMS=cpu`` environment
variable.  Any entry point that must never touch the (possibly wedged)
tunneled device link therefore has to pin the config back after importing
jax -- and BEFORE the first ``jax.devices()`` call, because merely
enumerating devices initializes the default backend.
"""

import os


def pin_cpu(force=False):
    """Pin jax to the CPU platform.

    With ``force=False`` (the default) the pin only happens when the
    caller's environment already requested CPU (``JAX_PLATFORMS=cpu``),
    so production entry points keep using the real device.  ``force=True``
    pins unconditionally (test conftest, multi-chip dryruns).

    Returns True when the pin was applied.
    """
    if not force and os.environ.get('JAX_PLATFORMS') != 'cpu':
        return False
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    jax.config.update('jax_platforms', 'cpu')
    return True
