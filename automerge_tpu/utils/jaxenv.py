"""JAX platform pinning shared by every CPU-only entry point.

The image's sitecustomize registers an accelerator plugin and PREPENDS it
to ``jax_platforms``, overriding a ``JAX_PLATFORMS=cpu`` environment
variable.  Any entry point that must never touch the (possibly wedged)
tunneled device link therefore has to pin the config back after importing
jax -- and BEFORE the first ``jax.devices()`` call, because merely
enumerating devices initializes the default backend.
"""

import os
import re


def pin_cpu(force=False):
    """Pin jax to the CPU platform.

    With ``force=False`` (the default) the pin only happens when the
    caller's environment already requested CPU (``JAX_PLATFORMS=cpu``),
    so production entry points keep using the real device.  ``force=True``
    pins unconditionally (test conftest, multi-chip dryruns).

    Returns True when the pin was applied.
    """
    if not force and os.environ.get('JAX_PLATFORMS') != 'cpu':
        return False
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    jax.config.update('jax_platforms', 'cpu')
    return True


def ensure_cpu_devices(n_devices):
    """Arranges for at least ``n_devices`` virtual CPU devices.

    Newer jax exposes ``jax_num_cpu_devices`` (settable after
    ``clear_backends()``); on versions without it the only working lever
    is ``XLA_FLAGS=--xla_force_host_platform_device_count=N``, which the
    XLA runtime parses ONCE per process at first backend init -- so the
    fallback must run BEFORE anything enumerates devices.  Call this
    before the first ``jax.devices()``; the caller still does the
    config-option path itself when the backend is already initialized
    (see ``__graft_entry__.dryrun_multichip``).

    Returns 'config' when the config option exists (applied here when
    the backend is still uninitialized; after an init, the caller must
    tear the backend down first -- see ``__graft_entry__``'s
    clear_backends path), 'flags' when the XLA_FLAGS fallback was
    applied or already satisfies the request.
    """
    import jax
    if hasattr(jax.config, 'jax_num_cpu_devices'):
        try:
            if jax.config.jax_num_cpu_devices < n_devices:
                jax.config.update('jax_num_cpu_devices', n_devices)
        except Exception:
            # backend already initialized: the option is frozen; callers
            # that can afford a teardown (the dryrun) handle it, pool
            # construction degrades with a counted+warned shortfall
            pass
        return 'config'
    flags = os.environ.get('XLA_FLAGS', '')
    m = re.search(r'--xla_force_host_platform_device_count=(\d+)', flags)
    if m is None or int(m.group(1)) < n_devices:
        flags = re.sub(r'--xla_force_host_platform_device_count=\d+',
                       '', flags)
        os.environ['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=%d'
            % n_devices).strip()
    return 'flags'


def enable_cpu_collectives():
    """Opts into jax's CPU cross-process collectives (the Gloo backend)
    so ``multihost_utils.process_allgather`` works on CPU-only hosts --
    without it, multi-process computations raise "Multiprocess
    computations aren't implemented on the CPU backend".  Must run
    before ``jax.distributed.initialize``.  Silently a no-op on jax
    versions without the option (their CPU backend either supports
    multiprocess natively or the caller's collective will surface the
    real error)."""
    import jax
    try:
        jax.config.update('jax_cpu_collectives_implementation', 'gloo')
        return True
    except (AttributeError, ValueError):
        return False
