"""Poison-batch isolation + graceful degradation (docs/RESILIENCE.md).

One malformed change, one transient XLA/device error, or one wedged
kernel used to take down an entire multi-thousand-doc batch.  This
module turns a device- or native-path failure inside
``NativeDocPool.apply_batch`` / ``ShardedNativePool`` into the smallest
possible blast radius:

  1. **retry** -- transient failures (``faults.is_transient``) get
     bounded retries with exponential backoff
     (``resilience.retry.*`` counters);
  2. **bisect** -- a failure that persists splits the doc set in half
     and re-applies each half independently, converging on the poison
     doc(s) in O(log n) extra applies (``resilience.bisect.rounds``);
  3. **quarantine / degrade** -- a poisoned singleton either degrades to
     the full-host path (``AMTPU_DEGRADE=1``; no device work at all;
     ``resilience.degraded`` -- deliberately distinct from
     ``fallback.oracle`` so the perf gates stay meaningful) or is
     quarantined: its slot in the batch result carries the protocol's
     per-doc error envelope ``{'error': ..., 'errorType': ...}`` while
     every healthy doc's patch commits normally
     (``resilience.quarantined``).

All of this is only byte-safe because a failed batch now ROLLS BACK:
`native.amtpu_batch_rollback` restores the pool to its pre-begin state
on any pre-emit failure, so re-applying the same changes is not
swallowed by seq dedup.  An exception marked ``amtpu_state_suspect``
(emit already ran; rollback impossible) is never retried or bisected --
it re-raises like the pre-resilience code.

Protocol-level errors (`AutomergeError`, `RangeError`, `TypeError`,
`KeyError` -- validation, not infrastructure) never START isolation:
on a batch whose only problem is validation they re-raise whole-batch
exactly as before, so error-contract tests and callers keep their
semantics.  Once isolation HAS begun (an infrastructure fault fired
first), sibling groups may already have committed, so even validation
errors then resolve per doc -- their envelope carries the real
errorType -- rather than falsely claiming "nothing applied".

``AMTPU_RESILIENCE=0`` disables the whole layer (failures re-raise,
post-rollback).
"""

import time

import msgpack

from . import faults, telemetry
from .errors import AutomergeError
from .telemetry import recorder
from .utils.common import env_bool, env_float, env_int
from .utils.wire import map_header as _map_header
from .utils.wire import read_map_header as _read_map_header


def enabled():
    return env_bool('AMTPU_RESILIENCE', True)


def _retry_max():
    return env_int('AMTPU_RETRY_MAX', 3)


def _backoff_base_s():
    return env_float('AMTPU_RETRY_BACKOFF_S', 0.05)


#: exponential backoff ceiling -- a wedged device should not turn one
#: batch into a minutes-long retry stall
_BACKOFF_CAP_S = 1.0


def _degrade_on():
    return env_bool('AMTPU_DEGRADE', False)


def should_isolate(exc):
    """Whether the resilience machinery may handle ``exc`` at all.

    Injected faults always qualify.  Real-world infrastructure failures
    (RuntimeError covers XlaRuntimeError, OSError covers device/file
    descriptors, MemoryError/SystemError cover allocator/interpreter
    trouble) qualify unless the batch is state-suspect.  Protocol
    validation errors never do -- the whole-batch raise IS their
    contract.
    """
    if not enabled():
        return False
    if getattr(exc, 'amtpu_state_suspect', False):
        return False
    if isinstance(exc, faults.InjectedFault):
        return True
    if isinstance(exc, (AutomergeError, TypeError, KeyError)):
        return False
    return isinstance(exc, (RuntimeError, OSError, MemoryError,
                            SystemError))


def error_envelope(exc):
    """The protocol's per-doc error envelope for a quarantined doc --
    the same ``error``/``errorType`` shape the sidecar answers for
    whole-request failures, embedded as that doc's result value."""
    return {'error': str(exc) or type(exc).__name__,
            'errorType': type(exc).__name__}


def is_quarantined(result):
    """True when a per-doc batch result is an error envelope rather
    than a patch (the caller-facing test for quarantine)."""
    return isinstance(result, dict) and 'errorType' in result \
        and 'error' in result and 'clock' not in result


#: the message shape `native._raise_if_quarantined` uses when a
#: SINGLE-doc entry point surfaces a quarantine envelope as its raise
#: contract -- defined here (the quarantine authority) so consumers
#: recognizing that surface (the gateway's fan-out, which owes
#: subscribers the envelope even when the doc was mutated through a
#: singleton path) share one contract with the raiser
QUARANTINE_RAISE_MARKER = ' quarantined: ['


def is_quarantine_error(resp):
    """True when a protocol error response is the single-doc surface of
    a quarantine (`_raise_if_quarantined`) rather than a validation
    error -- the fan-out test for 'envelope, not silence' on the
    exec/serial-fallback path."""
    return isinstance(resp, dict) \
        and resp.get('errorType') == 'AutomergeError' \
        and QUARANTINE_RAISE_MARKER in str(resp.get('error', ''))


def apply_payload(pool, payload, first_exc=None):
    """``apply_batch_bytes`` with retry/bisect/quarantine semantics.

    Returns result BYTES byte-compatible with ``apply_batch_bytes``
    output (msgpack ``{doc_key: patch}``), with quarantined docs mapped
    to their error envelope instead of a patch.  Exceptions the layer
    must not isolate re-raise unchanged.

    ``first_exc`` carries a failure the caller already observed (the
    sharded driver retries a failed shard's sub-payload here without
    paying a doomed extra attempt).
    """
    if first_exc is None:
        try:
            return pool.apply_batch_bytes(payload)
        except Exception as e:
            if not should_isolate(e):
                if getattr(e, 'amtpu_state_suspect', False):
                    recorder.record('resilience.state_suspect',
                                    detail=type(e).__name__)
                    recorder.dump('state_suspect')
                raise
            first_exc = e
    if isinstance(payload, tuple):   # zero-copy shard view: materialize
        import ctypes
        payload = ctypes.string_at(payload[0], payload[1])
    keyed = msgpack.unpackb(payload, raw=False, strict_map_key=False)
    # results merge at the BYTE level (sum the map headers, splice the
    # bodies -- the same trick as the sharded merge): every surviving
    # doc's patch bytes stay exactly as the C++ emit produced them, so
    # a retry-recovered batch is byte-identical to the fault-free run
    parts = []                       # (n_docs, body_bytes)
    _apply_group(pool, keyed, list(keyed), parts, pending_exc=first_exc)
    total = sum(n for n, _ in parts)
    return _map_header(total) + b''.join(b for _, b in parts)


def _append_raw(parts, raw):
    n, off = _read_map_header(raw)
    parts.append((n, memoryview(raw)[off:]))


def _apply_group(pool, keyed, doc_list, parts, pending_exc=None):
    """Recursive retry/bisect driver over one doc subset.  Healthy docs'
    raw patch bytes land in ``parts``; poisoned docs land as packed
    error envelopes."""
    delay = _backoff_base_s()
    attempts_left = _retry_max()
    retried = False
    exc = pending_exc
    sub = None          # built once; retries re-send the same bytes
    while True:
        if exc is None:
            try:
                if sub is None:
                    sub = msgpack.packb({k: keyed[k] for k in doc_list},
                                        use_bin_type=True)
                _append_raw(parts, pool.apply_batch_bytes(sub))
                if retried:
                    telemetry.metric('resilience.retry.success')
                return
            except Exception as e:
                # Isolation has already begun: sibling groups may have
                # committed, so re-raising here would claim "nothing
                # applied" while half the batch stands.  Even protocol
                # errors therefore resolve per doc inside this pass
                # (their envelope carries the real errorType); only a
                # state-suspect failure still re-raises -- re-applying
                # those docs is unsafe in any form.
                if getattr(e, 'amtpu_state_suspect', False):
                    recorder.record('resilience.state_suspect',
                                    n=len(doc_list),
                                    detail=type(e).__name__)
                    recorder.dump('state_suspect')
                    raise
                exc = e
        if faults.is_transient(exc) and attempts_left > 0:
            attempts_left -= 1
            retried = True
            telemetry.metric('resilience.retry.attempts')
            recorder.record('resilience.retry', n=len(doc_list),
                            detail=type(exc).__name__)
            time.sleep(delay)
            delay = min(delay * 2, _BACKOFF_CAP_S)
            exc = None
            continue
        break
    if faults.is_transient(exc):
        telemetry.metric('resilience.retry.exhausted')
    if len(doc_list) > 1:
        telemetry.metric('resilience.bisect.rounds')
        recorder.record('resilience.bisect', n=len(doc_list))
        mid = len(doc_list) // 2
        _apply_group(pool, keyed, doc_list[:mid], parts)
        _apply_group(pool, keyed, doc_list[mid:], parts)
        return
    key = doc_list[0]
    if _degrade_on():
        try:
            _append_raw(parts, _apply_degraded(pool, key, keyed[key]))
            telemetry.metric('resilience.degraded')
            telemetry.note_degraded()
            return
        except Exception as e:
            if getattr(e, 'amtpu_state_suspect', False):
                raise
            exc = e
    telemetry.metric('resilience.quarantined')
    telemetry.note_degraded()
    # the quarantine IS the post-mortem moment: stamp the event and
    # dump the surrounding ring (docs/RESILIENCE.md; rate-limited so a
    # poison-storm cannot become a disk-write storm)
    recorder.record('resilience.quarantine', doc=key,
                    detail=type(exc).__name__)
    recorder.dump('quarantine')
    parts.append((1, msgpack.packb(key, use_bin_type=True) +
                  msgpack.packb(error_envelope(exc), use_bin_type=True)))


def _apply_degraded(pool, key, changes):
    """Applies one poisoned doc on the FULL HOST path: the C++ pool
    resolves registers and list indexes itself with zero device
    dispatches, dodging whatever wedged the kernel path.  Returns the
    raw result bytes.  Counted as ``resilience.degraded`` -- NOT
    ``fallback.oracle``, which gates the healthy kernel path's
    escalation ladder."""
    from .native import _host_full_on, lib
    base = pool
    if hasattr(pool, '_shard_of'):       # route to the doc's shard pool
        base = pool.pools[pool._shard_of(key)]
    handle = getattr(base, '_pool', None)
    if handle is None:
        raise RuntimeError('degraded path needs a native pool')
    sub = msgpack.packb({key: changes}, use_bin_type=True)
    L = lib()
    L.amtpu_pool_set_hostfull(handle, 1)
    try:
        return base.apply_batch_bytes(sub)
    finally:
        L.amtpu_pool_set_hostfull(handle, 1 if _host_full_on() else 0)
