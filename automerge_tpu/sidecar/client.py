"""Sidecar client: drives a backend server process over stdio or a unix
socket.  This is the Python twin of the Node `backend=tpu` adapter -- it
implements the reference Backend call surface (backend/index.js:312-315)
by shipping requests across the process boundary, which is exactly the
deployment seam the reference designed the frontend/backend split for
(CHANGELOG.md:36-39, "work moved to a background thread").

Self-healing (docs/RESILIENCE.md): a client that SPAWNED its server
owns the process, so on a crashed/wedged server (EOF, broken pipe,
request deadline exceeded) it kills the remains, respawns the server
with capped exponential backoff, replays its state from the rolling
checkpoint WAL (periodic `save` snapshots + the mutating-request log
since, riding the existing save/load protocol), and retries the
in-flight request -- the request never received a response, so the
replayed state cannot contain it and the retry is exactly-once.  Each
respawn exports the restart count to the new server via
``AMTPU_SIDECAR_RESTARTS``, which `healthz` reports.  Clients that
ADOPTED a process or connected to a socket do not own the server;
for them a transport error marks the client dead so reuse raises a
clear error instead of desyncing request ids.
"""

import collections
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

from .. import telemetry
from ..utils.common import env_bool, env_float, env_int

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: commands that mutate server state -- the WAL records exactly these
WAL_CMDS = ('apply_changes', 'apply_batch', 'apply_local_change', 'load')


class SidecarTimeout(ConnectionError):
    """The server produced no response within the request deadline."""


class CheckpointWAL:
    """Rolling client-side write-ahead log for sidecar state replay.

    Two tiers: per-doc ``save()`` checkpoint snapshots (the v2 COLUMNAR
    containers since ISSUE 10 -- the server's save() compresses settled
    history, so snapshot memory and respawn-replay time shrink with
    it), plus the ordered log of mutating requests acknowledged since
    the last compaction.  Compaction triggers on EITHER bound: the log
    exceeds ``compact_every`` entries (AMTPU_WAL_COMPACT, default 32)
    or ``max_bytes`` of retained log bytes (AMTPU_WAL_MAX_BYTES,
    default 64 MiB) -- the byte trigger keeps a burst of huge batches
    (or a server that keeps failing compaction, the
    ``wal_compact_failed`` path) from growing the log without limit
    between entry-count trips.  ``sidecar.client.wal_bytes`` gauges the
    current snapshot+log footprint.  Replay = load every snapshot, then
    re-send the residual log in order.

    Caveat: checkpoints serialize change history only, so a server-side
    undo stack survives a respawn only as far as the residual log's
    `apply_local_change` entries rebuild it; an undo whose originating
    change was already compacted away replays as an error.
    """

    def __init__(self, compact_every=None, max_bytes=None):
        if compact_every is None:
            compact_every = env_int('AMTPU_WAL_COMPACT', 32)
        if max_bytes is None:
            max_bytes = env_int('AMTPU_WAL_MAX_BYTES', 67108864)
        self.compact_every = max(1, compact_every)
        self.max_bytes = max_bytes
        self.snapshots = {}      # doc -> checkpoint_b64
        self.log = []            # (cmd, kwargs, trace, n_bytes) in ack
        #                          order; trace is the request's wire
        #                          context so a replay re-sends it under
        #                          its ORIGINAL trace id (ISSUE 16)
        self.docs = set()
        self.log_bytes = 0
        self.snap_bytes = 0
        self._gauged = 0

    @staticmethod
    def _docs_of(cmd, kwargs):
        if cmd == 'apply_batch':
            return list(kwargs.get('docs', {}))
        doc = kwargs.get('doc')
        return [doc] if doc is not None else []

    @staticmethod
    def _entry_bytes(kwargs):
        try:
            import msgpack
            return len(msgpack.packb(kwargs, use_bin_type=True,
                                     default=str))
        except Exception:
            return len(repr(kwargs))

    def _gauge(self):
        """`sidecar.client.wal_bytes` tracks the CURRENT footprint:
        the flat map accumulates, so the gauge emits deltas."""
        now = self.log_bytes + self.snap_bytes
        if now != self._gauged:
            telemetry.metric('sidecar.client.wal_bytes',
                             now - self._gauged)
            self._gauged = now

    def record(self, cmd, kwargs, trace=None):
        """One mutating request was ACKNOWLEDGED by the server."""
        n = self._entry_bytes(kwargs)
        self.log.append((cmd, kwargs, trace, n))
        self.log_bytes += n
        self.docs.update(self._docs_of(cmd, kwargs))
        self._gauge()

    def maybe_compact(self, call_raw):
        """Snapshot + truncate when the log is due (entry count OR byte
        bound).  ``call_raw`` is the client's no-WAL no-heal request
        function.  A compaction failure (server died under us) is
        swallowed -- the uncompacted log still replays, the NEXT
        request heals the server, and the byte bound re-trips on every
        subsequent record until a compaction lands."""
        if len(self.log) < self.compact_every \
                and not (self.max_bytes > 0
                         and self.log_bytes >= self.max_bytes):
            return
        try:
            snaps = {}
            for doc in sorted(self.docs):
                snaps[doc] = call_raw('save',
                                      {'doc': doc})['checkpoint_b64']
        except Exception:
            telemetry.metric('sidecar.client.wal_compact_failed')
            return
        self.snapshots = snaps
        self.snap_bytes = sum(len(s) for s in snaps.values())
        del self.log[:]
        self.log_bytes = 0
        self._gauge()
        telemetry.metric('sidecar.client.wal_compactions')

    def replay(self, call_raw):
        """Rebuilds a FRESH server's state: snapshots first, then the
        residual log, in order.  Each residual entry replays under its
        ORIGINAL trace context, so the new server incarnation's spans
        join the traces that produced the state (one client-visible
        request = one trace id, across incarnations)."""
        for doc in sorted(self.snapshots):
            call_raw('load', {'doc': doc, 'data': self.snapshots[doc]})
        for cmd, kwargs, trace, _n in self.log:
            call_raw(cmd, dict(kwargs), trace=trace)
        telemetry.metric('sidecar.client.wal_replays')


class SidecarClient:
    """Thread-safe: one client may be shared across caller threads.
    Request ids are allocated under a lock, frames are written whole
    under a write lock, and responses are DEMULTIPLEXED by id -- the
    serve gateway (docs/SERVING.md) may answer a connection's requests
    out of request order (reads bypass the batch path), so whichever
    thread is waiting first becomes the reader and parks frames that
    answer other threads' ids.  Healing (respawn+replay) serializes on
    the transport lock; it remains designed for the single-threaded
    self-spawned case and is best-effort under concurrency."""

    # class-level defaults so a hand-assembled client (tests build one
    # via __new__ around BytesIO pipes) behaves like a non-healing
    # adopted-transport client
    _dead = False
    _heal = False
    _wal = None
    #: wire trace-context stamping (ISSUE 16); class-level so
    #: hand-assembled clients stamp too, latched per client in __init__
    _wire_trace = True
    _deadline_s = None
    _heartbeat_s = None
    _max_respawns = 3
    #: bounded WrongReplica auto-redirect retries (ISSUE 18): a doc
    #: migrated away mid-stream re-sends the SAME request (the op was
    #: NOT executed, so the retry is exactly-once) -- through a router
    #: the ring catches up within a try or two; a stale direct
    #: connection exhausts the budget and surfaces the typed error
    _max_redirects = 3
    _respawns = 0
    _last_ok = 0.0
    _proc = None
    _sock = None
    _id_lock = None
    _w_lock = None
    _life_lock = None
    _resp_cond = None
    _resp = None
    _reader_live = False
    _rx_exc = None
    _events = None
    _pump = None
    _inflight = None
    _subs = None
    _sub_clocks = None
    #: auto-resubscribe on a server {"event": "resync"} envelope
    #: (ISSUE 13 drop-to-resubscribe: the gateway freed this client's
    #: subscription rows under egress overload).  The pump re-issues
    #: each recorded subscribe at the last-seen clock on a side thread;
    #: the backfill's changes surface as a synthetic change event so
    #: the application stream stays gapless.
    auto_resubscribe = True

    def __init__(self, proc=None, sock_path=None, use_msgpack=False,
                 deadline_s=None, heal=None, max_respawns=None,
                 heartbeat_s=None, wal=None):
        """Connects to a server.  Exactly one of:
          * proc=None, sock_path=None: spawn a stdio server subprocess
          * sock_path: connect to a unix socket
          * proc: adopt an existing subprocess with stdio pipes

        `deadline_s` (AMTPU_SIDECAR_DEADLINE_S) bounds the wait for the
        first byte of each response; `heartbeat_s`
        (AMTPU_SIDECAR_HEARTBEAT_S) pings before a request when the
        connection has been idle longer than that, so a dead server is
        caught by a cheap probe instead of a shipped batch.  `heal`
        enables crash-respawn-replay; default: on iff this client spawns
        its own server (it owns the process).  `max_respawns`
        (AMTPU_SIDECAR_MAX_RESPAWNS, default 3) bounds heals per request.
        """
        self._msgpack = use_msgpack
        self._next_id = 0
        # AMTPU_TRACE_WIRE=0 turns off wire trace-context stamping
        # (latched per client: the stamp must not flip mid-stream)
        self._wire_trace = env_bool('AMTPU_TRACE_WIRE', True)
        self._init_locks()
        self._proc = None
        self._sock = None
        self._dead = False
        self._respawns = 0
        self._last_ok = time.monotonic()
        self._deadline_s = deadline_s if deadline_s is not None else \
            (env_float('AMTPU_SIDECAR_DEADLINE_S', 0) or None)
        self._heartbeat_s = heartbeat_s if heartbeat_s is not None else \
            (env_float('AMTPU_SIDECAR_HEARTBEAT_S', 0) or None)
        if max_respawns is None:
            max_respawns = env_int('AMTPU_SIDECAR_MAX_RESPAWNS', 3)
        self._max_respawns = max_respawns
        self._max_redirects = env_int('AMTPU_ROUTE_REDIRECTS', 3)
        if sock_path or proc is not None:
            # healing means killing + respawning the server from OUR
            # spawn recipe -- only meaningful for a server this client
            # created.  Refuse loudly rather than recording a WAL that
            # can never replay.
            if heal:
                raise ValueError('heal=True requires a self-spawned '
                                 'server (no proc=/sock_path=)')
            self._heal = False
        if sock_path:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.connect(sock_path)
            self._r = self._sock.makefile('rb')
            self._w = self._sock.makefile('wb')
        elif proc is not None:
            self._adopt(proc)
        else:
            self._spawn()
            self._heal = True if heal is None else bool(heal)
        self._wal = None
        if self._heal:
            self._wal = wal if wal is not None else CheckpointWAL()

    # -- process lifecycle ----------------------------------------------

    def _spawn(self):
        cmd = [sys.executable, '-m', 'automerge_tpu.sidecar.server']
        if self._msgpack:
            cmd.append('--msgpack')
        env = dict(os.environ)
        # cwd-independent import of this very package + restart count
        # surfaced by the new server's healthz
        env['PYTHONPATH'] = _REPO_ROOT + (
            os.pathsep + env['PYTHONPATH'] if env.get('PYTHONPATH') else '')
        env['AMTPU_SIDECAR_RESTARTS'] = str(self._respawns)
        self._adopt(subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                     stdout=subprocess.PIPE, env=env))

    def _adopt(self, proc):
        self._proc = proc
        self._r = proc.stdout
        self._w = proc.stdin

    def _teardown_proc(self):
        """Closes pipes and reaps the server process, escalating to
        kill() -- never leaks a zombie into the process tree."""
        proc, self._proc = self._proc, None
        for f in (getattr(self, '_w', None), getattr(self, '_r', None)):
            try:
                if f is not None:
                    f.close()
            except Exception:
                pass
        if proc is not None:
            try:
                proc.kill()
            except Exception:
                pass
            try:
                proc.wait(timeout=10)
            except Exception:
                pass

    def close(self):
        self._dead = True
        try:
            self._w.close()
        except Exception:
            pass
        if self._proc is not None:
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                # a wedged server must not leak past close(): escalate
                # to SIGKILL and reap the corpse
                self._proc.kill()
                self._proc.wait(timeout=10)
        if self._sock is not None:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- transport ------------------------------------------------------

    def _init_locks(self):
        """Demux state; lazy for hand-assembled clients (tests build one
        via __new__, which skips __init__)."""
        self._id_lock = threading.Lock()
        self._w_lock = threading.Lock()
        self._life_lock = threading.RLock()   # heal/WAL serialization
        self._resp_cond = threading.Condition()
        # demux state: rid -> parked response frame, the reader-role
        # election flag, and the sticky transport error -- all owned by
        # the response condition (`make static-check` enforces the
        # guarded-by annotations, docs/ANALYSIS.md)
        self._resp = {}           # guarded-by: self._resp_cond
        self._reader_live = False  # guarded-by: self._resp_cond
        self._rx_exc = None       # guarded-by: self._resp_cond
        # unsolicited fan-out event frames (docs/SERVING.md fan-out
        # section) parked by the pump for next_event()
        self._events = collections.deque()  # guarded-by: self._resp_cond
        self._pump = None         # guarded-by: self._resp_cond
        # rids awaiting a response: the pump attributes an id-less
        # parse-error frame to the OLDEST of these (ids are monotonic;
        # a serial server answers in order)
        self._inflight = set()    # guarded-by: self._resp_cond
        # live subscription registry + last-seen per-doc clocks (from
        # change events), the auto-resubscribe inputs
        self._subs = {}           # guarded-by: self._resp_cond
        self._sub_clocks = {}     # guarded-by: self._resp_cond

    def _await_response(self):
        """Blocks until the first byte of the response is available (or
        the request deadline passes).  Crash detection needs no timeout
        -- a dead server's pipe/socket EOFs immediately -- so the
        deadline only guards the WEDGED-server case."""
        if self._deadline_s is None:
            return
        import select
        ready, _, _ = select.select([self._r], [], [], self._deadline_s)
        if not ready:
            raise SidecarTimeout(
                'sidecar server produced no response within %.1fs'
                % self._deadline_s)

    def _write_frame(self, req):
        if self._msgpack:
            import msgpack
            body = msgpack.packb(req, use_bin_type=True)
            frame = struct.pack('>I', len(body)) + body
        else:
            frame = (json.dumps(req) + '\n').encode()
        with self._w_lock:
            self._w.write(frame)
            self._w.flush()

    def _read_frame(self, apply_deadline=True):
        """One framed response off the transport (reader role only).
        The pump reads with `apply_deadline=False`: between events there
        is legitimately no traffic, and per-request deadlines are
        enforced by the waiters' condition timeout instead."""
        if apply_deadline:
            self._await_response()
        if self._msgpack:
            import msgpack
            head = self._r.read(4)
            if len(head) < 4:
                raise ConnectionError('sidecar server closed the stream')
            (n,) = struct.unpack('>I', head)
            resp = msgpack.unpackb(self._r.read(n), raw=False,
                                   strict_map_key=False)
        else:
            line = self._r.readline()
            if not line:
                raise ConnectionError('sidecar server closed the stream')
            resp = json.loads(line)
        self._last_ok = time.monotonic()
        return resp

    def _roundtrip(self, req):
        """One framed request/response exchange; raises ConnectionError
        (incl. SidecarTimeout) on any transport-level failure.  The
        response for `req['id']` may arrive after responses for OTHER
        threads' requests (the gateway answers reads out of order):
        whichever waiter reaches the transport first reads frames,
        keeps its own, and parks the rest by id."""
        if self._resp_cond is None:
            self._init_locks()
        rid = req['id']
        with self._resp_cond:
            self._inflight.add(rid)
        try:
            return self._roundtrip_inner(req, rid)
        finally:
            with self._resp_cond:
                self._inflight.discard(rid)

    def _roundtrip_inner(self, req, rid):
        self._write_frame(req)
        deadline = None if self._deadline_s is None else \
            time.monotonic() + self._deadline_s
        while True:
            with self._resp_cond:
                while True:
                    if rid in self._resp:
                        return self._resp.pop(rid)
                    if self._rx_exc is not None:
                        raise ConnectionError(
                            'sidecar transport failed in another '
                            'thread: %s' % self._rx_exc)
                    if not self._reader_live:
                        self._reader_live = True
                        break          # this thread becomes the reader
                    timeout = None if deadline is None else \
                        deadline - time.monotonic()
                    if timeout is not None and timeout <= 0:
                        raise SidecarTimeout(
                            'sidecar server produced no response '
                            'within %.1fs' % self._deadline_s)
                    self._resp_cond.wait(timeout)
            # reader role (outside the condition: the read blocks)
            try:
                resp = self._read_frame()
            except BaseException as e:
                with self._resp_cond:
                    self._reader_live = False
                    self._rx_exc = e
                    self._resp_cond.notify_all()
                raise
            with self._resp_cond:
                self._reader_live = False
                r = resp.get('id') if isinstance(resp, dict) else None
                if r != rid and r is not None:
                    self._resp[r] = resp
                self._resp_cond.notify_all()
                if r == rid or r is None:
                    # (id None: a server-side parse error response --
                    # attribute it to this request, nobody else can
                    # claim it)
                    return resp

    def _reset_demux(self):
        """After a heal the old stream is gone: parked frames and the
        sticky receive error belong to the dead transport."""
        if self._resp_cond is None:
            return
        with self._resp_cond:
            self._resp.clear()
            self._rx_exc = None
            self._reader_live = False
            self._resp_cond.notify_all()

    # -- the event pump (fan-out subscriber mode) ------------------------

    def _ensure_pump(self):
        """Starts the dedicated frame pump subscriber mode needs: fan
        -out event frames arrive at ANY time (not in response to a
        request), so a background thread permanently owns the reader
        role, parking responses by id for RPC waiters and event frames
        for `next_event()`.  Idempotent; RPC threads then never read
        the transport themselves."""
        if self._resp_cond is None:
            self._init_locks()
        with self._resp_cond:
            if self._pump is not None:
                return
            while self._reader_live:    # an RPC thread is mid-read;
                self._resp_cond.wait()  # take over once it finishes
            self._reader_live = True
            self._pump = threading.Thread(target=self._pump_loop,
                                          name='amtpu-sidecar-pump',
                                          daemon=True)
            self._pump.start()

    def _pump_loop(self):
        while True:
            try:
                resp = self._read_frame(apply_deadline=False)
            except BaseException as e:
                with self._resp_cond:
                    self._rx_exc = e
                    self._reader_live = False
                    self._pump = None
                    self._resp_cond.notify_all()
                return
            resync = None
            with self._resp_cond:
                if isinstance(resp, dict) and 'event' in resp:
                    if resp['event'] in ('change', 'patch') \
                            and isinstance(resp.get('clock'), dict):
                        # track where each subscription stands so a
                        # resync can resubscribe at the last-seen
                        # clock instead of refetching full history
                        # (patch frames carry the same post clock)
                        self._sub_clocks[resp.get('doc')] = \
                            dict(resp['clock'])
                    elif resp['event'] == 'resync' \
                            and self.auto_resubscribe and self._subs:
                        resync = resp
                    self._events.append(resp)
                else:
                    r = resp.get('id') if isinstance(resp, dict) \
                        else None
                    if r is None:
                        # a parse-error frame carries no id: attribute
                        # it to the oldest outstanding request (ids are
                        # monotonic); with none outstanding, drop it --
                        # handing it to a LATER arbitrary waiter would
                        # misattribute the error
                        r = min(self._inflight) if self._inflight \
                            else None
                        if r is None:
                            self._resp_cond.notify_all()
                            continue
                    self._resp[r] = resp
                self._resp_cond.notify_all()
            if resync is not None:
                # resubscribing is an RPC; the pump must keep reading
                # (it parks the very response that RPC waits on), so
                # the re-subscribe runs on a side thread
                telemetry.metric('sidecar.client.resyncs')
                threading.Thread(target=self._auto_resub_worker,
                                 args=(resync,), daemon=True).start()

    def _auto_resub_worker(self, resync):
        """Drop-to-resubscribe recovery: re-issue every recorded
        subscription the resync envelope covers, at the last-seen
        clock; backfill changes surface as a synthetic change event
        (marked ``"resync": true``) so `next_event` consumers see a
        gapless stream.  An Overloaded answer honours the (jittered)
        ``retryAfterMs`` -- the stampede-control contract."""
        docs = resync.get('docs')
        with self._resp_cond:
            subs = list(self._subs.items())
            clocks = dict(self._sub_clocks)
        from ..errors import OverloadedError
        for key, kwargs in subs:
            if isinstance(docs, list) and docs \
                    and kwargs.get('doc') is not None \
                    and kwargs['doc'] not in docs:
                continue
            kw = dict(kwargs)
            if kw.get('doc') is not None:
                kw['clock'] = clocks.get(kw['doc'], kw.get('clock')) \
                    or {}
            done = False
            for _attempt in range(5):
                try:
                    r = self.call('subscribe', **kw)
                except OverloadedError as e:
                    time.sleep(max(1, e.retry_after_ms or 1) / 1000.0)
                    continue
                except ConnectionError:
                    # transport died; healing/close owns the outcome,
                    # but the loss must not be silent
                    telemetry.metric(
                        'sidecar.client.resubscribe_failed')
                    return
                except Exception:
                    break         # per-subscription failure: next one
                telemetry.metric('sidecar.client.resubscribes')
                self._surface_resub_backfill(kw, r)
                done = True
                break
            if not done:
                # overloaded past the retry budget or a protocol error:
                # the server already freed the rows, so the stream for
                # this subscription is dead -- surface it instead of
                # going quiet
                telemetry.metric('sidecar.client.resubscribe_failed')
                with self._resp_cond:
                    self._events.append(
                        {'event': 'resync_failed',
                         'doc': kw.get('doc'), 'docs': kw.get('docs'),
                         'prefix': kw.get('prefix')})
                    self._resp_cond.notify_all()

    def _surface_resub_backfill(self, kw, res):
        """Backfill changes from an auto-resubscribe surface as
        synthetic change events (marked ``"resync": true``) so
        `next_event` consumers see a gapless stream -- including the
        per-doc backfills of doc-set and prefix subscriptions.  A
        patch-mode resubscribe's full-state backfill surfaces the same
        way, as a ``full: true`` patch event (ISSUE 20)."""
        if not isinstance(res, dict):
            return
        per_doc = res.get('docs') if isinstance(res.get('docs'), dict) \
            else None
        if per_doc is None:
            per_doc = {kw.get('doc'): res}
        evs = []
        for d, r in per_doc.items():
            if not isinstance(r, dict):
                continue
            if r.get('changes'):
                evs.append({'event': 'change', 'doc': d,
                            'clock': r.get('clock'),
                            'changes': r['changes'], 'resync': True})
            elif r.get('patch') is not None:
                evs.append({'event': 'patch', 'doc': d,
                            'clock': r.get('clock'),
                            'patch': r['patch'], 'full': True,
                            'resync': True})
        if evs:
            with self._resp_cond:
                self._events.extend(evs)
                self._resp_cond.notify_all()

    def next_event(self, timeout=None):
        """Blocks for the next unsolicited fan-out event frame
        (``{"event": "change"|"patch"|"presence"|"quarantined",
        "doc": ...}``; docs/SERVING.md fan-out section), wrapped in its
        typed class (`readview.events` -- dict subclasses, so string
        demux keeps working).  Returns None on timeout."""
        from ..readview.events import typed_event
        self._ensure_pump()
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._resp_cond:
            while True:
                if self._events:
                    return typed_event(self._events.popleft())
                if self._rx_exc is not None:
                    raise ConnectionError(
                        'sidecar transport failed: %s' % self._rx_exc)
                wait = None if deadline is None \
                    else deadline - time.monotonic()
                if wait is not None and wait <= 0:
                    return None
                self._resp_cond.wait(wait)

    def _call_raw(self, cmd, kwargs, trace=None):
        """Request + protocol error mapping, NO healing and NO WAL
        recording -- the primitive heal/replay/compaction run on (a
        replayed request must not re-enter the WAL).  `trace` is the
        wire context to stamp (WAL replay passes each entry's original
        context); without one the ambient span's context is used."""
        if self._id_lock is None:
            self._init_locks()
        with self._id_lock:
            self._next_id += 1
            rid = self._next_id
        req = dict(kwargs, cmd=cmd, id=rid)
        tctx = trace if trace is not None \
            else telemetry.current_trace_context()
        if tctx is not None:
            req.setdefault('trace', tctx)
        resp = self._roundtrip(req)
        if 'error' in resp:
            from ..errors import (AutomergeError, OverloadedError,
                                  RangeError, ReplicaFailedError,
                                  ReplicaUnavailableError,
                                  WrongReplicaError)
            types = {'AutomergeError': AutomergeError,
                     'RangeError': RangeError, 'TypeError': TypeError,
                     'KeyError': KeyError}
            if resp.get('errorType') == 'Overloaded':
                raise OverloadedError(resp['error'],
                                      resp.get('retryAfterMs'))
            if resp.get('errorType') == 'WrongReplica':
                raise WrongReplicaError(
                    resp['error'], owner=resp.get('owner'),
                    ring_version=resp.get('ringVersion'))
            if resp.get('errorType') == 'ReplicaUnavailable':
                # retryable (fleet failover in progress); re-sending the
                # same change is exactly-once under (actor, seq) dedup
                raise ReplicaUnavailableError(resp['error'],
                                              resp.get('retryAfterMs'))
            if resp.get('errorType') == 'ReplicaFailed':
                raise ReplicaFailedError(resp['error'],
                                         doc=resp.get('doc'))
            raise types.get(resp.get('errorType'), AutomergeError)(
                resp['error'])
        return resp['result']

    def _respawn_and_replay(self):
        """Kills the server remains, respawns with capped exponential
        backoff until a ping answers, then replays the checkpoint WAL
        into the fresh process."""
        self._respawns += 1
        telemetry.metric('sidecar.client.respawns')
        # the dead server can no longer dump ITS ring; record + dump
        # the client-side view so the respawn leaves a post-mortem
        telemetry.recorder.record('sidecar.respawn', n=self._respawns)
        telemetry.recorder.dump('respawn')
        deadline = time.monotonic() + env_float(
            'AMTPU_SIDECAR_RESPAWN_DEADLINE_S', 30.0)
        delay = 0.05
        while True:
            self._teardown_proc()
            self._reset_demux()    # parked frames/errors died with the
            try:                   # old transport
                self._spawn()
                self._call_raw('ping', {})
                break
            except (OSError, ConnectionError) as e:
                if time.monotonic() > deadline:
                    self._dead = True
                    raise ConnectionError(
                        'sidecar server would not come back: %s' % e) \
                        from e
                time.sleep(delay)
                delay = min(delay * 2, 1.0)
        if self._wal is not None:
            try:
                self._wal.replay(self._call_raw)
            except Exception as e:
                # a half-replayed server is WORSE than a dead client:
                # later calls would silently build on state missing the
                # WAL's tail.  Refuse loudly.
                self._dead = True
                self._teardown_proc()
                raise ConnectionError(
                    'sidecar WAL replay failed after respawn (%s: %s); '
                    'client is dead' % (type(e).__name__, e)) from e

    # -- rpc ------------------------------------------------------------

    def _request_trace(self):
        """The wire context for ONE logical request (ISSUE 16): the
        ambient span's ids when the caller is traced, else a freshly
        minted root -- every outbound request carries a trace, so the
        gateway's spans, exemplars, recorder events, and fan-out frames
        are correlatable even when the caller runs untraced.  Minted
        ONCE per logical request, before the retry loop: a respawn
        retry re-sends the SAME ids (the request never got a response,
        so one client-visible request stays one trace)."""
        if not self._wire_trace:
            return None
        tctx = telemetry.current_trace_context()
        if tctx is not None:
            telemetry.metric('trace.propagated')
            return tctx
        telemetry.metric('trace.roots')
        return telemetry.new_root_context()

    def call(self, cmd, **kwargs):
        if self._dead:
            raise ConnectionError(
                'sidecar client is dead (server lost or close() called); '
                'build a new SidecarClient')
        # the client-side hop span: when span tracing is on, this is
        # the record `tools/amtpu_trace.py` anchors cross-process
        # assembly on (its wall is the client-observed request time);
        # the wire context is captured INSIDE it so the server's spans
        # become its children
        from ..errors import WrongReplicaError
        with telemetry.span('sidecar.client.request', cmd=cmd):
            tctx = self._request_trace()
            heals = redirects = 0
            while True:
                try:
                    if (self._heartbeat_s is not None and cmd != 'ping'
                            and time.monotonic() - self._last_ok
                            > self._heartbeat_s):
                        # cheap liveness probe: catch a dead server
                        # before shipping (and possibly losing) a batch
                        self._call_raw('ping', {})
                    result = self._call_raw(cmd, kwargs, trace=tctx)
                    break
                except WrongReplicaError:
                    # the doc migrated away (ISSUE 18): the op did NOT
                    # execute, so re-sending the SAME request is
                    # exactly-once -- through a router the ring catches
                    # up; past the budget the typed error surfaces with
                    # the new owner attached
                    telemetry.metric('sidecar.client.redirects')
                    redirects += 1
                    if redirects > self._max_redirects:
                        raise
                    time.sleep(0.01 * redirects)
                except ConnectionError as e:
                    telemetry.metric('sidecar.client.transport_errors')
                    if not self._heal or self._proc is None \
                            or heals >= self._max_respawns:
                        # reuse after this point would desync request
                        # ids / framing -- refuse loudly instead
                        self._dead = True
                        raise
                    heals += 1
                    with self._life_lock:
                        if not self._dead:   # another thread may have
                            self._respawn_and_replay()  # healed already
        if self._wal is not None and cmd in WAL_CMDS:
            with self._life_lock:
                self._wal.record(cmd, kwargs, trace=tctx)
                self._wal.maybe_compact(self._call_raw)
        return result

    # -- Backend surface -------------------------------------------------

    def apply_changes(self, doc, changes):
        return self.call('apply_changes', doc=doc, changes=changes)

    def apply_batch(self, docs):
        return self.call('apply_batch', docs=docs)

    def apply_local_change(self, doc, request):
        return self.call('apply_local_change', doc=doc, request=request)

    def get_patch(self, doc):
        return self.call('get_patch', doc=doc)

    def get_missing_deps(self, doc):
        return self.call('get_missing_deps', doc=doc)

    def get_missing_changes(self, doc, have_deps):
        return self.call('get_missing_changes', doc=doc,
                         have_deps=have_deps)

    def get_clock(self, doc):
        """Cheap frontier probe ({'clock', 'deps'}, no
        materialization) -- what a read replica polls to measure
        believed-vs-auth staleness (ISSUE 20)."""
        return self.call('get_clock', doc=doc)

    def snapshot(self, doc):
        """The doc's v2 container bytes at its current frontier, as a
        typed `readview.events.Snapshot` (``.data`` decodes the
        base64; ``.clock`` is the cache key -- equal clocks mean
        byte-identical artifacts).  The CDN-able cold-open path: load
        the bytes with ``load`` into any pool instead of replaying
        history (ISSUE 20)."""
        from ..readview.events import Snapshot
        return Snapshot(self.call('snapshot', doc=doc))

    # -- fan-out subscription surface (gateway socket mode) --------------

    def subscribe(self, doc=None, clock=None, peer=None, backfill=True,
                  docs=None, prefix=None, mode=None):
        """Subscribes this connection (optionally as named `peer`) to
        flush fan-out; returns the backfill ``{"doc", "clock",
        "changes"}``.  Event frames then arrive via `next_event()`.
        ``backfill=False`` registers at the advertised clock without
        shipping history (the next flush serves the gap through the
        straggler filter).  Doc-set and wildcard shapes (ISSUE 13):
        ``docs=[...]`` subscribes every listed doc in one request
        (result: ``{"docs": {doc: backfill}}``), ``prefix="ws/"``
        follows every current AND future doc under the prefix.  The
        subscription is recorded for resync auto-resubscribe.

        ``mode="patch"`` (ISSUE 20) asks for server-computed patch
        frames instead of change bytes -- the thin-client protocol;
        the backfill is then ``{"doc", "clock", "patch"}`` and
        auto-resubscribe preserves the mode across resyncs (the
        recorded kwargs carry it)."""
        self._ensure_pump()
        kwargs = {'clock': clock or {}}
        if doc is not None:
            kwargs['doc'] = doc
        if docs is not None:
            kwargs['docs'] = list(docs)
        if prefix is not None:
            kwargs['prefix'] = prefix
        if peer is not None:
            kwargs['peer'] = peer
        if not backfill:
            kwargs['backfill'] = False
        if mode is not None:
            kwargs['mode'] = mode
        res = self.call('subscribe', **kwargs)
        with self._resp_cond:
            self._subs[(doc, tuple(docs) if docs else None, prefix,
                        peer)] = dict(kwargs)
            got = res.get('docs') if isinstance(res, dict) else None
            if isinstance(got, dict):
                for d, r in got.items():
                    if isinstance(r, dict) and 'clock' in r:
                        self._sub_clocks.setdefault(d, r['clock'])
            elif isinstance(res, dict) and doc is not None:
                self._sub_clocks.setdefault(doc, res.get('clock') or {})
        return res

    def unsubscribe(self, doc=None, peer=None, docs=None, prefix=None):
        kwargs = {}
        if doc is not None:
            kwargs['doc'] = doc
        if docs is not None:
            kwargs['docs'] = list(docs)
        if prefix is not None:
            kwargs['prefix'] = prefix
        if peer is not None:
            kwargs['peer'] = peer
        res = self.call('unsubscribe', **kwargs)
        with self._resp_cond:
            self._subs.pop((doc, tuple(docs) if docs else None, prefix,
                            peer), None)
        return res

    def presence(self, doc, state, peer=None):
        """Ships ephemeral per-peer state (cursor position, selection)
        that rides the next flush's fan-out frames without touching the
        pool."""
        kwargs = {'doc': doc, 'state': state}
        if peer is not None:
            kwargs['peer'] = peer
        return self.call('presence', **kwargs)

    # -- observability ---------------------------------------------------

    def metrics(self):
        """Prometheus text exposition of the SERVER process
        ({'contentType': ..., 'body': ...})."""
        return self.call('metrics')

    def healthz(self):
        return self.call('healthz')

    def dump(self):
        """Triggers a SERVER-side flight-recorder dump; returns
        {'path', 'events', 'reason'} (docs/OBSERVABILITY.md)."""
        return self.call('dump')

    @property
    def restarts(self):
        """Server respawns this client has performed."""
        return self._respawns
