"""Sidecar client: drives a backend server process over stdio or a unix
socket.  This is the Python twin of the Node `backend=tpu` adapter -- it
implements the reference Backend call surface (backend/index.js:312-315)
by shipping requests across the process boundary, which is exactly the
deployment seam the reference designed the frontend/backend split for
(CHANGELOG.md:36-39, "work moved to a background thread")."""

import json
import socket
import struct
import subprocess
import sys

from .. import telemetry


class SidecarClient:
    def __init__(self, proc=None, sock_path=None, use_msgpack=False):
        """Connects to a server.  Exactly one of:
          * proc=None, sock_path=None: spawn a stdio server subprocess
          * sock_path: connect to a unix socket
          * proc: adopt an existing subprocess with stdio pipes
        """
        self._msgpack = use_msgpack
        self._next_id = 0
        self._proc = None
        self._sock = None
        if sock_path:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.connect(sock_path)
            self._r = self._sock.makefile('rb')
            self._w = self._sock.makefile('wb')
        else:
            if proc is None:
                cmd = [sys.executable, '-m', 'automerge_tpu.sidecar.server']
                if use_msgpack:
                    cmd.append('--msgpack')
                proc = subprocess.Popen(
                    cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE)
            self._proc = proc
            self._r = proc.stdout
            self._w = proc.stdin

    def close(self):
        try:
            self._w.close()
        except Exception:
            pass
        if self._proc is not None:
            self._proc.wait(timeout=10)
        if self._sock is not None:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- rpc ------------------------------------------------------------

    def call(self, cmd, **kwargs):
        self._next_id += 1
        req = dict(kwargs, cmd=cmd, id=self._next_id)
        # distributed tracing: when a span is active client-side, ship
        # its ids so the server's request span resumes the same trace
        # (server consumes the envelope; responses are unchanged)
        tctx = telemetry.current_trace_context()
        if tctx is not None:
            req.setdefault('trace', tctx)
        if self._msgpack:
            import msgpack
            body = msgpack.packb(req, use_bin_type=True)
            self._w.write(struct.pack('>I', len(body)) + body)
            self._w.flush()
            head = self._r.read(4)
            if len(head) < 4:
                raise ConnectionError('sidecar server closed the stream')
            (n,) = struct.unpack('>I', head)
            resp = msgpack.unpackb(self._r.read(n), raw=False,
                                   strict_map_key=False)
        else:
            self._w.write((json.dumps(req) + '\n').encode())
            self._w.flush()
            line = self._r.readline()
            if not line:
                raise ConnectionError('sidecar server closed the stream')
            resp = json.loads(line)
        if 'error' in resp:
            from ..errors import AutomergeError, RangeError
            types = {'AutomergeError': AutomergeError,
                     'RangeError': RangeError, 'TypeError': TypeError,
                     'KeyError': KeyError}
            raise types.get(resp.get('errorType'), AutomergeError)(
                resp['error'])
        return resp['result']

    # -- Backend surface -------------------------------------------------

    def apply_changes(self, doc, changes):
        return self.call('apply_changes', doc=doc, changes=changes)

    def apply_batch(self, docs):
        return self.call('apply_batch', docs=docs)

    def apply_local_change(self, doc, request):
        return self.call('apply_local_change', doc=doc, request=request)

    def get_patch(self, doc):
        return self.call('get_patch', doc=doc)

    def get_missing_deps(self, doc):
        return self.call('get_missing_deps', doc=doc)

    def get_missing_changes(self, doc, have_deps):
        return self.call('get_missing_changes', doc=doc,
                         have_deps=have_deps)

    # -- observability ---------------------------------------------------

    def metrics(self):
        """Prometheus text exposition of the SERVER process
        ({'contentType': ..., 'body': ...})."""
        return self.call('metrics')

    def healthz(self):
        return self.call('healthz')
