"""Backend sidecar: serves the reference's Backend protocol over
stdio or a unix socket, so a frontend in another process/language (the
reference's Node.js frontend via a `backend=tpu` adapter) can drive the
batched native resolver through the existing change/patch JSON boundary
(reference seam: frontend/index.js:98,315; surface: backend/index.js:312-315).

Two framings:
  * JSON lines (default): one request object per line, one response per
    line -- easy to drive from a shell or the reference's JS frontend.
  * msgpack (--msgpack): 4-byte big-endian length prefix + msgpack body.
    Patches/changes then stay msgpack end-to-end (the C++ runtime's
    native serialization); the request envelope itself is decoded in
    Python before dispatch.

Requests (fields beyond `cmd`/`id` per command):
  {"id": 1, "cmd": "apply_changes",      "doc": d, "changes": [...]}
  {"id": 2, "cmd": "apply_batch",        "docs": {d: [...], ...}}
  {"id": 3, "cmd": "apply_local_change", "doc": d, "request": {...}}
  {"id": 4, "cmd": "get_patch",          "doc": d}
  {"id": 5, "cmd": "get_missing_deps",   "doc": d}
  {"id": 6, "cmd": "get_missing_changes","doc": d, "have_deps": {...}}
  {"id": 7, "cmd": "ping"}

Responses: {"id": ..., "result": ...} or {"id": ..., "error": msg,
"errorType": "AutomergeError"|"RangeError"|"TypeError"}.

Run: python -m automerge_tpu.sidecar.server [--socket PATH] [--msgpack]
"""

import argparse
import json
import os
import socket
import struct
import sys

from ..errors import AutomergeError, RangeError


class SidecarBackend:
    """Protocol command dispatch over one NativeDocPool."""

    def __init__(self, pool=None):
        if pool is None:
            from ..native import NativeDocPool
            pool = NativeDocPool()
        self.pool = pool
        # per-doc clocks tracked from returned patches, so local-change
        # seq validation does not re-materialize the whole document
        self._clocks = {}
        # per-doc undo machinery (reference: op_set.js:297-308 push,
        # backend/index.js:254-310 execute): undo stack of inverse-op
        # lists, cursor position, redo stack
        self._undo = {}    # doc -> {'stack': [...], 'pos': int, 'redo': []}

    def _undo_state(self, doc):
        return self._undo.setdefault(doc, {'stack': [], 'pos': 0,
                                           'redo': []})

    def _note_patch(self, doc, patch):
        self._clocks[doc] = dict(patch.get('clock', {}))
        u = self._undo.get(doc)
        if u is not None:
            patch['canUndo'] = u['pos'] > 0
            patch['canRedo'] = len(u['redo']) > 0
        return patch

    # -- commands -------------------------------------------------------

    def apply_changes(self, doc, changes):
        return self._note_patch(doc, self.pool.apply_changes(doc, changes))

    def apply_batch(self, docs):
        patches = self.pool.apply_batch(docs)
        for doc, patch in patches.items():
            self._note_patch(doc, patch)
        return patches

    def apply_local_change(self, doc, request):
        """Local change request with the reference's validation and undo
        semantics (backend/index.js:175-197, 254-310)."""
        if not isinstance(request.get('actor'), str) or \
                not isinstance(request.get('seq'), int):
            # 'requries' [sic]: byte parity with the reference's own error
            # text (backend/index.js:177)
            raise TypeError(
                'Change request requries `actor` and `seq` properties')
        clock = self._clocks.get(doc)
        if clock is None:
            clock = self.pool.get_patch(doc)['clock']
            self._clocks[doc] = dict(clock)
        if request['seq'] <= clock.get(request['actor'], 0):
            raise RangeError('Change request has already been applied')
        request_type = request.get('requestType', 'change')
        if request_type == 'change':
            patch = self._local_change(doc, request)
        elif request_type == 'undo':
            patch = self._local_undo(doc, request)
        elif request_type == 'redo':
            patch = self._local_redo(doc, request)
        else:
            raise RangeError('Unknown requestType: %s' % request_type)
        patch['actor'] = request['actor']
        patch['seq'] = request['seq']
        return patch

    @staticmethod
    def _strip(record, drop):
        return {k: v for k, v in record.items() if k not in drop}

    def _local_change(self, doc, request):
        # inverse-op capture BEFORE applying (op_set.js:193-200): per
        # assign op, the current register projected to action/obj/key/value
        # -- or a del when the field was empty.  The frontend guarantees at
        # most one assignment per (obj, key) per change
        # (frontend/index.js:53), so pre-capture order equals the
        # reference's interleaved capture.
        undo_ops = []
        for op in request.get('ops', []):
            if op.get('action') not in ('set', 'del', 'link'):
                continue
            recs = self.pool.get_register(doc, op['obj'], op['key'])
            inv = [self._strip(r, ('actor', 'seq', 'datatype'))
                   for r in recs]
            undo_ops.extend(inv or [{'action': 'del', 'obj': op['obj'],
                                     'key': op['key']}])
        # requestType is transport-only: it must not leak into the stored
        # change history that get_missing_changes ships to peers
        change = {k: v for k, v in request.items() if k != 'requestType'}
        patch = self.pool.apply_changes(doc, [change])
        u = self._undo_state(doc)
        u['stack'] = u['stack'][:u['pos']] + [undo_ops]
        u['pos'] += 1
        u['redo'] = []
        return self._note_patch(doc, patch)

    def _local_undo(self, doc, request):
        u = self._undo_state(doc)
        if u['pos'] < 1 or u['pos'] > len(u['stack']):
            raise RangeError('Cannot undo: there is nothing to be undone')
        undo_ops = u['stack'][u['pos'] - 1]
        # redo ops from the CURRENT field state (backend/index.js:264-278)
        redo_ops = []
        for op in undo_ops:
            if op['action'] not in ('set', 'del', 'link'):
                raise RangeError(
                    'Unexpected operation type in undo history: %r' % (op,))
            recs = self.pool.get_register(doc, op['obj'], op['key'])
            if not recs:
                redo_ops.append({'action': 'del', 'obj': op['obj'],
                                 'key': op['key']})
            else:
                redo_ops.extend(self._strip(r, ('actor', 'seq'))
                                for r in recs)
        patch = self._apply_history_ops(doc, request, undo_ops)
        u['pos'] -= 1
        u['redo'].append(redo_ops)
        return self._note_patch(doc, patch)

    def _local_redo(self, doc, request):
        u = self._undo_state(doc)
        if not u['redo']:
            raise RangeError('Cannot redo: the last change was not an undo')
        redo_ops = u['redo'][-1]
        patch = self._apply_history_ops(doc, request, redo_ops)
        u['pos'] += 1
        u['redo'].pop()
        return self._note_patch(doc, patch)

    def _apply_history_ops(self, doc, request, ops):
        """Applies an undo/redo op list as a regular (non-undoable) change
        with the request's envelope (backend/index.js:255-262)."""
        change = {'actor': request['actor'], 'seq': request['seq'],
                  'deps': request.get('deps', {}), 'ops': ops}
        if request.get('message') is not None:
            change['message'] = request['message']
        return self.pool.apply_changes(doc, [change])

    def get_patch(self, doc):
        return self._note_patch(doc, self.pool.get_patch(doc))

    def get_missing_deps(self, doc):
        return self.pool.get_missing_deps(doc)

    def get_missing_changes(self, doc, have_deps):
        return self.pool.get_missing_changes(doc, have_deps)

    def get_changes_for_actor(self, doc, actor, after_seq=0):
        return self.pool.get_changes_for_actor(doc, actor, after_seq)

    # -- dispatch -------------------------------------------------------

    def handle(self, req):
        rid = req.get('id')
        try:
            cmd = req.get('cmd')
            if cmd == 'ping':
                result = {'ok': True}
            elif cmd == 'apply_changes':
                result = self.apply_changes(req['doc'], req['changes'])
            elif cmd == 'apply_batch':
                result = self.apply_batch(req['docs'])
            elif cmd == 'apply_local_change':
                result = self.apply_local_change(req['doc'], req['request'])
            elif cmd == 'get_patch':
                result = self.get_patch(req['doc'])
            elif cmd == 'get_missing_deps':
                result = self.get_missing_deps(req['doc'])
            elif cmd == 'get_missing_changes':
                result = self.get_missing_changes(req['doc'],
                                                  req.get('have_deps', {}))
            elif cmd == 'get_changes_for_actor':
                result = self.get_changes_for_actor(
                    req['doc'], req['actor'], req.get('after_seq', 0))
            else:
                raise RangeError('Unknown command: %r' % (cmd,))
            return {'id': rid, 'result': result}
        except (AutomergeError, RangeError, TypeError, KeyError) as e:
            return {'id': rid, 'error': str(e),
                    'errorType': type(e).__name__}


def serve_stream(rfile, wfile, use_msgpack=False, backend=None):
    """Serves requests from a byte stream until EOF."""
    backend = backend or SidecarBackend()
    if use_msgpack:
        import msgpack
        while True:
            head = rfile.read(4)
            if len(head) < 4:
                break
            (n,) = struct.unpack('>I', head)
            body = rfile.read(n)
            if len(body) < n:
                break
            try:
                req = msgpack.unpackb(body, raw=False, strict_map_key=False)
                if not isinstance(req, dict):
                    raise ValueError('request is not a map')
            except Exception as e:
                resp = {'id': None, 'error': 'bad msgpack: %s' % e,
                        'errorType': 'RangeError'}
            else:
                resp = backend.handle(req)
            out = msgpack.packb(resp, use_bin_type=True)
            wfile.write(struct.pack('>I', len(out)) + out)
            wfile.flush()
    else:
        for line in rfile:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except ValueError as e:
                resp = {'id': None, 'error': 'bad json: %s' % e,
                        'errorType': 'RangeError'}
            else:
                resp = backend.handle(req)
            wfile.write((json.dumps(resp) + '\n').encode())
            wfile.flush()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--socket', help='serve on a unix socket path '
                                     'instead of stdio')
    ap.add_argument('--msgpack', action='store_true',
                    help='length-prefixed msgpack framing instead of '
                         'JSON lines')
    args = ap.parse_args(argv)

    if args.socket:
        if os.path.exists(args.socket):
            os.unlink(args.socket)
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(args.socket)
        srv.listen(1)
        backend = SidecarBackend()   # pool shared across connections
        try:
            while True:
                conn, _ = srv.accept()
                with conn:
                    rfile = conn.makefile('rb')
                    wfile = conn.makefile('wb')
                    try:
                        serve_stream(rfile, wfile, args.msgpack, backend)
                    except (BrokenPipeError, ConnectionError, OSError) as e:
                        # one misbehaving client must not take down the
                        # shared pool for everyone else
                        print('sidecar: connection dropped: %s' % e,
                              file=sys.stderr)
        finally:
            srv.close()
            if os.path.exists(args.socket):
                os.unlink(args.socket)
    else:
        serve_stream(sys.stdin.buffer, sys.stdout.buffer, args.msgpack)


if __name__ == '__main__':
    main()
