"""Backend sidecar: serves the reference's Backend protocol over
stdio or a unix socket, so a frontend in another process/language (the
reference's Node.js frontend via a `backend=tpu` adapter) can drive the
batched native resolver through the existing change/patch JSON boundary
(reference seam: frontend/index.js:98,315; surface: backend/index.js:312-315).

Two framings:
  * JSON lines (default): one request object per line, one response per
    line -- easy to drive from a shell or the reference's JS frontend.
  * msgpack (--msgpack): 4-byte big-endian length prefix + msgpack body.
    Patches/changes then stay msgpack end-to-end (the C++ runtime's
    native serialization); the request envelope itself is decoded in
    Python before dispatch.

Requests (fields beyond `cmd`/`id` per command):
  {"id": 1, "cmd": "apply_changes",      "doc": d, "changes": [...]}
  {"id": 2, "cmd": "apply_batch",        "docs": {d: [...], ...}}
  {"id": 3, "cmd": "apply_local_change", "doc": d, "request": {...}}
  {"id": 4, "cmd": "get_patch",          "doc": d}
  {"id": 5, "cmd": "get_missing_deps",   "doc": d}
  {"id": 6, "cmd": "get_missing_changes","doc": d, "have_deps": {...}}
  {"id": 7, "cmd": "ping"}
  {"id": 8, "cmd": "save",               "doc": d}
  {"id": 9, "cmd": "load",               "doc": d, "data": <checkpoint>}

Checkpoints are binary; on the wire they travel base64-encoded
({"checkpoint_b64": ...} from save, and load's "data" field accepts the
base64 string or, under msgpack framing, raw bytes) so both framings can
carry them.

Responses: {"id": ..., "result": ...} or {"id": ..., "error": msg,
"errorType": "AutomergeError"|"RangeError"|"TypeError"}.

Run: python -m automerge_tpu.sidecar.server [--socket PATH] [--msgpack]
"""

import argparse
import json
import os
import socket
import struct
import sys

from ..errors import AutomergeError, RangeError
from ..utils.jaxenv import pin_cpu

# honor a JAX_PLATFORMS=cpu environment (the sitecustomize-registered
# accelerator plugin would otherwise override it and a wedged device
# tunnel would hang the sidecar at first kernel dispatch)
pin_cpu()


class SidecarBackend:
    """Protocol command dispatch over one NativeDocPool."""

    def __init__(self, pool=None):
        if pool is None:
            from ..native import NativeDocPool
            pool = NativeDocPool()
        self.pool = pool

    # -- commands -------------------------------------------------------

    def apply_changes(self, doc, changes):
        return self.pool.apply_changes(doc, changes)

    def apply_batch(self, docs):
        return self.pool.apply_batch(docs)

    def apply_local_change(self, doc, request):
        """Local change request with the reference's validation and undo
        semantics (backend/index.js:175-197, 254-310).  The undo capture
        runs inside the pool's runtime (amtpu_begin_local /
        TPUDocPool.apply_local_change), reading the register mirror
        in-process with the reference's topLevel gate."""
        return self.pool.apply_local_change(doc, request)

    def get_patch(self, doc):
        return self.pool.get_patch(doc)

    def save(self, doc):
        """Checkpoint for one doc (application-order history; reference:
        src/automerge.js:45-52), base64-wrapped so the JSON framing can
        carry it."""
        import base64
        return {'checkpoint_b64':
                base64.b64encode(self.pool.save(doc)).decode('ascii')}

    def load(self, doc, data):
        """Batched-replay restore of a save() checkpoint; `data` is the
        base64 string from save (or raw bytes under msgpack framing)."""
        if isinstance(data, str):
            import base64
            try:
                data = base64.b64decode(data, validate=True)
            except Exception:
                raise RangeError('checkpoint data is not valid base64')
        return self.pool.load(doc, data)

    def get_missing_deps(self, doc):
        return self.pool.get_missing_deps(doc)

    def get_missing_changes(self, doc, have_deps):
        return self.pool.get_missing_changes(doc, have_deps)

    def get_changes_for_actor(self, doc, actor, after_seq=0):
        return self.pool.get_changes_for_actor(doc, actor, after_seq)

    # -- dispatch -------------------------------------------------------

    def handle(self, req):
        rid = req.get('id')
        try:
            cmd = req.get('cmd')
            if cmd == 'ping':
                result = {'ok': True}
            elif cmd == 'apply_changes':
                result = self.apply_changes(req['doc'], req['changes'])
            elif cmd == 'apply_batch':
                result = self.apply_batch(req['docs'])
            elif cmd == 'apply_local_change':
                result = self.apply_local_change(req['doc'], req['request'])
            elif cmd == 'get_patch':
                result = self.get_patch(req['doc'])
            elif cmd == 'save':
                result = self.save(req['doc'])
            elif cmd == 'load':
                result = self.load(req['doc'], req['data'])
            elif cmd == 'get_missing_deps':
                result = self.get_missing_deps(req['doc'])
            elif cmd == 'get_missing_changes':
                result = self.get_missing_changes(req['doc'],
                                                  req.get('have_deps', {}))
            elif cmd == 'get_changes_for_actor':
                result = self.get_changes_for_actor(
                    req['doc'], req['actor'], req.get('after_seq', 0))
            else:
                raise RangeError('Unknown command: %r' % (cmd,))
            return {'id': rid, 'result': result}
        except KeyError as e:
            # a malformed request (missing field) maps into the protocol's
            # documented error set instead of leaking Python's KeyError
            return {'id': rid, 'error': 'missing required field: %s' % e,
                    'errorType': 'RangeError'}
        except (AutomergeError, RangeError, TypeError) as e:
            return {'id': rid, 'error': str(e),
                    'errorType': type(e).__name__}


def serve_stream(rfile, wfile, use_msgpack=False, backend=None):
    """Serves requests from a byte stream until EOF."""
    backend = backend or SidecarBackend()
    if use_msgpack:
        import msgpack
        while True:
            head = rfile.read(4)
            if len(head) < 4:
                break
            (n,) = struct.unpack('>I', head)
            body = rfile.read(n)
            if len(body) < n:
                break
            try:
                req = msgpack.unpackb(body, raw=False, strict_map_key=False)
                if not isinstance(req, dict):
                    raise ValueError('request is not a map')
            except Exception as e:
                resp = {'id': None, 'error': 'bad msgpack: %s' % e,
                        'errorType': 'RangeError'}
            else:
                resp = backend.handle(req)
            out = msgpack.packb(resp, use_bin_type=True)
            wfile.write(struct.pack('>I', len(out)) + out)
            wfile.flush()
    else:
        for line in rfile:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except ValueError as e:
                resp = {'id': None, 'error': 'bad json: %s' % e,
                        'errorType': 'RangeError'}
            else:
                resp = backend.handle(req)
            wfile.write((json.dumps(resp) + '\n').encode())
            wfile.flush()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--socket', help='serve on a unix socket path '
                                     'instead of stdio')
    ap.add_argument('--msgpack', action='store_true',
                    help='length-prefixed msgpack framing instead of '
                         'JSON lines')
    args = ap.parse_args(argv)

    if args.socket:
        if os.path.exists(args.socket):
            os.unlink(args.socket)
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(args.socket)
        srv.listen(1)
        backend = SidecarBackend()   # pool shared across connections
        try:
            while True:
                conn, _ = srv.accept()
                with conn:
                    rfile = conn.makefile('rb')
                    wfile = conn.makefile('wb')
                    try:
                        serve_stream(rfile, wfile, args.msgpack, backend)
                    except (BrokenPipeError, ConnectionError, OSError) as e:
                        # one misbehaving client must not take down the
                        # shared pool for everyone else
                        print('sidecar: connection dropped: %s' % e,
                              file=sys.stderr)
        finally:
            srv.close()
            if os.path.exists(args.socket):
                os.unlink(args.socket)
    else:
        serve_stream(sys.stdin.buffer, sys.stdout.buffer, args.msgpack)


if __name__ == '__main__':
    main()
