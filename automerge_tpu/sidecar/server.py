"""Backend sidecar: serves the reference's Backend protocol over
stdio or a unix socket, so a frontend in another process/language (the
reference's Node.js frontend via a `backend=tpu` adapter) can drive the
batched native resolver through the existing change/patch JSON boundary
(reference seam: frontend/index.js:98,315; surface: backend/index.js:312-315).

Two framings:
  * JSON lines (default): one request object per line, one response per
    line -- easy to drive from a shell or the reference's JS frontend.
  * msgpack (--msgpack): 4-byte big-endian length prefix + msgpack body.
    Patches/changes then stay msgpack end-to-end (the C++ runtime's
    native serialization); the request envelope itself is decoded in
    Python before dispatch.

Socket mode serves through the continuous-batching gateway
(automerge_tpu/scheduler/, docs/SERVING.md): many concurrent
connections, mutating requests coalesced across connections into one
pool batch per flush, typed Overloaded shedding past the queue
watermark.  Responses may then complete out of request order within a
connection (reads bypass the batch path); clients match responses by
id.  `--serial` (or AMTPU_GATEWAY=0) restores the one-connection
-at-a-time in-order loop.  Stdio mode is always serial.

Requests (fields beyond `cmd`/`id` per command):
  {"id": 1, "cmd": "apply_changes",      "doc": d, "changes": [...]}
  {"id": 2, "cmd": "apply_batch",        "docs": {d: [...], ...}}
  {"id": 3, "cmd": "apply_local_change", "doc": d, "request": {...}}
  {"id": 4, "cmd": "get_patch",          "doc": d}
  {"id": 5, "cmd": "get_missing_deps",   "doc": d}
  {"id": 6, "cmd": "get_missing_changes","doc": d, "have_deps": {...}}
  {"id": 7, "cmd": "ping"}
  {"id": 8, "cmd": "save",               "doc": d}
  {"id": 9, "cmd": "load",               "doc": d, "data": <checkpoint>}
  {"id": 10, "cmd": "metrics"}
  {"id": 11, "cmd": "healthz"}
  {"id": 12, "cmd": "subscribe",   "doc": d, "clock": {...}, "peer": p?}
      (doc-set/wildcard shapes: "docs": [d, ...] or "prefix": "ws/";
       "mode": "patch" flips the subscription to server-computed patch
       frames -- ISSUE 20, docs/SERVING.md read path)
  {"id": 13, "cmd": "unsubscribe", "doc": d, "peer": p?}
  {"id": 14, "cmd": "presence",    "doc": d, "state": ..., "peer": p?}
  {"id": 15, "cmd": "dump"}
  {"id": 16, "cmd": "snapshot",    "doc": d}
      -> {"doc": d, "clock": {...}, "snapshot_b64": <v2 container>}
      (cache-keyed by frontier clock: an unchanged doc answers the
       same CDN-able artifact without rebuilding it)
  {"id": 17, "cmd": "get_clock",   "doc": d}
      (the cheap frontier probe -- no materialization; read replicas
       measure believed-vs-auth staleness with it)

`dump` writes the always-on flight recorder's event ring as JSONL
(docs/OBSERVABILITY.md) and answers {"path": ..., "events": n}; the
same ring is served in place at the HTTP listener's /debug/recorder.

The last three are the batched fan-out control plane (ISSUE 9,
docs/SERVING.md fan-out section) and are served only by the gateway
(socket mode): subscribers receive unsolicited event frames (no `id`;
an `event` key instead) whenever a flush commits changes to their doc.
Stdio/--serial mode answers them with a RangeError.

Observability: `metrics` answers {"contentType": ..., "body": <Prometheus
text exposition>} for the whole process (docs/OBSERVABILITY.md), and
`healthz` a liveness dict -- the same payloads the optional HTTP
listener (--metrics-port) serves at /metrics and /healthz.  Requests may
carry {"trace": {"traceId": ..., "spanId": ...}} to resume a client-side
trace (traceId is 128-bit/32-hex, spanId 64-bit/16-hex; SidecarClient
stamps it on every outbound request, minting a root when the caller has
no ambient span, and keeps it stable across respawn retries and WAL
replay); the envelope is consumed server-side (responses are unchanged)
and surfaces in the JSONL span export (AMTPU_TRACE_FILE) -- each process
writes its OWN trace file and tools/amtpu_trace.py assembles the
cross-process tree.

Checkpoints are binary; on the wire they travel base64-encoded
({"checkpoint_b64": ...} from save, and load's "data" field accepts the
base64 string or, under msgpack framing, raw bytes) so both framings can
carry them.

Responses: {"id": ..., "result": ...} or {"id": ..., "error": msg,
"errorType": "AutomergeError"|"RangeError"|"TypeError"}.

Run: python -m automerge_tpu.sidecar.server [--socket PATH] [--msgpack]
         [--metrics-port N]
"""

import argparse
import json
import os
import signal
import socket
import struct
import sys
import time

from .. import faults, telemetry
from ..errors import AutomergeError, RangeError
from ..utils.common import env_bool, env_int, env_raw, env_str
from ..telemetry import httpd as telemetry_httpd
from ..utils.jaxenv import pin_cpu

# honor a JAX_PLATFORMS=cpu environment (the sitecustomize-registered
# accelerator plugin would otherwise override it and a wedged device
# tunnel would hang the sidecar at first kernel dispatch)
pin_cpu()


class SidecarBackend:
    """Protocol command dispatch over one NativeDocPool."""

    def __init__(self, pool=None):
        if pool is None:
            # AMTPU_MESH=dp[,sp] moves the whole serving stack (gateway
            # coalescing, resilience, this sidecar) onto the device
            # mesh; default stays the single-device pool
            from ..native import make_pool
            pool = make_pool()
        self.pool = pool
        # frontier-clock-keyed v2 container memo for the `snapshot`
        # command (ISSUE 20; readview/snapshot.py)
        from ..readview.snapshot import SnapshotCache
        self._snapshots = SnapshotCache()

    # -- commands -------------------------------------------------------

    def apply_changes(self, doc, changes):
        return self.pool.apply_changes(doc, changes)

    def apply_batch(self, docs):
        return self.pool.apply_batch(docs)

    def apply_local_change(self, doc, request):
        """Local change request with the reference's validation and undo
        semantics (backend/index.js:175-197, 254-310).  The undo capture
        runs inside the pool's runtime (amtpu_begin_local /
        TPUDocPool.apply_local_change), reading the register mirror
        in-process with the reference's topLevel gate."""
        return self.pool.apply_local_change(doc, request)

    def get_patch(self, doc):
        return self.pool.get_patch(doc)

    def save(self, doc):
        """Checkpoint for one doc (application-order history; reference:
        src/automerge.js:45-52), base64-wrapped so the JSON framing can
        carry it."""
        import base64
        return {'checkpoint_b64':
                base64.b64encode(self.pool.save(doc)).decode('ascii')}

    def load(self, doc, data):
        """Batched-replay restore of a save() checkpoint; `data` is the
        base64 string from save (or raw bytes under msgpack framing)."""
        if isinstance(data, str):
            import base64
            try:
                data = base64.b64decode(data, validate=True)
            except Exception:
                raise RangeError('checkpoint data is not valid base64')
        return self.pool.load(doc, data)

    def get_clock(self, doc):
        """Cheap frontier probe: the doc's {actor: seq} clock with no
        materialization -- the staleness measurement a read replica
        polls (ISSUE 20)."""
        return self.pool.get_clock(doc)

    def snapshot(self, doc):
        """The doc's v2 container bytes, cache-keyed by frontier clock
        (ISSUE 20 tentpole, piece c): a cold-opening client loads ONE
        CDN-able artifact instead of replaying history, and an
        unchanged doc serves the same bytes without rebuilding."""
        import base64
        clock = self.pool.get_clock(doc).get('clock') or {}
        data = self._snapshots.get(doc, clock,
                                   lambda: self.pool.save(doc))
        telemetry.metric('readview.snapshots_served')
        return {'doc': doc, 'clock': clock,
                'snapshot_b64':
                    base64.b64encode(data).decode('ascii')}

    def get_missing_deps(self, doc):
        return self.pool.get_missing_deps(doc)

    def get_missing_changes(self, doc, have_deps):
        return self.pool.get_missing_changes(doc, have_deps)

    def get_changes_for_actor(self, doc, actor, after_seq=0):
        return self.pool.get_changes_for_actor(doc, actor, after_seq)

    # -- dispatch -------------------------------------------------------

    # the protocol's command set -- also the label universe for the
    # per-command request metrics (an unknown wire string must not mint
    # unbounded label values)
    COMMANDS = ('ping', 'apply_changes', 'apply_batch',
                'apply_local_change', 'get_patch', 'save', 'load',
                'get_missing_deps', 'get_missing_changes',
                'get_changes_for_actor', 'metrics', 'healthz', 'dump',
                'subscribe', 'unsubscribe', 'presence',
                'migrate_out', 'migrate_in', 'snapshot', 'get_clock')

    def handle(self, req):
        """Wraps dispatch in the per-request telemetry: a span resuming
        the client's trace context (when the request carries one) plus
        always-on request count/latency series.  Responses are
        byte-identical to the un-instrumented protocol."""
        cmd = req.get('cmd')
        label = cmd if cmd in self.COMMANDS else 'unknown'
        tctx = req.get('trace')
        tctx = tctx if isinstance(tctx, dict) else {}
        t0 = time.perf_counter()
        with telemetry.span_with_context(
                'sidecar.request', tctx.get('traceId'), tctx.get('spanId'),
                cmd=label, rid=req.get('id')):
            resp = self._dispatch(req, cmd)
        telemetry.SIDECAR_LATENCY.labels(label).observe(
            time.perf_counter() - t0)
        telemetry.SIDECAR_REQS.labels(
            label, 'error' if 'error' in resp else 'ok').inc()
        return resp

    def _dispatch(self, req, cmd):
        rid = req.get('id')
        try:
            if cmd == 'ping':
                result = {'ok': True}
            elif cmd == 'metrics':
                result = {'contentType': telemetry_httpd.CONTENT_TYPE,
                          'body': telemetry.render_prometheus()}
            elif cmd == 'healthz':
                result = telemetry.healthz()
            elif cmd == 'dump':
                # on-demand flight-recorder dump (docs/OBSERVABILITY.md):
                # writes the ring as JSONL and answers the path, so an
                # operator can snapshot "what just happened" without
                # waiting for a fault to trigger it
                result = telemetry.recorder.dump('request', force=True) \
                    or {'path': None, 'events': 0, 'reason': 'request'}
            elif cmd == 'apply_changes':
                result = self.apply_changes(req['doc'], req['changes'])
            elif cmd == 'apply_batch':
                result = self.apply_batch(req['docs'])
            elif cmd == 'apply_local_change':
                result = self.apply_local_change(req['doc'], req['request'])
            elif cmd == 'get_patch':
                result = self.get_patch(req['doc'])
            elif cmd == 'save':
                result = self.save(req['doc'])
            elif cmd == 'load':
                result = self.load(req['doc'], req['data'])
            elif cmd == 'snapshot':
                result = self.snapshot(req['doc'])
            elif cmd == 'get_clock':
                result = self.get_clock(req['doc'])
            elif cmd == 'get_missing_deps':
                result = self.get_missing_deps(req['doc'])
            elif cmd == 'get_missing_changes':
                result = self.get_missing_changes(req['doc'],
                                                  req.get('have_deps', {}))
            elif cmd == 'get_changes_for_actor':
                result = self.get_changes_for_actor(
                    req['doc'], req['actor'], req.get('after_seq', 0))
            elif cmd in ('subscribe', 'unsubscribe', 'presence',
                         'migrate_out', 'migrate_in'):
                # the fan-out AND migration control planes live in the
                # gateway's flush cycle (migration needs the per-doc
                # FIFO to serialize against in-flight ops); a
                # serial/stdio server has no dispatcher to ride
                raise RangeError(
                    '%s requires the continuous-batching gateway '
                    '(socket mode without --serial/AMTPU_GATEWAY=0)'
                    % cmd)
            else:
                raise RangeError('Unknown command: %r' % (cmd,))
            return {'id': rid, 'result': result}
        except KeyError as e:
            # a malformed request (missing field) maps into the protocol's
            # documented error set instead of leaking Python's KeyError
            return {'id': rid, 'error': 'missing required field: %s' % e,
                    'errorType': 'RangeError'}
        except (AutomergeError, RangeError, TypeError) as e:
            return {'id': rid, 'error': str(e),
                    'errorType': type(e).__name__}
        except Exception as e:
            # an unexpected exception out of the pool (e.g. a RuntimeError
            # from JAX) must not kill the whole serve loop: answer the
            # protocol's InternalError envelope and keep serving -- one
            # poisoned request is one failed response, not an outage
            telemetry.SIDECAR_INTERNAL.inc()
            telemetry.metric('sidecar.internal_errors')
            return {'id': rid,
                    'error': '%s: %s' % (type(e).__name__, e),
                    'errorType': 'InternalError'}


def serve_stream(rfile, wfile, use_msgpack=False, backend=None):
    """Serves requests from a byte stream until EOF.

    The `sidecar.frame` fault site fires per request BEFORE dispatch and
    is deliberately uncaught: an armed frame fault kills the serve loop
    (and the process, under __main__), simulating the server crash the
    self-healing client exists to survive."""
    backend = backend or SidecarBackend()

    def frame_fault():
        if faults.ARMED:
            faults.fire('sidecar.frame')

    if use_msgpack:
        import msgpack
        while True:
            head = rfile.read(4)
            if len(head) < 4:
                break
            (n,) = struct.unpack('>I', head)
            body = rfile.read(n)
            if len(body) < n:
                break
            try:
                req = msgpack.unpackb(body, raw=False, strict_map_key=False)
                if not isinstance(req, dict):
                    raise ValueError('request is not a map')
            except Exception as e:
                resp = {'id': None, 'error': 'bad msgpack: %s' % e,
                        'errorType': 'RangeError'}
            else:
                frame_fault()
                resp = backend.handle(req)
            out = msgpack.packb(resp, use_bin_type=True)
            wfile.write(struct.pack('>I', len(out)) + out)
            wfile.flush()
    else:
        for line in rfile:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except ValueError as e:
                resp = {'id': None, 'error': 'bad json: %s' % e,
                        'errorType': 'RangeError'}
            else:
                frame_fault()
                resp = backend.handle(req)
            wfile.write((json.dumps(resp) + '\n').encode())
            wfile.flush()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--socket', help='serve on a unix socket path '
                                     'instead of stdio')
    ap.add_argument('--msgpack', action='store_true',
                    help='length-prefixed msgpack framing instead of '
                         'JSON lines')
    # a set-but-empty/garbage AMTPU_METRICS_PORT must not kill a server
    # that never asked for metrics -- fall back to off
    env_port = env_int('AMTPU_METRICS_PORT', -1)
    raw_port = env_raw('AMTPU_METRICS_PORT')
    if env_port == -1 and raw_port not in (None, ''):
        try:
            int(raw_port)       # an explicit -1 is a valid "off"
        except ValueError:
            print('sidecar: ignoring non-integer AMTPU_METRICS_PORT=%r'
                  % raw_port, file=sys.stderr)
    ap.add_argument('--metrics-port', type=int, default=env_port,
                    help='serve Prometheus /metrics + /healthz on this '
                         'HTTP port (0 = ephemeral; default: off, or '
                         'AMTPU_METRICS_PORT)')
    ap.add_argument('--metrics-host',
                    default=env_str('AMTPU_METRICS_HOST',
                                    '127.0.0.1'),
                    help='bind address for the metrics listener '
                         '(default loopback; 0.0.0.0 for a remote '
                         'Prometheus fleet scrape)')
    ap.add_argument('--serial', action='store_true',
                    help='socket mode only: serve one connection at a '
                         'time through the pre-gateway serial loop '
                         'instead of the continuous-batching gateway '
                         '(docs/SERVING.md)')
    ap.add_argument('--trace', action='store_true',
                    help='enable span tracing at startup (equivalent to '
                         'AMTPU_TRACE=1; pair with AMTPU_TRACE_FILE for '
                         'JSONL export)')
    args = ap.parse_args(argv)
    if not env_bool('AMTPU_GATEWAY', True):
        args.serial = True          # env kill-switch for the gateway

    if args.trace:
        telemetry.enable()
    if args.metrics_port >= 0:
        srv = telemetry_httpd.start_metrics_server(args.metrics_port,
                                                   host=args.metrics_host)
        print('sidecar: metrics on http://%s:%d/metrics'
              % (args.metrics_host, srv.server_port), file=sys.stderr)

    # supervised restarts deliver SIGTERM (and interactive runs SIGINT);
    # the handler does the listener/socket-path cleanup ITSELF and exits
    # hard -- raising SystemExit from a signal handler is unreliable
    # here (the signal may land inside a C-extension callback, e.g. the
    # XLA GC hook, where the exception is printed and swallowed), and a
    # stale socket path hands the next incarnation an "address already
    # in use" race
    cleanup = []      # filled by the socket branch below

    def _graceful_exit(signum, _frame):
        if signum == signal.SIGTERM:
            # a supervised shutdown is a post-mortem opportunity: dump
            # the flight recorder before the ring dies with the process
            try:
                telemetry.recorder.dump('sigterm', force=True)
            except Exception:
                pass
        for fn in cleanup:
            try:
                fn()
            except Exception:
                pass
        os._exit(128 + signum)

    try:
        signal.signal(signal.SIGTERM, _graceful_exit)
        signal.signal(signal.SIGINT, _graceful_exit)
    except ValueError:
        pass      # not the main thread (embedded serve): signals stay

    if args.socket and not args.serial:
        # default socket mode: the continuous-batching serve gateway
        # (docs/SERVING.md) -- many concurrent connections, cross
        # -connection coalescing into one pool batch per flush,
        # admission control past the queue watermark
        from ..scheduler import GatewayServer
        gw = GatewayServer(args.socket, use_msgpack=args.msgpack,
                           backend=SidecarBackend())
        cleanup.append(gw.stop)
        try:
            gw.serve_forever()
        finally:
            gw.stop()
    elif args.socket:
        # --serial: the pre-gateway loop -- one connection at a time,
        # strictly in-order responses (debugging / bisection aid)
        if os.path.exists(args.socket):
            os.unlink(args.socket)
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(args.socket)
        srv.listen(1)
        cleanup.append(srv.close)
        cleanup.append(lambda: os.path.exists(args.socket)
                       and os.unlink(args.socket))
        backend = SidecarBackend()   # pool shared across connections
        try:
            while True:
                conn, _ = srv.accept()
                with conn:
                    rfile = conn.makefile('rb')
                    wfile = conn.makefile('wb')
                    try:
                        serve_stream(rfile, wfile, args.msgpack, backend)
                    except (BrokenPipeError, ConnectionError, OSError) as e:
                        # one misbehaving client must not take down the
                        # shared pool for everyone else
                        print('sidecar: connection dropped: %s' % e,
                              file=sys.stderr)
        finally:
            srv.close()
            if os.path.exists(args.socket):
                os.unlink(args.socket)
    else:
        serve_stream(sys.stdin.buffer, sys.stdout.buffer, args.msgpack)


if __name__ == '__main__':
    main()
