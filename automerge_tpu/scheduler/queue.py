"""Admission-controlled request queue for the serve gateway
(docs/SERVING.md).

Connection reader threads `offer()` decoded mutating requests; the
single dispatcher thread `wait_for_work()`s until the coalescing window
closes and then `claim()`s one flush's worth of work.  Three invariants
live here:

  * **Bounded memory** -- the queue admits at most ``AMTPU_QUEUE_MAX_OPS``
    queued ops (high watermark).  Past it the queue enters *shedding*:
    new mutating requests raise :class:`Overloaded` (the gateway answers
    the typed ``{"errorType": "Overloaded", "retryAfterMs": ...}``
    envelope) until the backlog drains below the low watermark
    (``AMTPU_QUEUE_LOW_FRAC`` of max, default 0.5) -- hysteresis so one
    burst doesn't flap admission per request.  Read-only requests that
    must queue for ordering are admitted unconditionally (they answer
    from state, shedding them saves nothing).
  * **Per-doc FIFO** -- ``claim()`` walks the queue in arrival order and
    takes at most ONE op per doc per flush; an op whose doc is already
    taken parks (stays queued), and parking a doc blocks every later op
    touching it, so cross-doc reordering never reorders one doc's ops.
  * **Read-your-writes** -- ``doc_pending()`` tells the gateway whether
    a doc still has un-answered mutating ops (queued or in-flight until
    the response is written), which is what routes a read through the
    queue instead of the inline bypass.
"""

import threading
import time

from .. import telemetry
from ..utils.common import env_float as _env_float
from ..utils.common import env_int as _env_int


def flush_deadline_s():
    """Coalescing window: how long the dispatcher lets mutating requests
    accumulate after the first one before flushing
    (``AMTPU_FLUSH_DEADLINE_MS``, default 2ms)."""
    return max(0.0, _env_float('AMTPU_FLUSH_DEADLINE_MS', 2.0)) / 1000.0


def max_batch_docs():
    """Docs per coalesced flush cap (``AMTPU_MAX_BATCH_DOCS``)."""
    return max(1, _env_int('AMTPU_MAX_BATCH_DOCS', 256))


def max_batch_ops():
    """Queued-ops-per-flush cap -- a third flush trigger next to the
    deadline and the doc cap (``AMTPU_MAX_BATCH_OPS``)."""
    return max(1, _env_int('AMTPU_MAX_BATCH_OPS', 2048))


#: read-only commands: the gateway's routing table for the inline
#: bypass, and this module's pending-doc accounting (reads never count
#: as pending mutations -- counting them would wedge doc_pending when a
#: read queues behind another read).  Owned here so the two users
#: cannot drift.
READ_CMDS = ('get_patch', 'save', 'get_missing_deps',
             'get_missing_changes', 'get_changes_for_actor',
             'snapshot', 'get_clock')


class Overloaded(Exception):
    """Raised by ``offer()`` while shedding; carries the retry hint the
    wire envelope ships as ``retryAfterMs``."""

    def __init__(self, msg, retry_after_ms):
        super().__init__(msg)
        self.retry_after_ms = retry_after_ms


class PendingOp(object):
    """One decoded request parked between its reader thread and the
    dispatcher.  ``docs`` is the tuple of doc keys the op touches (one
    for apply_changes/apply_local_change/load/reads, many for a
    client-sent apply_batch); ``batchable`` marks ops the dispatcher may
    coalesce into one pool batch."""

    __slots__ = ('conn', 'rid', 'cmd', 'req', 'docs', 'n_ops',
                 'batchable', 'enq_t', 'clock', 'failed', 'answered')

    def __init__(self, conn, rid, cmd, req, docs, n_ops, batchable):
        self.conn = conn
        self.rid = rid
        self.cmd = cmd
        self.req = req
        self.docs = tuple(docs)
        self.n_ops = max(1, int(n_ops))
        self.batchable = bool(batchable)
        self.enq_t = time.perf_counter()
        # critical-path attribution (telemetry/attribution.py): the
        # gateway attaches a stage Clock before offer() and clears it
        # at finalization; `failed` records the response outcome;
        # `answered` guards the dispatcher's crash sweep from double
        # -finishing ops a partial flush already answered
        self.clock = None
        self.failed = False
        self.answered = False


class AdmissionQueue(object):
    def __init__(self, max_ops=None, low_frac=None):
        if max_ops is None:
            max_ops = _env_int('AMTPU_QUEUE_MAX_OPS', 4096)
        if low_frac is None:
            low_frac = _env_float('AMTPU_QUEUE_LOW_FRAC', 0.5)
        self.max_ops = max(1, int(max_ops))
        self.low_ops = max(0, min(self.max_ops - 1,
                                  int(self.max_ops * low_frac)))
        # `_work` is a Condition ON `_lock`: holding either IS holding
        # the one queue lock, so the guarded-by annotations (enforced
        # by `make static-check`, docs/ANALYSIS.md) accept both.
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        # arrival order; parked ops stay put
        self._items = []          # guarded-by: self._lock|self._work
        # queued (unclaimed) ops
        self.depth_ops = 0        # guarded-by: self._lock|self._work
        self.shedding = False     # guarded-by: self._lock|self._work
        # doc -> mutating ops not yet answered
        self._pending_docs = {}   # guarded-by: self._lock|self._work
        self._closed = False      # guarded-by: self._lock|self._work

    # -- producer side (connection reader threads) ----------------------

    def offer(self, op, admit_always=False):
        """Enqueues `op` or raises :class:`Overloaded`.  `admit_always`
        bypasses admission (ordered read-only ops: rejecting a read
        frees no meaningful memory and would break read-your-writes).
        An op bigger than the whole queue is admitted iff the queue is
        empty (see below) -- the watermark bounds backlog, not request
        size."""
        with self._work:
            if self._closed:
                raise Overloaded('gateway is shutting down', 0)
            if not admit_always:
                if self.shedding and self.depth_ops <= self.low_ops:
                    self.shedding = False
                    telemetry.recorder.record('shed.off',
                                              n=self.depth_ops)
                # a single request LARGER than the whole queue is
                # admitted when the queue is empty (the --serial loop
                # accepts it, and claim() serves an oversized op as its
                # own flush) -- the watermark bounds backlog, it is not
                # a request-size limit; depth then overshoots by at
                # most one request
                over = self.depth_ops + op.n_ops > self.max_ops \
                    and self.depth_ops > 0
                if self.shedding or over:
                    if not self.shedding:
                        # flight-recorder transition event (the per
                        # -request counter below stays per shed)
                        telemetry.recorder.record('shed.on',
                                                  n=self.depth_ops)
                    self.shedding = True
                    telemetry.metric('scheduler.shed')
                    raise Overloaded(
                        'gateway queue full (%d/%d queued ops); retry '
                        'after backoff' % (self.depth_ops, self.max_ops),
                        self.retry_after_ms())
            self._items.append(op)
            self.depth_ops += op.n_ops
            if op.cmd not in READ_CMDS:
                for d in op.docs:
                    self._pending_docs[d] = \
                        self._pending_docs.get(d, 0) + 1
            self._work.notify()

    def retry_after_ms(self):
        """Backoff hint: a couple of flush windows, floored at 1ms."""
        return max(1, int(4 * flush_deadline_s() * 1000))

    def doc_pending(self, doc):
        """True while `doc` has mutating ops that were admitted but not
        yet answered -- the read-bypass routing test."""
        with self._lock:
            return self._pending_docs.get(doc, 0) > 0

    def note_complete(self, op):
        """The response for a claimed op was written; releases its docs
        for the inline read bypass."""
        if op.cmd in READ_CMDS:
            return
        with self._lock:
            for d in op.docs:
                n = self._pending_docs.get(d, 0) - 1
                if n > 0:
                    self._pending_docs[d] = n
                else:
                    self._pending_docs.pop(d, None)

    # -- consumer side (the dispatcher thread) --------------------------

    def wait_for_work(self, deadline_s=None, max_docs=None,
                      max_ops=None):
        """Blocks until at least one op is queued, then holds the
        coalescing window open until the OLDEST queued op is
        `deadline_s` old, the queue holds `max_docs` candidate ops or
        `max_ops` queued ops, or the queue closes.  Returns False only
        when closed and drained."""
        if deadline_s is None:
            deadline_s = flush_deadline_s()
        if max_docs is None:
            max_docs = max_batch_docs()
        if max_ops is None:
            max_ops = max_batch_ops()
        with self._work:
            while not self._items and not self._closed:
                self._work.wait()
            if not self._items:
                return False
            first = self._items[0].enq_t
            while not self._closed:
                age = time.perf_counter() - first
                if age >= deadline_s:
                    break
                if len(self._items) >= max_docs:
                    break
                if self.depth_ops >= max_ops:
                    break
                self._work.wait(deadline_s - age)
            return True

    def claim(self, max_docs=None, max_ops=None):
        """One coalescing pass in arrival order.  Returns
        ``(batch_ops, exec_ops)``: `batch_ops` coalesce into one pool
        batch (disjoint docs, caps respected); `exec_ops` run serially
        in claim order (local changes, loads, ordered reads).  Ops left
        behind (doc conflict or caps) stay queued for the next flush;
        every doc they touch blocks later claims this pass, preserving
        per-doc FIFO."""
        if max_docs is None:
            max_docs = max_batch_docs()
        if max_ops is None:
            max_ops = max_batch_ops()
        with self._lock:
            taken, blocked = set(), set()
            batch, execs, remaining = [], [], []
            n_docs = n_ops = parked = 0
            for op in self._items:
                conflict = any(d in taken or d in blocked
                               for d in op.docs)
                # caps bound ADDITIONAL coalescing, never singleton
                # service: an op bigger than a cap still claims into an
                # empty flush (otherwise it would park forever, wedging
                # its doc and hot-spinning the dispatcher)
                over = op.batchable and batch and (
                    n_docs + len(op.docs) > max_docs
                    or n_ops + op.n_ops > max_ops)
                if conflict or over:
                    blocked.update(op.docs)
                    remaining.append(op)
                    parked += 1
                    continue
                taken.update(op.docs)
                self.depth_ops -= op.n_ops
                if op.batchable:
                    n_docs += len(op.docs)
                    n_ops += op.n_ops
                    batch.append(op)
                else:
                    execs.append(op)
            self._items = remaining
        if parked:
            telemetry.metric('scheduler.parked', parked)
        return batch, execs

    def close(self):
        with self._work:
            self._closed = True
            self._work.notify_all()

    def stats(self):
        with self._lock:
            return {'depth_ops': self.depth_ops,
                    'queued': len(self._items),
                    'shedding': self.shedding,
                    'max_ops': self.max_ops,
                    'low_ops': self.low_ops,
                    'pending_docs': len(self._pending_docs)}
