"""Continuous-batching serve gateway (docs/SERVING.md).

The single-connection sidecar left the batched resolver's throughput
unreachable from real traffic: N clients each applying changes to their
own doc produced N serialized single-doc passes.  This gateway is the
CRDT analogue of continuous batching in inference serving (Orca,
OSDI '22): many concurrent connections decode requests into one shared
admission-controlled queue, and a single dispatcher thread coalesces
pending mutations across connections into one ``NativeDocPool``
apply-batch per flush, routing each per-doc result back to the
``(connection, request id)`` that asked for it.

Three layers:

  * **connections** (:class:`_Conn`) -- one reader thread per accepted
    unix-socket connection, speaking the sidecar's existing framings
    (JSON lines or length-prefixed msgpack).  Every outbound frame is
    STAGED on the connection's bounded egress queue
    (:mod:`automerge_tpu.scheduler.egress`, ISSUE 13) and drained by a
    dedicated writer thread, so no producer ever blocks on a slow or
    dead client socket; frames never interleave (one writer).  Per
    connection, responses may complete out of request order (reads
    bypass the queue); clients match by id (``SidecarClient``
    demultiplexes).
  * **scheduling** (:class:`GatewayServer` + ``scheduler.queue``) --
    mutating commands queue; the dispatcher drains them when the flush
    deadline (``AMTPU_FLUSH_DEADLINE_MS``), the doc cap, or the op cap
    closes the window.  ``apply_changes`` (and client-sent
    ``apply_batch``) ops with disjoint docs merge into ONE pool batch --
    byte-identical per doc to serial application because the pool's
    single-doc entry points already route through the same batch path.
    ``apply_local_change`` and ``load`` are ordered singletons (their
    undo/replay semantics don't compose into a doc-keyed batch); they
    execute serially inside the same flush cycle under the same per-doc
    FIFO.  Read-only commands on docs with no pending mutation run
    inline on the reader thread (no flush wait); with a pending
    mutation they queue, preserving read-your-writes per connection.
  * **isolation** -- the flush runs the pool's RESILIENT path, so a
    poisoned doc answers only its own request with the per-doc error
    envelope while the rest of the coalesced batch commits.  A
    whole-batch protocol error (validation: nothing committed,
    post-rollback) replays the flush's ops serially so every request
    still gets exactly the result serial application would have
    produced (``scheduler.serial_fallback``).

Overload: past the queue's high watermark mutating requests answer the
typed ``{"errorType": "Overloaded", "retryAfterMs": ...}`` envelope
instead of growing memory; ``healthz`` gains a ``scheduler`` section
(queue depth, shed state, occupancy summary, live batch handles).

Fan-out (ISSUE 9, docs/SERVING.md fan-out section): ``subscribe`` /
``unsubscribe`` / ``presence`` requests route through the same flush
cycle (ordered against their doc's mutations), and every flush hands
its per-doc post clocks + quarantine envelopes to the batched
:class:`~automerge_tpu.sync.fanout.FanoutEngine`, which classifies all
subscribers of all dirty docs in one vectorized (peer x doc) clock
-matrix pass and fans each doc's delta out encode-once.  Change->fanout
latency is therefore bounded by the flush window; ``AMTPU_FANOUT=0``
disables the engine (subscribe answers a typed error).
"""

import json
import os
import random
import socket
import struct
import sys
import threading
import time

from .. import faults, telemetry
from ..resilience import is_quarantine_error, is_quarantined
from ..telemetry import attribution, capacity
from ..utils.common import env_bool
from .egress import EgressQueue
from .queue import (READ_CMDS, AdmissionQueue,  # noqa: F401 (re-export)
                    Overloaded, PendingOp, flush_deadline_s,
                    max_batch_docs, max_batch_ops)

#: commands answered without touching the pool (never queued, no lock)
PURE_CMDS = ('ping', 'metrics', 'healthz', 'dump')

# READ_CMDS (read-only pool commands: inline bypass when their doc has
# no pending mutation, queued/ordered otherwise) is owned by .queue --
# its pending-doc accounting must agree with this routing table

#: mutating commands the dispatcher coalesces into one pool batch
BATCH_CMDS = ('apply_changes', 'apply_batch')

#: mutating commands executed as ordered singletons within a flush
EXEC_CMDS = ('apply_local_change', 'load')

#: fan-out control plane (ISSUE 9): ordered through the flush cycle so
#: subscribe/backfill serializes with the doc's mutations; presence
#: admits normally (sheddable -- it is ephemeral by definition), the
#: subscription lifecycle admits always (control plane)
FANOUT_CMDS = ('subscribe', 'unsubscribe', 'presence')

#: live-migration control plane (ISSUE 18, docs/SERVING.md routing
#: section): migrate_out saves this replica's copy of the named docs
#: into a durable handoff ColdStore and disowns them; migrate_in
#: restores them from the handoff manifest on the new owner.  Both
#: ride the admission queue keyed on their docs (admit_always), so a
#: migrate_out serializes AFTER every in-flight op on those docs --
#: the per-doc FIFO is what makes the router's parking race-free.
ROUTER_CMDS = ('migrate_out', 'migrate_in')


def _op_weight(cmd, req):
    """Queued-op count a request admits as (the admission unit): number
    of changes for the apply commands, 1 for everything else."""
    try:
        if cmd == 'apply_changes':
            return max(1, len(req['changes']))
        if cmd == 'apply_batch':
            return max(1, sum(max(1, len(chs))
                              for chs in req['docs'].values()))
    except (TypeError, AttributeError, KeyError):
        pass
    return 1


def _op_docs(cmd, req):
    """Doc keys a request touches, or None when the request is too
    malformed to route (the serial backend then answers its protocol
    error inline).  Batchable commands also validate their changes
    payload here: a request the flush's merge step could not even
    ASSEMBLE must take the inline error path, not poison a coalesced
    flush into whole-InternalError."""
    if cmd == 'apply_batch':
        docs = req.get('docs')
        if not isinstance(docs, dict) or not docs:
            return None
        if any(not isinstance(chs, list) for chs in docs.values()):
            return None
        return tuple(docs)
    if cmd in ('subscribe', 'unsubscribe'):
        # doc-set / wildcard variants (ISSUE 13 satellite): a `docs`
        # list keys the per-doc FIFO on every member; a `prefix` keys
        # it on a pseudo-doc so two prefix ops on one prefix still
        # order (a real doc sharing the pseudo-key only over-parks)
        docs = req.get('docs')
        if docs is not None:
            if not isinstance(docs, list) or not docs or any(
                    isinstance(d, (dict, list, set)) for d in docs):
                return None
            return tuple(docs)
        prefix = req.get('prefix')
        if prefix is not None:
            if not isinstance(prefix, str) or not prefix:
                return None
            return ('prefix\x00%s' % prefix,)
    doc = req.get('doc')
    if doc is None:
        return None
    if isinstance(doc, (dict, list, set)):
        return None          # unhashable: cannot key FIFO state on it
    if cmd == 'apply_changes' and \
            not isinstance(req.get('changes'), list):
        return None
    return (doc,)


class _Conn(object):
    """One accepted connection: a reader thread decoding frames into
    the gateway, plus a bounded egress queue (ISSUE 13,
    docs/SERVING.md backpressure section) through which EVERY outbound
    frame -- responses and fan-out events alike -- is staged.  No
    producer thread (dispatcher, reader, healthz) ever blocks on this
    socket: a dedicated writer drains the queue, and an unhealthy
    consumer degrades through the shed -> resync -> evict tiers
    instead of stalling the flush."""

    def __init__(self, sock, gateway, cid):
        self.sock = sock
        self.gateway = gateway
        self.cid = cid
        self.rfile = sock.makefile('rb')
        self.closed = False
        # ONE stable transport object: the fan-out engine groups
        # subscription rows sharing a transport by identity, so peers
        # multiplexed on this connection receive their k copies of a
        # coalesced frame as a single staged write
        self.egress = EgressQueue(
            sock, label='conn-%d' % cid,
            on_overflow=self._egress_overflow,
            on_dead=self._egress_dead)

    def send(self, resp):
        """Stages one response frame (egress kind 'response': never
        shed by tier-1, delivered in staging order with event frames).
        Returns immediately; a dead peer's frames are dropped by the
        writer, which tears the connection down itself."""
        if self.closed:
            return
        try:
            if self.gateway.use_msgpack:
                import msgpack
                body = msgpack.packb(resp, use_bin_type=True)
                frame = struct.pack('>I', len(body)) + body
            else:
                frame = (json.dumps(resp) + '\n').encode()
        except (TypeError, ValueError):
            return
        self.egress.stage(frame, kind='response')

    def _egress_overflow(self, _queue):
        """Tier 2 (drop-to-resubscribe): this connection kept
        overflowing its egress bound without draining."""
        self.gateway._conn_slow(self)

    def _egress_dead(self, reason):
        """The writer declared the transport dead (write error or
        tier-3 wedge eviction): close without ever blocking on the
        socket -- close() only shutdown()s it."""
        if reason == 'wedge':
            print('gateway: evicting wedged consumer conn-%d '
                  '(no egress progress for AMTPU_EGRESS_WEDGE_S)'
                  % self.cid, file=sys.stderr)
        self.close()
        self.gateway._conn_gone(self)

    def run(self):
        """Reader loop: decode frames, route into the gateway.  The
        `sidecar.frame` fault site fires per request BEFORE routing and
        is deliberately uncaught (it tears this connection down,
        simulating a mid-stream transport crash)."""
        try:
            if self.gateway.use_msgpack:
                self._run_msgpack()
            else:
                self._run_jsonl()
        except (BrokenPipeError, ConnectionError, OSError, ValueError):
            pass
        finally:
            self.close()
            self.gateway._conn_gone(self)

    def _frame_fault(self):
        if faults.ARMED:
            faults.fire('sidecar.frame')

    def _run_jsonl(self):
        for line in self.rfile:
            # frame receipt: attribution's t0, so the `admit` stage
            # covers decode + routing, not just admission
            t0 = time.perf_counter()
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except ValueError as e:
                self.send({'id': None, 'error': 'bad json: %s' % e,
                           'errorType': 'RangeError'})
                continue
            self._frame_fault()
            self.gateway.submit(self, req, t0=t0)

    def _run_msgpack(self):
        import msgpack
        while True:
            head = self.rfile.read(4)
            if len(head) < 4:
                break
            (n,) = struct.unpack('>I', head)
            body = self.rfile.read(n)
            if len(body) < n:
                break
            t0 = time.perf_counter()    # frame receipt (see _run_jsonl)
            try:
                req = msgpack.unpackb(body, raw=False,
                                      strict_map_key=False)
                if not isinstance(req, dict):
                    raise ValueError('request is not a map')
            except Exception as e:
                self.send({'id': None, 'error': 'bad msgpack: %s' % e,
                           'errorType': 'RangeError'})
                continue
            self._frame_fault()
            self.gateway.submit(self, req, t0=t0)

    def close(self):
        self.closed = True
        # the egress queue drops its backlog first (on_drop callbacks
        # regress fan-out clocks; the writer thread exits) -- nothing
        # below blocks on the peer
        self.egress.close()
        # shutdown NEXT: a foreign thread closing the makefile object
        # would block on the BufferedReader lock the reader thread holds
        # inside its blocking recv -- shutdown EOFs that recv, releasing
        # the lock, and only then is the file object closed
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.rfile.close()
        except Exception:
            pass
        try:
            self.sock.close()
        except Exception:
            pass


class GatewayServer(object):
    """The multi-client continuously-batching unix-socket server.

    Embeddable: ``start()`` spawns the accept + dispatcher threads and
    returns; ``stop()`` drains and joins them.  ``serve_forever()`` is
    the blocking entry `python -m automerge_tpu.sidecar.server --socket`
    uses.
    """

    # class-level default: skeleton instances (``__new__`` in tests)
    # drive _run_batch/_run_exec without the ctor ever running
    _sync_store = None

    def __init__(self, sock_path, use_msgpack=False, backend=None,
                 queue=None, backlog=128, sync_dir=None,
                 read_only=False):
        if backend is None:
            from ..sidecar.server import SidecarBackend
            backend = SidecarBackend()
        self.sock_path = sock_path
        self.use_msgpack = use_msgpack
        self.backend = backend
        # read-only listener (ISSUE 20): a materialized read replica
        # serves get_patch/snapshot/healthz off its own pool but must
        # refuse mutations -- writes belong to the authoritative
        # gateway (readview/replica.py applies upstream fan-out frames
        # in-process, under pool_lock, never through the socket)
        self.read_only = read_only
        # write-through checkpointing (ISSUE 19): with AMTPU_STORAGE_SYNC
        # (or an explicit `sync_dir` -- in-process test fleets share one
        # env), every acked mutation is saved to a durable ColdStore
        # BEFORE the response goes out, so "acked" implies "restorable"
        # -- the property fleet failover's byte-parity gate rests on
        self._sync_dir = sync_dir
        self._sync_store = None
        self.queue = queue if queue is not None else AdmissionQueue()
        self.backlog = backlog
        # one pool, many threads: inline reads and the dispatcher's
        # flushes serialize on this lock (the C++ pool and the jax
        # client are driven single-threaded, as they always were)
        self.pool_lock = threading.RLock()
        self.fanout = None
        # cold-state tier (ISSUE 10, docs/STORAGE.md): LRU eviction past
        # AMTPU_RESIDENT_DOCS_MAX + the settled-history GC cadence;
        # every call into it happens under pool_lock
        self.storage_tier = None
        self._srv = None
        self._conns = {}
        self._conns_lock = threading.Lock()
        self._next_cid = 0
        self._accept_thread = None
        self._dispatch_thread = None
        self._stopping = False
        # fleet routing state (ISSUE 18): docs this replica migrated
        # away (-> the typed WrongReplica envelope names the new
        # owner), the last ring version a migrate command carried, and
        # the in/out migration counters the healthz `routing` section
        # reports
        self._routing_lock = threading.Lock()
        self._disowned = {}       # guarded-by: self._routing_lock
        self._ring_version = 0    # guarded-by: self._routing_lock
        self._migrations_in = 0   # guarded-by: self._routing_lock
        self._migrations_out = 0  # guarded-by: self._routing_lock

    # -- lifecycle ------------------------------------------------------

    def start(self):
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(self.sock_path)
        self._srv.listen(self.backlog)
        telemetry.register_healthz_section('scheduler',
                                           self._healthz_section)
        telemetry.register_healthz_section('egress',
                                           self._egress_healthz_section)
        from ..storage.coldstore import DocEvictor
        self.storage_tier = DocEvictor.from_env(self.backend.pool)
        telemetry.register_healthz_section(
            'storage', self.storage_tier.healthz_section)
        if self._sync_dir or env_bool('AMTPU_STORAGE_SYNC', False):
            from ..storage.coldstore import ColdStore
            self._sync_store = ColdStore(self._sync_dir or None,
                                         durable=True)
        if env_bool('AMTPU_FANOUT', True):
            from ..sync.fanout import FanoutEngine
            self.fanout = FanoutEngine(self.backend.pool,
                                       self._encode_frame)
            telemetry.register_healthz_section(
                'fanout', self.fanout.healthz_section)
        # per-doc capacity accounting + headroom (ISSUE 15): wire the
        # serving tiers into the process-wide tracker and surface the
        # healthz `capacity` section + /debug/docs off it
        capacity.attach(pool=self.backend.pool,
                        pool_lock=self.pool_lock,
                        storage_tier=self.storage_tier,
                        egress_fn=self._egress_healthz_section)
        telemetry.register_healthz_section(
            'capacity', capacity.capacity_section)
        telemetry.register_healthz_section(
            'routing', self._routing_section)
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, name='amtpu-gw-dispatch',
            daemon=True)
        self._dispatch_thread.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name='amtpu-gw-accept', daemon=True)
        self._accept_thread.start()
        return self

    def serve_forever(self):
        self.start()
        try:
            self._dispatch_thread.join()
        except KeyboardInterrupt:
            self.stop()

    def stop(self):
        self._stopping = True
        srv, self._srv = self._srv, None
        if srv is not None:
            try:
                srv.close()
            except Exception:
                pass
        if os.path.exists(self.sock_path):
            try:
                os.unlink(self.sock_path)
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns.values())
        for conn in conns:
            conn.close()
        self.queue.close()
        if self._dispatch_thread is not None:
            self._dispatch_thread.join(timeout=30)
        telemetry.register_healthz_section('scheduler', None)
        telemetry.register_healthz_section('egress', None)
        telemetry.register_healthz_section('fanout', None)
        telemetry.register_healthz_section('storage', None)
        telemetry.register_healthz_section('capacity', None)
        telemetry.register_healthz_section('routing', None)
        capacity.detach()

    def _healthz_section(self):
        from ..native import live_batch_handles
        stats = self.queue.stats()
        with self._conns_lock:
            stats['connections'] = len(self._conns)
        stats['occupancy'] = telemetry.BATCH_OCCUPANCY.summary()
        stats['queue_wait_ms'] = telemetry.QUEUE_WAIT.summary()
        stats['live_batch_handles'] = live_batch_handles()
        stats['fallback_oracle'] = telemetry.metrics_snapshot().get(
            'fallback.oracle', 0.0)
        return stats

    def _routing_section(self):
        """healthz `routing` (ISSUE 18): who this replica is in the
        fleet, the last ring version a migrate command carried, how
        many docs it serves vs has disowned, and the migration
        counters -- the router's gossip scrape reads exactly this."""
        with self._routing_lock:
            disowned = len(self._disowned)
            ring_version = self._ring_version
            mig_in = self._migrations_in
            mig_out = self._migrations_out
        owned = None
        try:
            owned = int(self.backend.pool.doc_count())
        except Exception:
            pass
        if self.storage_tier is not None:
            owned = (owned or 0) + len(self.storage_tier.store)
        return {'replica_id': telemetry.replica_id(),
                'ring_version': ring_version,
                'owned_docs': owned,
                'disowned_docs': disowned,
                'migrations_in': mig_in,
                'migrations_out': mig_out}

    # -- connection layer -----------------------------------------------

    def _accept_loop(self):
        while not self._stopping:
            try:
                sock, _ = self._srv.accept()
            except OSError:
                break           # listener closed by stop()
            with self._conns_lock:
                self._next_cid += 1
                conn = _Conn(sock, self, self._next_cid)
                self._conns[conn.cid] = conn
            threading.Thread(target=conn.run,
                             name='amtpu-gw-conn-%d' % conn.cid,
                             daemon=True).start()

    def _conn_gone(self, conn):
        with self._conns_lock:
            self._conns.pop(conn.cid, None)
        if self.fanout is not None:
            self.fanout.drop_conn(conn.cid)

    def _conn_slow(self, conn):
        """Tier-2 degradation (drop-to-resubscribe, ISSUE 13): a
        connection that keeps overflowing its egress bound without ever
        draining has its subscription rows freed and is told to resync
        with a typed envelope (a RESPONSE-lane frame, so tier-1
        shedding cannot drop it).  The peer resubscribes at its
        last-seen clock and the subscribe backfill -- the same
        transitive-deps machinery as any straggler -- makes it whole."""
        docs = []
        if self.fanout is not None:
            docs = self.fanout.resync_conn(conn.cid)
        telemetry.metric('egress.resyncs')
        telemetry.recorder.record('egress.resync', n=len(docs),
                                  detail='conn-%d' % conn.cid)
        conn.send({'event': 'resync', 'docs': docs,
                   'reason': 'slow-consumer',
                   'retryAfterMs': self.queue.retry_after_ms()})

    def _egress_healthz_section(self):
        """Aggregate egress state across live connections: the
        queue-depth gauges the backpressure tiers key off, plus the
        flat egress.* counters."""
        with self._conns_lock:
            conns = list(self._conns.values())
        stats = [c.egress.stats() for c in conns
                 if getattr(c, 'egress', None) is not None]
        out = {
            'connections': len(stats),
            'queued_bytes': sum(s['queued_bytes'] for s in stats),
            'queued_frames': sum(s['queued_frames'] for s in stats),
            'max_conn_queued_bytes': max(
                (s['queued_bytes'] for s in stats), default=0),
            'backlogged_conns': sum(1 for s in stats
                                    if s['queued_frames']),
        }
        flat = telemetry.metrics_snapshot()
        out.update({k.split('egress.', 1)[1]: v
                    for k, v in flat.items()
                    if k.startswith('egress.')})
        return out

    def _encode_frame(self, obj):
        """One wire frame in this server's framing -- the fan-out
        engine encodes each doc's delta through this exactly once."""
        if self.use_msgpack:
            import msgpack
            body = msgpack.packb(obj, use_bin_type=True)
            return struct.pack('>I', len(body)) + body
        return (json.dumps(obj) + '\n').encode()

    # -- request routing ------------------------------------------------

    def submit(self, conn, req, t0=None):
        """Routes one decoded request.  Runs on the connection's reader
        thread; anything that can block on the pool or the queue must
        not stall OTHER connections (it only stalls this reader).
        `t0` is the frame-receipt timestamp the reader stamped before
        decoding -- attribution backdates each Clock to it."""
        cmd = req.get('cmd')
        rid = req.get('id')
        if cmd in PURE_CMDS:
            conn.send(self.backend.handle(req))
            return
        if self.read_only and (cmd in BATCH_CMDS or cmd in EXEC_CMDS
                               or cmd in ROUTER_CMDS):
            # a read replica's listener refuses mutations with a typed
            # envelope naming the reason -- silently applying them
            # would fork the replica's view from the authoritative doc
            telemetry.metric('readview.read_only_refused')
            conn.send({'id': rid,
                       'error': '%s refused: this is a read-only '
                                'replica (writes go to the '
                                'authoritative gateway)' % cmd,
                       'errorType': 'ReadOnly'})
            return
        if cmd in ROUTER_CMDS:
            docs = req.get('docs')
            if not isinstance(docs, list) or not docs or any(
                    isinstance(d, (dict, list, set)) for d in docs):
                conn.send({'id': rid,
                           'error': "%s requires 'docs': [doc, ...]"
                                    % cmd,
                           'errorType': 'RangeError'})
                return
            op = PendingOp(conn, rid, cmd, req, tuple(docs), 1,
                           batchable=False)
            op.clock = attribution.Clock(attribution.class_of(cmd),
                                         t0=t0, trace=req.get('trace'))
            op.clock.mark('admit')
            try:
                # control plane: shedding a migrate op would wedge the
                # router's parked FIFO, so it always admits
                self.queue.offer(op, admit_always=True)
            except Overloaded as e:     # only on gateway shutdown
                conn.send({'id': rid, 'error': str(e),
                           'errorType': 'Overloaded',
                           'retryAfterMs': e.retry_after_ms})
            return
        resp = self._check_disowned(cmd, rid, req)
        if resp is not None:
            # a doc this replica migrated away: answer the typed
            # WrongReplica envelope naming the new owner instead of
            # silently re-creating a fresh empty doc
            conn.send(resp)
            return
        if cmd in FANOUT_CMDS:
            if self.fanout is None:
                conn.send({'id': rid,
                           'error': 'fan-out is disabled on this '
                                    'server (AMTPU_FANOUT=0)',
                           'errorType': 'RangeError'})
                return
            docs = _op_docs(cmd, req)
            if docs is None:
                conn.send({'id': rid,
                           'error': "missing or invalid routing field: "
                                    "'doc' (subscribe/unsubscribe also "
                                    "accept 'docs': [...] or 'prefix')",
                           'errorType': 'RangeError'})
                return
            op = PendingOp(conn, rid, cmd, req, docs, 1, batchable=False)
            # marked BEFORE offer: the dispatcher may claim (and stamp)
            # the op the instant offer releases the queue lock
            op.clock = attribution.Clock(attribution.class_of(cmd), t0=t0,
                                         trace=req.get('trace'))
            op.clock.mark('admit')
            try:
                # presence is ephemeral -- shedding it under overload
                # is the correct behaviour -- and subscribe is
                # stampede-controlled (ISSUE 13): a post-partition
                # resubscribe burst sheds through the same watermarks
                # as mutations, with a JITTERED retryAfterMs so the
                # herd decorrelates.  Only unsubscribe always admits
                # (it frees resources; refusing it helps nobody).
                self.queue.offer(op,
                                 admit_always=(cmd == 'unsubscribe'))
            except Overloaded as e:
                retry_ms = e.retry_after_ms
                if cmd == 'subscribe':
                    telemetry.metric('sync.fanout.subscribe_shed')
                    retry_ms = max(1, int(retry_ms *
                                          (1.0 + 3.0 * random.random())))
                conn.send({'id': rid, 'error': str(e),
                           'errorType': 'Overloaded',
                           'retryAfterMs': retry_ms})
            return
        if cmd in READ_CMDS:
            docs = _op_docs(cmd, req)
            if docs is None or not self.queue.doc_pending(docs[0]):
                # inline bypass: no queued mutation can be reordered
                # against, so answer straight off the reader thread.
                # Attribution: admit covers decode/route, dispatch the
                # pool-lock wait + backend handle, emit the send.
                telemetry.metric('scheduler.bypass_reads')
                clock = attribution.Clock(attribution.class_of(cmd),
                                          t0=t0,
                                          trace=req.get('trace'))
                clock.mark('admit')
                with self.pool_lock:
                    if docs is not None and self.storage_tier \
                            is not None:
                        # a read of a cold doc reloads it on touch --
                        # transparently, under the same pool lock the
                        # flush path uses.  A FAILED reload answers a
                        # typed error (reading the missing doc would
                        # silently serve empty state)
                        failed = self.storage_tier.ensure_resident(
                            docs)
                        if failed:
                            d, e = next(iter(failed.items()))
                            resp = self._cold_error(rid, d, e)
                        else:
                            self.storage_tier.note_touch(docs)
                            resp = self.backend.handle(req)
                    else:
                        resp = self.backend.handle(req)
                # send + finish OUTSIDE the pool lock: a failed read's
                # finish() may snapshot the recorder ring and write an
                # exemplar -- never on the lock every flush needs
                clock.mark('dispatch')
                conn.send(resp)
                clock.mark('emit')
                attribution.finish(clock, ok='error' not in resp,
                                   cmd=cmd, rid=rid,
                                   doc=docs[0] if docs else None)
                return
            op = PendingOp(conn, rid, cmd, req, docs, 1, batchable=False)
            op.clock = attribution.Clock(attribution.class_of(cmd), t0=t0,
                                         trace=req.get('trace'))
            op.clock.mark('admit')
            try:
                self.queue.offer(op, admit_always=True)
            except Overloaded as e:     # only on gateway shutdown
                conn.send({'id': rid, 'error': str(e),
                           'errorType': 'Overloaded',
                           'retryAfterMs': e.retry_after_ms})
            return
        if cmd in BATCH_CMDS or cmd in EXEC_CMDS:
            docs = _op_docs(cmd, req)
            if docs is None:
                # malformed routing fields: the serial backend's error
                # contract answers (missing field -> RangeError, bad
                # type -> TypeError), nothing mutates
                with self.pool_lock:
                    conn.send(self.backend.handle(req))
                return
            op = PendingOp(conn, rid, cmd, req, docs,
                           _op_weight(cmd, req),
                           batchable=(cmd in BATCH_CMDS))
            op.clock = attribution.Clock(attribution.class_of(cmd), t0=t0,
                                         trace=req.get('trace'))
            op.clock.mark('admit')
            try:
                self.queue.offer(op)
            except Overloaded as e:
                conn.send({'id': rid, 'error': str(e),
                           'errorType': 'Overloaded',
                           'retryAfterMs': e.retry_after_ms})
            return
        # unknown command: the serial backend's RangeError contract
        conn.send(self.backend.handle(req))

    # -- the dispatcher -------------------------------------------------

    def _dispatch_loop(self):
        deadline = flush_deadline_s()
        mdocs, mops = max_batch_docs(), max_batch_ops()
        while True:
            if not self.queue.wait_for_work(deadline, mdocs, mops):
                return          # closed and drained
            batch, execs = self.queue.claim(mdocs, mops)
            if not batch and not execs:
                continue
            try:
                self._flush(batch, execs)
            except Exception as e:
                # a dispatcher death would hang every queued client;
                # answer what we can and keep serving
                print('gateway: flush failed: %s: %s'
                      % (type(e).__name__, e), file=sys.stderr)
                for op in batch + execs:
                    # only UNANSWERED ops: a partial flush's completed
                    # ops already sent their real response -- a second
                    # _finish would double-count their emit/pending
                    # state and mislabel a success as failed
                    if not op.answered:
                        self._finish(op, {
                            'id': op.rid,
                            'error': '%s: %s' % (type(e).__name__, e),
                            'errorType': 'InternalError'})
                for op in batch + execs:
                    self._finalize_attribution(op)

    def _flush(self, batch, execs):
        telemetry.metric('scheduler.flushes')
        # attribution: the claim closed every op's queue stage
        claimed = batch + execs
        for op in claimed:
            if op.clock is not None:
                op.clock.mark('queue')
        fanout_s = 0.0
        fanned = ()
        # the flush span parents the pool's batch spans (contextvars
        # nesting), completing the request -> flush -> batch trace link
        with telemetry.span('scheduler.flush', batched=len(batch),
                            exec_ops=len(execs)) as fsp:
            with self.pool_lock:
                # WrongReplica shed FIRST: an op that passed submit's
                # disowned check but queued behind the migrate_out that
                # disowned its doc would otherwise execute against the
                # dropped doc and silently create a fresh one
                batch, execs = self._shed_disowned(batch, execs)
                touched = {d for op in batch + execs for d in op.docs}
                if self.storage_tier is not None and touched:
                    # reload-on-touch BEFORE the ops run: a cold doc's
                    # followers are already parked by the per-doc FIFO,
                    # so the reload is indistinguishable from an in-
                    # flight op taking a little longer.  Docs whose
                    # reload FAILED are shed per op (typed error, blob
                    # stays cold) so one corrupt blob cannot fail the
                    # whole flush's unrelated traffic
                    failed = self.storage_tier.ensure_resident(touched)
                    if failed:
                        batch, execs = self._shed_cold_failures(
                            batch, execs, failed)
                # per-flush fan-out inputs: doc -> post clock /
                # quarantine envelope / earliest admission time /
                # originator (conn, submitted-clock) for echo
                # suppression
                fan = {'updates': {}, 'quarantined': {}, 'enq': {},
                       'origins': {}, 'traces': {}, 'patches': {}} \
                    if self.fanout is not None else None
                if batch:
                    self._run_batch(batch, fsp, fan)
                for op in execs:
                    self._run_exec(op, fan=fan)
                if fan is not None:
                    fanout_s = self._fanout_flush(fan, fsp)
                    fanned = set(fan['updates']) | set(fan['quarantined'])
                if self.storage_tier is not None and touched:
                    self._storage_upkeep(batch, execs, touched)
        # attribution epilogue (responses are already on the wire;
        # histograms + tail sampling only): the fan-out wall lands on
        # every request whose doc actually fanned, then each request's
        # stage vector finalizes exactly once
        for op in claimed:
            self._finalize_attribution(op, fanout_s, fanned)

    def _finalize_attribution(self, op, fanout_s=0.0, fanned=()):
        """Final per-request accounting (idempotent: the clock detaches
        on first call, so the dispatcher's error path can sweep ops a
        partial flush already finalized)."""
        clock, op.clock = op.clock, None
        if clock is None:
            return
        if fanout_s and any(d in fanned for d in op.docs):
            clock.add('fanout', fanout_s)
        attribution.finish(clock, ok=not op.failed, cmd=op.cmd,
                           rid=op.rid,
                           doc=op.docs[0] if op.docs else None)

    @staticmethod
    def _cold_error(rid, doc, exc):
        return {'id': rid,
                'error': 'cold doc %r failed to reload: %s: %s'
                         % (doc, type(exc).__name__, exc),
                'errorType': 'InternalError'}

    def _shed_cold_failures(self, batch, execs, failed):
        """Answers every op touching a reload-failed doc with the typed
        error (running it would CREATE a fresh empty doc and silently
        diverge) and returns the surviving ops.  The cold blob stays in
        the store for a later attempt."""
        keep_batch, keep_execs = [], []
        for ops, keep in ((batch, keep_batch), (execs, keep_execs)):
            for op in ops:
                bad = next((d for d in op.docs if d in failed), None)
                if bad is None:
                    keep.append(op)
                    continue
                self._finish(op, self._cold_error(op.rid, bad,
                                                  failed[bad]))
        return keep_batch, keep_execs

    # -- fleet routing: disowned docs (ISSUE 18) ------------------------

    @staticmethod
    def _wrong_replica(rid, doc, owner, ring_version):
        """The typed envelope for an op on a doc this replica migrated
        away: names the new owner so the router (or a stale direct
        client) can re-route instead of guessing."""
        return {'id': rid,
                'error': 'doc %r has migrated to replica %r'
                         % (doc, owner),
                'errorType': 'WrongReplica', 'owner': owner,
                'ringVersion': ring_version}

    def _check_disowned(self, cmd, rid, req):
        """Submit-time fast reject: the WrongReplica envelope for a
        request touching a disowned doc, or None to admit.  Flush-time
        `_shed_disowned` closes the race this check alone would leave
        (an op admitted before the migrate_out claimed)."""
        with self._routing_lock:
            if not self._disowned:
                return None
            docs = _op_docs(cmd, req)
            if not docs:
                return None
            for d in docs:
                hit = self._disowned.get(d)
                if hit is not None:
                    telemetry.metric('migrate.wrong_replica')
                    return self._wrong_replica(rid, d, hit[0], hit[1])
        return None

    def _shed_disowned(self, batch, execs):
        """Answers every claimed op touching a disowned doc with the
        typed WrongReplica envelope (running it would CREATE a fresh
        empty doc and silently fork the migrated history) and returns
        the survivors.  Migrate commands are exempt: migrate_in is
        exactly how a disowned doc comes back."""
        with self._routing_lock:
            if not self._disowned:
                return batch, execs
            disowned = dict(self._disowned)
        keep_batch, keep_execs = [], []
        for ops, keep in ((batch, keep_batch), (execs, keep_execs)):
            for op in ops:
                bad = None if op.cmd in ROUTER_CMDS else next(
                    (d for d in op.docs if d in disowned), None)
                if bad is None:
                    keep.append(op)
                    continue
                owner, rv = disowned[bad]
                telemetry.metric('migrate.wrong_replica')
                self._finish(op, self._wrong_replica(op.rid, bad,
                                                     owner, rv))
        return keep_batch, keep_execs

    def _storage_upkeep(self, batch, execs, touched):
        """Post-flush cold-state maintenance (still under the pool
        lock): GC cadence per mutated doc, LRU touch, eviction past the
        residency cap."""
        muts = {}
        for op in batch + execs:
            if op.cmd in BATCH_CMDS + EXEC_CMDS:
                per_doc = max(1, op.n_ops // max(1, len(op.docs)))
                for d in op.docs:
                    muts[d] = muts.get(d, 0) + per_doc
        for d, n in muts.items():
            # the acked clock resolves LAZILY: note_mutations only
            # reads it on the rare flush whose debt actually folds, so
            # the hot path never pays the fanout matrix min
            acked_fn = None
            if self.fanout is not None:
                acked_fn = (lambda doc=d:
                            self.fanout.acked_clock(doc))
            try:
                self.storage_tier.note_mutations(d, n, acked_fn)
            except Exception as e:
                # GC is an optimization: a doc that will not compact
                # must never fail its flush
                telemetry.metric('storage.gc.failed')
                print('gateway: compaction failed for %r: %s: %s'
                      % (d, type(e).__name__, e), file=sys.stderr)
        self.storage_tier.note_touch(touched)
        self.storage_tier.maybe_evict(protect=touched)
        # proactive memory-pressure eviction (ISSUE 15): past
        # AMTPU_MEM_PRESSURE_EVICT of AMTPU_MEM_BUDGET_MB the LRU tail
        # checkpoints out even below the doc-count cap -- evict before
        # the OOM killer does.  The pressure read is throttled
        # (AMTPU_CAPACITY_REFRESH_S shares one native stats pass with
        # healthz scrapes), so the per-flush cost is a dict read.
        try:
            if capacity.TRACKER.evict_due():
                self.storage_tier.maybe_evict(protect=touched,
                                              pressure=True)
                # start the cooldown window: a stuck-high RSS signal
                # gets one bounded pass per window, never per flush
                capacity.TRACKER.note_pressure_pass()
        except Exception as e:
            # pressure eviction is an optimization: it must never fail
            # the flush that triggered it
            print('gateway: pressure eviction failed: %s: %s'
                  % (type(e).__name__, e), file=sys.stderr)

    def _observe_wait(self, ops):
        now = time.perf_counter()
        for op in ops:
            telemetry.QUEUE_WAIT.observe((now - op.enq_t) * 1000.0)

    def _run_batch(self, ops, fsp=None, fan=None):
        """One coalesced pool pass over disjoint-doc mutating ops, per
        -request responses routed back by (conn, id)."""
        self._observe_wait(ops)
        telemetry.metric('scheduler.coalesced_ops', len(ops))
        for op in ops:
            if op.clock is not None:
                op.clock.mark('claim')
        # bracket the pool call so the native driver's always-on phase
        # seams can split the shared apply wall into dispatch/collect
        attribution.flush_phases_begin()
        t0 = time.perf_counter()
        try:
            # merge building sits INSIDE the try: a request malformed in
            # a way routing didn't catch degrades to the serial replay
            # (per-request protocol errors), never to a whole-flush
            # InternalError
            merged = {}
            for op in ops:
                if op.cmd == 'apply_changes':
                    merged[op.req['doc']] = op.req['changes']
                else:                       # apply_batch
                    merged.update(op.req['docs'])
            telemetry.BATCH_OCCUPANCY.observe(len(merged))
            telemetry.metric('scheduler.batched_docs', len(merged))
            out = self.backend.pool.apply_batch(merged)
        except Exception as e:
            attribution.flush_phases_end()
            # whole-batch protocol error (validation; nothing committed,
            # post-rollback): replay serially so each request gets
            # exactly the result/error serial application produces
            if isinstance(e, (MemoryError, SystemExit,
                              KeyboardInterrupt)):
                raise
            telemetry.metric('scheduler.serial_fallback')
            for op in ops:
                self._run_exec(op, count=False, fan=fan)
            return
        dt = time.perf_counter() - t0
        # the collect share of the shared apply wall (zero when the
        # pool drove shard/mesh threads: their seams land in other
        # threads' brackets, and `dispatch` absorbs the whole wall)
        collect_s = attribution.flush_phases_end().get('collect', 0.0)
        # close every op's dispatch/collect segment BEFORE the response
        # loop: op k's dispatch must not absorb ops 1..k-1's response
        # builds and socket writes -- that serialized-emission wait is
        # real latency, but it belongs to each op's own emit delta
        for op in ops:
            if op.clock is not None:
                op.clock.mark_split('dispatch', 'collect', collect_s)
        # write-through (ISSUE 19): checkpoint every mutated doc BEFORE
        # any response goes out -- an acked change must be restorable
        if self._sync_store is not None:
            self._sync_save(list(merged))
        flush_id = getattr(fsp, 'span_id', None)
        for op in ops:
            if op.cmd == 'apply_changes':
                res = out[op.req['doc']]
                if is_quarantined(res):
                    telemetry.metric('scheduler.quarantined')
                    resp = {'id': op.rid, 'error': res['error'],
                            'errorType': res['errorType']}
                else:
                    resp = {'id': op.rid, 'result': res}
                if fan is not None:
                    self._fan_note(fan, op, op.req['doc'], res)
            else:
                sub = {d: out[d] for d in op.req['docs']}
                nq = sum(1 for r in sub.values() if is_quarantined(r))
                if nq:
                    telemetry.metric('scheduler.quarantined', nq)
                resp = {'id': op.rid, 'result': sub}
                if fan is not None:
                    for d, r in sub.items():
                        self._fan_note(fan, op, d, r)
            # the per-command request series the serial server emits in
            # handle(): batched requests record the shared flush apply
            # time (docs/OBSERVABILITY.md)
            telemetry.SIDECAR_LATENCY.labels(op.cmd).observe(dt)
            telemetry.SIDECAR_REQS.labels(
                op.cmd, 'error' if 'error' in resp else 'ok').inc()
            # request span resuming the client's trace, carrying the
            # flush span id as a link (request -> flush -> batch)
            tctx = op.req.get('trace')
            tctx = tctx if isinstance(tctx, dict) else {}
            with telemetry.span_with_context(
                    'sidecar.request', tctx.get('traceId'),
                    tctx.get('spanId'), cmd=op.cmd, rid=op.rid,
                    batched=True, flush=flush_id):
                self._finish(op, resp)

    def _run_exec(self, op, count=True, fan=None):
        """One ordered singleton through the serial backend dispatch --
        identical result envelope (and telemetry) to the pre-gateway
        server.  Fan-out control-plane ops dispatch into the engine
        instead (they never touch the pool's mutation path)."""
        if count:
            telemetry.metric('scheduler.exec_ops')
            self._observe_wait([op])
            if op.clock is not None:
                # serial-fallback replays (count=False) marked claim in
                # _run_batch already; marking again would double-count
                op.clock.mark('claim')
        if op.cmd in FANOUT_CMDS:
            resp = self._fanout_cmd(op)
            if op.clock is not None:
                op.clock.mark('dispatch')
            self._finish(op, resp)
            return
        if op.cmd in ROUTER_CMDS:
            resp = self._migrate_cmd(op)
            if op.clock is not None:
                op.clock.mark('dispatch')
            self._finish(op, resp)
            return
        resp = self.backend.handle(op.req)
        if op.clock is not None:
            op.clock.mark('dispatch')
        if self._sync_store is not None and 'error' not in resp \
                and op.cmd in BATCH_CMDS + EXEC_CMDS:
            self._sync_save(op.docs)
        if fan is not None and op.cmd in BATCH_CMDS + EXEC_CMDS:
            if 'error' not in resp:
                result = resp.get('result')
                if op.cmd == 'apply_batch' and isinstance(result, dict):
                    for d, r in result.items():
                        self._fan_note(fan, op, d, r)
                else:
                    self._fan_note(fan, op, op.req.get('doc'), result)
            elif is_quarantine_error(resp):
                # a single-doc entry point surfaced a quarantine as its
                # raise contract: subscribers still get the envelope,
                # not silence (the batch path gets this for free from
                # its per-doc envelopes)
                for d in op.docs:
                    self._fan_note(fan, op, d,
                                   {'error': resp['error'],
                                    'errorType': resp['errorType']})
        self._finish(op, resp)

    @staticmethod
    def _submitted_clock(op, doc, result):
        """The {actor: seq} clock of what THIS request itself shipped
        for `doc` -- the originating connection's peers advance by
        exactly this before classification (echo suppression), never by
        concurrent changes they may not have seen."""
        try:
            if op.cmd == 'apply_changes':
                changes = op.req['changes']
            elif op.cmd == 'apply_batch':
                changes = op.req['docs'][doc]
            elif op.cmd == 'apply_local_change':
                actor = result.get('actor') if isinstance(result, dict) \
                    else None
                return {actor: result['seq']} if actor else {}
            elif op.cmd == 'load':
                # the loader shipped the whole checkpoint: it holds
                # everything the doc now contains
                return dict(result.get('clock') or {}) \
                    if isinstance(result, dict) else {}
            else:
                return {}
            out = {}
            for c in changes:
                if isinstance(c, dict) and 'actor' in c:
                    out[c['actor']] = max(out.get(c['actor'], 0),
                                          int(c.get('seq', 0)))
            return out
        except (TypeError, KeyError, ValueError):
            return {}

    def _fan_note(self, fan, op, doc, result):
        """Records one committed per-doc result into the flush's fan-out
        inputs: the post clock for healthy docs, the error envelope for
        quarantined ones -- and the originating request's trace id, so
        fan-out event frames are correlatable with the request's
        cross-process trace tree (the per-doc FIFO admits one op per doc
        per flush, so the doc's originating trace is unique).

        For mutations whose result IS the per-doc patch (the pool's
        apply output, byte-identical to the serial backend), the patch
        is also captured into ``fan['patches']`` -- computed exactly
        once per dirty doc, it is what patch-mode subscriptions fan
        instead of change bytes (ISSUE 20).  `load` results are
        excluded: their diffs describe a restore against EMPTY state,
        not a delta an exact subscriber could apply incrementally (the
        engine falls back to a full-state patch for those docs)."""
        if doc is None:
            return
        tctx = op.req.get('trace')
        if isinstance(tctx, dict) and tctx.get('traceId'):
            fan['traces'][doc] = tctx['traceId']
        if is_quarantined(result):
            fan['quarantined'][doc] = result
        else:
            if op.cmd in ('apply_changes', 'apply_batch',
                          'apply_local_change') \
                    and isinstance(result, dict) \
                    and 'diffs' in result:
                fan['patches'][doc] = {
                    k: result[k] for k in ('clock', 'deps', 'canUndo',
                                           'canRedo', 'diffs')
                    if k in result}
            clock = result.get('clock') \
                if isinstance(result, dict) else None
            if clock is None:
                # results without an embedded clock (e.g. a load's
                # whole-state patch shape changing) resolve against the
                # pool -- we hold the pool lock
                try:
                    clock = self.backend.pool.get_clock(doc) \
                        .get('clock') or {}
                except Exception:
                    return
            fan['updates'][doc] = clock
            fan['origins'].setdefault(doc, []).append(
                (op.conn.cid, self._submitted_clock(op, doc, result)))
        prev = fan['enq'].get(doc)
        if prev is None or op.enq_t < prev:
            fan['enq'][doc] = op.enq_t

    def _fanout_cmd(self, op):
        """subscribe/unsubscribe/presence dispatch into the fan-out
        engine, answered with the protocol's result/error envelope.
        The transport handed to the engine is the connection's bounded
        egress queue (plain fakes fall back to their send callable)."""
        from ..errors import AutomergeError, RangeError
        req, rid = op.req, op.rid
        peer = (op.conn.cid, str(req.get('peer') or ''))
        transport = getattr(op.conn, 'egress', None)
        if transport is None:
            transport = getattr(op.conn, 'raw_send', op.conn.send)
        prefix = req.get('prefix')
        doc_set = req.get('docs') if isinstance(req.get('docs'), list) \
            else None
        try:
            if op.cmd == 'subscribe':
                clock = req.get('clock') or {}
                if not isinstance(clock, dict):
                    raise RangeError('subscribe clock must be a '
                                     '{actor: seq} map')
                backfill = bool(req.get('backfill', True))
                mode = req.get('mode') or 'change'
                if prefix is not None and doc_set is None:
                    if mode != 'change':
                        raise RangeError('prefix subscriptions do not '
                                         'support mode=%r (attach doc '
                                         'subscriptions for patch '
                                         'shipping)' % (mode,))
                    res = self.fanout.subscribe_prefix(peer, prefix,
                                                       transport)
                elif doc_set is not None:
                    res = self.fanout.subscribe_many(
                        peer, doc_set, clock, transport,
                        backfill=backfill, mode=mode)
                else:
                    res = self.fanout.subscribe(
                        peer, op.docs[0], clock, transport,
                        backfill=backfill, mode=mode)
            elif op.cmd == 'unsubscribe':
                if prefix is not None and doc_set is None:
                    removed = self.fanout.unsubscribe_prefix(peer,
                                                             prefix)
                elif doc_set is not None:
                    removed = sum(self.fanout.unsubscribe(peer, d)
                                  for d in doc_set)
                else:
                    removed = self.fanout.unsubscribe(peer, op.docs[0])
                res = {'ok': True, 'removed': removed}
            else:
                res = self.fanout.presence(peer, op.docs[0],
                                           req.get('state'))
            return {'id': rid, 'result': res}
        except (AutomergeError, RangeError, TypeError) as e:
            return {'id': rid, 'error': str(e),
                    'errorType': type(e).__name__}
        except Exception as e:
            telemetry.metric('sync.fanout.errors')
            return {'id': rid,
                    'error': '%s: %s' % (type(e).__name__, e),
                    'errorType': 'InternalError'}

    # -- live doc migration (ISSUE 18, docs/SERVING.md routing) ---------

    def _migrate_cmd(self, op):
        """migrate_out / migrate_in, executed under the pool lock and
        ordered through the per-doc FIFO like any other op -- a
        migrate_out therefore serializes AFTER every in-flight op on
        its docs, which is what makes the router's parking race-free.
        The handoff transport is a DURABLE ColdStore (fsynced blobs +
        checksummed manifest), so a kill at any point leaves either the
        source's committed copy or a manifest the target can restore
        from."""
        from ..errors import AutomergeError, RangeError
        req, rid = op.req, op.rid
        try:
            store_dir = req['store_dir']
            if not isinstance(store_dir, str) or not store_dir:
                raise RangeError('store_dir must be a directory path')
            if op.cmd == 'migrate_out':
                res = self._migrate_out(op.docs, store_dir,
                                        req.get('new_owner'),
                                        req.get('ring_version'))
            else:
                res = self._migrate_in(op.docs, store_dir,
                                       req.get('ring_version'))
            return {'id': rid, 'result': res}
        except KeyError as e:
            return {'id': rid,
                    'error': 'missing required field: %s' % e,
                    'errorType': 'RangeError'}
        except (AutomergeError, RangeError, TypeError) as e:
            return {'id': rid, 'error': str(e),
                    'errorType': type(e).__name__}
        except Exception as e:
            telemetry.metric('migrate.errors')
            return {'id': rid,
                    'error': '%s: %s' % (type(e).__name__, e),
                    'errorType': 'InternalError'}

    def _sync_save(self, docs):
        """Write-through checkpoint (AMTPU_STORAGE_SYNC): saves each
        just-mutated doc into the durable sync store in one batched
        manifest commit.  Runs pre-ack under pool_lock; a per-doc save
        failure only skips that doc (counted) -- the response path is
        never the place to invent new errors for committed changes."""
        from ..utils.common import doc_key
        blobs = {}
        for d in docs:
            try:
                blobs[doc_key(d)] = self.backend.pool.save(d)
            except Exception:
                telemetry.metric('storage.sync_failed')
        if blobs:
            try:
                self._sync_store.put_many(blobs)
                telemetry.metric('storage.sync_saves', len(blobs))
            except Exception:
                telemetry.metric('storage.sync_failed', len(blobs))

    def _migrate_out(self, docs, store_dir, new_owner, ring_version):
        """save -> durable put_many -> drop: checkpoints each doc into
        the handoff store (canonically keyed so the manifest round
        -trips int ids), drops it from the pool + cold tier, and
        records it disowned -- every later op answers WrongReplica.
        Per-doc failures (unknown doc) report in `failed`; the rest of
        the batch still moves."""
        from ..storage.coldstore import ColdStore
        from ..utils.common import doc_key
        store = ColdStore(store_dir, durable=True)
        blobs, failed = {}, {}
        order = []
        for d in docs:
            try:
                blobs[doc_key(d)] = self.backend.pool.save(d)
                order.append(d)
            except Exception as e:
                failed[str(d)] = '%s: %s' % (type(e).__name__, e)
        nbytes = sum(len(b) for b in blobs.values())
        if blobs:
            store.put_many(blobs)
            for d in order:
                self.backend.pool.drop_doc(d)
                if self.storage_tier is not None:
                    self.storage_tier.forget(d)
        with self._routing_lock:
            for d in order:
                self._disowned[d] = (new_owner, ring_version)
            if isinstance(ring_version, int):
                self._ring_version = max(self._ring_version,
                                         ring_version)
            self._migrations_out += 1
        telemetry.metric('migrate.out_docs', len(order))
        telemetry.metric('migrate.out_bytes', nbytes)
        telemetry.recorder.record('migrate.out', n=len(order),
                                  detail=str(new_owner))
        return {'migrated': order, 'failed': failed, 'bytes': nbytes}

    def _migrate_in(self, docs, store_dir, ring_version):
        """Restores the named docs from the handoff manifest via the
        parallel arena-direct path (`restore_from_store`, ISSUE 17),
        falling back to a batched replay for pools without it.  Docs
        absent from the manifest (or corrupt) report per-doc in
        `failed`; accepting a doc clears any disowned record for it
        (a doc can migrate back)."""
        from ..storage.coldstore import ColdStore
        from ..utils.common import doc_key
        store = ColdStore(store_dir, durable=True)
        keys = {d: doc_key(d) for d in docs}
        have = [d for d in docs if keys[d] in store]
        failed = {str(d): 'not in handoff manifest'
                  for d in docs if keys[d] not in store}
        restored, nbytes = [], 0
        if have:
            try:
                res = self.backend.pool.restore_from_store(
                    store, doc_ids=[keys[d] for d in have])
                bad = {}
                for m in (res.get('corrupt') or {},
                          res.get('failed') or {}):
                    bad.update({str(k): str(v) for k, v in m.items()})
                restored = [d for d in have
                            if str(keys[d]) not in bad]
                failed.update(bad)
                nbytes = int(res.get('bytes') or 0)
            except AttributeError:
                # pools without the parallel restore entry point (test
                # fakes, dict pools): the DocEvictor reload pattern --
                # batched replay, per-doc isolation on failure
                blobs = {d: store.get(keys[d]) for d in have}
                try:
                    self.backend.pool.load_batch(blobs)
                    restored = have
                except Exception:
                    for d in have:
                        try:
                            self.backend.pool.load_batch(
                                {d: blobs[d]})
                            restored.append(d)
                        except Exception as e:
                            failed[str(d)] = '%s: %s' \
                                % (type(e).__name__, e)
                nbytes = sum(len(blobs[d]) for d in restored)
        if restored and self.storage_tier is not None:
            self.storage_tier.note_touch(restored)
        with self._routing_lock:
            for d in restored:
                self._disowned.pop(d, None)
            if isinstance(ring_version, int):
                self._ring_version = max(self._ring_version,
                                         ring_version)
            self._migrations_in += 1
        telemetry.metric('migrate.in_docs', len(restored))
        telemetry.metric('migrate.in_bytes', nbytes)
        telemetry.recorder.record('migrate.in', n=len(restored))
        return {'restored': restored, 'failed': failed,
                'bytes': nbytes}

    def _fanout_flush(self, fan, fsp):
        """Hands the flush's committed docs to the fan-out engine; the
        span nests under scheduler.flush (contextvars) and carries the
        flush span id, exactly like the pool's batch spans.  Returns
        the pass's wall seconds (the `fanout` attribution stage)."""
        t0 = time.perf_counter()
        try:
            with telemetry.span('sync.fanout', docs=len(fan['updates']),
                                flush=getattr(fsp, 'span_id', None)):
                self.fanout.on_flush(fan['updates'],
                                     fan['quarantined'], fan['enq'],
                                     fan['origins'],
                                     traces=fan['traces'],
                                     patches=fan['patches'])
        except Exception as e:
            # fan-out failures must never re-answer (or hang) the
            # flush's already-answered requests
            telemetry.metric('sync.fanout.errors')
            print('gateway: fan-out failed: %s: %s'
                  % (type(e).__name__, e), file=sys.stderr)
        return time.perf_counter() - t0

    def _finish(self, op, resp):
        op.answered = True
        op.conn.send(resp)
        if op.clock is not None:
            op.failed = 'error' in resp
            op.clock.mark('emit')
        self.queue.note_complete(op)
