"""Per-connection bounded egress queues (ISSUE 13, docs/SERVING.md
backpressure section; degradation tiers: docs/RESILIENCE.md).

Before this module, every byte the gateway sent -- responses AND
fan-out event frames -- was written on whichever thread produced it,
under a per-connection lock, straight into a blocking socket.  One
subscriber that stopped reading therefore stalled the dispatcher (and
with it every doc and every other connection) the moment its kernel
socket buffer filled.  The egress queue fully decouples the
dispatcher/flush critical path from subscriber socket health:

  * **staging never blocks** -- producers (`stage`) append a frame to a
    byte-bounded queue (``AMTPU_EGRESS_MAX_BYTES``) and return; a
    dedicated writer thread per connection drains it through a
    select()-paced non-stalling send loop.  Per-frame completion
    callbacks fire on the writer thread, which is where the fan-out
    engine moves believed-clock advancement and
    ``amtpu_fanout_latency_ms`` observation.
  * **tier 1 -- event shedding** -- on overflow, queued *event* frames
    (kind ``'event'``: fan-out deltas, presence) are dropped and their
    ``on_drop`` callbacks run (the fan-out engine regresses the peer's
    believed clock to its acked row, so the next flush classifies it
    as a straggler and the transitive-deps filtered delta heals it --
    no dup, no gap).  Response frames (kind ``'response'``: request
    answers, control envelopes) are never shed.
  * **tier 2 -- drop-to-resubscribe** -- a connection that keeps
    overflowing without ever draining (``AMTPU_EGRESS_RESYNC_SHEDS``
    consecutive sheds) triggers ``on_overflow`` once: the gateway
    frees the connection's subscription rows and stages a typed
    ``{"event": "resync"}`` envelope (`SidecarClient` auto-resubscribes
    at its last-seen clock; the subscribe backfill closes the gap).
  * **tier 3 -- wedge eviction** -- a consumer whose socket accepts no
    bytes at all for ``AMTPU_EGRESS_WEDGE_S`` seconds is disconnected
    (``on_dead``), with recorder + telemetry breadcrumbs
    (``egress.wedge_evictions``, the ``egress.evict`` ring event).
    The writer paces on select(), so teardown never stalls on the dead
    socket.

Fault sites (docs/RESILIENCE.md): ``fanout.write`` fires as a per
-connection write failure inside the send loop; ``fanout.stall`` is an
armed wedge -- while it fires, the writer makes no progress, so a
permanent stall deterministically drives tier-3 eviction.  Disarmed
cost is the standard one module-attribute read (`faults.ARMED`).
"""

import select
import socket as _socket
import threading
import time

from .. import faults, telemetry
from ..utils.common import env_float, env_int

#: bytes per send() slice -- bounds how long one send can occupy the
#: writer after select() reports writability
_CHUNK = 65536

#: per-call non-blocking send: select() only guarantees SOME buffer
#: space, and a blocking send() of a full chunk would stall the writer
#: past the wedge deadline (AF_UNIX stream sends queue the whole
#: request).  Zero on platforms without it -- select pacing plus the
#: chunk bound still applies.
_DONTWAIT = getattr(_socket, 'MSG_DONTWAIT', 0)

#: select() pacing ceiling; the effective poll is min of this and a
#: quarter of the wedge deadline so eviction resolution stays sharp
_POLL_S = 0.25


def egress_max_bytes():
    """Queued-byte bound per connection before tier-1 shedding
    (``AMTPU_EGRESS_MAX_BYTES``, default 1 MiB)."""
    return max(1, env_int('AMTPU_EGRESS_MAX_BYTES', 1048576))


def egress_wedge_s():
    """Zero-progress seconds before a consumer is evicted
    (``AMTPU_EGRESS_WEDGE_S``, default 10)."""
    return env_float('AMTPU_EGRESS_WEDGE_S', 10.0)


def egress_resync_sheds():
    """Consecutive tier-1 sheds (without a full drain between) before
    tier-2 drop-to-resubscribe (``AMTPU_EGRESS_RESYNC_SHEDS``,
    default 3)."""
    return max(1, env_int('AMTPU_EGRESS_RESYNC_SHEDS', 3))


class _Frame(object):
    __slots__ = ('buf', 'kind', 'on_write', 'on_drop')

    def __init__(self, buf, kind, on_write, on_drop):
        self.buf = buf
        self.kind = kind
        self.on_write = on_write
        self.on_drop = on_drop


def _safe(cb):
    """Completion callbacks must never kill the writer thread or the
    staging caller."""
    if cb is None:
        return
    try:
        cb()
    except Exception:
        pass


class EgressQueue(object):
    """One connection's bounded egress: FIFO frame queue + writer
    thread.  ``stage`` is the only producer entry point and never
    blocks; it is safe from any thread (dispatcher, reader, healthz).

    The object's identity is stable for the connection's lifetime --
    the fan-out engine groups subscription rows sharing a transport by
    it, exactly as it grouped the pre-egress ``raw_send`` callables.
    """

    def __init__(self, sock, label='', max_bytes=None, wedge_s=None,
                 resync_sheds=None, on_overflow=None, on_dead=None):
        self._sock = sock
        self.label = label
        self._max_bytes = max_bytes if max_bytes is not None \
            else egress_max_bytes()
        self._wedge_s = wedge_s if wedge_s is not None else egress_wedge_s()
        self._resync_sheds = resync_sheds if resync_sheds is not None \
            else egress_resync_sheds()
        self._on_overflow = on_overflow   # tier 2 (fired once per backlog)
        self._on_dead = on_dead           # write error / tier-3 eviction
        self._cond = threading.Condition()
        self._frames = []         # guarded-by: self._cond
        self._bytes = 0           # guarded-by: self._cond
        # writes under the cond; the writer's mid-send peeks are
        # deliberately racy (a stale False only delays exit one poll)
        self._closed = False      # guarded-by(w): self._cond
        self._sheds = 0           # guarded-by: self._cond
        self._resynced = False    # guarded-by: self._cond
        self._thread = None       # guarded-by: self._cond
        self._dead = False

    # -- producer side ---------------------------------------------------

    def stage(self, buf, kind='event', on_write=None, on_drop=None):
        """Queues one already-encoded frame; returns False (after
        running ``on_drop``) when the queue is closed.  ``kind`` is the
        shed class: ``'event'`` frames are droppable under overflow,
        ``'response'`` frames are not.

        An event frame LARGER than the whole bound staged into an
        otherwise-empty queue is exempt from shedding (the same
        principle as the admission queue's oversized-op rule: the
        bound limits backlog, it is not a frame-size limit) --
        otherwise a single oversized coalesced delta would shed
        itself, regress, be re-staged as the same oversized straggler
        delta, and starve a healthy peer forever."""
        if kind == 'event' and len(buf) > self._max_bytes:
            with self._cond:
                if not self._frames:
                    kind = 'jumbo'    # unsheddable; delivery bounds it
        frame = _Frame(buf, kind, on_write, on_drop)
        dropped, overflowed = (), False
        evict = False
        with self._cond:
            if self._closed:
                _safe(on_drop)
                return False
            self._frames.append(frame)
            self._bytes += len(buf)
            telemetry.metric('egress.staged_frames')
            telemetry.metric('egress.staged_bytes', len(buf))
            if self._bytes > self._max_bytes:
                dropped, overflowed = self._shed_locked()
                if self._bytes > 4 * self._max_bytes \
                        and len(self._frames) > 1:
                    # unsheddable backlog (responses/jumbo) past the
                    # hard cap: the consumer is hopeless -- evict
                    # rather than grow without bound (a trickling
                    # reader defeats the wedge clock, so tier 3 alone
                    # cannot cover this).  A SINGLE oversized frame is
                    # exempt like the jumbo rule: delivery bounds it.
                    evict = True
            if self._thread is None and not evict:
                # lazy spawn: a connection that never sends never owns
                # a writer thread (hand-assembled test conns included)
                self._thread = threading.Thread(
                    target=self._writer, daemon=True,
                    name='amtpu-egress-%s' % (self.label or id(self)))
                self._thread.start()
            self._cond.notify()
        for f in dropped:
            _safe(f.on_drop)
        if evict:
            telemetry.metric('egress.overflow_evictions')
            telemetry.recorder.record('egress.evict', n=1,
                                      detail='%s:overflow' % self.label)
            self.close()
            if self._on_dead is not None:
                _safe(lambda: self._on_dead('overflow'))
            return False
        if overflowed and self._on_overflow is not None:
            # tier 2: fired once per persistent backlog, outside the
            # queue lock (the callback stages the resync envelope)
            _safe(lambda: self._on_overflow(self))
        return True

    def _shed_locked(self):  # holds-lock: self._cond
        """Tier 1: drop every queued event frame (responses survive).
        Returns (dropped frames, tier-2-due flag)."""
        kept, dropped, freed = [], [], 0
        for f in self._frames:
            if f.kind == 'event':
                dropped.append(f)
                freed += len(f.buf)
            else:
                kept.append(f)
        if not dropped:
            return (), False
        self._frames = kept
        self._bytes -= freed
        self._sheds += 1
        telemetry.metric('egress.sheds')
        telemetry.metric('egress.shed_frames', len(dropped))
        telemetry.metric('egress.shed_bytes', freed)
        telemetry.recorder.record('egress.shed', n=len(dropped),
                                  detail=self.label)
        due = self._sheds >= self._resync_sheds and not self._resynced
        if due:
            self._resynced = True
        return dropped, due

    def close(self):
        """Stops the writer and drops everything queued (their
        ``on_drop`` callbacks run).  Idempotent; never blocks on the
        socket."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            dropped, self._frames = self._frames, []
            self._bytes = 0
            self._cond.notify_all()
        for f in dropped:
            _safe(f.on_drop)

    def join(self, timeout=None):
        with self._cond:
            t = self._thread
        if t is not None:
            t.join(timeout)

    def stats(self):
        with self._cond:
            return {'queued_frames': len(self._frames),
                    'queued_bytes': self._bytes,
                    'sheds': self._sheds,
                    'resynced': self._resynced,
                    'dead': self._dead}

    # -- the writer thread -----------------------------------------------

    def _make_poller(self):
        """Writability poller: poll() where available -- select() caps
        out at FD_SETSIZE (1024) fds, exactly the regime a
        subscriber-scale gateway runs in -- with a select() fallback.
        Returns a callable(timeout_s) -> bool(writable)."""
        if hasattr(select, 'poll'):
            p = select.poll()
            p.register(self._sock, select.POLLOUT)
            return lambda t: bool(p.poll(t * 1000.0))
        return lambda t: bool(select.select((), (self._sock,), (),
                                            t)[1])

    def _writer(self):
        try:
            poller = self._make_poller()
        except (OSError, ValueError):
            poller = None
        while True:
            with self._cond:
                while not self._frames and not self._closed:
                    self._cond.wait()
                if self._closed:
                    return          # close() already drained
                frame = self._frames.pop(0)
                self._bytes -= len(frame.buf)
            reason = self._write_out(frame.buf, poller)
            if reason is None:
                telemetry.metric('egress.writes')
                _safe(frame.on_write)
                with self._cond:
                    if not self._frames:
                        # a full drain means the consumer recovered:
                        # the persistent-slow escalation starts over
                        self._sheds = 0
                        self._resynced = False
                continue
            # the connection is gone (write error, wedge eviction, or
            # a racing close): drop the in-flight frame + everything
            # queued, then tear the connection down -- off the socket's
            # critical path, never blocking on it
            _safe(frame.on_drop)
            with self._cond:
                self._dead = reason != 'closed'
                dropped, self._frames = self._frames, []
                self._bytes = 0
                closed = self._closed
            for f in dropped:
                _safe(f.on_drop)
            if not closed and self._on_dead is not None:
                _safe(lambda: self._on_dead(reason))
            return

    def _write_out(self, buf, poller):
        """Sends one frame fully.  Returns None on success, else the
        failure reason ('error' | 'wedge' | 'closed').  Paced by the
        writability poller: a consumer that accepts nothing for the
        wedge deadline is declared wedged instead of blocking
        forever."""
        if poller is None:
            return 'error' if not self._closed else 'closed'
        mv = memoryview(buf)
        poll = min(_POLL_S, max(0.01, self._wedge_s / 4.0))
        last_progress = time.monotonic()
        while mv:
            if self._closed:
                return 'closed'
            if faults.ARMED:
                try:
                    faults.fire('fanout.write')
                except faults.InjectedFault:
                    telemetry.metric('egress.write_errors')
                    return 'error'
                try:
                    faults.fire('fanout.stall')
                except faults.InjectedFault:
                    # armed wedge: no bytes move this poll; a permanent
                    # stall runs the zero-progress clock into tier-3
                    # eviction exactly like a real non-draining peer
                    time.sleep(poll)
                    if time.monotonic() - last_progress >= self._wedge_s:
                        return self._wedged()
                    continue
            try:
                writable = poller(poll)
            except (OSError, ValueError):
                return 'error' if not self._closed else 'closed'
            if not writable:
                if time.monotonic() - last_progress >= self._wedge_s:
                    return self._wedged()
                continue
            try:
                n = self._sock.send(mv[:_CHUNK], _DONTWAIT)
            except (BlockingIOError, InterruptedError):
                # select raced a buffer refill away: no progress this
                # poll, the wedge clock keeps running
                if time.monotonic() - last_progress >= self._wedge_s:
                    return self._wedged()
                continue
            except (OSError, ValueError):
                telemetry.metric('egress.write_errors')
                return 'error' if not self._closed else 'closed'
            if n:
                last_progress = time.monotonic()
                mv = mv[n:]
        return None

    def _wedged(self):
        telemetry.metric('egress.wedge_evictions')
        telemetry.recorder.record('egress.evict', n=1, detail=self.label)
        return 'wedge'
