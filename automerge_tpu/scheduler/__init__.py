"""automerge_tpu.scheduler -- the continuous-batching serve gateway.

Turns the single-connection sidecar into a multi-client server that
coalesces concurrent mutating requests across connections into full
device batches under a latency deadline, with admission control and
SLO telemetry.  Architecture + tunables: docs/SERVING.md.
"""

from .egress import EgressQueue  # noqa: F401
from .gateway import (BATCH_CMDS, EXEC_CMDS, PURE_CMDS,  # noqa: F401
                      READ_CMDS, GatewayServer)
from .queue import (AdmissionQueue, Overloaded,  # noqa: F401
                    PendingOp, flush_deadline_s, max_batch_docs,
                    max_batch_ops)
