"""automerge_tpu -- a TPU-native CRDT document framework.

A ground-up rebuild of the capabilities of unao/automerge (JSON-document
CRDTs: maps, lists, text, tables, causal sync, undo/redo, save/load) designed
for TPU execution: the causal-graph resolver runs as batched JAX/XLA kernels
over columnar operation records, resolving thousands of documents in one
vectorized pass, sharded over a device mesh.
"""

__version__ = '0.1.0'
