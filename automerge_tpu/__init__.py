"""automerge_tpu -- a TPU-native CRDT document framework.

A ground-up rebuild of the capabilities of unao/automerge (JSON-document
CRDTs: maps, lists, text, tables, causal sync, undo/redo, save/load) designed
for TPU execution: the causal-graph resolver runs as batched JAX/XLA kernels
over columnar operation records, resolving thousands of documents in one
vectorized pass, sharded over a device mesh.

Public API surface mirrors the reference (`/root/reference/src/automerge.js`):

    import automerge_tpu as am
    doc = am.init()
    doc = am.change(doc, lambda d: d.update({'cards': []}))
    doc2 = am.merge(am.init(), doc)
"""

from .api import (HistoryEntry, apply_changes, applyChanges, can_redo,
                  can_undo, canRedo, canUndo, change, diff, doc_from_changes,
                  docFromChanges, empty_change, emptyChange, equals,
                  get_actor_id, get_changes, get_conflicts, get_element_ids,
                  get_history, get_missing_deps, get_object_id, getActorId,
                  getChanges, getConflicts, getHistory, getMissingDeps,
                  getObjectId, init, inspect, load, merge, redo, save,
                  set_actor_id, setActorId, undo)
from . import backend as Backend
from . import frontend as Frontend
from .errors import AutomergeError, RangeError
from .models.table import Table
from .models.text import Text
from .sync.connection import Connection
from .sync.doc_set import DocSet
from .sync.watchable_doc import WatchableDoc
from .utils.uuid import uuid

__version__ = '0.1.0'

__all__ = [
    'init', 'change', 'empty_change', 'undo', 'redo', 'load', 'save', 'merge',
    'diff', 'get_changes', 'apply_changes', 'get_missing_deps', 'equals',
    'inspect', 'get_history', 'uuid', 'Frontend', 'Backend', 'DocSet',
    'WatchableDoc', 'Connection', 'Text', 'Table', 'can_undo', 'can_redo',
    'get_actor_id', 'set_actor_id', 'get_conflicts', 'get_object_id',
    'get_element_ids', 'doc_from_changes', 'HistoryEntry', 'AutomergeError',
    'RangeError',
]
