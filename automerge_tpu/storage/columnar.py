"""Columnar change codec (ISSUE 10 tentpole a; docs/STORAGE.md).

CRDT change metadata is monotone and repetitive: the same handful of
actors author runs of changes whose seq advances by one, whose deps
equal the clock the stream already implies, and whose ops repeat a tiny
set of (key-tuple, action) shapes over interned object/key/value
strings.  JSON (and even msgpack) re-spells all of it per change; the
upstream automerge binary format proved ~10x by splitting changes into
delta/RLE-encoded COLUMNS.  This codec is that idea over this repo's
JSON-native change schema:

  * one shared **string table** (actors, object ids, keys, string
    values, field names) referenced by LEB128 varint index;
  * **change shapes** (top-level key tuples) and **op shapes**
    (key tuple + action) interned and run-length encoded -- the per-op
    framing cost of a homogeneous stream is amortized to ~zero;
  * **seq deltas** per actor (zigzag; the +1 common case is one 0x00),
    **dep deltas** against the running clock the decoded stream
    implies (exact catch-up deps cost one byte per entry), elem-id
    deltas for list keys (`actor:elem` splits into an interned actor
    and a delta), typed value columns (small ints as zigzag varints,
    strings interned, anything else as a tagged msgpack residual);
  * a whole-blob **zlib** pass (the columns expose the redundancy;
    DEFLATE collects it -- same layering as the upstream format).

Byte-round-trip is GUARANTEED, not hoped for: `encode_columnar`
re-serializes each parsed change with the canonical writer
(`msgpack.packb`) and any change whose raw bytes differ from the
canonical form -- foreign encoders, exotic types -- is carried verbatim
in a residual column (`storage.columnar.residual_changes`).  Decoding
therefore always reproduces the exact input bytes, which is what lets
settled-history GC serve straggler backfills from a snapshot that is
byte-identical to the arena it replaced.
"""

import contextlib
import struct
import zlib

import msgpack

from .. import telemetry
from ..utils.common import env_bool


def storage_native_on():
    """Native-codec dispatch gate (ISSUE 14): AMTPU_STORAGE_NATIVE
    (default on) routes encode/decode through the C++ codec in
    native/core.cpp; 0 keeps this module's pure-Python codec as the
    parity oracle (same A/B pattern as AMTPU_FANOUT_VECTOR).  Checked
    per call, not latched, so interleaved A/B runs flip it
    in-process."""
    return env_bool('AMTPU_STORAGE_NATIVE', True)


def _native_codec():
    """The native bindings module when the dispatch gate is on and the
    library loads; None keeps everything on the Python codec."""
    if not storage_native_on():
        return None
    try:
        from .. import native
        native.lib()
        return native
    except Exception:
        return None


@contextlib.contextmanager
def corrupt_raises_value_error(what='columnar blob'):
    """The storage package's ONE corruption contract: whatever a
    decoder trips on internally (zlib, struct, msgpack, an out-of-range
    table index) surfaces as ValueError -- callers map that to their
    RangeError protocol surface."""
    try:
        yield
    except ValueError:
        raise
    except Exception as e:
        raise ValueError('corrupt %s: %s' % (what, e))

MAGIC = b'AMTC'
VERSION = 1
_FLAG_ZLIB = 1

#: change-shape id 0 is reserved for residual (verbatim) changes
_RESIDUAL_SHAPE = 0

# typed-value column tags
_V_INT, _V_STR, _V_TRUE, _V_FALSE, _V_NULL = 0, 1, 2, 3, 4
_V_FLOAT, _V_MSGPACK, _V_BIN = 5, 6, 7

# op 'key' column tags: interned string vs (actor, elem-delta) pair
_K_STR, _K_ELEM = 0, 1


def _uvarint(out, n):
    while True:
        b = n & 0x7f
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


# unbounded ints: zigzag via sign fold (Python ints have no fixed
# width, so the usual `(n << 1) ^ (n >> 63)` trick is just this)
def _zz_fold(n):
    return (-n << 1) - 1 if n < 0 else n << 1


def _zigzag(out, n):
    _uvarint(out, _zz_fold(n))


class _Reader(object):
    __slots__ = ('buf', 'pos')

    def __init__(self, buf, pos=0):
        self.buf = buf
        self.pos = pos

    def uvarint(self):
        n = shift = 0
        buf, pos = self.buf, self.pos
        while True:
            b = buf[pos]
            pos += 1
            n |= (b & 0x7f) << shift
            if not (b & 0x80):
                self.pos = pos
                return n
            shift += 7

    def zigzag(self):
        n = self.uvarint()
        return -((n + 1) >> 1) if n & 1 else n >> 1

    def take(self, n):
        out = self.buf[self.pos:self.pos + n]
        if len(out) != n:
            raise ValueError('columnar blob truncated')
        self.pos += n
        return out


class _RLE(object):
    """Run-length writer/reader for small-int columns (shape ids)."""

    def __init__(self):
        self.runs = []          # (value, count)

    def push(self, v):
        if self.runs and self.runs[-1][0] == v:
            self.runs[-1][1] += 1
        else:
            self.runs.append([v, 1])

    def dump(self):
        out = bytearray()
        _uvarint(out, len(self.runs))
        for v, c in self.runs:
            _uvarint(out, v)
            _uvarint(out, c)
        return bytes(out)

    @staticmethod
    def expand(r):
        n_runs = r.uvarint()
        for _ in range(n_runs):
            v = r.uvarint()
            c = r.uvarint()
            for _i in range(c):
                yield v


class _Strings(object):
    __slots__ = ('idx', 'table')

    def __init__(self):
        self.idx = {}
        self.table = []

    def of(self, s):
        i = self.idx.get(s)
        if i is None:
            i = len(self.table)
            self.idx[s] = i
            self.table.append(s)
        return i

    def dump(self):
        out = bytearray()
        _uvarint(out, len(self.table))
        for s in self.table:
            b = s.encode('utf-8')
            _uvarint(out, len(b))
            out += b
        return bytes(out)

    @staticmethod
    def load(r):
        n = r.uvarint()
        return [bytes(r.take(r.uvarint())).decode('utf-8')
                for _ in range(n)]


def _canonical(raw):
    """(parsed, ok): the parsed change iff msgpack.packb reproduces the
    exact input bytes (the canonical-writer check that guarantees
    decode-time byte identity)."""
    try:
        parsed = msgpack.unpackb(raw, raw=False, strict_map_key=False)
    except Exception:
        return None, False
    try:
        ok = msgpack.packb(parsed, use_bin_type=True) == raw
    except Exception:
        ok = False
    return parsed, ok


class _Encoder(object):
    def __init__(self):
        self.strings = _Strings()
        self.cshapes = {}        # key-tuple -> id (1-based)
        self.cshape_list = []
        self.oshapes = {}        # (key-tuple, action) -> id
        self.oshape_list = []
        self.cshape_col = _RLE()
        self.oshape_col = _RLE()
        self.cols = {}           # (level, name) -> bytearray
        self.residuals = bytearray()
        self.n_residual = 0
        self.n_changes = 0
        # mirrored decoder state (deltas)
        self.last_seq = {}       # actor idx -> seq
        self.run_clock = {}      # actor idx -> max applied seq
        self.last_elem = 0
        self.last_key_elem = 0

    def col(self, level, name):
        c = self.cols.get((level, name))
        if c is None:
            c = self.cols[(level, name)] = bytearray()
        return c

    def _cshape(self, keys):
        sid = self.cshapes.get(keys)
        if sid is None:
            sid = len(self.cshape_list) + 1
            self.cshapes[keys] = sid
            self.cshape_list.append(keys)
        return sid

    def _oshape(self, keys, action):
        sid = self.oshapes.get((keys, action))
        if sid is None:
            sid = len(self.oshape_list)
            self.oshapes[(keys, action)] = sid
            self.oshape_list.append((keys, action))
        return sid

    def _value(self, out, v):
        if v is True:
            out.append(_V_TRUE)
        elif v is False:
            out.append(_V_FALSE)
        elif v is None:
            out.append(_V_NULL)
        elif isinstance(v, int):
            out.append(_V_INT)
            _uvarint(out, _zz_fold(v))
        elif isinstance(v, str):
            out.append(_V_STR)
            _uvarint(out, self.strings.of(v))
        elif isinstance(v, float):
            out.append(_V_FLOAT)
            out += struct.pack('>d', v)
        elif isinstance(v, bytes):
            out.append(_V_BIN)
            _uvarint(out, len(v))
            out += v
        else:
            b = msgpack.packb(v, use_bin_type=True)
            out.append(_V_MSGPACK)
            _uvarint(out, len(b))
            out += b

    def add_residual(self, raw):
        self.cshape_col.push(_RESIDUAL_SHAPE)
        _uvarint(self.residuals, len(raw))
        self.residuals += raw
        self.n_residual += 1
        self.n_changes += 1

    def add(self, raw):
        parsed, ok = _canonical(raw)
        if not ok or not self._columnarizable(parsed):
            self.add_residual(raw)
            return
        self.n_changes += 1
        keys = tuple(parsed)
        self.cshape_col.push(self._cshape(keys))
        actor_i = self.strings.of(parsed['actor'])
        seq = parsed['seq']
        for k in keys:
            v = parsed[k]
            if k == 'actor':
                _uvarint(self.col(0, 'actor'), actor_i)
            elif k == 'seq':
                _zigzag(self.col(0, 'seq'),
                        seq - self.last_seq.get(actor_i, 0) - 1)
            elif k == 'deps':
                out = self.col(0, 'deps')
                _uvarint(out, len(v))
                for da, ds in v.items():
                    di = self.strings.of(da)
                    _uvarint(out, di)
                    _zigzag(out, ds - self.run_clock.get(di, 0))
            elif k == 'ops':
                _uvarint(self.col(0, 'ops'), len(v))
                for op in v:
                    self._op(op)
            else:
                self._value(self.col(0, k), v)
        self.last_seq[actor_i] = seq
        if seq > self.run_clock.get(actor_i, 0):
            self.run_clock[actor_i] = seq

    def _columnarizable(self, parsed):
        """The fast-shape test; anything else rides the residual
        column.  Checked BEFORE any column is written, so a reject
        leaves the encoder state untouched."""
        if not isinstance(parsed, dict):
            return False
        if not isinstance(parsed.get('actor'), str) \
                or not isinstance(parsed.get('seq'), int) \
                or isinstance(parsed.get('seq'), bool) \
                or parsed['seq'] < 0:
            return False
        if 'deps' in parsed:
            deps = parsed['deps']
            # present-but-wrong-typed (incl. an explicit null) rides
            # the residual column, never the deps column
            if not (isinstance(deps, dict)
                    and all(isinstance(a, str) and isinstance(s, int)
                            and not isinstance(s, bool)
                            for a, s in deps.items())):
                return False
        if 'ops' in parsed:
            ops = parsed['ops']
            if not (isinstance(ops, list)
                    and all(self._op_columnarizable(op)
                            for op in ops)):
                return False
        return all(isinstance(k, str) for k in parsed)

    @staticmethod
    def _op_columnarizable(op):
        """obj/key/elem must hold their schema types -- the decoder
        routes those fields to dedicated columns BY NAME, so an op
        smuggling, say, an int obj would desynchronize the streams."""
        return (isinstance(op, dict)
                and isinstance(op.get('action'), str)
                and all(isinstance(k, str) for k in op)
                and ('obj' not in op or isinstance(op['obj'], str))
                and ('key' not in op or isinstance(op['key'], str))
                and ('elem' not in op
                     or (isinstance(op['elem'], int)
                         and not isinstance(op['elem'], bool))))

    def _op(self, op):
        keys = tuple(op)
        self.oshape_col.push(self._oshape(keys, op['action']))
        for k in keys:
            if k == 'action':
                continue         # rides the shape id
            v = op[k]
            if k == 'obj':       # types pre-validated: see
                _uvarint(self.col(1, 'obj'),  # _op_columnarizable
                         self.strings.of(v))
            elif k == 'elem':
                _zigzag(self.col(1, 'elem'), v - self.last_elem)
                self.last_elem = v
            elif k == 'key':
                out = self.col(1, 'key')
                head, sep, tail = v.rpartition(':')
                # isdecimal(), not isdigit(): the latter accepts
                # Unicode digits (e.g. superscripts) that int() rejects
                if sep and head and tail.isdecimal() \
                        and str(int(tail)) == tail:
                    elem = int(tail)
                    out.append(_K_ELEM)
                    _uvarint(out, self.strings.of(head))
                    _zigzag(out, elem - self.last_key_elem)
                    self.last_key_elem = elem
                else:
                    out.append(_K_STR)
                    _uvarint(out, self.strings.of(v))
            else:
                self._value(self.col(1, k), v)

    def dump(self):
        # pre-intern every late string (shape keys, action names,
        # column names) BEFORE the table serializes -- the sections
        # below reference indices into the dumped table
        for keys in self.cshape_list:
            for k in keys:
                self.strings.of(k)
        for keys, action in self.oshape_list:
            for k in keys:
                self.strings.of(k)
            self.strings.of(action)
        for (_level, name) in self.cols:
            self.strings.of(name)
        body = bytearray()
        _uvarint(body, self.n_changes)
        body += self.strings.dump()
        _uvarint(body, len(self.cshape_list))
        for keys in self.cshape_list:
            _uvarint(body, len(keys))
            for k in keys:
                _uvarint(body, self.strings.of(k))
        _uvarint(body, len(self.oshape_list))
        for keys, action in self.oshape_list:
            _uvarint(body, len(keys))
            for k in keys:
                _uvarint(body, self.strings.of(k))
            _uvarint(body, self.strings.of(action))
        body += self.cshape_col.dump()
        body += self.oshape_col.dump()
        _uvarint(body, len(self.cols))
        for (level, name) in sorted(self.cols):
            col = self.cols[(level, name)]
            body.append(level)
            _uvarint(body, self.strings.of(name))
            _uvarint(body, len(col))
            body += col
        _uvarint(body, len(self.residuals))
        body += self.residuals
        packed = zlib.compress(bytes(body), 6)
        flags = _FLAG_ZLIB
        if len(packed) >= len(body):     # incompressible: store raw
            packed, flags = bytes(body), 0
        return MAGIC + bytes((VERSION, flags)) + packed


def encode_columnar(raw_changes):
    """Encodes an iterable of raw msgpack change bytes into one
    columnar blob.  `decode_columnar` reproduces the exact input
    byte-for-byte (foreign encodings ride the residual column).

    Dispatches to the native C++ codec when `AMTPU_STORAGE_NATIVE`
    (default on) -- blob bytes are identical either way (the fuzz
    parity lane pins it); `storage.native_encodes` vs
    `storage.python_encodes` makes the split observable.  A native
    failure (e.g. msgpack ext framing the C++ reader cannot skip)
    falls back to the Python codec, never to a failed save."""
    raws = [bytes(raw) for raw in raw_changes]
    n_in = sum(len(raw) for raw in raws)
    blob = n_changes = n_residual = None
    nat = _native_codec()
    if nat is not None:
        try:
            blob, n_changes, n_residual = nat.columnar_encode_native(raws)
            telemetry.metric('storage.native_encodes')
        except Exception:
            blob = None
    if blob is None:
        enc = _Encoder()
        for raw in raws:
            enc.add(raw)
        blob = enc.dump()
        n_changes, n_residual = enc.n_changes, enc.n_residual
        telemetry.metric('storage.python_encodes')
    telemetry.metric('storage.columnar.encodes')
    telemetry.metric('storage.columnar.changes', n_changes)
    if n_residual:
        telemetry.metric('storage.columnar.residual_changes',
                         n_residual)
    telemetry.metric('storage.columnar.bytes_in', n_in)
    telemetry.metric('storage.columnar.bytes_out', len(blob))
    return blob


def encode_columnar_dicts(changes):
    """Dict-level convenience (the Python engine pool): canonical
    msgpack per change, then columnar."""
    return encode_columnar(msgpack.packb(c, use_bin_type=True)
                           for c in changes)


class _Decoder(object):
    def __init__(self, blob):
        if blob[:4] != MAGIC:
            raise ValueError('not a columnar change blob (bad magic)')
        if blob[4] != VERSION:
            raise ValueError('unsupported columnar version %d' % blob[4])
        body = blob[6:]
        if blob[5] & _FLAG_ZLIB:
            body = zlib.decompress(body)
        r = _Reader(body)
        self.n_changes = r.uvarint()
        self.strings = _Strings.load(r)
        self.cshapes = [tuple(self.strings[r.uvarint()]
                              for _ in range(r.uvarint()))
                        for _ in range(r.uvarint())]
        self.oshapes = []
        for _ in range(r.uvarint()):
            keys = tuple(self.strings[r.uvarint()]
                         for _ in range(r.uvarint()))
            self.oshapes.append((keys, self.strings[r.uvarint()]))
        self.cshape_ids = list(_RLE.expand(r))
        self.oshape_ids = iter(list(_RLE.expand(r)))
        self.cols = {}
        for _ in range(r.uvarint()):
            level = r.buf[r.pos]
            r.pos += 1
            name = self.strings[r.uvarint()]
            n = r.uvarint()
            self.cols[(level, name)] = _Reader(bytes(r.take(n)))
        self.residuals = _Reader(bytes(r.take(r.uvarint())))
        self.last_seq = {}
        self.run_clock = {}
        self.last_elem = 0
        self.last_key_elem = 0

    def col(self, level, name):
        c = self.cols.get((level, name))
        if c is None:
            raise ValueError('columnar blob missing column %d/%s'
                             % (level, name))
        return c

    def _value(self, r):
        tag = r.buf[r.pos]
        r.pos += 1
        if tag == _V_TRUE:
            return True
        if tag == _V_FALSE:
            return False
        if tag == _V_NULL:
            return None
        if tag == _V_INT:
            n = r.uvarint()
            return -((n + 1) >> 1) if n & 1 else n >> 1
        if tag == _V_STR:
            return self.strings[r.uvarint()]
        if tag == _V_FLOAT:
            return struct.unpack('>d', r.take(8))[0]
        if tag == _V_BIN:
            return bytes(r.take(r.uvarint()))
        if tag == _V_MSGPACK:
            return msgpack.unpackb(r.take(r.uvarint()), raw=False,
                                   strict_map_key=False)
        raise ValueError('bad value tag %d' % tag)

    def changes(self):
        """Yields (raw_bytes, actor_or_None, seq_or_None) per change in
        input order.  Residual changes decode their meta lazily only
        when the caller unpacks them (actor None)."""
        for sid in self.cshape_ids:
            if sid == _RESIDUAL_SHAPE:
                raw = bytes(self.residuals.take(
                    self.residuals.uvarint()))
                yield raw, None, None
                continue
            keys = self.cshapes[sid - 1]
            change = {}
            # actor resolves FIRST regardless of its key position: the
            # encoder's seq delta is keyed on the actor even when the
            # change dict spells seq before actor (column order within
            # one change is per-field, so this reorder is free)
            actor_i = self.col(0, 'actor').uvarint()
            actor = self.strings[actor_i]
            d = self.col(0, 'seq').zigzag()
            seq = self.last_seq.get(actor_i, 0) + 1 + d
            for k in keys:
                if k == 'actor':
                    change[k] = actor
                elif k == 'seq':
                    change[k] = seq
                elif k == 'deps':
                    r = self.col(0, 'deps')
                    n = r.uvarint()
                    deps = {}
                    for _ in range(n):
                        di = r.uvarint()
                        deps[self.strings[di]] = \
                            self.run_clock.get(di, 0) + r.zigzag()
                    change[k] = deps
                elif k == 'ops':
                    n = self.col(0, 'ops').uvarint()
                    change[k] = [self._op() for _ in range(n)]
                else:
                    change[k] = self._value(self.col(0, k))
            self.last_seq[actor_i] = seq
            if seq > self.run_clock.get(actor_i, 0):
                self.run_clock[actor_i] = seq
            yield msgpack.packb(change, use_bin_type=True), actor, seq

    def _op(self):
        keys, action = self.oshapes[next(self.oshape_ids)]
        op = {}
        for k in keys:
            if k == 'action':
                op[k] = action
            elif k == 'obj':
                op[k] = self.strings[self.col(1, 'obj').uvarint()]
            elif k == 'elem':
                r = self.col(1, 'elem')
                self.last_elem += r.zigzag()
                op[k] = self.last_elem
            elif k == 'key':
                r = self.col(1, 'key')
                tag = r.buf[r.pos]
                r.pos += 1
                if tag == _K_ELEM:
                    head = self.strings[r.uvarint()]
                    self.last_key_elem += r.zigzag()
                    op[k] = '%s:%d' % (head, self.last_key_elem)
                else:
                    op[k] = self.strings[r.uvarint()]
            else:
                op[k] = self._value(self.col(1, k))
        return op


def decode_columnar(blob):
    """-> list of raw msgpack change bytes, byte-identical to the
    `encode_columnar` input.  A corrupt blob raises ValueError
    whatever the decoder tripped on internally (zlib, struct, an
    out-of-range table index).  Dispatches to the native codec under
    `AMTPU_STORAGE_NATIVE` (corruption surfaces as the same
    ValueError)."""
    telemetry.metric('storage.columnar.decodes')
    nat = _native_codec()
    if nat is not None:
        telemetry.metric('storage.native_decodes')
        return nat.columnar_decode_native(bytes(blob))
    telemetry.metric('storage.python_decodes')
    with corrupt_raises_value_error():
        return [raw for raw, _a, _s in _Decoder(blob).changes()]


def decode_columnar_meta(blob):
    """-> list of (raw_bytes, actor, seq); residual changes pay one
    unpack for their meta (the merge paths in native/__init__.py key
    on actor/seq).  Corruption raises ValueError, like
    `decode_columnar`.  Always the Python decoder (the meta tuple is a
    Python-object product anyway; the hot arena-direct path is
    `amtpu_begin_columnar`)."""
    telemetry.metric('storage.columnar.decodes')
    telemetry.metric('storage.python_decodes')
    with corrupt_raises_value_error():
        entries = list(_Decoder(blob).changes())
    out = []
    for raw, actor, seq in entries:
        if actor is None:
            try:
                parsed = msgpack.unpackb(raw, raw=False,
                                         strict_map_key=False)
                actor = parsed.get('actor') \
                    if isinstance(parsed, dict) else None
                seq = parsed.get('seq') \
                    if isinstance(parsed, dict) else None
            except Exception:
                actor = seq = None
        out.append((raw, actor, seq))
    return out


def decode_columnar_dicts(blob):
    """Dict-level convenience: decoded change dicts in input order."""
    return [msgpack.unpackb(raw, raw=False, strict_map_key=False)
            for raw in decode_columnar(blob)]
