"""Cold-doc disk tier + LRU eviction (ISSUE 10 tentpole c,
docs/STORAGE.md).

A host serving millions of docs cannot keep every doc's arena resident:
past ``AMTPU_RESIDENT_DOCS_MAX`` live docs, the least-recently-touched
doc checkpoints to disk (`pool.save()` -- the v2 columnar container,
so cold bytes are already compressed) and drops out of the pool
entirely (`pool.drop_doc()`).  A later request touching a cold doc
takes a transparent reload-on-touch: the gateway re-loads it inside the
flush that wants it, under the pool lock, so the scheduler's per-doc
FIFO parks followers exactly as it would behind an in-flight op.

Thread model: the gateway's own tier runs under the pool lock, but the
store is no longer single-threaded by construction -- live migration
(ISSUE 18) writes handoff batches from the router's migration threads
while WAL compaction and the flush path may race the same directory's
manifest.  Every public method therefore serializes on an internal
RLock: blob writes and the read-modify-write manifest rewrite
(`put_many` -> `_write_manifest`) are atomic with respect to each
other, so concurrent callers can never interleave a manifest that
drops the other writer's committed docs (`make static-check` enforces
the guarded-by discipline).  The disk directory
(``AMTPU_STORAGE_DIR``, default a
fresh tempdir) is by default an extension of pool memory, not durable
storage -- a process that dies with evicted docs loses them exactly as
it loses resident ones (durability remains the checkpoint-WAL's job).

**Durable mode** (``AMTPU_STORAGE_DURABLE=1``, ISSUE 14): the store
becomes a crash-safe handoff transport -- every blob write fsyncs
(file + directory) and lands in a per-dir **manifest**
(``manifest.amtm``: doc id -> file name, byte count, sha1 checksum;
itself written tempfile + rename + fsync), so a FRESH process pointed
at the same directory recovers the exact committed doc set
(`doc_ids()`), a kill at ANY byte of a save leaves the prior blob and
manifest intact, and a torn/bit-rotted blob fails its checksum at
`get` instead of replaying garbage.  This is the replica-handoff
transport ROADMAP #1 needs (ColdStore.save on the source + load_batch
on the target).

Writes are crash-safe in BOTH modes: blobs land via tempfile + atomic
``os.replace``, so a partial write can never corrupt the previous
committed copy (the ``storage.save`` fault lane pins it).
"""

import collections
import hashlib
import os
import tempfile
import threading

import msgpack

from .. import faults, telemetry
from ..utils.common import env_bool, env_int, env_str

#: per-dir manifest file name (durable mode)
MANIFEST = 'manifest.amtm'


class ColdStoreCorrupt(ValueError):
    """A cold blob failed its manifest checksum at read time (torn
    write survived a crash, bit rot, external truncation).  Subclasses
    ValueError so pre-existing whole-restore callers keep their raise
    contract; the parallel restore path (`native.restore_from_store`,
    ISSUE 17) catches THIS type to quarantine the one doc (typed
    per-doc error + ``storage.restore.corrupt``) instead of failing a
    million-doc restore on one bad blob."""

    def __init__(self, doc_id, detail):
        super(ColdStoreCorrupt, self).__init__(
            'cold blob checksum mismatch for %r (%s)' % (doc_id, detail))
        self.doc_id = doc_id


class ColdStore(object):
    """File-per-doc blob store: checkpoint containers keyed by doc id."""

    def __init__(self, root=None, durable=None):
        if root is None:
            root = env_str('AMTPU_STORAGE_DIR', '')
        self.root = root or tempfile.mkdtemp(prefix='amtpu-cold-')
        os.makedirs(self.root, exist_ok=True)
        if durable is None:
            durable = env_bool('AMTPU_STORAGE_DURABLE', False)
        self.durable = durable
        # concurrent callers (migration threads + WAL compaction +
        # the gateway flush) serialize here; RLock so the compound
        # public paths (pop = get + discard) stay atomic
        self._lock = threading.RLock()
        # doc id -> (path, n_bytes, sha1|None)
        self._index = {}          # guarded-by: self._lock
        if self.durable:
            with self._lock:
                self._recover()

    def _path(self, doc_id):
        h = hashlib.sha1(str(doc_id).encode('utf-8')).hexdigest()
        return os.path.join(self.root, h + '.amtc')

    def __contains__(self, doc_id):
        with self._lock:
            return doc_id in self._index

    def __len__(self):
        with self._lock:
            return len(self._index)

    def doc_ids(self):
        """Committed doc ids (durable mode: exactly what a fresh
        process recovers from the manifest -- the handoff inventory)."""
        with self._lock:
            return list(self._index)

    def disk_bytes(self, doc_id):
        """On-disk bytes of one cold doc (0 when not stored) -- the
        `disk_bytes` tier of the capacity cost vector
        (telemetry/capacity.py)."""
        with self._lock:
            entry = self._index.get(doc_id)
        return entry[1] if entry is not None else 0

    @property
    def bytes(self):
        with self._lock:
            return sum(e[1] for e in self._index.values())

    # -- durable-mode manifest ------------------------------------------

    def _recover(self):  # holds-lock: self._lock
        """Rebuilds the index from the manifest: only entries whose
        file exists at the recorded size are adopted (a killed save
        leaves at most a stray ``.tmp``, which is ignored -- the
        manifest names the last COMMITTED copy)."""
        mpath = os.path.join(self.root, MANIFEST)
        if not os.path.exists(mpath):
            return
        try:
            with open(mpath, 'rb') as f:
                m = msgpack.unpackb(f.read(), raw=False)
            docs = m.get('docs') or {}
        except Exception:
            telemetry.metric('storage.manifest_corrupt')
            return
        n = 0
        for doc_id, ent in docs.items():
            path = os.path.join(self.root, ent['file'])
            try:
                if os.path.getsize(path) != ent['bytes']:
                    continue
            except OSError:
                continue
            self._index[doc_id] = (path, ent['bytes'], ent.get('sha1'))
            n += 1
        if n:
            telemetry.metric('storage.manifest_recovered', n)

    def _fsync_dir(self):
        try:
            fd = os.open(self.root, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass

    def _write_manifest(self):  # holds-lock: self._lock
        docs = {}
        for doc_id, (path, n, digest) in self._index.items():
            docs[str(doc_id)] = {'file': os.path.basename(path),
                                 'bytes': n, 'sha1': digest}
        mpath = os.path.join(self.root, MANIFEST)
        tmp = mpath + '.tmp'
        with open(tmp, 'wb') as f:
            f.write(msgpack.packb({'format': 'amtpu-manifest-v1',
                                   'docs': docs}, use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, mpath)
        self._fsync_dir()
        telemetry.metric('storage.manifest_writes')

    # -- blob I/O -------------------------------------------------------

    def _put_blob(self, doc_id, blob):  # holds-lock: self._lock
        """Writes one blob crash-safely and updates the in-memory
        index; returns the obsolete prior path (durable mode) for the
        caller to unlink AFTER the manifest commits.

        Crash-safety: tempfile + atomic rename, so a kill at any byte
        of the write leaves the PRIOR committed copy intact (the
        ``storage.save`` fault lane fires mid-write -- partial
        tempfile on disk, rename not yet run -- modeling exactly that
        kill).  Durable mode additionally VERSIONS the file name by
        content hash: a re-save never overwrites the committed copy in
        place, so a kill between the rename and the manifest write
        still leaves the manifest naming the intact prior file; the
        new file is simply a stray the next recovery ignores."""
        digest = hashlib.sha1(blob).hexdigest() if self.durable else None
        base = self._path(doc_id)
        path = '%s-%s.amtc' % (base[:-5], digest[:12]) if self.durable \
            else base
        tmp = path + '.tmp'
        with open(tmp, 'wb') as f:
            if faults.ARMED:
                # a real kill interrupts the write stream itself: leave
                # a genuinely partial tempfile behind the fault
                half = len(blob) // 2
                f.write(blob[:half])
                faults.fire('storage.save', [str(doc_id)])
                f.write(blob[half:])
            else:
                f.write(blob)
            if self.durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        prior = None
        if self.durable:
            self._fsync_dir()
            telemetry.metric('storage.durable_writes')
            old = self._index.get(doc_id)
            if old is not None and old[0] != path:
                prior = old[0]
        telemetry.metric('storage.cold_bytes_written', len(blob))
        self._index[doc_id] = (path, len(blob), digest)
        return prior

    def _retire(self, paths):
        """Unlinks obsolete blob versions AFTER the manifest named
        their replacements (a kill in between leaves strays the next
        recovery ignores, never a lost committed copy)."""
        for path in paths:
            if path is None:
                continue
            try:
                os.unlink(path)
            except OSError:
                pass

    def put(self, doc_id, blob):
        with self._lock:
            prior = self._put_blob(doc_id, blob)
            if self.durable:
                self._write_manifest()
                self._retire([prior])

    def put_many(self, blobs):
        """Batched handoff writes ({doc_id: blob}): one manifest
        rewrite + fsync for the whole batch instead of one per doc --
        the replica-handoff path saves thousands of docs in a burst,
        and per-put manifests would make that O(n^2).  The whole batch
        (blobs + manifest) commits under the store lock, so a racing
        writer's manifest can never drop this batch's docs."""
        with self._lock:
            priors = [self._put_blob(d, b) for d, b in blobs.items()]
            if self.durable:
                self._write_manifest()
                self._retire(priors)

    def get(self, doc_id):
        """Reads a cold blob WITHOUT removing it -- reload reads first
        and discards only after the replay committed, so a failed
        reload cannot destroy the only copy of a doc.  Durable mode
        verifies the manifest checksum, so a torn or bit-rotted blob
        raises here instead of replaying garbage."""
        with self._lock:
            path, n, digest = self._index[doc_id]
            with open(path, 'rb') as f:
                data = f.read()
        if digest is not None \
                and hashlib.sha1(data).hexdigest() != digest:
            telemetry.metric('storage.checksum_failed')
            raise ColdStoreCorrupt(
                doc_id, '%d bytes on disk, %d committed'
                        % (len(data), n))
        return data

    def discard(self, doc_id):
        with self._lock:
            entry = self._index.pop(doc_id, None)
            if entry is None:
                return
            try:
                os.unlink(entry[0])
            except OSError:
                pass
            if self.durable:
                self._write_manifest()

    def pop(self, doc_id):
        with self._lock:
            blob = self.get(doc_id)
            self.discard(doc_id)
        return blob


class DocEvictor(object):
    """LRU residency manager one gateway owns (all calls under the
    gateway's pool lock).  Also hosts the per-doc GC cadence: every
    ``AMTPU_STORAGE_GC_MIN`` mutations a doc's settled history folds
    into its columnar snapshot (`pool.compact`)."""

    def __init__(self, pool, max_resident=None, store=None,
                 gc_every=None):
        self.pool = pool
        self.max = env_int('AMTPU_RESIDENT_DOCS_MAX', 0) \
            if max_resident is None else max_resident
        self.gc_every = env_int('AMTPU_STORAGE_GC_MIN', 256) \
            if gc_every is None else gc_every
        self.store = store if store is not None else ColdStore()
        self._lru = collections.OrderedDict()   # doc id -> True
        self._gc_debt = {}       # doc id -> mutations since last fold

    @classmethod
    def from_env(cls, pool):
        """The gateway's constructor: None when eviction is disabled
        (``AMTPU_RESIDENT_DOCS_MAX`` unset/0) AND GC is off -- an
        evictor with max=0 still drives the GC cadence."""
        return cls(pool)

    # -- residency ------------------------------------------------------

    def ensure_resident(self, docs):
        """Reloads every cold doc in `docs` (ONE batched replay) before
        the caller touches the pool -- the reload-on-touch half of the
        eviction contract.  Returns {doc: exception} for docs whose
        reload FAILED: their blobs stay cold (the only copy must
        survive a transient replay failure), the failure is isolated
        per doc (one corrupt blob must not pin the batch's other cold
        docs), and the caller must NOT run ops against them -- an
        apply on the missing doc would create a fresh empty doc and
        silently diverge."""
        cold = [d for d in docs if d in self.store]
        if not cold:
            return {}
        # read WITHOUT removing: if the replay raises (armed
        # checkpoint.load fault, poisoned history), the cold blobs must
        # survive -- they are the only copy of those docs
        blobs = {d: self.store.get(d) for d in cold}
        failed = {}
        try:
            self.pool.load_batch(blobs)
            ok = cold
        except Exception:
            ok = []
            for d in cold:           # isolate the poison blob(s)
                try:
                    self.pool.load_batch({d: blobs[d]})
                    ok.append(d)
                except Exception as e:
                    failed[d] = e
        for d in ok:
            self.store.discard(d)
            self._lru[d] = True
            self._lru.move_to_end(d)
        if ok:
            telemetry.metric('storage.reloads', len(ok))
            telemetry.recorder.record('storage.reload', n=len(ok))
        if failed:
            telemetry.metric('storage.reload_failed', len(failed))
            telemetry.recorder.record(
                'storage.reload', n=len(failed),
                doc=next(iter(failed)), detail='failed')
        return failed

    def note_touch(self, docs):
        for d in docs:
            self._lru[d] = True
            self._lru.move_to_end(d)

    def forget(self, doc):
        """Drops every trace of a doc this replica migrated away
        (ISSUE 18): LRU slot, GC debt, and any cold copy -- the new
        owner serves it now, and a stale cold blob here would resurrect
        pre-migration state on a later reload-on-touch."""
        self._lru.pop(doc, None)
        self._gc_debt.pop(doc, None)
        if doc in self.store:
            self.store.discard(doc)

    def maybe_evict(self, protect=(), pressure=False, max_evict=None):
        """Evicts least-recently-touched docs past the residency cap
        (never one in `protect` -- the flush's own docs).

        ``pressure=True`` is the headroom estimator's proactive mode
        (telemetry/capacity.py; docs/STORAGE.md eviction-pressure
        section): the doc-count cap is ignored and up to `max_evict`
        (default ``AMTPU_PRESSURE_EVICT_DOCS``) LRU docs checkpoint out
        regardless -- evict BEFORE the OOM killer does, not just past a
        count.  Each eviction records the arena bytes it actually freed
        (per-doc stats, captured pre-drop) under
        ``storage.evicted_bytes`` and a per-doc ``storage.evict``
        recorder event carrying doc + bytes."""
        if pressure:
            budget = max_evict if max_evict is not None \
                else env_int('AMTPU_PRESSURE_EVICT_DOCS', 16)
            target = 0
        else:
            if self.max <= 0:
                return 0
            budget = len(self._lru)
            target = self.max
        protect = set(protect)
        evicted = freed = 0
        # bounded walk: each pass either evicts the oldest unprotected
        # doc or skips a protected one (requeued at the end)
        attempts = len(self._lru)
        while len(self._lru) > target and attempts > 0 \
                and evicted < budget:
            attempts -= 1
            doc, _ = next(iter(self._lru.items()))
            if doc in protect:
                self._lru.move_to_end(doc)
                continue
            try:
                # bytes actually freed: the doc's retained arena span
                # sum, read BEFORE the drop erases the DocState
                doc_bytes = self.pool.history_bytes(doc)
                blob = self.pool.save(doc)
                self.store.put(doc, blob)
                self.pool.drop_doc(doc)
            except Exception:
                # a doc that will not checkpoint must NOT be dropped;
                # requeue it hot so the walk cannot spin on it
                telemetry.metric('storage.evict_failed')
                self._lru.move_to_end(doc)
                continue
            self._lru.pop(doc, None)
            self._gc_debt.pop(doc, None)
            evicted += 1
            freed += doc_bytes
            telemetry.recorder.record('storage.evict', doc=doc,
                                      n=doc_bytes,
                                      detail='pressure' if pressure
                                      else None)
        if evicted:
            telemetry.metric('storage.evictions', evicted)
            telemetry.metric('storage.evicted_bytes', freed)
            if pressure:
                telemetry.metric('storage.pressure_evictions', evicted)
        return evicted

    # -- settled-history GC cadence -------------------------------------

    def note_mutations(self, doc, n, acked_fn=None):
        """`n` changes committed for `doc` this flush; past the
        ``AMTPU_STORAGE_GC_MIN`` debt the settled prefix folds into the
        doc's columnar snapshot.  `acked_fn` resolves the frontier
        LAZILY (the fan-out engine's pointwise-min believed clock,
        None = no subscribers) -- it is only called on the rare flush
        that actually folds, so the per-flush cost is one dict add."""
        if self.gc_every <= 0:
            return 0
        debt = self._gc_debt.get(doc, 0) + max(1, n)
        if debt < self.gc_every:
            self._gc_debt[doc] = debt
            return 0
        self._gc_debt[doc] = 0
        frontier = acked_fn() if acked_fn is not None else None
        return self.pool.compact(doc, frontier=frontier)

    # -- observability --------------------------------------------------

    def healthz_section(self):
        flat = telemetry.metrics_snapshot()
        return {'resident_docs': len(self._lru),
                'max_resident': self.max,
                'cold_docs': len(self.store),
                'cold_bytes': self.store.bytes,
                'durable': self.store.durable,
                'gc_every': self.gc_every,
                'evictions': int(flat.get('storage.evictions', 0)),
                'evicted_bytes': int(flat.get('storage.evicted_bytes',
                                              0)),
                'pressure_evictions': int(flat.get(
                    'storage.pressure_evictions', 0))}
