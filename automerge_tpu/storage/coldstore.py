"""Cold-doc disk tier + LRU eviction (ISSUE 10 tentpole c,
docs/STORAGE.md).

A host serving millions of docs cannot keep every doc's arena resident:
past ``AMTPU_RESIDENT_DOCS_MAX`` live docs, the least-recently-touched
doc checkpoints to disk (`pool.save()` -- the v2 columnar container,
so cold bytes are already compressed) and drops out of the pool
entirely (`pool.drop_doc()`).  A later request touching a cold doc
takes a transparent reload-on-touch: the gateway re-loads it inside the
flush that wants it, under the pool lock, so the scheduler's per-doc
FIFO parks followers exactly as it would behind an in-flight op.

Thread model: every method is called under the gateway's pool lock
(the single serialization point for all pool state); the store itself
is therefore single-threaded by construction and keeps its index as a
plain dict.  The disk directory (``AMTPU_STORAGE_DIR``, default a
fresh tempdir) is an extension of pool memory, not durable storage --
a process that dies with evicted docs loses them exactly as it loses
resident ones (durability remains the checkpoint-WAL's job).
"""

import collections
import hashlib
import os
import tempfile

from .. import telemetry
from ..utils.common import env_int, env_str


class ColdStore(object):
    """File-per-doc blob store: checkpoint containers keyed by doc id."""

    def __init__(self, root=None):
        if root is None:
            root = env_str('AMTPU_STORAGE_DIR', '')
        self.root = root or tempfile.mkdtemp(prefix='amtpu-cold-')
        os.makedirs(self.root, exist_ok=True)
        self._index = {}         # doc id -> (path, n_bytes)

    def _path(self, doc_id):
        h = hashlib.sha1(str(doc_id).encode('utf-8')).hexdigest()
        return os.path.join(self.root, h + '.amtc')

    def __contains__(self, doc_id):
        return doc_id in self._index

    def __len__(self):
        return len(self._index)

    @property
    def bytes(self):
        return sum(n for _p, n in self._index.values())

    def put(self, doc_id, blob):
        path = self._path(doc_id)
        tmp = path + '.tmp'
        with open(tmp, 'wb') as f:
            f.write(blob)
        os.replace(tmp, path)
        telemetry.metric('storage.cold_bytes_written', len(blob))
        self._index[doc_id] = (path, len(blob))

    def get(self, doc_id):
        """Reads a cold blob WITHOUT removing it -- reload reads first
        and discards only after the replay committed, so a failed
        reload cannot destroy the only copy of a doc."""
        path, _n = self._index[doc_id]
        with open(path, 'rb') as f:
            return f.read()

    def discard(self, doc_id):
        entry = self._index.pop(doc_id, None)
        if entry is None:
            return
        try:
            os.unlink(entry[0])
        except OSError:
            pass

    def pop(self, doc_id):
        blob = self.get(doc_id)
        self.discard(doc_id)
        return blob


class DocEvictor(object):
    """LRU residency manager one gateway owns (all calls under the
    gateway's pool lock).  Also hosts the per-doc GC cadence: every
    ``AMTPU_STORAGE_GC_MIN`` mutations a doc's settled history folds
    into its columnar snapshot (`pool.compact`)."""

    def __init__(self, pool, max_resident=None, store=None,
                 gc_every=None):
        self.pool = pool
        self.max = env_int('AMTPU_RESIDENT_DOCS_MAX', 0) \
            if max_resident is None else max_resident
        self.gc_every = env_int('AMTPU_STORAGE_GC_MIN', 256) \
            if gc_every is None else gc_every
        self.store = store if store is not None else ColdStore()
        self._lru = collections.OrderedDict()   # doc id -> True
        self._gc_debt = {}       # doc id -> mutations since last fold

    @classmethod
    def from_env(cls, pool):
        """The gateway's constructor: None when eviction is disabled
        (``AMTPU_RESIDENT_DOCS_MAX`` unset/0) AND GC is off -- an
        evictor with max=0 still drives the GC cadence."""
        return cls(pool)

    # -- residency ------------------------------------------------------

    def ensure_resident(self, docs):
        """Reloads every cold doc in `docs` (ONE batched replay) before
        the caller touches the pool -- the reload-on-touch half of the
        eviction contract.  Returns {doc: exception} for docs whose
        reload FAILED: their blobs stay cold (the only copy must
        survive a transient replay failure), the failure is isolated
        per doc (one corrupt blob must not pin the batch's other cold
        docs), and the caller must NOT run ops against them -- an
        apply on the missing doc would create a fresh empty doc and
        silently diverge."""
        cold = [d for d in docs if d in self.store]
        if not cold:
            return {}
        # read WITHOUT removing: if the replay raises (armed
        # checkpoint.load fault, poisoned history), the cold blobs must
        # survive -- they are the only copy of those docs
        blobs = {d: self.store.get(d) for d in cold}
        failed = {}
        try:
            self.pool.load_batch(blobs)
            ok = cold
        except Exception:
            ok = []
            for d in cold:           # isolate the poison blob(s)
                try:
                    self.pool.load_batch({d: blobs[d]})
                    ok.append(d)
                except Exception as e:
                    failed[d] = e
        for d in ok:
            self.store.discard(d)
            self._lru[d] = True
            self._lru.move_to_end(d)
        if ok:
            telemetry.metric('storage.reloads', len(ok))
            telemetry.recorder.record('storage.reload', n=len(ok))
        if failed:
            telemetry.metric('storage.reload_failed', len(failed))
            telemetry.recorder.record(
                'storage.reload', n=len(failed),
                doc=next(iter(failed)), detail='failed')
        return failed

    def note_touch(self, docs):
        for d in docs:
            self._lru[d] = True
            self._lru.move_to_end(d)

    def maybe_evict(self, protect=()):
        """Evicts least-recently-touched docs past the residency cap
        (never one in `protect` -- the flush's own docs)."""
        if self.max <= 0:
            return 0
        protect = set(protect)
        evicted = 0
        # bounded walk: each pass either evicts the oldest unprotected
        # doc or skips a protected one (requeued at the end)
        attempts = len(self._lru)
        while len(self._lru) > self.max and attempts > 0:
            attempts -= 1
            doc, _ = next(iter(self._lru.items()))
            if doc in protect:
                self._lru.move_to_end(doc)
                continue
            try:
                blob = self.pool.save(doc)
                self.store.put(doc, blob)
                self.pool.drop_doc(doc)
            except Exception:
                # a doc that will not checkpoint must NOT be dropped;
                # requeue it hot so the walk cannot spin on it
                telemetry.metric('storage.evict_failed')
                self._lru.move_to_end(doc)
                continue
            self._lru.pop(doc, None)
            self._gc_debt.pop(doc, None)
            evicted += 1
        if evicted:
            telemetry.metric('storage.evictions', evicted)
            telemetry.recorder.record('storage.evict', n=evicted)
        return evicted

    # -- settled-history GC cadence -------------------------------------

    def note_mutations(self, doc, n, acked_fn=None):
        """`n` changes committed for `doc` this flush; past the
        ``AMTPU_STORAGE_GC_MIN`` debt the settled prefix folds into the
        doc's columnar snapshot.  `acked_fn` resolves the frontier
        LAZILY (the fan-out engine's pointwise-min believed clock,
        None = no subscribers) -- it is only called on the rare flush
        that actually folds, so the per-flush cost is one dict add."""
        if self.gc_every <= 0:
            return 0
        debt = self._gc_debt.get(doc, 0) + max(1, n)
        if debt < self.gc_every:
            self._gc_debt[doc] = debt
            return 0
        self._gc_debt[doc] = 0
        frontier = acked_fn() if acked_fn is not None else None
        return self.pool.compact(doc, frontier=frontier)

    # -- observability --------------------------------------------------

    def healthz_section(self):
        return {'resident_docs': len(self._lru),
                'max_resident': self.max,
                'cold_docs': len(self.store),
                'cold_bytes': self.store.bytes,
                'gc_every': self.gc_every}
