"""automerge_tpu.storage -- cold-state economics (ISSUE 10,
docs/STORAGE.md).

Three pieces, wired through the pool, the sidecar WAL, and the serve
gateway:

  * :mod:`.columnar` -- the delta/RLE columnar change codec
    (`encode_columnar` / `decode_columnar`, byte-round-trip
    guaranteed);
  * checkpoint containers (this module) -- `pack_checkpoint` /
    `unpack_checkpoint`: the v2 ``amtpu-doc-v2c`` container a
    `pool.save()` emits (columnar snapshot chunks behind the settled
    frontier + a columnar tail), plus v1 compatibility for every
    pre-existing blob;
  * :mod:`.coldstore` -- the disk tier + LRU evictor the gateway uses
    for working-set >> RAM (``AMTPU_RESIDENT_DOCS_MAX``).

``AMTPU_STORAGE_FORMAT=json`` is the escape hatch / parity oracle:
save() then emits the PR-4 v1 container (raw change history) and
settled-history GC is a no-op -- the A/B arm the storage gate compares
against, same pattern as ``AMTPU_FANOUT_VECTOR``.
"""

import msgpack

from .. import telemetry
from ..utils.common import env_str
from .columnar import (corrupt_raises_value_error,  # noqa: F401
                       decode_columnar, decode_columnar_dicts,
                       decode_columnar_meta, encode_columnar,
                       encode_columnar_dicts, storage_native_on)

FORMAT_V1 = 'amtpu-doc-v1'
FORMAT_V2 = 'amtpu-doc-v2c'

#: fixed byte prefixes: both containers are msgpack maps opening with
#: their format key, so a prefix compare classifies a blob without a
#: parse (native._load_batch splices checkpoints at the byte level)
CKPT_V1_PREFIX = (b'\x82' + msgpack.packb('format') +
                  msgpack.packb(FORMAT_V1) + msgpack.packb('changes'))
CKPT_V2_PREFIX = (b'\x84' + msgpack.packb('format') +
                  msgpack.packb(FORMAT_V2))


def storage_format():
    """'columnar' (default) or 'json' (the v1 parity-oracle arm)."""
    fmt = env_str('AMTPU_STORAGE_FORMAT', 'columnar')
    if fmt not in ('columnar', 'json'):
        raise ValueError('AMTPU_STORAGE_FORMAT must be columnar|json, '
                         'got %r' % (fmt,))
    return fmt


def split_changes_array(buf):
    """Splits a raw msgpack array of changes into per-change byte
    slices without building any Python objects (Unpacker.skip walks
    the framing)."""
    buf = bytes(buf)
    u = msgpack.Unpacker(None, max_buffer_size=0)
    u.feed(buf)
    n = u.read_array_header()
    out = []
    start = u.tell()
    for _ in range(n):
        u.skip()
        end = u.tell()
        out.append(buf[start:end])
        start = end
    return out


def join_changes_array(raws):
    """Inverse of `split_changes_array`: one msgpack array of the raw
    change byte strings."""
    out = bytearray()
    n = len(raws)
    if n < 16:
        out.append(0x90 | n)
    elif n < (1 << 16):
        out += b'\xdc' + n.to_bytes(2, 'big')
    else:
        out += b'\xdd' + n.to_bytes(4, 'big')
    for raw in raws:
        out += raw
    return bytes(out)


def pack_checkpoint_v1(raws):
    """The PR-4 container: raw change history, application order."""
    return CKPT_V1_PREFIX + join_changes_array(raws)


def pack_checkpoint(frontier, chunks, tail_raws):
    """The v2 columnar container: settled snapshot chunks (columnar
    blobs, application order, exactly the changes <= `frontier`) + the
    tail (everything after, columnar-encoded here).  Loading replays
    chunks then tail and re-establishes the frontier."""
    telemetry.metric('storage.save_v2')
    return (CKPT_V2_PREFIX +
            msgpack.packb('frontier') +
            msgpack.packb(dict(frontier or {}), use_bin_type=True) +
            msgpack.packb('chunks') +
            msgpack.packb(list(chunks), use_bin_type=True) +
            msgpack.packb('tail') +
            msgpack.packb(encode_columnar(tail_raws),
                          use_bin_type=True))


def is_checkpoint(data):
    return data.startswith(CKPT_V1_PREFIX) \
        or data.startswith(CKPT_V2_PREFIX)


def unpack_checkpoint(data):
    """-> (frontier, chunks, tail_raws): per-format normalize.  v1
    blobs have no frontier and no chunks; v2 blobs decode their tail
    here (chunks stay encoded -- the caller adopts them verbatim into
    the doc's storage state).  A corrupted container surfaces as
    ValueError whatever the decoder tripped on internally (zlib,
    struct, an out-of-range table index) -- callers map it to their
    RangeError contract."""
    if data.startswith(CKPT_V1_PREFIX):
        with corrupt_raises_value_error('checkpoint container'):
            return {}, [], split_changes_array(
                memoryview(data)[len(CKPT_V1_PREFIX):])
    if not data.startswith(CKPT_V2_PREFIX):
        raise ValueError('not an amtpu checkpoint container')
    with corrupt_raises_value_error('checkpoint container'):
        obj = msgpack.unpackb(data, raw=False, strict_map_key=False)
        return (obj.get('frontier') or {},
                list(obj.get('chunks') or ()),
                decode_columnar(obj['tail']))


def unpack_checkpoint_parts(data):
    """v2-only LAZY parse: (frontier, chunks, tail_blob) without
    decoding anything columnar -- the native arena-direct loader
    (`amtpu_begin_columnar`) takes the blobs as-is, so a cold restart
    never builds Python change objects.  Corruption raises ValueError
    like `unpack_checkpoint`."""
    if not data.startswith(CKPT_V2_PREFIX):
        raise ValueError('not an amtpu v2 checkpoint container')
    with corrupt_raises_value_error('checkpoint container'):
        obj = msgpack.unpackb(data, raw=False, strict_map_key=False)
        tail = obj.get('tail')
        if not isinstance(tail, (bytes, bytearray)):
            raise ValueError('checkpoint tail missing')
        chunks = list(obj.get('chunks') or ())
        if not all(isinstance(c, (bytes, bytearray)) for c in chunks):
            raise ValueError('checkpoint chunks not bytes')
        return (obj.get('frontier') or {}, [bytes(c) for c in chunks],
                bytes(tail))


def checkpoint_raw_changes(data):
    """Every raw change of a checkpoint (either format), application
    order -- what load() replays.  Corruption surfaces as ValueError
    (see `unpack_checkpoint`)."""
    _frontier, chunks, tail = unpack_checkpoint(data)
    out = []
    for chunk in chunks:
        out.extend(decode_columnar(chunk))
    out.extend(tail)
    return out
