"""Fault injection registry (docs/RESILIENCE.md).

The reference library scripts a network fault model for its connection
tests (mirrored by the ``drop`` hook in `sync/replica_set.py`); this
module extends that philosophy to the layers the reference never had:
device dispatch, the native C++ pool, and the sidecar process boundary.
Named injection SITES are threaded through the hot paths; arming a site
makes the next matching pass raise a typed fault exactly where a real
XLA/device/runtime error would surface, so the resilience machinery
(`automerge_tpu.resilience`, the self-healing sidecar client) can be
driven deterministically in tests and chaos smokes.

Sites (see docs/RESILIENCE.md for what each models):

  native.begin      C++ decode/schedule/encode (amtpu_begin succeeded,
                    fault fires before any dispatch)
  device.dispatch   JAX kernel dispatch (phase a; kernel path only)
  device.collect    device->host result collection (phase b, pre-mid)
  native.mid        C++ mid phase (fires before any amtpu_mid* call)
  escalation.tier   wider-window escalation tier dispatch
  sidecar.frame     sidecar server request framing (uncaught by design:
                    the serve loop dies, simulating a process crash)
  checkpoint.load   save()-checkpoint restore (WAL replay path)
  fanout.write      per-connection egress write failure (the writer
                    thread treats it as a dead transport and tears the
                    connection down off the flush critical path)
  fanout.stall      armed wedge: the egress writer makes no progress
                    while it fires, so a permanent stall drives the
                    AMTPU_EGRESS_WEDGE_S tier-3 eviction
                    deterministically
  storage.save      cold-store blob write, mid-stream (a partial
                    tempfile exists, the atomic rename has not run --
                    models a kill mid-save; the prior committed copy
                    and the durable manifest must survive)
  router.forward    router -> replica raw-frame forward (the data
                    path); the router answers the retryable
                    ReplicaUnavailable envelope, exactly as a dead
                    upstream socket would
  router.heartbeat  router health-monitor probe; `docs` carries the
                    probed member id so `match` pins the fault to one
                    replica -- a permanent spec drives the
                    up -> suspect -> dead -> failover ladder
                    deterministically, a counted transient spec clears
                    as a recovery

Arming:

  * environment -- ``AMTPU_FAULT=site:kind:prob[:count]`` where kind is
    ``transient`` | ``permanent``, prob in [0, 1], count bounds total
    fires (omitted = unlimited).  Multiple comma-separated specs
    compose.  Parsed at import, so armed specs propagate into sidecar
    server subprocesses through the environment.
  * programmatic -- ``faults.arm(site, kind, prob, count=..., match=...)``;
    ``match`` pins the fault to batches containing a doc key with that
    substring (poison-doc simulation; env specs cannot pin).

Cost model: disarmed, the hot paths pay ONE module-attribute read per
site (``if faults.ARMED:`` -- the same shim pattern as ``trace.ENABLED``);
no call, no dict lookup.  ``make perf-smoke`` / ``make fallback-check``
run with the hooks in place and gate that the fast paths are unchanged.
"""

import random
import threading

from . import telemetry
from .utils.common import env_raw, env_str

#: the site universe -- arm() rejects anything else so a typo'd env spec
#: fails loudly instead of never firing
SITES = ('native.begin', 'native.mid', 'device.dispatch',
         'device.collect', 'escalation.tier', 'sidecar.frame',
         'checkpoint.load', 'fanout.write', 'fanout.stall',
         'storage.save', 'router.forward', 'router.heartbeat')

KINDS = ('transient', 'permanent')

#: fast gate: True iff any spec is armed.  Hot paths read this ONE
#: attribute and skip everything else when False.
ARMED = False


class InjectedFault(Exception):
    """Base of the injected fault types; carries its site and kind."""

    kind = 'permanent'

    def __init__(self, site, detail=''):
        self.site = site
        super().__init__('injected %s fault at %s%s'
                         % (self.kind, site,
                            ' (%s)' % detail if detail else ''))


class TransientFault(InjectedFault):
    """A fault that models a retryable condition (device hiccup,
    preemption, transient allocator pressure): bounded retries with
    backoff are expected to clear it."""

    kind = 'transient'


class PermanentFault(InjectedFault):
    """A fault that models a deterministic failure (poison doc, wedged
    kernel): retries never clear it; isolation/quarantine must."""

    kind = 'permanent'


class _Spec:
    __slots__ = ('site', 'kind', 'prob', 'count', 'match')

    def __init__(self, site, kind, prob, count, match):
        self.site = site
        self.kind = kind
        self.prob = prob
        self.count = count       # remaining fires; None = unlimited
        self.match = match       # doc-key substring pin; None = any


_lock = threading.Lock()
_specs = []
# deterministic across a test lane when seeded (AMTPU_FAULT_SEED)
_rng = random.Random()


def _refresh_armed():
    global ARMED
    ARMED = bool(_specs)


def arm(site, kind='transient', prob=1.0, count=None, match=None):
    """Arms one fault spec; returns it (pass to :func:`disarm`)."""
    if site not in SITES:
        raise ValueError('unknown fault site %r (one of %s)'
                         % (site, ', '.join(SITES)))
    if kind not in KINDS:
        raise ValueError('unknown fault kind %r (transient|permanent)'
                         % (kind,))
    prob = float(prob)
    if not 0.0 <= prob <= 1.0:
        raise ValueError('fault probability %r outside [0, 1]' % (prob,))
    if count is not None and int(count) < 1:
        raise ValueError('fault count must be >= 1, got %r' % (count,))
    spec = _Spec(site, kind, prob,
                 None if count is None else int(count), match)
    with _lock:
        _specs.append(spec)
        _refresh_armed()
    return spec


def disarm(spec=None):
    """Removes one spec, or every spec when called without arguments."""
    with _lock:
        if spec is None:
            del _specs[:]
        else:
            try:
                _specs.remove(spec)
            except ValueError:
                pass
        _refresh_armed()


def reset(env=None):
    """Test isolation: drop every armed spec, then re-arm from the
    environment (``env`` overrides ``os.environ['AMTPU_FAULT']``)."""
    disarm()
    load_env(env)


def load_env(value=None):
    """Parses ``AMTPU_FAULT=site:kind:prob[:count][,spec...]`` and arms
    each spec.  A malformed spec raises (a chaos run with a typo'd fault
    must not silently test nothing)."""
    if value is None:
        value = env_str('AMTPU_FAULT', '')
    seed = env_raw('AMTPU_FAULT_SEED')
    if seed:
        _rng.seed(seed)
    for part in filter(None, (p.strip() for p in value.split(','))):
        bits = part.split(':')
        if len(bits) not in (3, 4):
            raise ValueError(
                'bad AMTPU_FAULT spec %r (want site:kind:prob[:count])'
                % (part,))
        arm(bits[0], bits[1], float(bits[2]),
            count=int(bits[3]) if len(bits) == 4 else None)


def fire(site, docs=None):
    """Raises a typed fault when an armed spec matches this pass.

    ``docs`` is the batch's doc-key list when the site has one (None
    where no doc scope exists, e.g. sidecar framing); a spec armed with
    ``match`` only fires when some doc key contains the pin, so
    bisection converges on exactly the poisoned doc(s).

    Only called behind the ``faults.ARMED`` gate -- never on the
    disarmed fast path.
    """
    with _lock:
        for spec in _specs:
            if spec.site != site:
                continue
            if spec.match is not None:
                if docs is None or not any(spec.match in d for d in docs):
                    continue
            if spec.prob < 1.0 and _rng.random() >= spec.prob:
                continue
            if spec.count is not None:
                spec.count -= 1
                if spec.count <= 0:
                    _specs.remove(spec)
                    _refresh_armed()
            kind = spec.kind
            break
        else:
            return
    telemetry.metric('resilience.fault_injected')
    telemetry.metric('resilience.fault_injected.' + site)
    telemetry.recorder.record('fault.injected', n=1,
                              doc=spec.match, detail='%s:%s'
                              % (site, kind))
    cls = TransientFault if kind == 'transient' else PermanentFault
    detail = spec.match if spec.match is not None else ''
    raise cls(site, detail)


def is_transient(exc):
    """Whether bounded retries are worth attempting for ``exc``.

    Injected faults declare themselves; real-world classification keeps
    a deliberately narrow allowlist -- OS-level hiccups and the XLA
    status codes that name retryable conditions.  Everything else (and
    every :class:`PermanentFault`) is permanent: retrying a
    deterministic failure just triples its latency.
    """
    if isinstance(exc, TransientFault):
        return True
    if isinstance(exc, InjectedFault):
        return False
    if isinstance(exc, (BrokenPipeError, ConnectionError, InterruptedError,
                        TimeoutError)):
        return True
    if type(exc).__name__ == 'XlaRuntimeError':
        msg = str(exc).upper()
        return any(code in msg for code in
                   ('RESOURCE_EXHAUSTED', 'UNAVAILABLE', 'ABORTED',
                    'DEADLINE_EXCEEDED', 'CANCELLED'))
    return False


# armed specs must propagate into subprocesses (the sidecar server, the
# bench/check subprocess drivers) without every entry point re-parsing
load_env()
