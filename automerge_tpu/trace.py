"""Compatibility shim over `automerge_tpu.telemetry` (PR 1).

The original trace.py was a flat occupancy counter gated on an
import-time AMTPU_TRACE snapshot.  The real implementation now lives in
`automerge_tpu.telemetry` (structured spans, metric registry, Prometheus
exposition); this module keeps every pre-PR-1 call site working:

  * `trace.span / add / count` -- phase occupancy, gated on the runtime
    enable flag (`telemetry.enable()` / `disable()`).
  * `trace.metric / metrics_reset / metrics_snapshot` -- the always-on
    flat counters every bench line embeds.
  * `trace.ENABLED` -- reads AND writes forward to the runtime flag via
    a module-class property, so `trace.ENABLED = True` (tests,
    __graft_entry__) now toggles tracing at runtime instead of racing an
    import-order snapshot.

New code should import `automerge_tpu.telemetry` directly.
"""

import sys
import types

from . import telemetry as _t

span = _t.span


def add(phase, seconds, n=1):
    _t.phase_add(phase, seconds, n)


def count(counter, n=1):
    _t.phase_count(counter, n)


def metric(name, n=1):
    _t.metric(name, n)


def metrics_reset():
    _t.metrics_reset()


def metrics_snapshot():
    return _t.metrics_snapshot()


def reset():
    _t.phase_reset()


def snapshot():
    return _t.phase_snapshot()


def report():
    return _t.phase_report()


class _TraceModule(types.ModuleType):
    @property
    def ENABLED(self):
        return _t.enabled()

    @ENABLED.setter
    def ENABLED(self, value):
        if value:
            _t.enable()
        else:
            _t.disable()


sys.modules[__name__].__class__ = _TraceModule
