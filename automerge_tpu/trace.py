"""Opt-in phase tracing (AMTPU_TRACE=1).

The reference ships no instrumentation (SURVEY.md section 5); since this
framework's metric is ops/sec, it adds an opt-in timing/counter layer:
per-phase wall time and op counts accumulated across every pool dispatch.

Enable with AMTPU_TRACE=1 (checked once at import).  Phases are
accumulated under a lock because `ShardedNativePool` drives shards from
concurrent threads -- phase sums therefore measure *occupancy* (total
seconds spent in a phase across all threads), which can exceed wall time
when shards overlap.  That is the useful number on a 1-core host: it shows
where the serialized host budget goes.

Usage:
    from automerge_tpu import trace
    trace.reset()
    ... run workload ...
    print(trace.report())
"""

import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager

ENABLED = os.environ.get('AMTPU_TRACE', '0') not in ('', '0')

_lock = threading.Lock()
_seconds = defaultdict(float)
_counts = defaultdict(int)


def add(phase, seconds, n=1):
    if not ENABLED:
        return
    with _lock:
        _seconds[phase] += seconds
        _counts[phase] += n


def count(counter, n=1):
    if not ENABLED:
        return
    with _lock:
        _counts[counter] += n


# ---------------------------------------------------------------------------
# Always-on metrics (NOT gated by AMTPU_TRACE): the handful of numbers a
# bench run must be able to report unconditionally -- oracle-fallback
# rates (a degraded run must be visible in every bench JSON line, VERDICT
# r3 #7) and measured device time (VERDICT r3 #2).  Incremented once per
# BATCH, never per op, so the cost is one dict update per dispatch.
# ---------------------------------------------------------------------------

_metrics = defaultdict(float)


def metric(name, n=1):
    """Unconditionally accumulates `n` into the always-on counter."""
    with _lock:
        _metrics[name] += n


def metrics_reset():
    with _lock:
        _metrics.clear()


def metrics_snapshot():
    """{name: value} of the always-on counters since metrics_reset()."""
    with _lock:
        return dict(_metrics)


@contextmanager
def span(phase):
    """Times a with-block into `phase` (no-op unless AMTPU_TRACE=1)."""
    if not ENABLED:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        add(phase, time.perf_counter() - t0)


def reset():
    with _lock:
        _seconds.clear()
        _counts.clear()


def snapshot():
    """{phase: {'s': seconds, 'n': calls}} accumulated since reset()."""
    with _lock:
        keys = set(_seconds) | set(_counts)
        return {k: {'s': _seconds.get(k, 0.0), 'n': _counts.get(k, 0)}
                for k in sorted(keys)}


def report():
    snap = snapshot()
    if not snap:
        return 'trace: (empty)'
    width = max(len(k) for k in snap)
    lines = ['trace (occupancy seconds; threads overlap):']
    for k, v in sorted(snap.items(), key=lambda kv: -kv[1]['s']):
        lines.append('  %-*s %8.3fs  x%d' % (width, k, v['s'], v['n']))
    return '\n'.join(lines)
