from .text import Text, get_elem_id
from .table import Table, WriteableTable, instantiate_table

__all__ = ['Text', 'Table', 'WriteableTable', 'get_elem_id', 'instantiate_table']
