"""Table -- the relational-style row-collection CRDT view
(reference: `/root/reference/frontend/table.js`).

A table has an ordered list of columns and an unordered set of rows keyed by
row objectId.  `WriteableTable` is the variant handed out inside change()
callbacks; it records row adds/removes through the mutation context.
"""

from ..errors import AutomergeError, RangeError
from ..utils.common import is_object


def compare_rows(properties, row1, row2):
    """Multi-column row comparison (reference: table.js:4-17)."""
    for prop in properties:
        v1 = _row_prop(row1, prop)
        v2 = _row_prop(row2, prop)
        if v1 == v2:
            continue
        if isinstance(v1, (int, float)) and isinstance(v2, (int, float)) \
                and not isinstance(v1, bool) and not isinstance(v2, bool):
            return -1 if v1 < v2 else 1
        s1, s2 = str(v1), str(v2)
        if s1 == s2:
            continue
        return -1 if s1 < s2 else 1
    return 0


def _row_prop(row, prop):
    if prop == '_objectId':
        return getattr(row, '_object_id', None)
    return row.get(prop) if hasattr(row, 'get') else None


class _SortKey:
    __slots__ = ('row', 'props')

    def __init__(self, row, props):
        self.row = row
        self.props = props

    def __lt__(self, other):
        return compare_rows(self.props, self.row, other.row) < 0


class Table:
    """Frozen table view (reference: table.js:26-196)."""

    _am_object = True

    def __init__(self, columns=None):
        if columns is not None and not isinstance(columns, list):
            raise TypeError('When creating a table you must supply a list of columns')
        self._columns = columns
        self.entries = {}
        self._object_id = None
        self._conflicts = {}
        self._am_frozen = columns is not None  # user-created tables are frozen

    @property
    def columns(self):
        """The column list: the linked 'columns' entry once the table lives
        in a document, else the constructor-supplied list.  A property (not a
        snapshot attribute) so it survives the clone-on-patch cycle."""
        if 'columns' in self.entries:
            return self.entries['columns']
        return self._columns

    def by_id(self, id_):
        """Row lookup by unique ID (reference: table.js:43-45)."""
        return self.entries.get(id_)

    @property
    def ids(self):
        """Unique IDs of all rows, in no particular order
        (reference: table.js:51-56)."""
        return [key for key, entry in self.entries.items()
                if is_object(entry) and getattr(entry, '_object_id', None) == key]

    @property
    def count(self):
        return len(self.ids)

    @property
    def rows(self):
        return [self.by_id(id_) for id_ in self.ids]

    def filter(self, callback):
        return [row for row in self.rows if callback(row)]

    def find(self, callback):
        for row in self.rows:
            if callback(row):
                return row
        return None

    def map(self, callback):
        return [callback(row) for row in self.rows]

    def sort(self, arg=None):
        """Rows sorted by comparator / column name / column list / row ID
        (reference: table.js:107-119)."""
        import functools
        if callable(arg):
            return sorted(self.rows, key=functools.cmp_to_key(arg))
        elif isinstance(arg, str):
            props = [arg]
        elif isinstance(arg, list):
            props = arg
        elif arg is None:
            props = ['_objectId']
        else:
            raise TypeError('Unsupported sorting argument: %r' % (arg,))
        return sorted(self.rows, key=lambda row: _SortKey(row, props))

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return self.count

    def _clone(self):
        """Writable shallow clone used during patch application
        (reference: table.js:144-149)."""
        if not self._object_id:
            raise RangeError('clone() requires the objectId to be set')
        return instantiate_table(self._object_id, dict(self.entries))

    def set(self, id_, value):
        """(reference: table.js:154-160)"""
        if self._am_frozen:
            raise AutomergeError('A table can only be modified in a change function')
        self.entries[id_] = value

    def remove(self, id_):
        """(reference: table.js:165-170)"""
        if self._am_frozen:
            raise AutomergeError('A table can only be modified in a change function')
        del self.entries[id_]

    def _freeze(self):
        self._am_frozen = True

    def get_writeable(self, context):
        """Writeable view handed out inside change callbacks
        (reference: table.js:185-195)."""
        if not self._object_id:
            raise RangeError('get_writeable() requires the objectId to be set')
        instance = WriteableTable.__new__(WriteableTable)
        instance._object_id = self._object_id
        instance._conflicts = {}
        instance._am_frozen = False
        instance.context = context
        instance.entries = self.entries
        return instance


class WriteableTable(Table):
    """Change-callback variant that records mutations through the context
    (reference: table.js:202-250)."""

    @property
    def columns(self):
        columns_id = self.entries['columns']._object_id
        return self.context.instantiate_object(columns_id)

    def by_id(self, id_):
        entry = self.entries.get(id_)
        if is_object(entry) and getattr(entry, '_object_id', None) == id_:
            return self.context.instantiate_object(id_)
        return None

    def add(self, row):
        """Adds a row given as a dict or a list of values in column order;
        returns the new row's objectId (reference: table.js:228-237)."""
        if isinstance(row, list):
            columns = self.columns
            row = {columns[i]: row[i] for i in range(len(columns))}
        return self.context.add_table_row(self._object_id, row)

    def remove(self, id_):
        """(reference: table.js:243-249)"""
        entry = self.entries.get(id_)
        if is_object(entry) and getattr(entry, '_object_id', None) == id_:
            self.context.delete_table_row(self._object_id, id_)
        else:
            raise RangeError('There is no row with ID %s in this table' % id_)


def instantiate_table(object_id, entries=None):
    """Table instantiation during patch application
    (reference: table.js:256-262)."""
    instance = Table.__new__(Table)
    instance._object_id = object_id
    instance._conflicts = {}
    instance._am_frozen = False
    instance._columns = None
    instance.entries = entries if entries is not None else {}
    return instance
