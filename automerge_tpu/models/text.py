"""Text -- the character-sequence CRDT view
(reference: `/root/reference/frontend/text.js`).

A Text object is a list of `{elemId, value, conflicts}` element records; the
backend linearizes them by RGA order.  Reads behave like a sequence of
single-character values; edits happen through the list proxy inside a
change() callback (splice/insert_at/delete_at), exactly like the reference
routes Text edits through its list proxy.
"""


class Text:
    _am_object = True

    def __init__(self, object_id=None, elems=None, max_elem=0):
        self._object_id = object_id
        self.elems = elems if elems is not None else []
        self._max_elem = max_elem
        self._conflicts = ()

    @property
    def length(self):
        return len(self.elems)

    def __len__(self):
        return len(self.elems)

    def get(self, index):
        """Value of the index-th character (reference: text.js:12-14)."""
        return self.elems[index]['value']

    def get_elem_id(self, index):
        """ElemId of the index-th character (reference: text.js:16-18)."""
        return self.elems[index]['elemId']

    def __getitem__(self, index):
        if isinstance(index, slice):
            return [e['value'] for e in self.elems[index]]
        return self.elems[index]['value']

    def __iter__(self):
        for elem in self.elems:
            yield elem['value']

    def __eq__(self, other):
        if isinstance(other, Text):
            return list(self) == list(other)
        if isinstance(other, (list, str)):
            return list(self) == list(other)
        return NotImplemented

    def __ne__(self, other):
        result = self.__eq__(other)
        return NotImplemented if result is NotImplemented else not result

    def __hash__(self):
        return object.__hash__(self)

    def __str__(self):
        """The text content as a plain string (join of all elements)."""
        return ''.join(str(v) for v in self)

    def __repr__(self):
        return 'Text(%r)' % str(self)

    # Read-only sequence helpers mirroring the reference's delegated array
    # methods (text.js:36-43)
    def index_of(self, value):
        for i, v in enumerate(self):
            if v == value:
                return i
        return -1

    def includes(self, value):
        return self.index_of(value) >= 0

    def join(self, sep=''):
        return sep.join(str(v) for v in self)

    def slice(self, start=None, end=None):
        return list(self)[start:end]

    def map(self, fn):
        return [fn(v) for v in self]

    def filter(self, fn):
        return [v for v in self if fn(v)]

    def _freeze(self):
        pass  # Text instances are replaced wholesale on patch application


def get_elem_id(obj, index):
    """ElemId of the index-th element of a list or Text object
    (reference: text.js:57-59)."""
    if isinstance(obj, Text):
        return obj.get_elem_id(index)
    return obj._elem_ids[index]
